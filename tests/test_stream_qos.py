"""Zero-copy streaming wire + per-tenant QoS tests.

The system invariants under test:

- **Byte identity or typed error**: a streamed response, reassembled
  client-side, is byte-identical to the buffered JSON response for the
  same request — across every witness encoding (plain, aggregated,
  delta, zlib) — or fails with a typed in-band abort. Never silently
  different, never torn bytes.
- **Zero-copy on the warm path**: disk-warm block payloads leave the
  server as CRC-verified `memoryview` slices of segment-store frames
  (``serve.stream.zero_copy_bytes``), with copied bytes EXACTLY zero;
  eviction mid-stream degrades to the copying path, never to torn bytes.
- **Tenant fairness**: token buckets refuse sustained excess with a
  typed 429 + Retry-After, and the batcher's per-tenant queues keep a
  light tenant's latency bounded while a heavy tenant saturates the
  workers (mirror of test_backfill.py's backfill-vs-interactive check).

Everything is hermetic (build_range_world stores, ephemeral localhost
ports, no egress) and tier-1.
"""

import json
import os
import threading
import time
from http.client import HTTPConnection

import pytest

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import TipsetPair
from ipc_proofs_tpu.serve.httpd import ProofHTTPServer
from ipc_proofs_tpu.serve.qos import (
    FairQueue,
    TenantQoS,
    TenantThrottledError,
    TokenBucket,
)
from ipc_proofs_tpu.serve.service import ProofService, ServiceConfig
from ipc_proofs_tpu.storex.segments import SegmentStore
from ipc_proofs_tpu.utils.metrics import Metrics
from ipc_proofs_tpu.witness import expand_response_fields
from ipc_proofs_tpu.witness.stream import (
    STREAM_CONTENT_TYPE,
    decode_bundle_stream,
    decode_bundle_stream_docs,
    negotiate_stream,
)

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001


@pytest.fixture(scope="module")
def world():
    return build_range_world(
        4,
        receipts_per_pair=6,
        events_per_receipt=3,
        match_rate=0.5,
        signature=SIG,
        topic1=SUBNET,
        actor_id=ACTOR,
        base_height=51_000,
    )


def _canon(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def _post(port, path, obj, headers=None, raw=False, timeout=60):
    conn = HTTPConnection("127.0.0.1", port, timeout=timeout)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request("POST", path, json.dumps(obj), hdrs)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, dict(resp.getheaders()), (data if raw else json.loads(data))


def _get(port, path, headers=None, raw=False):
    conn = HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", path, None, headers or {})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, dict(resp.getheaders()), (data if raw else json.loads(data))


# --------------------------------------------------------------------------
# the stream × encoding differential grid
# --------------------------------------------------------------------------


class TestStreamDifferentialGrid:
    """{stream, buffered} × {plain, aggregated, delta, zlib}: every cell
    reassembles byte-identical to its buffered twin or fails typed."""

    @pytest.fixture()
    def server(self, world, tmp_path):
        store, pairs, _ = world
        svc = ProofService(
            store=store,
            spec=EventProofSpec(event_signature=SIG, topic_1=SUBNET),
            config=ServiceConfig(
                max_batch=8, max_wait_ms=5.0, workers=2,
                store_dir=str(tmp_path / "seg"),
            ),
        )
        httpd = ProofHTTPServer(svc, pairs=pairs).start()
        yield httpd, svc, pairs
        httpd.shutdown(timeout=30)

    def _stream(self, httpd, path, body, headers=None):
        status, hdrs, raw = _post(httpd.port, path, body, headers, raw=True)
        assert status == 200, raw[:300]
        assert hdrs.get("Content-Type") == STREAM_CONTENT_TYPE
        assert hdrs.get("Transfer-Encoding") == "chunked"
        return hdrs, decode_bundle_stream(raw)

    def test_generate_plain_and_zlib_stream_equals_buffered(self, server):
        httpd, _svc, _pairs = server
        for enc in ("identity", "zlib"):
            body = {"pair_index": 0, "witness_encoding": enc}
            st, _, buffered = _post(httpd.port, "/v1/generate", body)
            assert st == 200
            hdrs, fields = self._stream(
                httpd, "/v1/generate", {**body, "stream": True}
            )
            assert hdrs.get("Witness-Encoding") == enc
            assert fields["witness_encoding"] == enc
            # the reassembled fields expand to the identical bundle
            a = expand_response_fields(dict(buffered))
            b = expand_response_fields(dict(fields))
            assert _canon(a.to_json_obj()) == _canon(b.to_json_obj())
            if enc == "identity":
                assert _canon(fields["bundle"]) == _canon(buffered["bundle"])
            assert fields["digest"] == buffered["digest"]

    def test_generate_range_aggregated_stream_equals_buffered(self, server):
        httpd, _svc, _pairs = server
        idxs = [0, 1, 0, 2]
        body = {"pair_indexes": idxs, "aggregate": True}
        st, _, buffered = _post(httpd.port, "/v1/generate_range", body)
        assert st == 200
        _, fields = self._stream(
            httpd, "/v1/generate_range", body,
            headers={"Accept": STREAM_CONTENT_TYPE},
        )
        assert _canon(fields["bundle"]) == _canon(buffered["bundle"])
        assert fields["claims"] == buffered["claims"]
        assert fields["n_event_proofs"] == buffered["n_event_proofs"]

    def test_delta_stream_equals_buffered(self, server):
        httpd, _svc, _pairs = server
        st, _, first = _post(
            httpd.port, "/v1/generate_range", {"pair_indexes": [0, 1]}
        )
        assert st == 200
        base = expand_response_fields(dict(first))
        req = {"pair_indexes": [0, 1, 2], "base_digest": first["digest"]}
        st, _, buffered = _post(httpd.port, "/v1/generate_range", req)
        assert st == 200
        assert "bundle_delta" in buffered
        _, fields = self._stream(
            httpd, "/v1/generate_range", {**req, "stream": True}
        )
        assert fields["witness_base"] == first["digest"]
        a = expand_response_fields(dict(buffered), base=base)
        b = expand_response_fields(dict(fields), base=base)
        assert _canon(a.to_json_obj()) == _canon(b.to_json_obj())

    def test_warm_stream_is_zero_copy(self, server):
        httpd, svc, _pairs = server
        # warm pass spills every block into the disk tier's segments
        st, _, _ = _post(httpd.port, "/v1/generate", {"pair_index": 1})
        assert st == 200
        c0 = svc.metrics_snapshot()["counters"]
        _, fields = self._stream(
            httpd, "/v1/generate", {"pair_index": 1, "stream": True}
        )
        c1 = svc.metrics_snapshot()["counters"]
        assert fields["bundle"]["blocks"], "grid cell must carry blocks"
        zc = c1.get("serve.stream.zero_copy_bytes", 0) - c0.get(
            "serve.stream.zero_copy_bytes", 0
        )
        copied = c1.get("serve.stream.copied_bytes", 0) - c0.get(
            "serve.stream.copied_bytes", 0
        )
        assert zc > 0, "disk-warm blocks must stream as frame slices"
        assert copied == 0, f"{copied} block bytes copied on the warm path"
        assert c1.get("storex.slice_hits", 0) > c0.get("storex.slice_hits", 0)

    def test_bad_stream_field_typed_400(self, server):
        httpd, _svc, _pairs = server
        st, _, err = _post(
            httpd.port, "/v1/generate", {"pair_index": 0, "stream": "yes"}
        )
        assert st == 400
        assert err["error_type"] == "witness_encoding"

    def test_stream_ms_rides_server_timing(self, server):
        httpd, _svc, _pairs = server
        t0 = time.monotonic()
        _, fields = self._stream(
            httpd, "/v1/generate", {"pair_index": 0, "stream": True}
        )
        wall_ms = (time.monotonic() - t0) * 1000.0
        timing = fields["server_timing"]
        assert set(timing) >= {
            "queue_ms", "batch_wait_ms", "generate_ms", "stream_ms"
        }
        assert timing["stream_ms"] >= 0.0
        # admission → completion: the server's own accounting can never
        # exceed what the client observed around the whole exchange
        assert sum(timing.values()) <= wall_ms


# --------------------------------------------------------------------------
# eviction mid-stream: copied fallback or typed error, never torn bytes
# --------------------------------------------------------------------------


class TestEvictionMidStream:
    def test_slice_survives_file_deletion(self, tmp_path):
        """The mmap contract the wire relies on: a handed-out frame slice
        stays byte-valid after the segment file is unlinked (POSIX keeps
        the mapping's backing alive until the last reference goes)."""
        store = SegmentStore(str(tmp_path), cap_bytes=1 << 20)
        data = os.urandom(4096)
        cid = CID.hash_of(data)
        assert store.put(cid, data)
        view = store.read_frame_slice(cid)
        assert view is not None
        for name in os.listdir(tmp_path):
            if name.startswith("seg-"):
                os.unlink(tmp_path / name)
        assert bytes(view) == data  # pages pinned through the view
        view.release()

    def test_evicted_store_falls_back_to_copies_byte_identical(
        self, world, tmp_path
    ):
        """Kill every segment file under a warm server: the stream must
        answer from the copying path — byte-identical, copied counter up,
        zero-copy counter flat. Availability degrades; bytes never do."""
        store, pairs, _ = world
        seg_root = tmp_path / "seg"
        svc = ProofService(
            store=store,
            spec=EventProofSpec(event_signature=SIG, topic_1=SUBNET),
            config=ServiceConfig(
                max_batch=8, max_wait_ms=5.0, workers=2,
                store_dir=str(seg_root),
            ),
        )
        httpd = ProofHTTPServer(svc, pairs=pairs).start()
        try:
            st, _, buffered = _post(httpd.port, "/v1/generate", {"pair_index": 0})
            assert st == 200
            for name in os.listdir(seg_root):
                if name.startswith("seg-"):
                    os.unlink(seg_root / name)
            c0 = svc.metrics_snapshot()["counters"]
            st, _, raw = _post(
                httpd.port, "/v1/generate",
                {"pair_index": 0, "stream": True}, raw=True,
            )
            assert st == 200
            fields = decode_bundle_stream(raw)  # digest re-derivation passes
            assert _canon(fields["bundle"]) == _canon(buffered["bundle"])
            c1 = svc.metrics_snapshot()["counters"]
            assert c1.get("serve.stream.copied_bytes", 0) > c0.get(
                "serve.stream.copied_bytes", 0
            )
            assert c1.get("serve.stream.zero_copy_bytes", 0) == c0.get(
                "serve.stream.zero_copy_bytes", 0
            )
        finally:
            httpd.shutdown(timeout=30)


# --------------------------------------------------------------------------
# per-tenant QoS: token buckets, fair queues, and the HTTP door
# --------------------------------------------------------------------------


class TestTokenBucket:
    def test_burst_then_refusal_with_retry_after(self):
        b = TokenBucket(rate=1.0, burst=2.0, now=100.0)
        ok1, _ = b.take(100.0)
        ok2, _ = b.take(100.0)
        ok3, retry = b.take(100.0)
        assert (ok1, ok2, ok3) == (True, True, False)
        assert retry > 0.0
        ok4, _ = b.take(100.0 + retry + 0.01)  # refill at `rate`
        assert ok4

    def test_qos_admit_counts_and_types(self):
        m = Metrics()
        qos = TenantQoS(rate=1.0, burst=1.0, metrics=m)
        qos.admit("acme")
        with pytest.raises(TenantThrottledError) as exc:
            qos.admit("acme")
        assert exc.value.retry_after_s > 0.0
        qos.admit("globex")  # an unrelated tenant's bucket is untouched
        c = m.snapshot()["counters"]
        assert c["qos.throttled"] == 1
        assert c["tenant.throttled.acme"] == 1


class TestFairQueue:
    def _pending(self, tenant, tag):
        class P:
            pass

        p = P()
        p.tenant = tenant
        p.tag = tag
        return p

    def test_round_robin_across_tenants_fifo_within(self):
        q = FairQueue()
        for tenant, tag in (
            ("a", "a1"), ("a", "a2"), ("a", "a3"), ("b", "b1"), ("b", "b2"),
        ):
            q.append(self._pending(tenant, tag))
        assert len(q) == 5
        order = [q.popleft().tag for _ in range(len(q))]
        # tenant b's first request overtakes tenant a's backlog, and
        # within each tenant order stays FIFO
        assert order.index("b1") < order.index("a2")
        assert order.index("a1") < order.index("a2") < order.index("a3")
        assert order.index("b1") < order.index("b2")

    def test_anonymous_requests_share_one_queue(self):
        q = FairQueue()
        q.append(self._pending(None, "n1"))
        q.append(self._pending(None, "n2"))
        assert [q.popleft().tag, q.popleft().tag] == ["n1", "n2"]
        assert len(q) == 0

    def test_weighted_round_order_is_pinned(self):
        """`--tenant-weight a=2`: tenant a takes TWO requests per round
        turn, b (default weight 1) takes one — the exact deficit
        round-robin order, pinned."""
        q = FairQueue(weights={"a": 2, "b": 1})
        for tenant, tag in (
            ("a", "a1"), ("a", "a2"), ("a", "a3"), ("b", "b1"), ("b", "b2"),
        ):
            q.append(self._pending(tenant, tag))
        order = [q.popleft().tag for _ in range(len(q))]
        assert order == ["a1", "a2", "b1", "a3", "b2"]

    def test_weight_spent_mid_round_does_not_carry_over(self):
        """A tenant that drains mid-quantum re-enters later rounds with
        a FRESH quantum, not banked credit."""
        q = FairQueue(weights={"a": 3})
        q.append(self._pending("a", "a1"))  # drains with 2 credits unspent
        q.append(self._pending("b", "b1"))
        assert q.popleft().tag == "a1"
        q.append(self._pending("a", "a2"))
        q.append(self._pending("a", "a3"))
        q.append(self._pending("a", "a4"))
        q.append(self._pending("a", "a5"))
        # b is at the head of the round now; then a gets a fresh 3
        order = [q.popleft().tag for _ in range(len(q))]
        assert order == ["b1", "a2", "a3", "a4", "a5"]

    def test_tenant_weight_flag_parses_and_rejects(self):
        from ipc_proofs_tpu.cli import _parse_tenant_weights

        assert _parse_tenant_weights(None) is None
        assert _parse_tenant_weights([]) is None
        assert _parse_tenant_weights(["a=2", "b=1"]) == {"a": 2, "b": 1}
        for bad in ("a", "a=", "=2", "a=0", "a=x"):
            with pytest.raises(SystemExit):
                _parse_tenant_weights([bad])


class TestQoSHTTPDoor:
    @pytest.fixture()
    def throttled_server(self, world):
        store, pairs, _ = world
        svc = ProofService(
            store=store,
            spec=EventProofSpec(event_signature=SIG, topic_1=SUBNET),
            config=ServiceConfig(
                max_batch=8, max_wait_ms=5.0, workers=2,
                tenant_rate=0.001, tenant_burst=2.0,
            ),
        )
        httpd = ProofHTTPServer(svc, pairs=pairs).start()
        yield httpd, svc
        httpd.shutdown(timeout=30)

    def test_429_with_retry_after_and_counters(self, throttled_server):
        httpd, svc = throttled_server
        statuses = []
        for _ in range(3):
            st, hdrs, out = _post(
                httpd.port, "/v1/generate", {"pair_index": 0, "tenant": "acme"}
            )
            statuses.append(st)
        assert statuses[:2] == [200, 200] and statuses[2] == 429
        assert out["error_type"] == "tenant_throttled"
        assert out["retry_after_s"] > 0
        assert int(hdrs["Retry-After"]) >= 1
        c = svc.metrics_snapshot()["counters"]
        assert c["qos.throttled"] >= 1
        assert c["tenant.throttled.acme"] >= 1
        # a different tenant still admits — buckets are per tenant
        st, _, _ = _post(
            httpd.port, "/v1/generate", {"pair_index": 0, "tenant": "globex"}
        )
        assert st == 200

    def test_response_bytes_charge_tenant_at_send_time(self, throttled_server):
        httpd, svc = throttled_server
        c0 = svc.metrics_snapshot()["counters"].get("tenant.bytes.ledgerco", 0)
        st, _, raw = _post(
            httpd.port, "/v1/generate",
            {"pair_index": 0, "tenant": "ledgerco", "stream": True}, raw=True,
        )
        assert st == 200
        # the handler charges send-time bytes a beat after the client has
        # the full body (the terminator lands first) — poll, don't race
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            c1 = svc.metrics_snapshot()["counters"].get("tenant.bytes.ledgerco", 0)
            if c1 - c0 > len(raw) // 2:
                break
            time.sleep(0.01)
        # admission charged the request body; the stream charged its own
        # sent bytes on top — the response is far bigger than the request
        assert c1 - c0 > len(raw) // 2


class TestLightTenantUnderLoad:
    def test_light_tenant_p99_bounded_under_heavy_flood(self, world):
        """Mirror of test_backfill's starvation check, across tenants: a
        heavy tenant's closed-loop flood must not starve a light tenant —
        the per-tenant fair queue bounds each light request's wait to a
        constant number of rounds, not the heavy backlog's drain."""
        store, pairs, _ = world
        svc = ProofService(
            store=store,
            spec=EventProofSpec(event_signature=SIG, topic_1=SUBNET),
            config=ServiceConfig(max_batch=4, max_wait_ms=1.0, workers=1),
        )
        stop = threading.Event()
        heavy_n = []

        def heavy():
            n = 0
            while not stop.is_set():
                svc.generate(pairs[n % len(pairs)], tenant="bulk", timeout_s=60.0)
                n += 1
            heavy_n.append(n)

        threads = [threading.Thread(target=heavy) for _ in range(4)]
        try:
            for t in threads:
                t.start()
            time.sleep(0.2)  # let the heavy backlog establish
            lat_ms = []
            for i in range(12):
                t0 = time.monotonic()
                resp = svc.generate(
                    TipsetPair(
                        parent=pairs[i % len(pairs)].parent,
                        child=pairs[i % len(pairs)].child,
                    ),
                    tenant="light",
                    timeout_s=60.0,
                )
                assert resp.bundle is not None
                lat_ms.append((time.monotonic() - t0) * 1000.0)
        finally:
            stop.set()
            for t in threads:
                t.join()
            svc.drain(timeout=60.0)
        assert sum(heavy_n) > 0, "the heavy tenant must actually have competed"
        lat_ms.sort()
        p99 = lat_ms[max(0, int(len(lat_ms) * 0.99) - 1)]
        # generous: one demo-world generate is tens of ms; starvation
        # (heavy backlog draining first) would push this into the minutes
        assert p99 < 30_000.0, f"light tenant p99 {p99:.0f}ms under heavy load"


# --------------------------------------------------------------------------
# scatter-gather stream dedup
# --------------------------------------------------------------------------


class TestFoldFirstSight:
    def test_fold_returns_only_first_sight_blocks(self):
        """The streamed scatter door sends exactly what fold() returns —
        a block shipped by several shards' sub-bundles must cross the
        client wire once (the decoder's dedup is a safety net, not the
        plan)."""
        from ipc_proofs_tpu.cluster.gather import BundleFold
        from ipc_proofs_tpu.proofs.bundle import ProofBlock, UnifiedProofBundle

        def blk(data):
            return ProofBlock(cid=CID.hash_of(data), data=data)

        shared, only_a, only_b = blk(b"shared"), blk(b"only-a"), blk(b"only-b")
        fold = BundleFold([], [])
        sub_a = UnifiedProofBundle(
            storage_proofs=[], event_proofs=[], blocks=[shared, only_a]
        )
        sub_b = UnifiedProofBundle(
            storage_proofs=[], event_proofs=[], blocks=[only_b, shared]
        )
        assert [b.data for b in fold.fold(sub_a)] == [b"shared", b"only-a"]
        assert [b.data for b in fold.fold(sub_b)] == [b"only-b"]
        sealed = fold.seal()
        assert sorted(b.data for b in sealed.blocks) == [
            b"only-a", b"only-b", b"shared",
        ]


# --------------------------------------------------------------------------
# negotiation unit
# --------------------------------------------------------------------------


class TestNegotiation:
    def test_body_flag_and_accept_header(self):
        assert negotiate_stream({"stream": True}) is True
        assert negotiate_stream({}) is False
        assert negotiate_stream({"stream": False}) is False

        class H(dict):
            def get(self, k, d=None):
                return super().get(k.lower(), d)

        assert negotiate_stream({}, headers=H(accept=STREAM_CONTENT_TYPE))
        assert not negotiate_stream({}, headers=H(accept="application/json"))

    def test_non_bool_stream_is_typed(self):
        from ipc_proofs_tpu.witness.errors import WitnessEncodingError

        with pytest.raises(WitnessEncodingError):
            negotiate_stream({"stream": "yes"})


class TestHonestRetryAfter:
    """The 429's ``Retry-After`` is a real estimate, not a constant: the
    bucket's exact refill time, and waiting it out actually admits."""

    def test_bucket_retry_after_is_the_refill_time(self):
        b = TokenBucket(rate=4.0, burst=1.0, now=50.0)
        ok, _ = b.take(50.0)
        assert ok
        ok2, retry = b.take(50.0)
        assert not ok2
        # one token at 4/s from an empty bucket: exactly 0.25 s
        assert retry == pytest.approx(0.25, rel=1e-9)
        # honesty cuts both ways: just before the estimate still refuses,
        # at the estimate admits
        early_ok, early_retry = b.take(50.0 + retry * 0.5)
        assert not early_ok and early_retry > 0
        ok3, _ = b.take(50.0 + retry)
        assert ok3

    def test_http_door_retry_after_admits_when_honored(self, world):
        store, pairs, _ = world
        svc = ProofService(
            store=store,
            spec=EventProofSpec(event_signature=SIG, topic_1=SUBNET),
            config=ServiceConfig(
                max_batch=8, max_wait_ms=5.0, workers=2,
                tenant_rate=5.0, tenant_burst=1.0,
            ),
        )
        httpd = ProofHTTPServer(svc, pairs=pairs).start()
        try:
            st, _, _ = _post(
                httpd.port, "/v1/generate",
                {"pair_index": 0, "tenant": "honest"},
            )
            assert st == 200
            st, hdrs, out = _post(
                httpd.port, "/v1/generate",
                {"pair_index": 0, "tenant": "honest"},
            )
            assert st == 429 and out["error_type"] == "tenant_throttled"
            # the estimate is the refill time (≤ 1/rate from empty), not
            # some pessimistic constant — and the header rounds it UP so
            # a naive client never retries early
            assert 0.0 < out["retry_after_s"] <= 1.0 / 5.0 + 0.05
            assert int(hdrs["Retry-After"]) >= 1
            time.sleep(out["retry_after_s"] + 0.02)
            st, _, _ = _post(
                httpd.port, "/v1/generate",
                {"pair_index": 0, "tenant": "honest"},
            )
            assert st == 200  # honoring the hint admits on the first try
        finally:
            httpd.shutdown(timeout=30)
