"""Replicated segment tier tests (storex.replica).

The invariants under test:

- **Replication transport**: `ReplicaClient` round-trips whole segment
  files and single blocks over the shard HTTP replication routes, with
  typed `ReplicaError` on any transport or HTTP failure.
- **Read-repair before Lotus**: a local frame that fails CRC/multihash
  (integrity eviction) repairs from a replica peer BEFORE the inner
  store is ever consulted (``storex.replica_repairs`` pinned exact,
  inner-store gets pinned zero), re-spills to disk, and a lying replica
  is indistinguishable from a miss.
- **Pull sync**: `Replicator.sync_from` pulls exactly the rolled foreign
  segments it is missing — never active tails, never its own owner's
  segments, never outside an owner filter — and is idempotent.
- **Rebalance journal discipline**: a `RebalanceJob` SIGKILLed at ANY
  append boundary (plan, each push, commit) or torn mid-record resumes
  to the same final segment placement, byte for byte
  (tools/crashtest.py ``--rebalance`` grid).

Everything is hermetic (ephemeral localhost ports, no egress) and
tier-1.
"""

import json
import os
import sys

import pytest

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.jobs.journal import read_journal
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.serve.httpd import ProofHTTPServer
from ipc_proofs_tpu.serve.service import ProofService, ServiceConfig
from ipc_proofs_tpu.storex import (
    RebalanceJob,
    ReplicaClient,
    ReplicaError,
    ReplicaSet,
    Replicator,
    SegmentStore,
    TieredBlockstore,
)
from ipc_proofs_tpu.utils.metrics import Metrics

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools"))

import crashtest  # noqa: E402

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"


def _block(i: int) -> "tuple[CID, bytes]":
    data = (b"replica-%04d-" % i) * (i + 2)
    return CID.hash_of(data), data


def _flip_last_byte(path: str) -> None:
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.seek(size - 1)
        b = fh.read(1)
        fh.seek(size - 1)
        fh.write(bytes([b[0] ^ 0x40]))


class _CountingInner:
    """Minimal inner Blockstore that counts every get — the stand-in for
    Lotus. A read-repair that touches it is the bug under test."""

    def __init__(self, mapping=None):
        self.mapping = dict(mapping or {})
        self.gets = 0

    def get(self, cid):
        self.gets += 1
        return self.mapping.get(cid)

    def put_keyed(self, cid, data):
        self.mapping[cid] = bytes(data)

    def has(self, cid):
        return cid in self.mapping


@pytest.fixture(scope="module")
def world():
    return build_range_world(
        2,
        receipts_per_pair=2,
        events_per_receipt=2,
        match_rate=0.5,
        signature=SIG,
        topic1=SUBNET,
        base_height=51_000,
    )


def _shard(world, store_dir, owner, seg_max=1):
    """One serve daemon exposing the replication routes over a private
    disk tier (1-byte roll threshold: every put becomes a rolled,
    pullable segment immediately)."""
    store, pairs, _ = world
    svc = ProofService(
        store=store,
        spec=EventProofSpec(event_signature=SIG, topic_1=SUBNET),
        config=ServiceConfig(
            max_batch=4, max_wait_ms=5.0, workers=1,
            store_dir=str(store_dir),
            store_owner=owner,
            store_segment_max_bytes=seg_max,
        ),
    )
    httpd = ProofHTTPServer(svc, pairs=pairs).start()
    return httpd, svc


class TestReplicaClient:
    def test_segment_and_block_round_trip(self, world, tmp_path):
        httpd_a, svc_a = _shard(world, tmp_path / "a", "a")
        httpd_b, svc_b = _shard(world, tmp_path / "b", "b")
        try:
            blocks = [_block(i) for i in range(3)]
            for cid, data in blocks:
                svc_a.disk_store.put(cid, data)
            client_a = ReplicaClient("a", f"http://127.0.0.1:{httpd_a.port}")
            segs = client_a.list_segments()
            rolled = [s for s in segs if not s["active"]]
            assert len(rolled) == 3
            assert all(s["owner"] == "a" for s in rolled)
            # whole-file fetch is byte-exact against the on-disk segment
            name = rolled[0]["name"]
            raw = client_a.fetch_segment(name)
            with open(svc_a.disk_store.segment_path(name), "rb") as fh:
                assert raw == fh.read()
            # push into the other shard: ingest is atomic and idempotent
            client_b = ReplicaClient("b", f"http://127.0.0.1:{httpd_b.port}")
            client_b.push_segment(name, raw)
            client_b.push_segment(name, raw)  # idempotent re-push
            cid0, data0 = blocks[0]
            assert svc_b.disk_store.get(cid0) == data0
            # single-block route: present locally vs a clean 404 miss
            assert client_a.fetch_block(cid0) == data0
            missing, _ = _block(999)
            assert client_a.fetch_block(missing) is None
        finally:
            httpd_a.shutdown(timeout=30)
            httpd_b.shutdown(timeout=30)

    def test_active_tail_is_listed_but_never_pulled(self, world, tmp_path):
        """The tail another process may still be appending to is marked
        ``active`` in the inventory and the Replicator filter skips it —
        its bytes move once they roll. (A direct `fetch_segment` still
        works: the server flushes and serves the committed tail bytes.)"""
        httpd, svc = _shard(world, tmp_path / "a", "a", seg_max=1 << 20)
        try:
            cid, data = _block(1)
            svc.disk_store.put(cid, data)  # stays in the active tail
            client = ReplicaClient("a", f"http://127.0.0.1:{httpd.port}")
            segs = client.list_segments()
            assert [s["active"] for s in segs] == [True]
            assert len(client.fetch_segment(segs[0]["name"])) > 0
            local = SegmentStore(str(tmp_path / "b"), owner="b")
            assert Replicator(local).sync_from(client)["pulled"] == 0
            assert local.get(cid) is None
            local.close()
        finally:
            httpd.shutdown(timeout=30)

    def test_unreachable_peer_is_typed(self):
        client = ReplicaClient("ghost", "http://127.0.0.1:1", timeout_s=0.5)
        with pytest.raises(ReplicaError):
            client.list_segments()


class TestReadRepair:
    def _corrupt_local(self, tmp_path, m, cid, data):
        local = SegmentStore(str(tmp_path / "local"), metrics=m)
        local.put(cid, data)
        seg = [d["name"] for d in local.segment_files()][0]
        # flipping the payload tail fails the frame CRC on the next read
        _flip_last_byte(os.path.join(str(tmp_path / "local"), seg))
        return local

    def test_corrupt_frame_repairs_from_replica_not_inner(
        self, world, tmp_path
    ):
        """The tentpole pin: integrity eviction → replica refetch, with
        the inner (Lotus stand-in) store untouched and the repaired
        bytes re-spilled for the next reader."""
        httpd, svc = _shard(world, tmp_path / "peer", "peer")
        try:
            cid, data = _block(7)
            svc.disk_store.put(cid, data)
            m = Metrics()
            local = self._corrupt_local(tmp_path, m, cid, data)
            inner = _CountingInner()
            tiered = TieredBlockstore(
                inner, local, metrics=m,
                replicas=ReplicaSet(
                    [ReplicaClient("peer", f"http://127.0.0.1:{httpd.port}")],
                    metrics=m,
                ),
            )
            assert tiered.get(cid) == data
            assert inner.gets == 0
            counters = m.snapshot()["counters"]
            assert counters["storex.integrity_evictions"] == 1
            assert counters["storex.replica_repairs"] == 1
            assert "storex.replica_repair_misses" not in counters
            # re-spilled: a fresh tiered view with NO replicas and an empty
            # cache serves the repaired frame straight from local disk
            inner2 = _CountingInner()
            tiered2 = TieredBlockstore(inner2, local, metrics=m)
            assert tiered2.get(cid) == data
            assert inner2.gets == 0
            assert m.snapshot()["counters"]["storex.replica_repairs"] == 1
            local.close()
        finally:
            httpd.shutdown(timeout=30)

    def test_repair_miss_falls_back_to_inner(self, world, tmp_path):
        """A peer that lacks the block is a counted miss — the inner
        store remains the fallback of record."""
        httpd, _svc = _shard(world, tmp_path / "peer", "peer")
        try:
            cid, data = _block(9)  # never pushed to the peer
            m = Metrics()
            local = self._corrupt_local(tmp_path, m, cid, data)
            inner = _CountingInner({cid: data})
            tiered = TieredBlockstore(
                inner, local, metrics=m,
                replicas=ReplicaSet(
                    [ReplicaClient("peer", f"http://127.0.0.1:{httpd.port}")],
                    metrics=m,
                ),
            )
            assert tiered.get(cid) == data
            assert inner.gets == 1
            counters = m.snapshot()["counters"]
            assert counters["storex.replica_repair_misses"] == 1
            assert "storex.replica_repairs" not in counters
            local.close()
        finally:
            httpd.shutdown(timeout=30)

    def test_lying_replica_is_a_miss(self):
        """Replica bytes re-verify against the CID: garbage from a peer
        is never served and never counted as a repair."""

        class _Liar(ReplicaClient):
            def fetch_block(self, cid):
                return b"not the bytes you wanted"

        m = Metrics()
        cid, _data = _block(3)
        rs = ReplicaSet([_Liar("liar", "http://127.0.0.1:1")], metrics=m)
        assert rs.repair(cid) is None
        counters = m.snapshot()["counters"]
        assert counters["storex.replica_repair_misses"] == 1
        assert "storex.replica_repairs" not in counters

    def test_plain_miss_never_consults_replicas(self, tmp_path):
        """Only CORRUPT frames repair — a block that was never here has
        no reason to exist on a peer, so the peer is never dialed."""

        calls = []

        class _Recorder(ReplicaClient):
            def fetch_block(self, cid):
                calls.append(cid)
                return None

        m = Metrics()
        local = SegmentStore(str(tmp_path / "local"), metrics=m)
        cid, data = _block(5)
        inner = _CountingInner({cid: data})
        tiered = TieredBlockstore(
            inner, local, metrics=m,
            replicas=ReplicaSet(
                [_Recorder("peer", "http://127.0.0.1:1")], metrics=m
            ),
        )
        assert tiered.get(cid) == data
        assert inner.gets == 1
        assert calls == []
        local.close()


class TestReplicatorSync:
    def test_pull_sync_rolled_foreign_segments(self, world, tmp_path):
        httpd, svc = _shard(world, tmp_path / "a", "a")
        try:
            blocks = [_block(i) for i in range(4)]
            for cid, data in blocks:
                svc.disk_store.put(cid, data)
            peer = ReplicaClient("a", f"http://127.0.0.1:{httpd.port}")
            m = Metrics()
            local = SegmentStore(str(tmp_path / "b"), owner="b", metrics=m)
            r = Replicator(local, metrics=m).sync_from(peer)
            assert r == {"pulled": 4, "bytes": r["bytes"], "blocks": 4,
                         "pending": 0}
            assert r["bytes"] > 0
            for cid, data in blocks:
                assert local.get(cid) == data
            # idempotent: a second pass pulls nothing
            r2 = Replicator(local, metrics=m).sync_from(peer)
            assert r2["pulled"] == 0
            counters = m.snapshot()["counters"]
            assert counters["storex.replica_segments_pulled"] == 4
            local.close()
        finally:
            httpd.shutdown(timeout=30)

    def test_owner_filter_and_own_segments_skipped(self, world, tmp_path):
        httpd, svc = _shard(world, tmp_path / "a", "a")
        try:
            cid, data = _block(1)
            svc.disk_store.put(cid, data)
            peer = ReplicaClient("a", f"http://127.0.0.1:{httpd.port}")
            # an owner filter that names nobody pulls nothing
            other = SegmentStore(str(tmp_path / "c"), owner="c")
            assert Replicator(other).sync_from(peer, owners=["zzz"])[
                "pulled"] == 0
            other.close()
            # a store that IS owner "a" never re-pulls its own segments
            mine = SegmentStore(str(tmp_path / "a2"), owner="a")
            assert Replicator(mine).sync_from(peer)["pulled"] == 0
            mine.close()
        finally:
            httpd.shutdown(timeout=30)


class TestRebalanceJob:
    def _src(self, tmp_path, m=None, n=3):
        src = SegmentStore(
            str(tmp_path / "src"), owner="a", segment_max_bytes=1, metrics=m
        )
        blocks = [_block(i) for i in range(n)]
        for cid, data in blocks:
            src.put(cid, data)
        return src, blocks

    def test_handoff_commits_and_source_drops_after(self, tmp_path):
        m = Metrics()
        src, blocks = self._src(tmp_path, m)
        dest = SegmentStore(str(tmp_path / "dest"), owner="b", metrics=m)
        segments = [d["name"] for d in src.segment_files() if not d["active"]]
        assert len(segments) == 3

        def read_segment(name):
            path = src.segment_path(name)
            with open(path, "rb") as fh:
                return fh.read()

        journal = str(tmp_path / "rebalance.journal")
        job = RebalanceJob(
            journal, "dest", segments,
            dest.ingest_segment_file, read_segment, metrics=m,
        )
        assert job.run() is True
        assert job.committed
        records, _off, torn = read_journal(journal)
        assert not torn
        assert [r["kind"] for r in records] == (
            ["plan"] + ["pushed"] * 3 + ["commit"]
        )
        counters = m.snapshot()["counters"]
        assert counters["storex.rebalance_segments_pushed"] == 3
        assert "storex.rebalance_resumes" not in counters
        # the OLD owner served until the commit landed; only now drop
        for name in segments:
            src.drop_segment(name)
        for cid, data in blocks:
            assert src.get(cid) is None
            assert dest.get(cid) == data
        src.close()
        dest.close()

    def test_resume_skips_pushed_prefix(self, tmp_path):
        """Die after the first push (exception, not SIGKILL — the kill
        grid below covers real process death), resume, and demand every
        committed push be skipped and counted as a resume."""
        src, blocks = self._src(tmp_path)
        segments = [d["name"] for d in src.segment_files() if not d["active"]]
        pushed = {}

        def read_segment(name):
            with open(src.segment_path(name), "rb") as fh:
                return fh.read()

        def flaky_push(name, data):
            if pushed:
                raise ReplicaError("dest went away")
            pushed[name] = data

        journal = str(tmp_path / "rebalance.journal")
        with pytest.raises(ReplicaError):
            RebalanceJob(
                journal, "dest", segments, flaky_push, read_segment
            ).run()
        assert len(pushed) == 1
        m2 = Metrics()
        job = RebalanceJob(
            journal, "dest", segments, pushed.__setitem__, read_segment,
            metrics=m2,
        )
        assert job.run() is True
        assert sorted(pushed) == segments
        counters = m2.snapshot()["counters"]
        assert counters["storex.rebalance_resumes"] == 1
        assert counters["storex.rebalance_segments_pushed"] == 2
        src.close()

    def test_journal_refuses_a_different_plan(self, tmp_path):
        src, _blocks = self._src(tmp_path)
        segments = [d["name"] for d in src.segment_files() if not d["active"]]

        def read_segment(name):
            with open(src.segment_path(name), "rb") as fh:
                return fh.read()

        journal = str(tmp_path / "rebalance.journal")
        RebalanceJob(
            journal, "dest", segments, lambda n, d: None, read_segment
        ).run()
        with pytest.raises(ReplicaError):
            RebalanceJob(
                journal, "other-dest", segments, lambda n, d: None,
                read_segment,
            ).run()
        src.close()

    def test_sigkill_grid_resumes_to_same_placement(self):
        """The crashtest grid: SIGKILL at EVERY append boundary (plan,
        each push, commit) plus torn mid-record writes — every point
        must resume to the byte-identical final placement."""
        summary = crashtest.run_rebalance_grid(20260807)
        assert summary["ok"], summary["violations"]
        assert summary["counts"] == {"identical": summary["points"]}
