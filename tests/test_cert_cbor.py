"""go-f3 certexchange CBOR codec: golden layout, round trip, strictness."""

import pytest

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.core.dagcbor import decode as cbor_decode
from ipc_proofs_tpu.crypto.rleplus import encode_rleplus
from ipc_proofs_tpu.proofs.cert import (
    ECTipSet,
    FinalityCertificate,
    PowerTableDelta,
    SupplementalData,
)
from ipc_proofs_tpu.proofs.cert_cbor import (
    bigint_from_bytes,
    bigint_to_bytes,
    certificate_from_cbor,
    certificate_to_cbor,
    split_tipset_key,
)


def _cid(tag: str) -> CID:
    return CID.hash_of(tag.encode())


def _cert() -> FinalityCertificate:
    import base64

    return FinalityCertificate(
        instance=42,
        ec_chain=[
            ECTipSet(
                key=[str(_cid("blk-a")), str(_cid("blk-b"))],
                epoch=100,
                power_table=str(_cid("pt-0")),
                # wire form is [32]byte: decode materializes zeros, so the
                # fixture uses the materialized form for ==-comparability
                commitments=bytes(32),
            ),
            ECTipSet(
                key=[str(_cid("blk-c"))],
                epoch=101,
                power_table=str(_cid("pt-1")),
                commitments=b"\x11" * 32,
            ),
        ],
        supplemental_data=SupplementalData(
            commitments=b"\x22" * 32, power_table=str(_cid("pt-next"))
        ),
        signers=encode_rleplus([0, 2, 3]),
        signature=b"\xab" * 96,
        power_table_delta=[
            PowerTableDelta(
                participant_id=7,
                power_delta="-50",
                signing_key=base64.b64encode(b"\xcd" * 48).decode(),
            ),
            PowerTableDelta(participant_id=9, power_delta="10", signing_key=""),
        ],
    )


class TestBigInt:
    @pytest.mark.parametrize(
        "value,raw",
        [
            (0, b""),
            (1, b"\x00\x01"),
            (255, b"\x00\xff"),
            (-1, b"\x01\x01"),
            (1 << 80, b"\x00\x01" + bytes(10)),
        ],
    )
    def test_vectors(self, value, raw):
        assert bigint_to_bytes(value) == raw
        assert bigint_from_bytes(raw) == value

    @pytest.mark.parametrize(
        "bad",
        [b"\x02\x01", b"\x00", b"\x01", b"\x00\x00\x01", b"\x01\x00"],
    )
    def test_non_canonical_rejected(self, bad):
        with pytest.raises(ValueError):
            bigint_from_bytes(bad)


class TestTipsetKey:
    def test_split_roundtrip(self):
        cids = [_cid("a"), _cid("b"), CID.hash_of(b"raw", codec=0x55)]
        raw = b"".join(c.to_bytes() for c in cids)
        assert split_tipset_key(raw) == cids
        assert split_tipset_key(b"") == []

    def test_truncated_rejected(self):
        raw = _cid("a").to_bytes()
        with pytest.raises(ValueError):
            split_tipset_key(raw[:-1])


class TestCodec:
    def test_round_trip(self):
        cert = _cert()
        raw = certificate_to_cbor(cert)
        back = certificate_from_cbor(raw)
        assert back == cert
        assert certificate_to_cbor(back) == raw  # stable re-encode

    def test_golden_layout(self):
        """Pin the tuple structure field-for-field through an independent
        decode: any accidental reorder breaks here."""
        cert = _cert()
        obj = cbor_decode(certificate_to_cbor(cert))
        assert obj[0] == 42  # GPBFTInstance
        ts0 = obj[1][0]  # ECChain[0] = [Epoch, Key, PowerTable, Commitments]
        assert ts0[0] == 100
        assert ts0[1] == _cid("blk-a").to_bytes() + _cid("blk-b").to_bytes()
        assert ts0[2] == _cid("pt-0")
        assert ts0[3] == bytes(32)
        assert obj[2] == [b"\x22" * 32, _cid("pt-next")]  # SupplementalData
        assert obj[3] == encode_rleplus([0, 2, 3])  # Signers (RLE+)
        assert obj[4] == b"\xab" * 96  # Signature
        assert obj[5][0] == [7, b"\x01\x32", b"\xcd" * 48]  # delta (-50)
        assert obj[5][1] == [9, b"\x00\x0a", b""]

    def test_list_signers_encode_as_rleplus(self):
        cert = _cert()
        cert.signers = [3, 0, 2]
        raw = certificate_to_cbor(cert)
        assert cbor_decode(raw)[3] == encode_rleplus([0, 2, 3])
        assert certificate_from_cbor(raw).signer_indices() == [0, 2, 3]

    def test_verification_survives_wire_round_trip(self):
        """A certificate rebuilt from its wire bytes must produce the same
        signing payload (the aggregate signature stays checkable)."""
        cert = _cert()
        back = certificate_from_cbor(certificate_to_cbor(cert))
        assert back.signing_payload() == cert.signing_payload()

    @pytest.mark.parametrize(
        "mutate",
        [
            lambda o: o[:5],  # 5-tuple
            lambda o: o + [0],  # 7-tuple
            lambda o: [o[0], o[1], o[2], b"\x01", o[4], o[5]],  # bad RLE+
            lambda o: [-1, o[1], o[2], o[3], o[4], o[5]],  # negative instance
            lambda o: [o[0], [[1, 2, 3]], o[2], o[3], o[4], o[5]],  # bad tipset
        ],
    )
    def test_structural_garbage_rejected(self, mutate):
        from ipc_proofs_tpu.core.dagcbor import encode as cbor_encode

        obj = cbor_decode(certificate_to_cbor(_cert()))
        with pytest.raises(ValueError):
            certificate_from_cbor(cbor_encode(mutate(obj)))

    def test_nonminimal_link_varint_rejected(self):
        """Regression for the round-5 soak find: a tag-42 link whose
        multihash-code varint is non-minimal is a second wire form for the
        same certificate. Since the later exec-order fuzz find, the CID
        decoders reject non-minimal varints outright ('malformed CID
        bytes' / 'non-canonical'); the whole-certificate canonical
        re-encode check remains as defense in depth behind them."""
        base = certificate_to_cbor(_cert())
        canon = bytes.fromhex("58270001 71a0e402 20".replace(" ", ""))
        assert canon in base  # byte-string head + identity prefix + CIDv1
        # lengthen the mh-code varint 0xb220: a0 e4 02 -> a0 e4 82 00
        # (adds a redundant zero group) and bump the byte-string length
        noncanon = bytes.fromhex("58280001 71a0e482 0020".replace(" ", ""))
        mutated = base.replace(canon, noncanon, 1)
        assert mutated != base
        with pytest.raises(ValueError, match="non-canonical|malformed CID"):
            certificate_from_cbor(mutated)

    def test_fuzz_garbage_never_leaks_and_accepts_are_canonical(self):
        """Byte-level mutations must reject as ValueError only (the same
        contract as the JSON trust boundary), and every ACCEPTED mutant —
        e.g. a bit flip inside the signature blob, still structurally
        valid — must re-encode to exactly its own bytes: one wire form per
        certificate, no malleability."""
        import random

        rng = random.Random(3)
        base = certificate_to_cbor(_cert())
        accepted = rejected = 0
        for _ in range(2000):
            raw = bytearray(base)
            for _ in range(rng.randrange(1, 4)):
                k = rng.randrange(3)
                if k == 0 and raw:
                    raw[rng.randrange(len(raw))] ^= 1 << rng.randrange(8)
                elif k == 1 and raw:
                    del raw[rng.randrange(len(raw))]
                else:
                    raw.insert(rng.randrange(len(raw) + 1), rng.randrange(256))
            raw = bytes(raw)
            try:
                cert = certificate_from_cbor(raw)
            except ValueError:
                rejected += 1
                continue
            accepted += 1
            assert certificate_to_cbor(cert) == raw, raw.hex()
        assert accepted and rejected  # both regimes exercised
