"""Pinned-seed crash-recovery grid (tools/crashtest.py harness).

Each grid point SIGKILLs a real child process running the journaled
pipelined range driver — at a chunk-commit boundary or mid-record (torn
frame) — then resumes it and demands the final bundle be byte-identical
to an uninterrupted run. The seeds are pinned so the exact kill points
are reproducible; `tools/soak.py crash` runs the same harness with fresh
seeds at scale."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools"))

import crashtest  # noqa: E402


@pytest.mark.parametrize("seed", [20260805, 7])
def test_sigkill_grid_resumes_byte_identical(seed):
    summary = crashtest.run_grid(seed, points=8, n_pairs=12, chunk_size=2)
    assert summary["ok"], summary["violations"]
    assert summary["counts"] == {"identical": summary["points"]}
    # the grid must exercise BOTH kill flavors: clean boundary commits and
    # torn mid-record frames (different recovery paths)
    torn = [t for _, t in summary["kill_points"] if t is not None]
    assert torn and len(torn) < summary["points"]


def test_sigkill_during_concurrent_record_commits():
    """SIGKILL while TWO record workers are committing chunks concurrently.

    The journal's count-clock is serialized under the job lock, so the kill
    still lands at exactly the N-th append — but which chunk indices
    committed first is scheduling-dependent. The invariant is unchanged:
    the resumed run must reuse every committed record (whatever order they
    landed in) and reproduce the reference byte-for-byte."""
    summary = crashtest.run_grid(
        20260805, points=4, n_pairs=12, chunk_size=2, record_workers=2
    )
    assert summary["ok"], summary["violations"]
    assert summary["counts"] == {"identical": summary["points"]}


def test_single_boundary_kill_point_detail(tmp_path):
    """One kill point end to end with the internals exposed: the journal
    holds exactly crash_at+1 records after a boundary kill, and the resumed
    run replays every one of them."""
    shape = {
        "pairs": 8, "chunk_size": 2, "receipts": 3, "events": 2,
        "match_rate": 0.3,
    }
    store, pairs, spec = crashtest._build_world(8, 3, 2, 0.3)
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined

    reference = generate_event_proofs_for_range_pipelined(
        store, pairs, spec, chunk_size=2, scan_threads=2, force_pipeline=True
    ).to_json()
    res = crashtest.crash_run(
        reference, shape, crash_at=1, torn=None, workdir=str(tmp_path), tag="t"
    )
    assert res["outcome"] == "identical", res
    assert res["records_after_crash"] == 2
    assert res["chunks_replayed"] == 2
    assert not res["torn_tail"]


def test_single_torn_kill_point_detail(tmp_path):
    """Torn mid-record kill: the partial frame is visible post-mortem as a
    torn tail, then discarded on resume."""
    shape = {
        "pairs": 8, "chunk_size": 2, "receipts": 3, "events": 2,
        "match_rate": 0.3,
    }
    store, pairs, spec = crashtest._build_world(8, 3, 2, 0.3)
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined

    reference = generate_event_proofs_for_range_pipelined(
        store, pairs, spec, chunk_size=2, scan_threads=2, force_pipeline=True
    ).to_json()
    res = crashtest.crash_run(
        reference, shape, crash_at=2, torn=64, workdir=str(tmp_path), tag="t"
    )
    assert res["outcome"] == "identical", res
    assert res["records_after_crash"] == 2  # the torn 3rd record is not counted
    assert res["torn_tail"]
    assert res["chunks_replayed"] == 2


@pytest.mark.parametrize("seed", [20260805])
def test_compaction_crash_grid_resumes_byte_identical(seed):
    """SIGKILL during journal compaction — mid-sidecar-write (torn
    ``.compact`` tmp) and immediately after the atomic swap — must leave a
    parseable journal that resumes to the reference bytes. The original
    journal is untouched until the `os.replace`, so both kill flavors
    recover."""
    summary = crashtest.run_compaction_grid(seed, n_pairs=12, chunk_size=2)
    assert summary["ok"], summary["violations"]
    assert summary["counts"] == {"identical": summary["points"]}
    modes = {m for m, _ in summary["kill_points"]}
    assert modes == {"torn_tmp", "post_swap"}


def test_single_compaction_kill_point_detail(tmp_path):
    """One torn-sidecar kill end to end: the ``.compact`` tmp exists (the
    crash landed mid-snapshot), the real journal still parses, and the
    resume reproduces the reference byte-for-byte."""
    shape = {
        "pairs": 8, "chunk_size": 2, "receipts": 3, "events": 2,
        "match_rate": 0.3,
    }
    store, pairs, spec = crashtest._build_world(8, 3, 2, 0.3)
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined

    reference = generate_event_proofs_for_range_pipelined(
        store, pairs, spec, chunk_size=2, scan_threads=2, force_pipeline=True
    ).to_json()
    res = crashtest.compaction_crash_run(
        reference, shape, "torn_tmp", str(tmp_path), tag="t", torn_bytes=7
    )
    assert res["outcome"] == "identical", res


@pytest.mark.parametrize("seed", [20260807])
def test_registry_crash_grid_extends_same_head(seed):
    """SIGKILL the provenance-registry writer at frame boundaries and torn
    mid-record: reopen must truncate the residue, the hash chain must
    re-verify end to end, the committed prefix must be EXACT (crash_at+1
    records for a boundary kill, crash_at for a torn one), and post-crash
    appends must extend the same head — the pre-resume root is a proven
    consistency prefix of the post-resume root."""
    summary = crashtest.run_registry_grid(seed, points=8, n_records=12)
    assert summary["ok"], summary["violations"]
    assert summary["counts"] == {"identical": summary["points"]}
    torn = [t for _, t in summary["kill_points"] if t is not None]
    assert torn and len(torn) < summary["points"]


def test_single_registry_kill_point_detail(tmp_path):
    """One torn registry kill with internals exposed: residue visible as a
    torn tail post-mortem, exactly crash_at committed records, and the
    resume doubles the chain on the same head."""
    shape = {
        "pairs": 6, "chunk_size": 2, "receipts": 1, "events": 1,
        "match_rate": 0.0, "record_workers": 1,
    }
    res = crashtest.registry_crash_run(
        shape, crash_at=3, torn=13, workdir=str(tmp_path), tag="t"
    )
    assert res["outcome"] == "identical", res
    assert res["records_after_crash"] == 3  # the torn 4th frame is residue
    assert res["torn_tail"]
    assert res["records_after_resume"] == 3 + 6


@pytest.mark.parametrize("seed", [20260807])
def test_sigterm_grid_backfill_and_stream(seed):
    """SIGTERM — the orchestrator-preemption signal — at both surfaces:

    - at an in-flight backfill window commit (later windows un-run), the
      resumed engine must replay every committed window and produce the
      byte-identical bundle, exactly as after a SIGKILL;
    - mid-IPBS-stream, the committed prefix left on the wire must decode
      to a typed `WitnessError` (torn frame / open document), never parse
      as a complete document."""
    summary = crashtest.run_sigterm_grid(seed)
    assert summary["ok"], summary["violations"]
    assert summary["counts"].get("identical", 0) == len(summary["backfill_points"])
    assert summary["counts"].get("typed_tear", 0) == len(summary["stream_points"])
    assert "silent_partial" not in summary["counts"]
