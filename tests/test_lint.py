"""ipclint: each rule family fires on a known-bad fixture, annotations and
suppressions are honored, and — the actual point — the real tree is clean.

The fixture tests pin the *meaning* of each rule with a minimal snippet, so
a future engine change that silently stops detecting (say) unguarded writes
fails here rather than going unnoticed while the tree check keeps passing
vacuously. The tree test is the enforcement: `python -m tools.ipclint
ipc_proofs_tpu tools` exiting 0 is a tier-1 invariant of this repo.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from tools.ipclint import RULES, lint_paths

REPO_ROOT = Path(__file__).resolve().parent.parent


def run_lint(tmp_path: Path, files: "dict[str, str]", check_vocab: bool = False):
    """Write ``files`` (rel path → source) under tmp_path and lint them."""
    for rel, source in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(source))
    run = lint_paths([str(tmp_path)], repo_root=str(tmp_path), check_vocab=check_vocab)
    return [(f.rule, f.line) for f in run.findings]


def rules_of(findings) -> set:
    return {rule for rule, _ in findings}


class TestRaceRules:
    def test_unguarded_write_fires_race_guard(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.hits = 0  # guarded-by: _lock

                def ok(self):
                    with self._lock:
                        self.hits += 1

                def bad(self):
                    self.hits += 1
        '''})
        assert rules_of(findings) == {"race-guard"}

    def test_guarded_access_is_clean(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.hits = 0  # guarded-by: _lock

                def ok(self):
                    with self._lock:
                        self.hits += 1
        '''})
        assert findings == []

    def test_locked_decorator_counts_as_held(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.hits = 0  # guarded-by: _lock

                @locked
                def ok(self):
                    self.hits += 1
        '''})
        assert findings == []

    def test_thread_spawner_with_shared_attr_needs_annotation(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            import threading

            class Spawner:
                def __init__(self):
                    self.total = 0
                    self._t = threading.Thread(target=self._work)
                    self._t.start()

                def _work(self):
                    self.total += 1

                def read(self):
                    return self.total
        '''})
        assert "race-unannotated" in rules_of(findings)


class TestDetRules:
    DET_REL = "ipc_proofs_tpu/core/mod.py"  # inside a proof-path package

    def test_wall_clock_in_det_scope(self, tmp_path):
        findings = run_lint(tmp_path, {self.DET_REL: '''
            import time

            def stamp():
                return time.time()
        '''})
        assert rules_of(findings) == {"det-wallclock"}

    def test_unseeded_random_in_det_scope(self, tmp_path):
        findings = run_lint(tmp_path, {self.DET_REL: '''
            import random

            def pick():
                return random.random()
        '''})
        assert rules_of(findings) == {"det-random"}

    def test_seeded_rng_is_clean(self, tmp_path):
        findings = run_lint(tmp_path, {self.DET_REL: '''
            import random

            def pick():
                return random.Random("seed").random()
        '''})
        assert findings == []

    def test_set_iteration_in_det_scope(self, tmp_path):
        findings = run_lint(tmp_path, {self.DET_REL: '''
            def walk(items):
                for x in set(items):
                    yield x
        '''})
        assert rules_of(findings) == {"det-setiter"}

    def test_float_arithmetic_in_det_scope(self, tmp_path):
        findings = run_lint(tmp_path, {self.DET_REL: '''
            def scale(n):
                return n * 0.5
        '''})
        assert rules_of(findings) == {"det-float"}

    def test_pathlib_join_is_not_float_division(self, tmp_path):
        findings = run_lint(tmp_path, {self.DET_REL: '''
            from pathlib import Path

            def build_dir(root):
                return Path(root) / "backend" / "native"
        '''})
        assert findings == []

    def test_same_code_outside_det_scope_is_clean(self, tmp_path):
        findings = run_lint(tmp_path, {"ipc_proofs_tpu/serve/mod.py": '''
            import time

            def stamp():
                return time.time()
        '''})
        assert findings == []


class TestErrRules:
    def test_bare_except(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            def f():
                try:
                    g()
                except:
                    pass
        '''})
        assert rules_of(findings) == {"err-bare"}

    def test_swallowed_exception(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            def f():
                try:
                    g()
                except Exception:
                    pass
        '''})
        assert rules_of(findings) == {"err-swallow"}

    def test_fail_soft_comment_justifies_swallow(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            def f():
                try:
                    g()
                except Exception:  # fail-soft: diagnostics must never take the app down
                    pass
        '''})
        assert findings == []

    def test_reraise_is_clean(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            def f():
                try:
                    g()
                except Exception as exc:
                    raise RuntimeError("wrapped") from exc
        '''})
        assert findings == []


class TestVocabRules:
    METRICS_REL = "ipc_proofs_tpu/utils/metrics.py"

    def test_unknown_counter_and_dead_entry(self, tmp_path):
        findings = run_lint(tmp_path, {
            self.METRICS_REL: '''
                DEMO_COUNTERS = (
                    "events.seen",
                    "events.never_counted",
                )
            ''',
            "ipc_proofs_tpu/serve/mod.py": '''
                def f(metrics):
                    metrics.count("events.seen")
                    metrics.count("events.with_typo")
            ''',
        }, check_vocab=True)
        assert rules_of(findings) == {"vocab-unknown", "vocab-dead"}

    def test_wildcard_entry_matches_fstring(self, tmp_path):
        findings = run_lint(tmp_path, {
            self.METRICS_REL: '''
                DEMO_COUNTERS = ("serve.accepted.*",)
            ''',
            "ipc_proofs_tpu/serve/mod.py": '''
                def f(metrics, kind):
                    metrics.count(f"serve.accepted.{kind}")
            ''',
        }, check_vocab=True)
        assert findings == []

    def test_concrete_literal_does_not_keep_wildcard_alive(self, tmp_path):
        # a wildcard family whose only "use" is a concrete literal under
        # the prefix is dead: the dynamic call sites it existed for are
        # gone, and the literal belongs in the vocabulary by name
        findings = run_lint(tmp_path, {
            self.METRICS_REL: '''
                DEMO_COUNTERS = ("serve.accepted.*",)
            ''',
            "ipc_proofs_tpu/serve/mod.py": '''
                def f(metrics):
                    metrics.count("serve.accepted.grpc")
            ''',
        }, check_vocab=True)
        assert rules_of(findings) == {"vocab-dead"}


class TestLockOrderRules:
    PAIR_PREAMBLE = '''
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
    '''

    def test_abba_nesting_is_a_cycle(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": self.PAIR_PREAMBLE + '''
            def fwd(self):
                with self._a:
                    with self._b:
                        pass

            def rev(self):
                with self._b:
                    with self._a:
                        pass
        '''})
        assert rules_of(findings) == {"lock-order-cycle"}

    def test_nonreentrant_reentry_is_a_cycle(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        with self._lock:
                            pass
        '''})
        assert rules_of(findings) == {"lock-order-cycle"}

    def test_undeclared_nesting_needs_lock_order_comment(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": self.PAIR_PREAMBLE + '''
            def fwd(self):
                with self._a:
                    with self._b:
                        pass
        '''})
        assert rules_of(findings) == {"lock-order-undeclared"}

    def test_declared_nesting_is_clean(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": self.PAIR_PREAMBLE + '''
            def fwd(self):
                # lock-order: Pair._a < Pair._b
                with self._a:
                    with self._b:
                        pass
        '''})
        assert findings == []

    def test_leaf_wildcard_declaration_covers_all_outers(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": self.PAIR_PREAMBLE + '''
            def fwd(self):
                # lock-order: * < Pair._b
                with self._a:
                    with self._b:
                        pass
        '''})
        assert findings == []

    def test_stale_lock_order_declaration(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            import threading

            # lock-order: Ghost._a < Ghost._b

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
        '''})
        assert rules_of(findings) == {"stale-suppression"}

    def test_interprocedural_edge_through_method_call(self, tmp_path):
        # outer() never lexically nests the two locks — the edge only
        # exists through the call, which is the whole point of the pass
        findings = run_lint(tmp_path, {"mod.py": self.PAIR_PREAMBLE + '''
            def helper(self):
                with self._b:
                    pass

            def outer(self):
                with self._a:
                    self.helper()
        '''})
        assert rules_of(findings) == {"lock-order-undeclared"}

    def test_blocking_call_under_lock(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            import threading
            import time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        time.sleep(1.0)
        '''})
        assert rules_of(findings) == {"lock-held-blocking"}

    def test_blocking_reachable_through_callee(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            import threading
            import time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def helper(self):
                    time.sleep(1.0)

                def outer(self):
                    with self._lock:
                        self.helper()
        '''})
        assert rules_of(findings) == {"lock-held-blocking"}

    def test_bounded_wait_is_not_blocking(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._done = threading.Event()

                def ok(self):
                    with self._lock:
                        self._done.wait(timeout=0.5)
        '''})
        assert findings == []


class TestParseError:
    def test_unparseable_file_is_a_finding(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            def broken(:
                pass
        '''})
        assert rules_of(findings) == {"parse-error"}

    def test_cli_exits_nonzero_and_emits_json(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def broken(:\n    pass\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.ipclint", str(bad),
             "--no-vocab", "--json"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        records = [json.loads(line) for line in proc.stdout.splitlines() if line]
        assert any(r["rule"] == "parse-error" for r in records)
        assert all({"rule", "path", "line", "message"} <= set(r) for r in records)


class TestSuppression:
    def test_disable_comment_suppresses(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            def f():
                try:
                    g()
                except Exception:  # ipclint: disable=err-swallow
                    pass
        '''})
        assert findings == []

    def test_unused_disable_is_stale(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            def f():  # ipclint: disable=err-swallow
                return 1
        '''})
        assert rules_of(findings) == {"stale-suppression"}

    def test_unknown_rule_in_disable_is_stale(self, tmp_path):
        findings = run_lint(tmp_path, {"mod.py": '''
            def f():
                try:
                    g()
                except Exception:  # ipclint: disable=no-such-rule
                    pass
        '''})
        assert "stale-suppression" in rules_of(findings)


class TestRealTree:
    def test_repo_is_lint_clean(self):
        """The enforcement test: the shipped tree has zero findings."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.ipclint", "ipc_proofs_tpu", "tools"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, f"ipclint found violations:\n{proc.stdout}"

    def test_check_all_gate_passes(self):
        """The chained gate (ipclint → bench schema → sanitizer probe)."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.check_all"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=600,
        )
        assert proc.returncode == 0, f"{proc.stdout}\n{proc.stderr}"

    def test_check_all_lockdep_gate_passes(self):
        """The dynamic gate: lock-heavy tier-1 files under IPC_LOCKDEP=1
        observe zero inversions (the runtime counterpart of the clean
        static tree above)."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.check_all", "--lockdep"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=900,
        )
        assert proc.returncode == 0, f"{proc.stdout}\n{proc.stderr}"

    def test_rule_registry_is_stable(self):
        # every rule the fixtures above exercise must stay registered —
        # removing one from RULES would turn its disables into stale noise
        assert {
            "race-guard", "race-unannotated", "det-wallclock", "det-random",
            "det-setiter", "det-float", "err-bare", "err-swallow",
            "vocab-unknown", "vocab-dead", "lock-order-cycle",
            "lock-held-blocking", "lock-order-undeclared",
            "stale-suppression", "parse-error",
        } <= set(RULES)


class TestSanitizerHarness:
    def test_probe_reports_availability(self):
        from tools.build_native_san import probe_toolchain

        ok, detail = probe_toolchain()
        assert isinstance(ok, bool)
        assert detail  # libasan preload string, or a human-readable reason
        if not ok:
            pytest.skip(f"sanitizer toolchain unavailable: {detail}")
