"""Tiered witness-block store tests: segment framing round-trips, the
corruption grid (CRC flips in every header field, torn tails, forged
frames with recomputed CRCs), index rebuild on reopen, byte-capped LRU
eviction, tier on/off/cold/warm bundle bit-identity with a zero-RPC warm
run, and chain-follower prefetch determinism — including under the
seeded fault harness. All hermetic and tier-1."""

import base64
import builtins
import os
import random
import zlib

import pytest

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.jobs.journal import FRAME_HEADER
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_chunked
from ipc_proofs_tpu.store.faults import FaultPlan, FaultySession, LocalLotusSession
from ipc_proofs_tpu.store.rpc import LotusClient, RpcBlockstore
from ipc_proofs_tpu.storex import (
    SEGMENT_MAGIC,
    ChainFollower,
    SegmentStore,
    SegmentStoreError,
    TieredBlockstore,
)
from ipc_proofs_tpu.utils.metrics import Metrics

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001


def _block(i: int) -> "tuple[CID, bytes]":
    data = (b"block-%04d-" % i) * (i + 2)
    return CID.hash_of(data), data


def _scan_frames(path: str) -> "list[tuple[int, int]]":
    """(offset, frame_len) of every frame in a segment file, via the
    public framing contract (shared FRAME_HEADER struct)."""
    with open(path, "rb") as fh:
        data = fh.read()
    frames = []
    off = 0
    while off + FRAME_HEADER.size <= len(data):
        magic, length, _crc = FRAME_HEADER.unpack_from(data, off)
        assert magic == SEGMENT_MAGIC
        frames.append((off, FRAME_HEADER.size + length))
        off += FRAME_HEADER.size + length
    assert off == len(data)
    return frames


def _seg_paths(root: str) -> "list[str]":
    return sorted(
        os.path.join(root, n) for n in os.listdir(root) if n.endswith(".blk")
    )


def _flip(path: str, offset: int) -> None:
    with open(path, "r+b") as fh:
        fh.seek(offset)
        b = fh.read(1)
        fh.seek(offset)
        fh.write(bytes([b[0] ^ 0x40]))


class TestSegmentStore:
    def test_round_trip_and_stats(self, tmp_path):
        m = Metrics()
        store = SegmentStore(str(tmp_path), metrics=m)
        blocks = [_block(i) for i in range(8)]
        for cid, data in blocks:
            assert store.put(cid, data) is True
        for cid, data in blocks:
            assert store.contains(cid)
            assert store.get(cid) == data
        assert len(store) == 8
        stats = store.stats()
        assert stats["entries"] == 8
        assert stats["bytes"] == os.path.getsize(_seg_paths(str(tmp_path))[0])
        assert stats["segments"] == 1
        assert not stats["degraded"]
        counters = m.snapshot()["counters"]
        assert counters["storex.disk_hits"] == 8
        assert "storex.disk_misses" not in counters
        store.close()

    def test_duplicate_put_is_noop(self, tmp_path):
        store = SegmentStore(str(tmp_path))
        cid, data = _block(1)
        assert store.put(cid, data) is True
        size = store.stats()["bytes"]
        assert store.put(cid, data) is True
        assert store.stats()["bytes"] == size
        assert len(store) == 1
        store.close()

    def test_miss_counts(self, tmp_path):
        m = Metrics()
        store = SegmentStore(str(tmp_path), metrics=m)
        cid, _ = _block(99)
        assert store.get(cid) is None
        assert m.snapshot()["counters"]["storex.disk_misses"] == 1
        store.close()

    def test_reopen_rebuilds_index(self, tmp_path):
        blocks = [_block(i) for i in range(6)]
        with SegmentStore(str(tmp_path)) as store:
            for cid, data in blocks:
                store.put(cid, data)
        reopened = SegmentStore(str(tmp_path))
        assert len(reopened) == 6
        for cid, data in blocks:
            assert reopened.get(cid) == data
        reopened.close()

    def test_typed_errors(self, tmp_path):
        with pytest.raises(SegmentStoreError):
            SegmentStore(str(tmp_path), cap_bytes=0)
        (tmp_path / "seg-bogus.blk").write_bytes(b"")
        with pytest.raises(SegmentStoreError):
            SegmentStore(str(tmp_path))


class TestCorruptionGrid:
    """Byte-level damage at every structurally distinct frame position.
    The contract under test: corruption is an *availability* event — a
    typed truncation on reopen or a verified miss on read — never bytes
    served that don't match the CID (silent divergence)."""

    # (frame index, byte offset within the frame): magic, len, crc, payload
    POINTS = [
        (k, field_off)
        for k in (0, 1, 2)
        for field_off in (0, 4, 8, FRAME_HEADER.size + 3)
    ]

    def _store_with_blocks(self, root, n=3):
        blocks = [_block(i) for i in range(n)]
        with SegmentStore(root) as store:
            for cid, data in blocks:
                store.put(cid, data)
        return blocks

    @pytest.mark.parametrize("frame_idx,field_off", POINTS)
    def test_reopen_truncates_at_flip(self, tmp_path, frame_idx, field_off):
        blocks = self._store_with_blocks(str(tmp_path))
        path = _seg_paths(str(tmp_path))[0]
        frames = _scan_frames(path)
        off, _ = frames[frame_idx]
        _flip(path, off + field_off)
        store = SegmentStore(str(tmp_path))
        # everything before the damaged frame survives; the damaged frame
        # and everything after it is truncated away (refetch on demand)
        for i, (cid, data) in enumerate(blocks):
            if i < frame_idx:
                assert store.get(cid) == data
            else:
                assert store.get(cid) is None
        assert os.path.getsize(path) == off
        store.close()

    @pytest.mark.parametrize("extra", [1, 7, FRAME_HEADER.size + 1])
    def test_reopen_truncates_torn_tail(self, tmp_path, extra):
        blocks = self._store_with_blocks(str(tmp_path))
        path = _seg_paths(str(tmp_path))[0]
        frames = _scan_frames(path)
        last_off, _ = frames[-1]
        with open(path, "r+b") as fh:
            fh.truncate(last_off + extra)
        store = SegmentStore(str(tmp_path))
        for cid, data in blocks[:-1]:
            assert store.get(cid) == data
        assert store.get(blocks[-1][0]) is None
        assert os.path.getsize(path) == last_off
        store.close()

    def test_inplace_flip_is_verified_miss(self, tmp_path):
        m = Metrics()
        store = SegmentStore(str(tmp_path), metrics=m)
        blocks = [_block(i) for i in range(3)]
        for cid, data in blocks:
            store.put(cid, data)
        path = _seg_paths(str(tmp_path))[0]
        off, frame_len = _scan_frames(path)[1]
        _flip(path, off + frame_len - 1)  # last payload byte of block 1
        assert store.get(blocks[1][0]) is None  # CRC catches it
        counters = m.snapshot()["counters"]
        assert counters["storex.integrity_evictions"] == 1
        assert not store.contains(blocks[1][0])  # entry evicted
        assert store.get(blocks[0][0]) == blocks[0][1]  # neighbours intact
        assert store.get(blocks[2][0]) == blocks[2][1]
        store.close()

    def test_forged_frame_caught_by_multihash(self, tmp_path):
        """A frame rewritten with a *valid* CRC but wrong block bytes must
        be caught by the multihash re-verification layer — the CRC only
        proves the disk returned what was written, not that what was
        written is the block the CID names."""
        m = Metrics()
        store = SegmentStore(str(tmp_path), metrics=m)
        cid, data = _block(0)
        store.put(cid, data)
        path = _seg_paths(str(tmp_path))[0]
        off, frame_len = _scan_frames(path)[0]
        with open(path, "r+b") as fh:
            frame = fh.read(frame_len)
            payload = bytearray(frame[FRAME_HEADER.size :])
            payload[-1] ^= 0xFF  # forge the block bytes…
            forged = FRAME_HEADER.pack(
                SEGMENT_MAGIC, len(payload), zlib.crc32(bytes(payload))
            ) + bytes(payload)  # …and recompute a valid CRC
            fh.seek(off)
            fh.write(forged)
        assert store.get(cid) is None
        assert m.snapshot()["counters"]["storex.integrity_evictions"] == 1
        store.close()

    def test_forged_frame_repaired_by_refetch(self, tmp_path):
        """Through the tiered store, the forged frame reads as a miss and
        the refetched clean bytes re-spill: availability, not correctness."""

        class _Inner:
            def __init__(self, mapping):
                self.mapping = mapping
                self.gets = 0

            def get(self, cid):
                self.gets += 1
                return self.mapping.get(cid)

            def has(self, cid):
                return cid in self.mapping

            def put_keyed(self, cid, data):
                self.mapping[cid] = data

        m = Metrics()
        cid, data = _block(0)
        disk = SegmentStore(str(tmp_path), metrics=m)
        disk.put(cid, data)
        path = _seg_paths(str(tmp_path))[0]
        off, frame_len = _scan_frames(path)[0]
        _flip(path, off + frame_len - 1)
        inner = _Inner({cid: data})
        tiered = TieredBlockstore(inner, disk, metrics=m)
        assert tiered.get(cid) == data  # correct bytes despite disk damage
        assert inner.gets == 1  # repaired via refetch…
        assert m.snapshot()["counters"]["storex.integrity_evictions"] == 1
        assert tiered.get(cid) == data
        assert inner.gets == 1  # …and served from the local tiers after
        disk.close()


class TestEviction:
    def test_lru_eviction_respects_cap(self, tmp_path):
        m = Metrics()
        # segment_max_bytes=1 → every put seals its own segment, so the
        # LRU operates at single-block granularity here
        store = SegmentStore(
            str(tmp_path), cap_bytes=2048, segment_max_bytes=1, metrics=m
        )
        blocks = [_block(i) for i in range(20)]
        for cid, data in blocks:
            store.put(cid, data)
        stats = store.stats()
        assert stats["bytes"] <= 2048
        assert 0 < stats["entries"] < 20
        assert m.snapshot()["counters"]["storex.evictions"] == 20 - stats["entries"]
        assert m.snapshot()["gauges"]["storex.disk_bytes"] == stats["bytes"]
        # LRU: the oldest blocks are gone, the newest survive
        assert not store.contains(blocks[0][0])
        assert store.get(blocks[-1][0]) == blocks[-1][1]
        # evicted segment files are actually deleted from disk
        assert len(_seg_paths(str(tmp_path))) == stats["segments"]
        store.close()

    def test_evicted_blocks_refetch_through_tiers(self, tmp_path):
        bs, pairs, _ = build_range_world(
            2, 4, 2, 0.5, signature=SIG, topic1=SUBNET, base_height=500
        )
        m = Metrics()
        disk = SegmentStore(
            str(tmp_path), cap_bytes=4096, segment_max_bytes=1, metrics=m
        )
        tiered = TieredBlockstore(bs, disk, cache={}, metrics=m)
        cids = [c for pair in pairs for c in pair.parent.cids + pair.child.cids]
        for cid in cids:
            assert tiered.get(cid) == bs.get(cid)
        # a fresh wrapper (cold memory tier) still returns correct bytes
        # for every CID, evicted or not
        tiered2 = TieredBlockstore(bs, disk, cache={}, metrics=m)
        for cid in cids:
            assert tiered2.get(cid) == bs.get(cid)
        disk.close()


class TestDegrade:
    def test_write_failure_degrades_to_read_only(self, tmp_path, monkeypatch):
        m = Metrics()
        store = SegmentStore(str(tmp_path), metrics=m)
        cid0, data0 = _block(0)
        store.put(cid0, data0)
        store.close()  # seal the active segment so the next put reopens
        store = SegmentStore(str(tmp_path), metrics=m)
        real_open = builtins.open

        def deny_append(path, mode="r", *args, **kwargs):
            if str(path).startswith(str(tmp_path)) and "a" in mode:
                raise OSError(28, "No space left on device")
            return real_open(path, mode, *args, **kwargs)

        monkeypatch.setattr(builtins, "open", deny_append)
        cid1, data1 = _block(1)
        assert store.put(cid1, data1) is False
        assert store.degraded
        assert m.snapshot()["counters"]["storex.write_failures"] == 1
        # degraded means read-only, not dead: existing blocks still serve
        assert store.get(cid0) == data0
        # further puts fail fast without re-counting
        assert store.put(cid1, data1) is False
        assert m.snapshot()["counters"]["storex.write_failures"] == 1
        store.close()


@pytest.fixture(scope="module")
def world():
    return build_range_world(
        3, 6, 3, 0.3, signature=SIG, topic1=SUBNET, actor_id=ACTOR,
        base_height=41_000,
    )


def _spec():
    return EventProofSpec(
        event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR
    )


def _rpc_client(bs, metrics):
    return LotusClient(
        "http://test-storex", session=LocalLotusSession(bs), metrics=metrics
    )


def _bundle(store, pairs):
    return generate_event_proofs_for_range_chunked(
        store, pairs, _spec(), chunk_size=2
    ).to_json()


class TestTierBitIdentity:
    """The ISSUE's acceptance criterion: identical bundles with the disk
    tier off / on / cold / warm, and the disk-warm repeat issues ZERO RPC
    block fetches (``rpc.calls`` delta = 0)."""

    def test_off_on_cold_warm_identical_and_warm_is_rpc_free(self, tmp_path, world):
        bs, pairs, n_matching = world
        assert n_matching > 0
        baseline = _bundle(bs, pairs)  # tier off, direct memory store

        # tier off, over RPC (cold): establishes the RPC call count
        m_cold = Metrics()
        cold = _bundle(RpcBlockstore(_rpc_client(bs, m_cold)), pairs)
        rpc_cold = m_cold.snapshot()["counters"]["rpc.calls"]
        assert cold == baseline
        assert rpc_cold > 0

        # tier on, cold disk: populates the segment files
        store_dir = str(tmp_path / "store")
        m_pop = Metrics()
        disk = SegmentStore(store_dir, metrics=m_pop)
        tiered = TieredBlockstore(
            RpcBlockstore(_rpc_client(bs, m_pop)), disk, metrics=m_pop
        )
        assert _bundle(tiered, pairs) == baseline
        disk.close()

        # tier on, warm disk, simulated restart: fresh index rebuild,
        # empty memory cache, fresh client — and not one RPC call
        m_warm = Metrics()
        disk = SegmentStore(store_dir, metrics=m_warm)
        tiered = TieredBlockstore(
            RpcBlockstore(_rpc_client(bs, m_warm)), disk, metrics=m_warm
        )
        assert _bundle(tiered, pairs) == baseline
        counters = m_warm.snapshot()["counters"]
        assert counters.get("rpc.calls", 0) == 0
        assert counters["storex.disk_hits"] > 0
        disk.close()


def _tipset_api_json(tipset):
    return {
        "Cids": [{"/": str(c)} for c in tipset.cids],
        "Height": tipset.height,
        "Blocks": [
            {
                "Parents": [{"/": str(p)} for p in header.parents],
                "Height": header.height,
                "ParentStateRoot": {"/": str(header.parent_state_root)},
                "ParentMessageReceipts": {"/": str(header.parent_message_receipts)},
                "Messages": {"/": str(header.messages)},
                "Timestamp": header.timestamp,
            }
            for header in tipset.blocks
        ],
    }


def _fresh_tiered(bs, root, metrics):
    disk = SegmentStore(str(root), metrics=metrics)
    return (
        TieredBlockstore(
            RpcBlockstore(_rpc_client(bs, metrics)), disk, metrics=metrics
        ),
        disk,
    )


class TestChainFollower:
    def test_prefetch_is_deterministic(self, tmp_path, world):
        """Two fresh stores prefetched from the same chain end up with
        byte-identical segment files — write order is pinned (spine order
        + sorted-key link order), not incidental."""
        bs, pairs, _ = world
        results = []
        for tag in ("a", "b"):
            m = Metrics()
            tiered, disk = _fresh_tiered(bs, tmp_path / tag, m)
            follower = ChainFollower(_rpc_client(bs, m), tiered, metrics=m)
            for pair in pairs:
                follower.prefetch_tipset(pair.parent)
                follower.prefetch_tipset(pair.child)
            disk.close()
            counters = m.snapshot()["counters"]
            seg_bytes = b"".join(
                open(p, "rb").read() for p in _seg_paths(str(tmp_path / tag))
            )
            results.append((counters["follow.blocks_prefetched"], seg_bytes))
        assert results[0] == results[1]
        assert results[0][0] > 0

    def test_prefetched_blocks_match_the_chain(self, tmp_path, world):
        bs, pairs, _ = world
        m = Metrics()
        tiered, disk = _fresh_tiered(bs, tmp_path / "f", m)
        follower = ChainFollower(_rpc_client(bs, m), tiered, metrics=m)
        follower.prefetch_tipset(pairs[0].parent)
        for header in pairs[0].parent.blocks:
            for cid in (
                header.parent_state_root,
                header.parent_message_receipts,
                header.messages,
            ):
                assert tiered.has_local(cid)
                assert tiered.get(cid) == bs.get(cid)
        for cid in pairs[0].parent.cids:
            assert tiered.get(cid) == bs.get(cid)
        disk.close()

    def test_poll_once_advances_and_is_idempotent(self, tmp_path, world):
        bs, pairs, _ = world
        child = pairs[0].child
        responses = {
            "Filecoin.ChainHead": {
                "Height": child.height + 1,
                "Cids": [{"/": str(c)} for c in child.cids],
            },
            "Filecoin.ChainGetTipSetByHeight": _tipset_api_json(child),
        }
        m = Metrics()
        client = LotusClient(
            "http://test-follow",
            session=LocalLotusSession(bs, responses=responses),
            metrics=m,
        )
        tiered, disk = _fresh_tiered(bs, tmp_path / "p", m)
        follower = ChainFollower(client, tiered, metrics=m, lag=1)
        assert follower.poll_once() == 1
        counters = m.snapshot()["counters"]
        assert counters["follow.tipsets"] == 1
        assert counters["follow.blocks_prefetched"] > 0
        assert "follow.errors" not in counters
        # same head again: nothing newly finalized, nothing re-fetched
        before = m.snapshot()["counters"]["follow.blocks_prefetched"]
        assert follower.poll_once() == 0
        assert m.snapshot()["counters"]["follow.blocks_prefetched"] == before
        disk.close()

    def test_head_poll_failure_is_fail_soft(self, tmp_path, world):
        bs, _, _ = world

        class _DeadClient:
            def request(self, method, params):
                raise ConnectionError("node is down")

        m = Metrics()
        tiered, disk = _fresh_tiered(bs, tmp_path / "dead", m)
        follower = ChainFollower(_DeadClient(), tiered, metrics=m)
        assert follower.poll_once() == 0
        assert m.snapshot()["counters"]["follow.errors"] == 1
        disk.close()

    def test_lying_endpoint_cannot_poison_the_disk_tier(self, tmp_path, world):
        """Every ChainReadObj response is bit-flipped: the follower must
        verify-and-skip each block (counted), storing nothing."""
        bs, pairs, _ = world

        class _LyingSession:
            def __init__(self, inner):
                self._inner = inner

            def post(self, url, data=None, headers=None, timeout=None):
                resp = self._inner.post(
                    url, data=data, headers=headers, timeout=timeout
                )
                body = resp.json()
                if isinstance(body.get("result"), str):
                    raw = bytearray(base64.b64decode(body["result"]))
                    raw[0] ^= 0x01
                    body["result"] = base64.b64encode(bytes(raw)).decode()
                return type(resp)(body)

        m = Metrics()
        disk = SegmentStore(str(tmp_path / "lie"), metrics=m)
        client = LotusClient(
            "http://test-liar",
            session=_LyingSession(LocalLotusSession(bs)),
            metrics=m,
        )
        tiered = TieredBlockstore(RpcBlockstore(client), disk, metrics=m)
        follower = ChainFollower(client, tiered, metrics=m)
        follower.prefetch_tipset(pairs[0].parent)
        counters = m.snapshot()["counters"]
        assert counters["follow.errors"] > 0
        assert counters.get("follow.blocks_prefetched", 0) == 0
        assert disk.stats()["entries"] == 0
        disk.close()

    def test_prefetch_deterministic_under_seeded_faults(self, tmp_path, world):
        """Seeded fault harness: transient RPC faults (errors, timeouts,
        rate limits, bit flips) injected on every wire call. Two runs with
        the same seed produce identical segment files and counters, and
        nothing stored ever diverges from the chain."""
        bs, pairs, _ = world

        def _run(tag, seed):
            m = Metrics()
            plan = FaultPlan(seed=seed, fault_rate=0.25)
            session = FaultySession(
                LocalLotusSession(bs), plan, sleep=lambda s: None
            )
            client = LotusClient(
                "http://test-faulty",
                session=session,
                metrics=m,
                max_retries=8,
                backoff_base_s=0.0,
                backoff_max_s=0.0,
                rng=random.Random(seed),
            )
            disk = SegmentStore(str(tmp_path / tag), metrics=m)
            tiered = TieredBlockstore(RpcBlockstore(client), disk, metrics=m)
            follower = ChainFollower(client, tiered, metrics=m)
            for pair in pairs:
                follower.prefetch_tipset(pair.parent)
            disk.close()
            seg_bytes = b"".join(
                open(p, "rb").read() for p in _seg_paths(str(tmp_path / tag))
            )
            counters = m.snapshot()["counters"]
            # poisoning check: everything that landed on disk re-verifies
            check = SegmentStore(str(tmp_path / tag))
            for pair in pairs:
                for header in pair.parent.blocks:
                    got = check.get(header.parent_state_root)
                    if got is not None:
                        assert got == bs.get(header.parent_state_root)
            check.close()
            return seg_bytes, counters.get("follow.blocks_prefetched", 0)

        assert _run("s1", 1234) == _run("s2", 1234)
