"""Seeded randomized differential fuzz: batch ↔ scalar STORAGE verification.

The event-side fuzz (test_batch_verifier_fuzz.py) found two real soundness
divergences between the native batch walkers and the scalar replay; this
sweep applies the same method to the storage pair — random claim-field
garbage and witness damage, asserting `verify_storage_proofs_batch` agrees
with the scalar `verify_storage_proof` loop on every verdict vector and on
the abort family when both raise.
"""

import dataclasses
import random

import pytest

from ipc_proofs_tpu.core.cid import CID, RAW
from ipc_proofs_tpu.proofs.bundle import ProofBlock
from ipc_proofs_tpu.proofs.storage_verifier import (
    verify_storage_proof,
    verify_storage_proofs_batch,
)
from ipc_proofs_tpu.proofs.witness import load_witness_store

from tests.test_storage_batch_verifier import _native_or_skip, make_storage_bundle

ACCEPT = lambda *_: True


def _outcome(proofs, blocks, batch):
    """("ok", verdicts) or ("raise", family, type, message) — same contract
    as the event fuzz's `_outcome` (see its docstring for why messages and
    exact ValueError subclasses are not compared)."""
    try:
        store = load_witness_store(blocks, verify_cids=False)
        if batch:
            out = verify_storage_proofs_batch(store, proofs, ACCEPT)
            assert out is not None  # native availability gated by the skip
        else:
            out = [verify_storage_proof(p, blocks, ACCEPT, store=store) for p in proofs]
        return ("ok", out)
    except Exception as exc:  # noqa: BLE001 — parity includes the exception
        family = (
            "KeyError"
            if isinstance(exc, KeyError)
            else "ValueError"
            if isinstance(exc, ValueError)
            else type(exc).__name__
        )
        return ("raise", family, type(exc).__name__, str(exc))


def _comparable(outcome):
    if outcome[0] == "ok":
        return outcome[:2]
    family = outcome[1]
    return ("raise", "abort" if family in ("KeyError", "ValueError") else family)


def _mutate_proof(rng: random.Random, proof):
    choice = rng.randrange(9)
    if choice == 0:
        return dataclasses.replace(
            proof, child_epoch=proof.child_epoch + rng.choice([-1, 1, 999])
        )
    if choice == 1:
        return dataclasses.replace(
            proof,
            child_block_cid=rng.choice(
                ["", "b", "junk", str(CID.hash_of(rng.randbytes(4)))]
            ),
        )
    if choice == 2:
        return dataclasses.replace(
            proof,
            parent_state_root=rng.choice(
                [str(CID.hash_of(rng.randbytes(4))), proof.parent_state_root.upper()]
            ),
        )
    if choice == 3:
        return dataclasses.replace(
            proof, actor_id=rng.choice([-1, 0, proof.actor_id + 1, 2**63])
        )
    if choice == 4:
        return dataclasses.replace(
            proof, actor_state_cid=str(CID.hash_of(rng.randbytes(4), codec=RAW))
        )
    if choice == 5:
        return dataclasses.replace(
            proof, storage_root=rng.choice(["", str(CID.hash_of(rng.randbytes(4)))])
        )
    if choice == 6:
        slot = proof.slot
        return dataclasses.replace(
            proof,
            slot=rng.choice(
                [slot[:-1], slot + "0", slot.removeprefix("0x"), "0x" + "zz" * 32,
                 slot.upper().replace("0X", "0x")]
            ),
        )
    if choice == 7:
        value = proof.value
        return dataclasses.replace(
            proof,
            value=rng.choice(
                ["0x" + "ff" * 32, value.upper().replace("0X", "0x"),
                 value[:-2], value[2:], value[:6] + " " + value[6:]]
            ),
        )
    return dataclasses.replace(
        proof, slot=proof.value, value=proof.slot  # cross-wire the hex fields
    )


def _mutate(rng: random.Random, proofs, blocks):
    kind = rng.randrange(8)
    if kind == 0 and blocks:
        drop = rng.randrange(len(blocks))
        return proofs, [b for i, b in enumerate(blocks) if i != drop]
    if kind == 1 and blocks:
        i = rng.randrange(len(blocks))
        data = bytearray(blocks[i].data)
        if data:
            data[rng.randrange(len(data))] ^= 1 << rng.randrange(8)
        blocks = list(blocks)
        blocks[i] = ProofBlock(cid=blocks[i].cid, data=bytes(data))
        return proofs, blocks
    if kind == 2 and blocks:  # trailing garbage after a block
        i = rng.randrange(len(blocks))
        blocks = list(blocks)
        blocks[i] = ProofBlock(cid=blocks[i].cid, data=blocks[i].data + b"\x00")
        return proofs, blocks
    if kind == 3 and len(proofs) >= 2:  # cross-wire two proofs' roots
        i, j = rng.sample(range(len(proofs)), 2)
        proofs = list(proofs)
        proofs[i] = dataclasses.replace(
            proofs[i],
            actor_state_cid=proofs[j].actor_state_cid,
            storage_root=proofs[j].storage_root,
        )
        return proofs, blocks
    if kind == 4:
        proofs = list(proofs)
        rng.shuffle(proofs)
        return proofs, blocks
    proofs = list(proofs)
    for _ in range(rng.randrange(1, 4)):
        i = rng.randrange(len(proofs))
        proofs[i] = _mutate_proof(rng, proofs[i])
    return proofs, blocks


class TestMalformedTreeNodes:
    """Crafted tree-node corruption pinning Python↔C reader acceptance
    parity (each was a real divergence found by review/fuzz: IndexError
    leaks, a lax C bucket rule, an unvalidated inline root, and
    bitmap-length rules differing between the readers)."""

    def _store_with(self, obj):
        from ipc_proofs_tpu.core.cid import CID as _CID
        from ipc_proofs_tpu.core.dagcbor import encode
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

        bs = MemoryBlockstore()
        raw = encode(obj)
        cid = _CID.hash_of(raw)
        bs.put_keyed(cid, raw)
        return bs, cid

    def test_hamt_bucket_arity_rejected_both_readers(self):
        from ipc_proofs_tpu.ipld.hamt import HAMT, _bitfield_encode, _hash_bits
        from ipc_proofs_tpu.ipld.hamt import hamt_get_batch

        _native_or_skip()
        # the ONE set bit sits on the lookup key's hash path, so both
        # walks reach the bucket — whose entry has THREE fields. The
        # reference's KeyValuePair is a serde 2-tuple, so both readers
        # must reject (the C walker used to accept >= 2)
        idx = _hash_bits(b"k", 0, 5)
        bs, cid = self._store_with(
            [_bitfield_encode(1 << idx), [[[b"k", b"VAL", b"x"]]]]
        )
        with pytest.raises(ValueError):
            HAMT(bs, cid, 5).get(b"k")
        with pytest.raises(ValueError):
            hamt_get_batch(bs, [cid], [0], [b"k"], validate_blocks=True)

    def test_hamt_bitmap_exceeding_pointers_rejected(self):
        from ipc_proofs_tpu.ipld.hamt import HAMT

        bs, cid = self._store_with([b"\xff\xff\xff\xff", [[[b"k", b"VAL"]]]])
        with pytest.raises(ValueError):
            HAMT(bs, cid, 5).get(b"zz")  # pos beyond the pointer list

    def test_amt_non_list_root_node_rejected(self):
        from ipc_proofs_tpu.ipld.amt import AMT

        bs, cid = self._store_with([5, 0, 0, 7])
        with pytest.raises(ValueError):
            AMT.load(bs, cid)

    def test_amt_short_bitmap_rejected(self):
        from ipc_proofs_tpu.ipld.amt import AMT

        # bit_width 5 ⇒ 32 slots ⇒ 4 bitmap bytes required; 1 supplied.
        # The native walker has always rejected this shape; the Python
        # reader used to read the missing bytes as zero and verify it.
        bs, cid = self._store_with([5, 0, 1, [b"\x01", [], [b"hello"]]])
        with pytest.raises(ValueError):
            AMT.load(bs, cid).get(0)

    def test_amt_bitmap_exceeding_values_rejected(self):
        from ipc_proofs_tpu.ipld.amt import AMT

        # v0 root (bit_width 3 ⇒ 1 bitmap byte): two bits set, one value
        bs, cid = self._store_with([0, 2, [b"\x03", [], [b"only-one"]]])
        with pytest.raises(ValueError):
            AMT.load(bs, cid, expected_version=0).get(1)

    def test_amt_padded_leaf_values_rejected(self):
        from ipc_proofs_tpu.ipld.amt import AMT

        # one bit set, TWO values: the native full walk requires the leaf
        # value count to EQUAL the bitmap popcount ('AMT leaf value count
        # mismatch'); the Python reader must reject identically — it used
        # to accept the padded node, verifying what the batch walk rejects
        bs, cid = self._store_with([0, 1, [b"\x01", [], [b"v", b"extra"]]])
        amt = AMT.load(bs, cid, expected_version=0)
        with pytest.raises(ValueError):
            amt.get(0)
        with pytest.raises(ValueError):
            list(amt.items())


def _run_differential(rng, seed, base_proofs, base_blocks, rounds):
    """Shared mutate-and-compare loop for the fixed-shape and shape-varied
    differentials: mutate (occasionally twice), run both verify paths,
    assert outcome parity. Returns (agree_raise, agree_ok) tallies."""
    agree_raise = agree_ok = 0
    for _ in range(rounds):
        proofs, blocks = _mutate(rng, base_proofs, base_blocks)
        if rng.random() < 0.3:
            proofs, blocks = _mutate(rng, proofs, blocks)
        scalar = _outcome(proofs, blocks, batch=False)
        batch = _outcome(proofs, blocks, batch=True)
        assert _comparable(scalar) == _comparable(batch), (
            f"divergence under seed={seed}: scalar={scalar!r} batch={batch!r}"
        )
        if scalar[0] == "raise":
            agree_raise += 1
        else:
            agree_ok += 1
    return agree_raise, agree_ok


@pytest.mark.parametrize("seed", [0x5A5A, 88230])
def test_shape_varied_storage_mutation_differential(seed):
    """Same mutation machinery over base worlds of VARIED shape (storage
    encoding mix, slot count) — in-suite slice of the round-5 shape-varied
    soak (2,000 worlds x 120 mutants, clean)."""
    _native_or_skip()
    rng = random.Random(seed)
    encs = ["direct", "wrapper_tuple", "wrapper_map", "inline"]
    agree_raise = agree_ok = 0
    for _ in range(3):
        base = make_storage_bundle(
            encodings=tuple(rng.choice(encs) for _ in range(rng.randrange(1, 5))),
            n_slots=rng.choice([1, 2, 3, 5]),
        )
        r, o = _run_differential(rng, seed, base.storage_proofs, base.blocks, 30)
        agree_raise += r
        agree_ok += o
    assert agree_raise and agree_ok  # the sweep exercised both regimes


@pytest.mark.parametrize("seed", [7, 0xA17, 424242, 102662185])
def test_randomized_storage_mutation_differential(seed):
    # 102662185: round-5 soak find — a SmallMap mutant whose value decoded
    # as CBOR text leaked a TypeError out of left_pad_32 on the scalar
    # path; _small_map_shape now requires bytes values (the arm falls
    # through, serde-parity) and the HAMT arms reject non-bytes values.
    _native_or_skip()
    rng = random.Random(seed)
    base = make_storage_bundle(encodings=("direct", "inline", "wrapper_tuple"))
    agree_raise, agree_ok = _run_differential(
        rng, seed, base.storage_proofs, base.blocks, 120
    )
    assert agree_raise and agree_ok  # both regimes exercised
