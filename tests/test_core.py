"""Golden-vector and round-trip tests for the core IPLD byte layer."""

import pytest

from ipc_proofs_tpu.core.bigint import bigint_from_bytes, bigint_to_bytes
from ipc_proofs_tpu.core.cid import BLAKE2B_256, CID, DAG_CBOR, RAW
from ipc_proofs_tpu.core.dagcbor import decode, encode
from ipc_proofs_tpu.core.hashes import blake2b_256, keccak256
from ipc_proofs_tpu.core.varint import decode_uvarint, encode_uvarint


class TestVarint:
    @pytest.mark.parametrize(
        "value,expected",
        [
            (0, b"\x00"),
            (1, b"\x01"),
            (127, b"\x7f"),
            (128, b"\x80\x01"),
            (300, b"\xac\x02"),
            (0xB220, b"\xa0\xe4\x02"),  # blake2b-256 multihash code
        ],
    )
    def test_roundtrip(self, value, expected):
        assert encode_uvarint(value) == expected
        decoded, offset = decode_uvarint(expected)
        assert decoded == value
        assert offset == len(expected)

    def test_negative_rejected(self):
        with pytest.raises(ValueError):
            encode_uvarint(-1)


class TestKeccak256:
    def test_empty(self):
        # Universal Keccak-256 test vector
        assert (
            keccak256(b"").hex()
            == "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
        )

    def test_abc(self):
        assert (
            keccak256(b"abc").hex()
            == "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"
        )

    def test_transfer_topic(self):
        # The canonical ERC-20 Transfer event topic0
        assert (
            keccak256(b"Transfer(address,address,uint256)").hex()
            == "ddf252ad1be2c89b69c2b068fc378daa952ba7f163c4a11628f55a4df523b3ef"
        )

    def test_multiblock(self):
        # > 136-byte (rate) input exercises the multi-block sponge path;
        # check self-consistency against incremental property: determinism
        data = bytes(range(256)) * 3
        assert keccak256(data) == keccak256(bytes(data))
        assert len(keccak256(data)) == 32

    def test_rate_boundary(self):
        for n in (135, 136, 137, 271, 272, 273):
            assert len(keccak256(b"\xaa" * n)) == 32


class TestBlake2b:
    def test_known_vector(self):
        # blake2b-256 of empty string (from the BLAKE2 reference implementation)
        assert (
            blake2b_256(b"").hex()
            == "0e5751c026e543b2e8ab2eb06099daa1d1e5df47778f7787faab45cdf12fe3a8"
        )


class TestCID:
    def test_hash_and_string_roundtrip(self):
        c = CID.hash_of(b"hello world")
        assert c.version == 1
        assert c.codec == DAG_CBOR
        assert c.mh_code == BLAKE2B_256
        s = str(c)
        assert s.startswith("b")
        assert CID.from_string(s) == c

    def test_bytes_roundtrip(self):
        c = CID.hash_of(b"data", codec=RAW)
        assert CID.from_bytes(c.to_bytes()) == c

    def test_ordering_matches_byte_order(self):
        a = CID.hash_of(b"a")
        b = CID.hash_of(b"b")
        assert (a < b) == (a.to_bytes() < b.to_bytes())

    def test_known_filecoin_cid_parses(self):
        # A real CIDv1/dag-cbor/blake2b-256 string shape from Filecoin
        c = CID.hash_of(b"\x82\x00\x01")
        s = str(c)
        assert s.startswith("bafy2bza")  # v1 + dag-cbor + blake2b-256 prefix
        parsed = CID.from_string(s)
        assert parsed.digest == c.digest

    def test_nonminimal_varint_bytes_rejected(self):
        # go-varint and rust unsigned-varint both reject non-minimal varint
        # encodings, so a second byte form for one logical CID must not
        # decode at all (it would diverge raw spans vs re-encodes across
        # the batch/scalar paths — round-5 exec-order fuzz find)
        canonical = CID.hash_of(b"payload")
        raw = canonical.to_bytes()
        assert raw[:2] == b"\x01\x71"
        nonminimal = b"\x01\xf1\x00" + raw[2:]  # codec 0x71 as two bytes
        with pytest.raises(ValueError, match="non-canonical"):
            CID.from_bytes(nonminimal)


class TestDagCbor:
    @pytest.mark.parametrize(
        "value",
        [
            0,
            1,
            23,
            24,
            255,
            256,
            65535,
            65536,
            2**32 - 1,
            2**32,
            2**64 - 1,
            -1,
            -24,
            -25,
            -(2**63),
            b"",
            b"\x00\x01\x02",
            "",
            "hello",
            "héllo ünïcode",
            [],
            [1, [2, [3]]],
            {},
            {"a": 1, "b": [2]},
            True,
            False,
            None,
        ],
    )
    def test_roundtrip(self, value):
        assert decode(encode(value)) == value

    def test_canonical_int_heads(self):
        assert encode(0) == b"\x00"
        assert encode(23) == b"\x17"
        assert encode(24) == b"\x18\x18"
        assert encode(255) == b"\x18\xff"
        assert encode(256) == b"\x19\x01\x00"
        assert encode(-1) == b"\x20"

    def test_cid_tag42(self):
        c = CID.hash_of(b"block")
        raw = encode(c)
        # tag 42 head
        assert raw[0] == 0xD8 and raw[1] == 42
        # bytestring head 0x58 0x25 (37 bytes), then identity multibase 0x00
        # 39 = identity prefix + 38 CID bytes (1 ver + 1 codec + 3 mh-code + 1 len + 32 digest)
        assert raw[2] == 0x58 and raw[3] == 39 and raw[4] == 0x00
        assert decode(raw) == c

    def test_tuple_encodes_as_array(self):
        assert encode((1, 2)) == encode([1, 2])

    def test_map_key_ordering_is_canonical(self):
        # length-first, then bytewise
        raw = encode({"bb": 1, "a": 2, "ab": 3})
        assert decode(raw) == {"a": 2, "ab": 3, "bb": 1}
        ordered = encode({"a": 2, "ab": 3, "bb": 1})
        assert raw == ordered

    def test_trailing_bytes_rejected(self):
        with pytest.raises(ValueError):
            decode(encode(1) + b"\x00")

    def test_indefinite_rejected(self):
        with pytest.raises(ValueError):
            decode(b"\x9f\x01\xff")  # indefinite array

    def test_nested_structure_with_cids(self):
        c1 = CID.hash_of(b"one")
        c2 = CID.hash_of(b"two", codec=RAW)
        obj = [c1, {"link": c2, "n": 42}, [c1, c2]]
        assert decode(encode(obj)) == obj


class TestBigInt:
    @pytest.mark.parametrize("value", [0, 1, -1, 255, 256, 10**30, -(10**30)])
    def test_roundtrip(self, value):
        assert bigint_from_bytes(bigint_to_bytes(value)) == value

    def test_zero_is_empty(self):
        assert bigint_to_bytes(0) == b""

    def test_sign_bytes(self):
        assert bigint_to_bytes(5) == b"\x00\x05"
        assert bigint_to_bytes(-5) == b"\x01\x05"
