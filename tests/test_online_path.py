"""Online-path tests: the full RPC pipeline against a fake Lotus node.

The reference can only exercise this path against the live calibration net
(its `main.rs` smoke test); here the identical flow runs hermetically:
ChainGetTipSetByHeight JSON → Tipset → RpcBlockstore(ChainReadObj) →
generate → verify, plus CLI verify on the saved bundle.
"""

import json

from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
from ipc_proofs_tpu.proofs.chain import Tipset
from ipc_proofs_tpu.proofs.generator import (
    EventProofSpec,
    StorageProofSpec,
    generate_proof_bundle,
)
from ipc_proofs_tpu.proofs.trust import TrustPolicy
from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle
from ipc_proofs_tpu.state.storage import calculate_storage_slot
from ipc_proofs_tpu.store.rpc import RpcBlockstore
from ipc_proofs_tpu.store.testing import FakeLotusClient

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001
SLOT = calculate_storage_slot(SUBNET, 0)


def _tipset_json(tipset: Tipset) -> dict:
    return {
        "Cids": [{"/": str(c)} for c in tipset.cids],
        "Blocks": [
            {
                "Parents": [{"/": str(p)} for p in h.parents],
                "Height": h.height,
                "ParentStateRoot": {"/": str(h.parent_state_root)},
                "ParentMessageReceipts": {"/": str(h.parent_message_receipts)},
                "Messages": {"/": str(h.messages)},
                "Timestamp": h.timestamp,
            }
            for h in tipset.blocks
        ],
        "Height": tipset.height,
    }


def _world_and_client():
    world = build_chain(
        [ContractFixture(actor_id=ACTOR, storage={SLOT: b"\x2a"})],
        [[EventFixture(emitter=ACTOR, signature=SIG, topic1=SUBNET)], []],
        parent_height=500,
    )
    by_height = {world.parent.height: world.parent, world.child.height: world.child}
    client = FakeLotusClient(
        world.store,
        responses={
            "Filecoin.ChainGetTipSetByHeight": lambda params: _tipset_json(
                by_height[params[0]]
            ),
            "Filecoin.EthAddressToFilecoinAddress": "f410f" + "a" * 39,  # unused here
            "Filecoin.StateLookupID": f"f0{ACTOR}",
        },
    )
    return world, client


class TestOnlinePipeline:
    def test_fetch_generate_verify_over_rpc(self):
        world, client = _world_and_client()
        parent = Tipset.fetch(client, 500)
        child = Tipset.fetch(client, 501)
        assert parent.cids == world.parent.cids
        assert child.blocks[0].parent_message_receipts == world.receipts_root

        store = RpcBlockstore(client)
        bundle = generate_proof_bundle(
            store,
            parent,
            child,
            [StorageProofSpec(actor_id=ACTOR, slot=SLOT)],
            [EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)],
        )
        assert len(bundle.storage_proofs) == 1 and len(bundle.event_proofs) == 1
        # every witness byte came over the (fake) wire
        read_calls = [c for c in client.calls if c[0] == "Filecoin.ChainReadObj"]
        assert len(read_calls) > 0

        result = verify_proof_bundle(bundle, TrustPolicy.accept_all())
        assert result.all_valid()

    def test_shared_cache_dedupes_rpc_traffic(self):
        world, client = _world_and_client()
        parent = Tipset.fetch(client, 500)
        child = Tipset.fetch(client, 501)
        store = RpcBlockstore(client)
        client.calls.clear()
        generate_proof_bundle(
            store,
            parent,
            child,
            [StorageProofSpec(actor_id=ACTOR, slot=SLOT)] * 3,  # same spec 3x
            [],
        )
        reads = [json.dumps(c[1]) for c in client.calls if c[0] == "Filecoin.ChainReadObj"]
        # the shared cache must make repeated specs nearly free: every block
        # fetched at most once (the reference claims ~80% reduction)
        assert len(reads) == len(set(reads))

    def test_cli_verify_on_saved_bundle(self, tmp_path, capsys):
        world, client = _world_and_client()
        parent = Tipset.fetch(client, 500)
        child = Tipset.fetch(client, 501)
        bundle = generate_proof_bundle(
            RpcBlockstore(client),
            parent,
            child,
            [StorageProofSpec(actor_id=ACTOR, slot=SLOT)],
            [EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)],
        )
        path = tmp_path / "bundle.json"
        path.write_text(bundle.to_json())

        from ipc_proofs_tpu.cli import main

        rc = main(["verify", str(path), "--check-cids", "--event-sig", SIG, "--topic1", SUBNET])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["all_valid"] is True

    def test_cli_demo_exit_code(self, capsys):
        from ipc_proofs_tpu.cli import main

        assert main(["demo"]) == 0
        assert "All valid: True" in capsys.readouterr().out


class TestApiReceiptsPathway:
    """The `ChainGetParentReceipts` fallback (reference
    `events/generator.rs:199-204`, `client/types.rs:22-37`)."""

    def test_receipt_from_api_json(self):
        import base64

        from ipc_proofs_tpu.core.cid import CID, RAW
        from ipc_proofs_tpu.proofs.chain import receipt_from_api_json

        root = CID.hash_of(b"events", codec=RAW)
        r = receipt_from_api_json(
            {
                "ExitCode": 0,
                "Return": base64.b64encode(b"\x01\x02").decode(),
                "GasUsed": 77,
                "EventsRoot": {"/": str(root)},
            }
        )
        assert (r.exit_code, r.return_data, r.gas_used, r.events_root) == (0, b"\x01\x02", 77, root)
        # null Return / EventsRoot (the common case)
        r = receipt_from_api_json({"ExitCode": 1, "Return": None, "GasUsed": 0, "EventsRoot": None})
        assert r.return_data == b"" and r.events_root is None

    def test_api_pathway_produces_identical_proofs(self):
        world, client = _world_and_client()
        parent = Tipset.fetch(client, 500)
        child = Tipset.fetch(client, 501)
        store = RpcBlockstore(client)
        specs = [EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)]

        via_amt = generate_proof_bundle(store, parent, child, [], specs)
        via_api = generate_proof_bundle(
            store, parent, child, [], specs, receipts_client=client
        )
        assert [p.to_json_obj() for p in via_api.event_proofs] == [
            p.to_json_obj() for p in via_amt.event_proofs
        ]
        # pass 2 still records the receipts AMT, so the witnesses agree too
        assert [b.cid for b in via_api.blocks] == [b.cid for b in via_amt.blocks]
        assert any(c[0] == "Filecoin.ChainGetParentReceipts" for c in client.calls)
        assert verify_proof_bundle(via_api, TrustPolicy.accept_all()).all_valid()

    def test_null_api_receipts_raises_not_empty_bundle(self):
        import pytest

        world, client = _world_and_client()
        client.responses["Filecoin.ChainGetParentReceipts"] = lambda _cid: None
        parent = Tipset.fetch(client, 500)
        child = Tipset.fetch(client, 501)
        with pytest.raises(KeyError, match="ChainGetParentReceipts"):
            generate_proof_bundle(
                RpcBlockstore(client), parent, child, [],
                [EventProofSpec(event_signature=SIG, topic_1=SUBNET)],
                receipts_client=client,
            )


class TestCliRangeHermetic:
    """The `range` CLI subcommand end-to-end against the fake Lotus node:
    mixed storage+event proofs over an epoch range, checkpoint resume, and
    offline verify of the emitted bundle — the north-star user journey at
    the CLI layer, fully offline."""

    def _fake_range_client(self, n_pairs=6):
        from ipc_proofs_tpu.fixtures import build_range_world

        bs, pairs, n_matching = build_range_world(
            n_pairs, 4, 2, 0.5, base_height=7000
        )
        by_height = {}
        for pair in pairs:
            by_height[pair.parent.height] = pair.parent
            by_height[pair.child.height] = pair.child
        client = FakeLotusClient(
            bs,
            responses={
                "Filecoin.ChainGetTipSetByHeight": lambda params: _tipset_json(
                    by_height[params[0]]
                ),
                # ID-form address: resolution short-circuits StateLookupID
                "Filecoin.EthAddressToFilecoinAddress": "f01001",
            },
        )
        lo = min(by_height)
        hi = max(by_height)
        return client, lo, hi, n_matching

    def test_range_cli_mixed_bundle_and_resume(self, tmp_path, monkeypatch):
        from ipc_proofs_tpu import cli
        from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle

        client, lo, hi, n_matching = self._fake_range_client()
        import ipc_proofs_tpu.store.rpc as rpc_mod

        monkeypatch.setattr(rpc_mod, "LotusClient", lambda *a, **k: client)
        out = tmp_path / "range_bundle.json"
        ckpt = tmp_path / "ckpt"
        args = [
            "range",
            "--endpoint", "http://fake.invalid/rpc/v1",
            "--from-height", str(lo),
            "--to-height", str(hi - 1),
            "--contract", "0x" + "52" * 20,
            "--event-sig", SIG,
            "--topic1", SUBNET,
            "--storage-slot", SUBNET,
            "--chunk-size", "2",
            "--checkpoint-dir", str(ckpt),
            "--backend", "cpu",
            "-o", str(out),
        ]
        assert cli.main(args) == 0
        bundle = UnifiedProofBundle.from_json(out.read_text())
        assert len(bundle.event_proofs) == n_matching
        assert len(bundle.storage_proofs) > 0  # one per pair for the slot
        assert len(list(ckpt.glob("chunk_*.json"))) >= 2

        # verify the emitted bundle offline through the CLI
        assert cli.main(["verify", str(out), "--check-cids"]) == 0

        # resume: a second run consumes the checkpoints, identical output
        calls_before = len(client.calls)
        out2 = tmp_path / "range_bundle_2.json"
        assert cli.main(args[:-1] + [str(out2)]) == 0
        assert out2.read_text() == out.read_text()
        # resumed chunks skip generation-side block reads
        resumed_reads = sum(
            1 for m, _ in client.calls[calls_before:] if m == "Filecoin.ChainReadObj"
        )
        assert resumed_reads == 0
