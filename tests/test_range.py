"""Multi-tipset range driver tests: batched pass 1, merged witness, and
backend-accelerated witness CID verification."""

import pytest

from ipc_proofs_tpu.backend import get_backend
from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
from ipc_proofs_tpu.proofs.bundle import ProofBlock
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import TipsetPair, generate_event_proofs_for_range
from ipc_proofs_tpu.proofs.trust import TrustPolicy
from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle
from ipc_proofs_tpu.store.blockstore import MemoryBlockstore
from ipc_proofs_tpu.utils.metrics import Metrics

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "range-subnet"
ACTOR = 777


def _make_range(n_pairs=4, store=None):
    """n_pairs independent synthetic worlds sharing one blockstore."""
    bs = store or MemoryBlockstore()
    pairs = []
    expected = 0
    for p in range(n_pairs):
        events = [
            [EventFixture(emitter=ACTOR, signature=SIG, topic1=SUBNET,
                          data=p.to_bytes(32, "big"))] if p % 2 == 0 else [],
            [EventFixture(emitter=ACTOR, signature="Noise()", topic1=SUBNET)],
        ]
        if p % 2 == 0:
            expected += 1
        world = build_chain(
            [ContractFixture(actor_id=ACTOR)],
            events,
            parent_height=100 + 2 * p,
            store=bs,
        )
        pairs.append(TipsetPair(parent=world.parent, child=world.child))
    return bs, pairs, expected


class TestRangeDriver:
    def test_scalar_and_backend_agree(self):
        bs, pairs, expected = _make_range(6)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        scalar = generate_event_proofs_for_range(bs, pairs, spec, match_backend=None)
        cpu = generate_event_proofs_for_range(bs, pairs, spec, match_backend=get_backend("cpu"))
        assert scalar.to_json() == cpu.to_json()
        assert len(scalar.event_proofs) == expected

    def test_backend_tpu_agrees(self):
        pytest.importorskip("jax")
        bs, pairs, _ = _make_range(4)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        scalar = generate_event_proofs_for_range(bs, pairs, spec)
        tpu = generate_event_proofs_for_range(bs, pairs, spec, match_backend=get_backend("tpu"))
        assert scalar.to_json() == tpu.to_json()

    def test_range_bundle_verifies(self):
        bs, pairs, expected = _make_range(4)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        bundle = generate_event_proofs_for_range(bs, pairs, spec)
        result = verify_proof_bundle(bundle, TrustPolicy.accept_all())
        assert result.event_results == [True] * expected
        assert result.all_valid()

    def test_witness_merged_and_deduped(self):
        bs, pairs, _ = _make_range(4)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        bundle = generate_event_proofs_for_range(bs, pairs, spec)
        cids = [b.cid for b in bundle.blocks]
        assert cids == sorted(cids)
        assert len(cids) == len(set(cids))

    def test_pipelined_bit_identical(self):
        """The phase-overlapped driver must emit exactly the unpipelined
        bundle: same proofs in the same order, same CID-sorted witness —
        across chunk sizes that split pairs unevenly, with and without a
        match backend."""
        from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined

        bs, pairs, expected = _make_range(7)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        reference = generate_event_proofs_for_range(bs, pairs, spec).to_json()
        for backend in (None, get_backend("cpu")):
            for chunk_size in (1, 2, 3, 7, 100):
                piped = generate_event_proofs_for_range_pipelined(
                    bs, pairs, spec, chunk_size=chunk_size, match_backend=backend
                )
                assert piped.to_json() == reference, (backend, chunk_size)
        assert len(piped.event_proofs) == expected

    def test_overlapped_gen_verify_bit_identical(self):
        """The generation/verification-overlapped driver (bench headline
        path on multi-core hosts) must emit exactly the chunked driver's
        merged bundle, and its per-chunk verdicts must equal whole-bundle
        verification verdict-for-verdict."""
        from ipc_proofs_tpu.proofs.range import (
            generate_and_verify_range_overlapped,
            generate_event_proofs_for_range_chunked,
        )

        bs, pairs, expected = _make_range(7)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)

        def verify_chunk(bundle):
            return verify_proof_bundle(bundle, TrustPolicy.accept_all()).event_results

        for chunk_size in (1, 3, 7, 100):
            reference = generate_event_proofs_for_range_chunked(
                bs, pairs, spec, chunk_size=chunk_size
            )
            merged, chunk_results = generate_and_verify_range_overlapped(
                bs, pairs, spec, chunk_size=chunk_size, verify_chunk=verify_chunk
            )
            assert merged.to_json() == reference.to_json(), chunk_size
            flat = [r for res in chunk_results for r in res]
            whole = verify_proof_bundle(merged, TrustPolicy.accept_all()).event_results
            assert flat == whole, chunk_size
            assert all(flat) and len(flat) == expected

    def test_overlapped_empty_range(self):
        from ipc_proofs_tpu.proofs.range import generate_and_verify_range_overlapped

        bs, pairs, _ = _make_range(1)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        merged, results = generate_and_verify_range_overlapped(
            bs, [], spec, chunk_size=4, verify_chunk=lambda b: ["ran"]
        )
        assert merged.event_proofs == [] and results == []

    def test_pipelined_empty_range(self):
        from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined

        bs, _, _ = _make_range(1)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        bundle = generate_event_proofs_for_range_pipelined(bs, [], spec)
        assert bundle.event_proofs == [] and bundle.blocks == []

    def test_mixed_storage_and_event_range(self):
        """A range run carrying storage specs emits BOTH proof kinds in one
        deduplicated witness and round-trips verify_proof_bundle
        (reference unified-bundle semantics, `generator.rs:25-95`,
        generalized over the range)."""
        from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
        from ipc_proofs_tpu.proofs.range import (
            generate_event_proofs_for_range_pipelined,
        )
        from ipc_proofs_tpu.proofs.storage_batch import MappingSlotSpec
        from ipc_proofs_tpu.state.storage import calculate_storage_slot

        bs = MemoryBlockstore()
        pairs = []
        for p in range(4):
            world = build_chain(
                [
                    ContractFixture(
                        actor_id=ACTOR,
                        storage={
                            calculate_storage_slot("subnet-x", 0): bytes([p + 1])
                        },
                    )
                ],
                [[EventFixture(emitter=ACTOR, signature=SIG, topic1=SUBNET)]],
                parent_height=100 + 2 * p,
                store=bs,
            )
            pairs.append(TipsetPair(parent=world.parent, child=world.child))

        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        storage_specs = [MappingSlotSpec(actor_id=ACTOR, key="subnet-x", slot_index=0)]
        bundle = generate_event_proofs_for_range(
            bs, pairs, spec, match_backend=get_backend("cpu"), storage_specs=storage_specs
        )
        assert len(bundle.event_proofs) == 4
        assert len(bundle.storage_proofs) == 4
        # per-pair slot values surfaced correctly
        values = sorted(p.value for p in bundle.storage_proofs)
        assert values == sorted(
            "0x" + bytes([v + 1]).rjust(32, b"\x00").hex() for v in range(4)
        )
        # one deduplicated CID-sorted witness covering both kinds
        cids = [b.cid for b in bundle.blocks]
        assert cids == sorted(cids) and len(cids) == len(set(cids))
        result = verify_proof_bundle(bundle, TrustPolicy.accept_all())
        assert result.all_valid()
        assert len(result.storage_results) == 4 and len(result.event_results) == 4

        # pipelined and chunked drivers emit the same mixed bundle
        piped = generate_event_proofs_for_range_pipelined(
            bs, pairs, spec, chunk_size=2,
            match_backend=get_backend("cpu"), storage_specs=storage_specs,
        )
        assert piped.to_json() == bundle.to_json()
        from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_chunked

        chunked = generate_event_proofs_for_range_chunked(
            bs, pairs, spec, chunk_size=2,
            match_backend=get_backend("cpu"), storage_specs=storage_specs,
        )
        assert sorted(p.to_json_obj().items().__str__() for p in chunked.storage_proofs) == sorted(
            p.to_json_obj().items().__str__() for p in bundle.storage_proofs
        )
        assert [str(b.cid) for b in chunked.blocks] == [str(b.cid) for b in bundle.blocks]

    def test_mixed_range_checkpoint_resume(self, tmp_path):
        """Storage proofs ride the chunk checkpoints: a resumed run loads
        them from disk instead of regenerating."""
        from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_chunked
        from ipc_proofs_tpu.proofs.storage_batch import MappingSlotSpec

        bs, pairs, _ = _make_range(4)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        storage_specs = [MappingSlotSpec(actor_id=ACTOR, key="missing-key", slot_index=0)]
        m1 = Metrics()
        first = generate_event_proofs_for_range_chunked(
            bs, pairs, spec, chunk_size=2, checkpoint_dir=str(tmp_path),
            storage_specs=storage_specs, metrics=m1,
        )
        # missing key ⇒ zero value, matching the reference's semantics
        assert all(p.value == "0x" + "00" * 32 for p in first.storage_proofs)
        m2 = Metrics()
        resumed = generate_event_proofs_for_range_chunked(
            bs, pairs, spec, chunk_size=2, checkpoint_dir=str(tmp_path),
            storage_specs=storage_specs, metrics=m2,
        )
        assert resumed.to_json() == first.to_json()
        assert m2.snapshot()["counters"].get("range_chunks_resumed") == 2

    def test_metrics_populated(self):
        bs, pairs, expected = _make_range(4)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        metrics = Metrics()
        generate_event_proofs_for_range(
            bs, pairs, spec, match_backend=get_backend("cpu"), metrics=metrics
        )
        snap = metrics.snapshot()
        assert snap["counters"]["range_proofs"] == expected
        assert snap["counters"]["range_events"] > 0
        assert {"range_scan", "range_match", "range_record"} <= set(snap["timers"])


class TestBatchCidVerification:
    def test_batch_backend_accepts_valid(self):
        bs, pairs, _ = _make_range(2)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        bundle = generate_event_proofs_for_range(bs, pairs, spec)
        result = verify_proof_bundle(
            bundle,
            TrustPolicy.accept_all(),
            verify_witness_cids=True,
            cid_backend=get_backend("cpu"),
        )
        assert result.all_valid()

    def test_batch_backend_rejects_tampered(self):
        bs, pairs, _ = _make_range(2)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        bundle = generate_event_proofs_for_range(bs, pairs, spec)
        bundle.blocks[0] = ProofBlock(cid=bundle.blocks[0].cid, data=b"\x82\x00\x01")
        with pytest.raises(ValueError):
            verify_proof_bundle(
                bundle,
                TrustPolicy.accept_all(),
                verify_witness_cids=True,
                cid_backend=get_backend("cpu"),
            )


class TestConcurrentScan:
    def test_scan_workers_same_result(self):
        bs, pairs, expected = _make_range(6)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        serial = generate_event_proofs_for_range(bs, pairs, spec)
        threaded = generate_event_proofs_for_range(bs, pairs, spec, scan_workers=4)
        assert serial.to_json() == threaded.to_json()
        assert len(threaded.event_proofs) == expected

    def test_scan_workers_over_rpc_store(self):
        from ipc_proofs_tpu.store.rpc import RpcBlockstore
        from ipc_proofs_tpu.store.testing import FakeLotusClient

        bs, pairs, expected = _make_range(4)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        rpc_store = RpcBlockstore(FakeLotusClient(bs))
        bundle = generate_event_proofs_for_range(rpc_store, pairs, spec, scan_workers=8)
        assert len(bundle.event_proofs) == expected


class TestDriverEquivalenceAcrossAmtShapes:
    """All three range drivers (flat, fused/unfused, pipelined) must emit
    byte-identical bundles on worlds whose receipt/event counts force
    multi-level v0 and v3 AMTs — heights the bench shape never reaches."""

    @pytest.mark.parametrize(
        "n_pairs,receipts,events,rate",
        [(5, 33, 9, 0.3), (3, 65, 17, 0.9), (7, 9, 1, 0.0)],
    )
    def test_all_drivers_bit_identical(self, n_pairs, receipts, events, rate, monkeypatch):
        from ipc_proofs_tpu.fixtures import build_range_world
        from ipc_proofs_tpu.proofs.range import (
            generate_event_proofs_for_range_pipelined,
        )

        bs, pairs, n_match = build_range_world(
            n_pairs, receipts, events, rate,
            signature=SIG, topic1=SUBNET, actor_id=ACTOR,
            base_height=90_000,
        )
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        backend = get_backend("cpu")
        fused = generate_event_proofs_for_range(bs, pairs, spec, match_backend=backend)
        monkeypatch.setenv("IPC_SCAN_FUSED_MATCH", "0")
        unfused = generate_event_proofs_for_range(bs, pairs, spec, match_backend=backend)
        monkeypatch.delenv("IPC_SCAN_FUSED_MATCH")
        piped = generate_event_proofs_for_range_pipelined(
            bs, pairs, spec, chunk_size=max(1, n_pairs // 3), match_backend=backend
        )
        assert fused.to_json() == unfused.to_json() == piped.to_json()
        assert len(fused.event_proofs) == n_match
        result = verify_proof_bundle(
            fused, TrustPolicy.accept_all(), verify_witness_cids=True
        )
        assert result.all_valid()

    @pytest.mark.parametrize("seed", [0xAB5, 300271])
    def test_random_worlds_bit_identical(self, seed, monkeypatch):
        """Seeded random world shapes and chunkings — in-suite slice of the
        round-5 range-driver soak (500 random worlds, clean)."""
        import random

        from ipc_proofs_tpu.fixtures import build_range_world
        from ipc_proofs_tpu.proofs.range import (
            generate_event_proofs_for_range_pipelined,
        )

        rng = random.Random(seed)
        for _ in range(5):
            bs, pairs, n_match = build_range_world(
                rng.choice([1, 3, 7, 16]),
                rng.choice([1, 4, 16]),
                rng.choice([1, 2, 5]),
                rng.choice([0.0, 0.05, 0.3]),
                signature=SIG,
                topic1=SUBNET,
                actor_id=ACTOR,
            )
            spec = EventProofSpec(
                event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR
            )
            monkeypatch.setenv("IPC_SCAN_FUSED_MATCH", "1")
            flat = generate_event_proofs_for_range(bs, pairs, spec)
            monkeypatch.setenv("IPC_SCAN_FUSED_MATCH", "0")
            unfused = generate_event_proofs_for_range(bs, pairs, spec)
            monkeypatch.setenv("IPC_SCAN_FUSED_MATCH", "1")
            piped = generate_event_proofs_for_range_pipelined(
                bs, pairs, spec, chunk_size=rng.choice([1, 2, 5, 64])
            )
            assert flat.to_json() == unfused.to_json() == piped.to_json()
            assert len(flat.event_proofs) == n_match
