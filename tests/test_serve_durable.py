"""Durable admission queue tests (`serve/durable.py`): journaled-before-ACK,
idempotency-key dedup (in-process, across restart, and concurrent), restart
replay of admitted-but-unfinished requests, fail-soft journal degrade, and
the HTTP wiring (`idempotency_key` pass-through, /healthz durable fields).
Hermetic: MemoryBlockstore worlds, ephemeral ports."""

import json
import threading
from http.client import HTTPConnection

import pytest

from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.jobs import read_journal
from ipc_proofs_tpu.jobs.journal import JournalWriter
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.trust import TrustPolicy
from ipc_proofs_tpu.serve import (
    DurableAdmission,
    ProofHTTPServer,
    ProofService,
    ServiceConfig,
)
from ipc_proofs_tpu.utils.metrics import Metrics

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"


@pytest.fixture(scope="module")
def world():
    store, pairs, _ = build_range_world(4, 2, 2, 0.5, signature=SIG, topic1=SUBNET)
    spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET)
    return store, pairs, spec


def _service(world, metrics=None):
    store, pairs, spec = world
    return ProofService(
        store=store,
        spec=spec,
        trust_policy=TrustPolicy.accept_all(),
        event_filter=None,
        config=ServiceConfig(workers=1, max_wait_ms=1.0),
        metrics=metrics,
    )


class TestDurableAdmission:
    def test_journaled_before_ack_and_idempotent(self, tmp_path, world):
        _, pairs, _ = world
        svc = _service(world)
        d = DurableAdmission(svc, str(tmp_path), pairs=pairs)
        try:
            key, done, cached = d.submit("generate", 0, idempotency_key="g-1")
            assert key == "g-1" and done["ok"] and not cached
            # the ACKed request is on disk: one admit + one done record
            records, _, torn = read_journal(str(tmp_path / "queue.bin"))
            assert [r["t"] for r in records] == ["admit", "done"] and not torn
            assert records[0]["key"] == records[1]["key"] == "g-1"
            # retry with the same key: cached, no re-execution
            _, done2, cached2 = d.submit("generate", 0, idempotency_key="g-1")
            assert cached2 and done2 == done
            records2, _, _ = read_journal(str(tmp_path / "queue.bin"))
            assert len(records2) == 2  # the cache hit wrote nothing
        finally:
            d.close()
            svc.drain()

    def test_verify_and_semantic_failure_roundtrip(self, tmp_path, world):
        _, pairs, _ = world
        svc = _service(world)
        d = DurableAdmission(svc, str(tmp_path), pairs=pairs)
        try:
            _, gen, _ = d.submit("generate", 1, idempotency_key="g")
            _, ver, _ = d.submit(
                "verify", gen["result"]["bundle"], idempotency_key="v"
            )
            assert ver["ok"] and ver["result"]["all_valid"] is True
            # a bad pair index is a SEMANTIC failure: cached as a done-error
            # so a poison request can never crash-loop the restart replay
            _, bad, cached = d.submit("generate", 99, idempotency_key="bad")
            assert not bad["ok"] and "pair_index" in bad["error"] and not cached
            _, bad2, cached2 = d.submit("generate", 99, idempotency_key="bad")
            assert cached2 and bad2 == bad
        finally:
            d.close()
            svc.drain()

    def test_cache_survives_restart(self, tmp_path, world):
        _, pairs, _ = world
        svc = _service(world)
        d = DurableAdmission(svc, str(tmp_path), pairs=pairs)
        _, done, _ = d.submit("generate", 0, idempotency_key="g-1")
        d.close()
        svc.drain()
        svc2 = _service(world)
        d2 = DurableAdmission(svc2, str(tmp_path), pairs=pairs)
        try:
            assert d2.resumed_jobs == 0  # nothing was unfinished
            _, done2, cached = d2.submit("generate", 0, idempotency_key="g-1")
            assert cached and done2 == done
        finally:
            d2.close()
            svc2.drain()

    def test_unfinished_admit_replayed_on_restart(self, tmp_path, world):
        """An admit with no done record is a request that was ACKed but died
        with the process — the restart re-executes it."""
        _, pairs, _ = world
        w = JournalWriter(str(tmp_path / "queue.bin"))
        w.append({"t": "admit", "key": "crashed", "kind": "generate", "payload": 1})
        w.close()
        metrics = Metrics()
        svc = _service(world, metrics=metrics)
        d = DurableAdmission(svc, str(tmp_path), pairs=pairs)
        try:
            assert d.resumed_jobs == 1
            assert (
                metrics.snapshot()["counters"]["serve.requests_replayed"] == 1
            )
            # the replayed result is cached under the client's key
            _, done, cached = d.submit("generate", 1, idempotency_key="crashed")
            assert cached and done["ok"]
            # and durably recorded: a second restart replays nothing
            d.close()
            svc.drain()
            svc2 = _service(world)
            d2 = DurableAdmission(svc2, str(tmp_path), pairs=pairs)
            assert d2.resumed_jobs == 0
            d2.close()
            svc2.drain()
        finally:
            d.close()
            svc.drain()

    def test_poison_admit_finishes_with_error_once(self, tmp_path, world):
        _, pairs, _ = world
        w = JournalWriter(str(tmp_path / "queue.bin"))
        w.append({"t": "admit", "key": "poison", "kind": "generate", "payload": 999})
        w.close()
        svc = _service(world)
        d = DurableAdmission(svc, str(tmp_path), pairs=pairs)
        d.close()
        svc.drain()
        # the failed replay wrote a done-error record: no second replay
        svc2 = _service(world)
        d2 = DurableAdmission(svc2, str(tmp_path), pairs=pairs)
        try:
            assert d2.resumed_jobs == 0
            _, done, cached = d2.submit("generate", 999, idempotency_key="poison")
            assert cached and not done["ok"]
        finally:
            d2.close()
            svc2.drain()

    def test_torn_queue_tail_truncated(self, tmp_path, world):
        _, pairs, _ = world
        w = JournalWriter(str(tmp_path / "queue.bin"))
        w.append({"t": "admit", "key": "k1", "kind": "generate", "payload": 0})
        w.append({"t": "done", "key": "k1", "payload": {"ok": True, "result": {}}})
        w.close()
        with open(tmp_path / "queue.bin", "ab") as fh:
            fh.write(b"IPJ1\x99")  # crash mid-append
        svc = _service(world)
        d = DurableAdmission(svc, str(tmp_path), pairs=pairs)
        try:
            _, done, cached = d.submit("verify", {}, idempotency_key="k1")
            assert cached and done == {"ok": True, "result": {}}
            records, _, torn = read_journal(str(tmp_path / "queue.bin"))
            assert len(records) == 2 and not torn
        finally:
            d.close()
            svc.drain()

    def test_concurrent_same_key_coalesces(self, tmp_path, world):
        _, pairs, _ = world
        svc = _service(world)
        d = DurableAdmission(svc, str(tmp_path), pairs=pairs)
        results = []

        def go():
            results.append(d.submit("generate", 0, idempotency_key="same"))

        try:
            threads = [threading.Thread(target=go) for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
            assert len(results) == 6
            dones = [r[1] for r in results]
            assert all(done == dones[0] and done["ok"] for done in dones)
            # exactly one execution reached the journal
            records, _, _ = read_journal(str(tmp_path / "queue.bin"))
            assert [r["t"] for r in records] == ["admit", "done"]
            assert sum(1 for _, _, cached in results if not cached) == 1
        finally:
            d.close()
            svc.drain()

    def test_journal_degrade_keeps_serving(self, tmp_path, world):
        _, pairs, _ = world
        metrics = Metrics()
        svc = _service(world, metrics=metrics)
        d = DurableAdmission(svc, str(tmp_path), pairs=pairs)

        class _Broken:
            def write(self, data):
                raise OSError(28, "No space left on device")

            def flush(self):
                pass

            def close(self):
                pass

        d._writer._fh = _Broken()
        try:
            _, done, _ = d.submit("generate", 0, idempotency_key="g")
            assert done["ok"]  # request served despite the dead journal
            assert d.health_fields()["journal_degraded"] is True
            assert metrics.snapshot()["counters"]["jobs.journal_failures"] >= 1
        finally:
            d.close()
            svc.drain()


def _post(port, path, obj):
    conn = HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request(
            "POST", path, body=json.dumps(obj),
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _get(port, path):
    conn = HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


class TestDurableHTTP:
    @pytest.fixture()
    def server(self, tmp_path, world):
        _, pairs, _ = world
        svc = _service(world)
        d = DurableAdmission(svc, str(tmp_path), pairs=pairs)
        srv = ProofHTTPServer(svc, pairs=pairs, durable=d).start()
        yield srv
        srv.shutdown(timeout=30)

    def test_generate_verify_with_keys(self, server):
        status, resp = _post(
            server.port, "/v1/generate",
            {"pair_index": 0, "idempotency_key": "g-1"},
        )
        assert status == 200 and resp["ok"]
        assert resp["idempotency_key"] == "g-1" and resp["cached"] is False
        status2, resp2 = _post(
            server.port, "/v1/generate",
            {"pair_index": 0, "idempotency_key": "g-1"},
        )
        assert status2 == 200 and resp2["cached"] is True
        assert resp2["result"] == resp["result"]
        status3, resp3 = _post(
            server.port, "/v1/verify",
            {"bundle": resp["result"]["bundle"], "idempotency_key": "v-1"},
        )
        assert status3 == 200 and resp3["ok"]
        assert resp3["result"]["all_valid"] is True

    def test_omitted_key_gets_auto_key(self, server):
        _, gen = _post(server.port, "/v1/generate", {"pair_index": 1})
        status, resp = _post(
            server.port, "/v1/verify", {"bundle": gen["result"]["bundle"]}
        )
        assert status == 200 and resp["idempotency_key"].startswith("auto-")

    def test_non_string_key_rejected(self, server):
        status, resp = _post(
            server.port, "/v1/generate", {"pair_index": 0, "idempotency_key": 5}
        )
        assert status == 400 and "idempotency_key" in resp["error"]

    def test_malformed_bundle_still_400(self, server):
        """Validation happens before admission: garbage never reaches the
        journal."""
        status, _ = _post(
            server.port, "/v1/verify",
            {"bundle": {"nope": 1}, "idempotency_key": "bad"},
        )
        assert status == 400
        records, _, _ = read_journal(
            str(server.durable._writer.path)
        )
        assert all(r["key"] != "bad" for r in records)

    def test_healthz_reports_durable_fields(self, server):
        status, health = _get(server.port, "/healthz")
        assert status == 200
        assert health["durable_queue"] is True
        assert health["resumed_jobs"] == 0
        assert isinstance(health["journal_bytes"], int)
        assert health["journal_degraded"] is False


def _done_frame_span(jpath: str, key: str):
    """(offset, end) of the ``done`` frame for ``key`` in the queue journal."""
    from ipc_proofs_tpu.jobs.journal import read_journal_entries

    entries, _, _ = read_journal_entries(jpath)
    for rec, offset, end in entries:
        if rec.get("t") == "done" and rec.get("key") == key:
            return offset, end
    raise AssertionError(f"no done record for {key!r}")


class TestResultSpill:
    """The completed-request cache is byte-bounded: cold results are
    re-read from their own ``done`` frame in the journal (CRC-verified),
    so dedup survives eviction AND restart without unbounded RSS — and a
    corrupt spilled frame re-executes instead of serving garbage."""

    def test_evicted_result_served_from_disk(self, tmp_path, world):
        _, pairs, _ = world
        svc = _service(world)
        # 1-byte hot tier: no payload ever stays in memory
        d = DurableAdmission(svc, str(tmp_path), pairs=pairs, results_max_bytes=1)
        try:
            _, done, cached = d.submit("generate", 0, idempotency_key="g-1")
            assert done["ok"] and not cached
            assert d.health_fields()["result_cache_hot_bytes"] == 0
            # the repeat is a disk hit: same payload, nothing re-executed
            _, done2, cached2 = d.submit("generate", 0, idempotency_key="g-1")
            assert cached2 and done2 == done
            records, _, torn = read_journal(str(tmp_path / "queue.bin"))
            assert [r["t"] for r in records] == ["admit", "done"] and not torn
        finally:
            d.close()
            svc.drain()

    def test_hot_tier_bounded_and_evictions_counted(self, tmp_path, world):
        _, pairs, _ = world
        metrics = Metrics()
        svc = _service(world, metrics=metrics)
        cap = 4096
        d = DurableAdmission(
            svc, str(tmp_path), pairs=pairs, results_max_bytes=cap
        )
        try:
            for i in range(6):
                _, done, _ = d.submit("generate", i % 2, idempotency_key=f"g-{i}")
                assert done["ok"]
            assert d.health_fields()["result_cache_hot_bytes"] <= cap
            snap = metrics.snapshot()
            assert snap["counters"]["serve.result_cache_evictions"] >= 1
            assert snap["gauges"]["serve.result_cache_bytes"] <= cap
            # every key still deduplicates, hot or spilled
            for i in range(6):
                _, done, cached = d.submit("generate", i % 2, idempotency_key=f"g-{i}")
                assert cached and done["ok"]
        finally:
            d.close()
            svc.drain()

    def test_spilled_dedup_survives_restart(self, tmp_path, world):
        _, pairs, _ = world
        svc = _service(world)
        d = DurableAdmission(svc, str(tmp_path), pairs=pairs, results_max_bytes=1)
        _, done, _ = d.submit("generate", 1, idempotency_key="g-r")
        d.close()
        # restart with the same 1-byte hot tier: the replay seeds only the
        # key → offset index (no payload load), the hit re-reads the frame
        d2 = DurableAdmission(svc, str(tmp_path), pairs=pairs, results_max_bytes=1)
        try:
            assert d2.health_fields()["result_cache_hot_bytes"] == 0
            _, done2, cached = d2.submit("generate", 1, idempotency_key="g-r")
            assert cached and done2 == done
            records, _, _ = read_journal(str(tmp_path / "queue.bin"))
            assert len(records) == 2  # nothing re-executed, nothing re-written
        finally:
            d2.close()
            svc.drain()

    def test_corrupt_spilled_frame_reexecutes(self, tmp_path, world):
        _, pairs, _ = world
        svc = _service(world)
        d = DurableAdmission(svc, str(tmp_path), pairs=pairs, results_max_bytes=1)
        try:
            _, done, _ = d.submit("generate", 0, idempotency_key="g-c")
            jpath = str(tmp_path / "queue.bin")
            offset, end = _done_frame_span(jpath, "g-c")
            with open(jpath, "r+b") as fh:  # flip a byte inside the payload
                fh.seek(end - 2)
                b = fh.read(1)
                fh.seek(end - 2)
                fh.write(bytes([b[0] ^ 0x40]))
            # the CRC check rejects the frame → the entry drops → the
            # request re-executes (at-least-once, never garbage)
            _, done2, cached = d.submit("generate", 0, idempotency_key="g-c")
            assert not cached
            # a fresh execution: same bundle bytes, fresh trace identity
            assert done2["ok"]
            assert done2["result"]["bundle"] == done["result"]["bundle"]
            # the fresh done frame makes the key cacheable again
            _, done3, cached3 = d.submit("generate", 0, idempotency_key="g-c")
            assert cached3 and done3 == done2
        finally:
            d.close()
            svc.drain()
