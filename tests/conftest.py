"""Test configuration: force an 8-device virtual CPU mesh for sharding tests.

Multi-chip TPU hardware is not available in CI; all `jax.sharding.Mesh` tests
run against 8 virtual CPU devices. The driver separately dry-run-compiles the
multi-chip path via `__graft_entry__.dryrun_multichip`.
"""

import os

# Force CPU for tests: the environment's axon TPU plugin registers at
# interpreter startup and sets jax.config jax_platforms="axon,cpu", which
# would make the first jnp op claim the single real TPU chip through the
# relay (slow, serialized across processes). Overriding the env var is not
# enough — the config must be updated after the sitecustomize registration.
os.environ["JAX_PLATFORMS"] = "cpu"

# CI hosts are often single-core, where the pipelined drivers would
# auto-fall-back to the inline serial path — force the real threaded
# pipeline so its concurrency stays under test. Dedicated fallback tests
# (tests/test_failover.py) clear this and pin the single-core behavior.
os.environ.setdefault("IPC_FORCE_PIPELINE", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # pragma: no cover
    pass
