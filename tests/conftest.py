"""Test configuration: force an 8-device virtual CPU mesh for sharding tests.

Multi-chip TPU hardware is not available in CI; all `jax.sharding.Mesh` tests
run against 8 virtual CPU devices. The driver separately dry-run-compiles the
multi-chip path via `__graft_entry__.dryrun_multichip`.
"""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
