"""Test configuration: force an 8-device virtual CPU mesh for sharding tests.

Multi-chip TPU hardware is not available in CI; all `jax.sharding.Mesh` tests
run against 8 virtual CPU devices. The driver separately dry-run-compiles the
multi-chip path via `__graft_entry__.dryrun_multichip`.
"""

import os

# Force CPU for tests: the environment's axon TPU plugin registers at
# interpreter startup and sets jax.config jax_platforms="axon,cpu", which
# would make the first jnp op claim the single real TPU chip through the
# relay (slow, serialized across processes). Overriding the env var is not
# enough — the config must be updated after the sitecustomize registration.
os.environ["JAX_PLATFORMS"] = "cpu"

# CI hosts are often single-core, where the pipelined drivers would
# auto-fall-back to the inline serial path — force the real threaded
# pipeline so its concurrency stays under test. Dedicated fallback tests
# (tests/test_failover.py) clear this and pin the single-core behavior.
os.environ.setdefault("IPC_FORCE_PIPELINE", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # pragma: no cover
    pass

import gc
import threading
import time

import pytest

# Resource-leak sentinel: a test that exits while a non-daemon thread it
# started is still running, or with a journal/segment/lock file handle
# still open, passes today and hangs (or corrupts) a future run. The
# autouse fixture below fails the *leaking* test, which is the only
# place the leak is still attributable.

# fd targets worth policing: the durable on-disk artifacts whose handles
# must not outlive their owner (journals, segment files, election locks).
_FD_PATTERNS = (
    "journal.bin",
    "queue.bin",
    ".blk",
    "evict.lock",
    "follow.leader.lock",
)

_LEAK_GRACE_S = 2.0


def _interesting_fds() -> "dict[str, str]":
    """fd -> target for open fds pointing at durable artifacts (POSIX
    /proc only; elsewhere the fd half of the sentinel is a no-op)."""
    out: "dict[str, str]" = {}
    try:
        entries = os.listdir("/proc/self/fd")
    except OSError:  # pragma: no cover - non-/proc platform
        return out
    for fd in entries:
        try:
            target = os.readlink(f"/proc/self/fd/{fd}")
        except OSError:
            continue  # the fd closed between listdir and readlink
        if any(pat in target for pat in _FD_PATTERNS):
            out[fd] = target
    return out


@pytest.fixture(autouse=True)
def _leak_sentinel():
    threads_before = set(threading.enumerate())
    fds_before = set(_interesting_fds())
    yield

    def leaked_threads():
        return [
            t for t in threading.enumerate()
            if t not in threads_before and t.is_alive() and not t.daemon
        ]

    def leaked_fds():
        return {
            fd: target for fd, target in _interesting_fds().items()
            if fd not in fds_before
        }

    deadline = time.monotonic() + _LEAK_GRACE_S
    threads, fds = leaked_threads(), leaked_fds()
    collected = False
    while (threads or fds) and time.monotonic() < deadline:
        if fds and not collected:
            # a handle owned by an unreferenced object is a GC artifact,
            # not an unclosed-file bug; collect once before accusing
            gc.collect()
            collected = True
        time.sleep(0.05)
        threads, fds = leaked_threads(), leaked_fds()
    problems = []
    if threads:
        problems.append(
            "leaked non-daemon threads: "
            + ", ".join(sorted(t.name for t in threads))
        )
    if fds:
        problems.append(
            "leaked durable-artifact fds: "
            + ", ".join(f"{fd} -> {target}" for fd, target in sorted(fds.items()))
        )
    assert not problems, "; ".join(problems)
