#!/usr/bin/env python
"""The five BASELINE.json benchmark configs, reproducible offline.

Usage: python benchmarks/run_configs.py [--config N] [--platform cpu|default]
                                        [--quick]

Each config prints one JSON line to stdout; diagnostics go to stderr.

1. single-tipset CPU reference: Transfer(address,address,uint256) event spec,
   full generate+verify through the scalar engines (the reference shape).
2. 4096-tipset batch event-proof generation (sparse ~1% match) — device
   match pipeline (same as bench.py).
3. EVM HAMT storage-slot batch: 65k slots across 256 contract state roots —
   keccak slot derivation on device + HAMT lookups on host.
4. witness verification: 1M recorded IPLD blocks → blake2b CID recompute
   (scaled by --quick).
5. topdown-messenger end-to-end: cross-subnet checkpoint bundle over a
   synthetic chain (storage nonce slots + NewTopDownMessage events).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _log(*args):
    print(*args, file=sys.stderr, flush=True)


def _emit(metric, value, unit, vs_baseline=None, **extra):
    print(json.dumps({"metric": metric, "value": round(value, 2), "unit": unit,
                      "vs_baseline": vs_baseline, **extra}))


SIG_TRANSFER = "Transfer(address,address,uint256)"
SIG_TOPDOWN = "NewTopDownMessage(bytes32,uint256)"


def config1_single_tipset(quick: bool):
    """Single tipset, Transfer event spec — the CPU reference path E2E."""
    from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
    from ipc_proofs_tpu.proofs.generator import EventProofSpec, generate_proof_bundle
    from ipc_proofs_tpu.proofs.trust import TrustPolicy
    from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle

    n_msgs = 8 if quick else 32
    events = []
    for i in range(n_msgs):
        if i % 4 == 0:
            events.append([EventFixture(emitter=1, signature=SIG_TRANSFER, topic1="from-a")])
        else:
            events.append([EventFixture(emitter=1, signature="Noise(uint256)", topic1="x")])
    world = build_chain([ContractFixture(actor_id=1)], events)
    spec = [EventProofSpec(event_signature=SIG_TRANSFER, topic_1="from-a", actor_id_filter=1)]

    iters = 5 if quick else 20
    start = time.perf_counter()
    for _ in range(iters):
        bundle = generate_proof_bundle(world.store, world.parent, world.child, [], spec)
        result = verify_proof_bundle(bundle, TrustPolicy.accept_all())
        assert result.all_valid()
    elapsed = time.perf_counter() - start
    per_roundtrip_ms = elapsed / iters * 1000
    _log(f"config1: {len(bundle.event_proofs)} proofs, {per_roundtrip_ms:.1f} ms gen+verify")
    # reference README claims ~10 ms verification alone on its (unspecified) CPU
    _emit("single_tipset_gen_verify_ms", per_roundtrip_ms, "ms",
          vs_baseline=round(10.0 / per_roundtrip_ms, 2) if per_roundtrip_ms else None)


def config2_batch_events(quick: bool):
    """Delegates to the headline bench (same measurement)."""
    import subprocess

    cmd = [sys.executable, "bench.py",
           "--platform", os.environ.get("IPC_BENCH_PLATFORM", "cpu")]
    if quick:
        cmd.append("--quick")
    # the bench is a per-leg watchdogged orchestrator; bound config2 above
    # its own worst case (bench.worst_case_seconds keeps the bound next to
    # the retry policy it bounds), scaled by the same mult the child will
    # read from the env, plus probe/assembly slack — and survive the bound
    # so the remaining configs still run and emit their lines
    import bench

    mult = float(os.environ.get("IPC_BENCH_LEG_TIMEOUT_MULT", "1.0"))
    ceiling = bench.worst_case_seconds(quick, mult) + 600.0
    try:
        out = subprocess.run(cmd, capture_output=True, text=True, timeout=ceiling)
    except subprocess.TimeoutExpired as exc:
        sys.stderr.write((exc.stderr or b"").decode(errors="replace")
                         if isinstance(exc.stderr, bytes) else (exc.stderr or ""))
        _log(f"config2: headline bench exceeded its {ceiling:.0f}s ceiling — skipped")
        return
    sys.stderr.write(out.stderr)
    print(out.stdout.strip())


def config3_storage_slots(quick: bool):
    """65k slots × 256 contract roots: device keccak slot derivation + host
    HAMT lookups (the pointer-chasing stays on host by design)."""
    import numpy as np

    from ipc_proofs_tpu.backend import get_backend
    from ipc_proofs_tpu.core.hashes import keccak256
    from ipc_proofs_tpu.ipld.hamt import HAMT, hamt_build
    from ipc_proofs_tpu.state.events import ascii_to_bytes32
    from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

    n_slots = 4096 if quick else 65536
    n_contracts = 32 if quick else 256
    slots_per_contract = n_slots // n_contracts

    # batch leg: derive all mapping slots (keccak over 64-byte preimages).
    # The backend picks the path — below IPC_TPU_KECCAK_MIN_BYTES the C++
    # host batch wins the dispatch+transfer economics; the device-kernel
    # slope line below reports the chip's own rate either way.
    backend = get_backend("tpu")
    preimages = [
        ascii_to_bytes32(f"subnet-{c}") + int(i).to_bytes(32, "big")
        for c in range(n_contracts)
        for i in range(slots_per_contract)
    ]
    backend.keccak256_batch(preimages)  # discard: compile/warm either path
    start = time.perf_counter()
    slot_keys = backend.keccak256_batch(preimages)  # warmed, backend-chosen path
    t_hash_e2e = time.perf_counter() - start

    # device kernel rate, slope-timed (tunnel RTT cancelled)
    import jax.numpy as jnp

    from ipc_proofs_tpu.ops.keccak_jax import keccak256_blocks
    from ipc_proofs_tpu.ops.pack import pad_keccak
    from ipc_proofs_tpu.utils.timing import measure_pass_seconds

    kb, kc = pad_keccak(preimages)
    kb_j, kc_j = jnp.asarray(kb), jnp.asarray(kc)

    def one_pass(i, b, c):
        return keccak256_blocks(b ^ i.astype(jnp.uint32), c).sum(dtype=jnp.uint32).astype(jnp.int32)

    pt = measure_pass_seconds(one_pass, (kb_j, kc_j), k_small=3, k_large=43)
    t_hash = pt.seconds

    # host leg: build one storage HAMT per contract (shared store), then
    # look up every slot — ONE batched C walk over all (root, key) pairs
    # (`hamt_get_batch`), scalar loop when the extension is absent
    build_start = time.perf_counter()
    bs = MemoryBlockstore()
    roots = []
    for c in range(n_contracts):
        entries = {
            slot_keys[c * slots_per_contract + i]: (i % 251).to_bytes(2, "big")
            for i in range(slots_per_contract)
        }
        roots.append(hamt_build(bs, entries))
    t_build = time.perf_counter() - build_start

    from ipc_proofs_tpu.ipld.hamt import hamt_get_batch

    owners = [c for c in range(n_contracts) for _ in range(slots_per_contract)]
    hamt_get_batch(bs, roots, owners[:8], slot_keys[:8])  # warm/load the ext
    start = time.perf_counter()
    values = hamt_get_batch(bs, roots, owners, slot_keys)
    if values is not None:
        hits = sum(v is not None for v in values)
        lookup_path = "batched-C"
    else:
        hits = 0
        for c in range(n_contracts):
            hamt = HAMT.load(bs, roots[c])
            for i in range(slots_per_contract):
                if hamt.get(slot_keys[c * slots_per_contract + i]) is not None:
                    hits += 1
        lookup_path = "scalar"
    t_lookup = time.perf_counter() - start
    assert hits == n_slots

    scalar_start = time.perf_counter()
    sample = min(2048, n_slots)
    for p in preimages[:sample]:
        keccak256(p)
    scalar_rate = sample / (time.perf_counter() - scalar_start)

    # Two honest numbers: device kernel slope (tunnel cancelled) and the warmed
    # end-to-end batch call (host pack + transfer + kernel) that a user actually
    # pays. vs_baseline compares e2e-to-e2e so the ratio is apples-to-apples.
    device_rate = n_slots / t_hash
    e2e_rate = n_slots / t_hash_e2e
    rate = n_slots / (t_hash_e2e + t_lookup)
    _log(
        f"config3: {n_slots} slots / {n_contracts} roots — device hash {t_hash*1e3:.2f}ms "
        f"(warmed backend-chosen hash leg {t_hash_e2e:.2f}s), build {t_build:.1f}s, "
        f"lookup {t_lookup:.2f}s ({lookup_path})"
    )
    _emit("storage_slot_lookups_per_sec", rate, "slots/s",
          vs_baseline=round(e2e_rate / scalar_rate, 2),
          device_hash_rate=round(device_rate, 1), e2e_hash_rate=round(e2e_rate, 1))


def config4_witness_cids(quick: bool):
    """1M recorded IPLD blocks → blake2b-256 CID recompute, measured on
    the best backend the verifier would pick for THIS platform: the
    device kernel on a chip, the C++ batch hasher off-chip (timing the
    XLA emulation of the device kernel on a CPU host produced a
    ~4-orders-slower number that said nothing about the platform)."""
    import jax
    import numpy as np

    from ipc_proofs_tpu.core.hashes import blake2b_256

    n_blocks = 50_000 if quick else 1_000_000
    block_size = 200  # typical IPLD node size, < 2 blake2b blocks
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 256, size=(n_blocks, block_size), dtype=np.uint8)
    messages = [payload[i].tobytes() for i in range(n_blocks)]

    if jax.devices()[0].platform != "tpu":
        from ipc_proofs_tpu.backend.native import load_native

        from ipc_proofs_tpu.backend.native import load_native, load_scan_ext

        candidates = []
        sample = min(20_000, n_blocks)
        t0 = time.perf_counter()
        for i in range(sample):
            blake2b_256(messages[i])
        scalar_rate = sample / (time.perf_counter() - t0)
        candidates.append((scalar_rate, "scalar-hashlib"))
        scan = load_scan_ext()
        if scan is not None and hasattr(scan, "verify_blake2b_blocks"):
            # THE production verify path: in-place recompute+compare
            digests = [blake2b_256(m) for m in messages]
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                assert scan.verify_blake2b_blocks(digests, messages) is True
                best = min(best, time.perf_counter() - t0)
            candidates.append((n_blocks / best, "scan-ext-verify"))
        native = load_native()
        if native is not None:
            assert native.blake2b256_batch(messages[:1])[0] == blake2b_256(messages[0])
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                native.blake2b256_batch(messages)
                best = min(best, time.perf_counter() - t0)
            candidates.append((n_blocks / best, "cpp-batch"))
        if len(candidates) > 1:
            # report the best path the platform actually offers, labeled —
            # the verifier itself picks scan-ext-verify when built
            rate, kernel = max(candidates)
            detail = ", ".join(f"{k} {r:,.0f}" for r, k in candidates)
            _log(f"config4: {rate:,.0f} CIDs/s best ({kernel}; {detail})")
            _emit("witness_cid_recompute_per_sec", rate, "CIDs/s",
                  vs_baseline=round(rate / scalar_rate, 2), kernel=kernel)
            return
        messages = messages[: min(n_blocks, 20_000)]
        n_blocks = len(messages)  # no native paths: tiny-shape XLA fallback

    from ipc_proofs_tpu.ops.cid_bench import blake2b_cid_bench_setup
    from ipc_proofs_tpu.utils.timing import measure_pass_seconds

    # shared harness: two-block Pallas on a chip that accepts it (5.2× the
    # XLA scan kernel on v5e, measured), XLA otherwise — incl. a runtime
    # Mosaic-rejection fallback
    t_pack = time.perf_counter()
    one_pass, args_j, digests, kernel = blake2b_cid_bench_setup(messages)
    _log(
        f"config4: packed {n_blocks} blocks in {time.perf_counter() - t_pack:.1f}s; "
        f"kernel = {kernel}"
    )

    pt = measure_pass_seconds(one_pass, args_j, k_small=3, k_large=23)
    _log(f"config4: slope timing k={pt.k_small}/{pt.k_large} → {pt.per_pass_ms:.2f} ms/pass")
    rate = n_blocks / pt.seconds

    for i in range(4):
        assert digests[i].tobytes() == blake2b_256(messages[i])

    sample = min(20_000, n_blocks)
    scalar_start = time.perf_counter()
    for i in range(sample):
        blake2b_256(messages[i])
    scalar_rate = sample / (time.perf_counter() - scalar_start)

    _log(f"config4: {rate:,.0f} CIDs/s device vs {scalar_rate:,.0f} scalar")
    _emit("witness_cid_recompute_per_sec", rate, "CIDs/s",
          vs_baseline=round(rate / scalar_rate, 2))


def config5_topdown_e2e(quick: bool):
    """topdown-messenger checkpoint bundle: nonce slots + events, E2E."""
    from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
    from ipc_proofs_tpu.proofs.event_verifier import create_event_filter
    from ipc_proofs_tpu.proofs.generator import (
        EventProofSpec,
        StorageProofSpec,
        generate_proof_bundle,
    )
    from ipc_proofs_tpu.proofs.trust import TrustPolicy
    from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle
    from ipc_proofs_tpu.state.storage import calculate_storage_slot

    n_subnets = 4 if quick else 16
    actor = 4242
    # TopdownMessenger: mapping(bytes32 => Subnet{topDownNonce}) at slot 0;
    # trigger() pre-increments the nonce then emits NewTopDownMessage.
    storage = {}
    events = []
    for s in range(n_subnets):
        subnet = f"subnet-{s}"
        nonce = s + 1
        storage[calculate_storage_slot(subnet, 0)] = nonce.to_bytes(1, "big")
        events.append(
            [
                EventFixture(
                    emitter=actor,
                    signature=SIG_TOPDOWN,
                    topic1=subnet,
                    data=nonce.to_bytes(32, "big"),
                )
            ]
        )
    world = build_chain([ContractFixture(actor_id=actor, storage=storage)], events)

    storage_specs = [
        StorageProofSpec(actor_id=actor, slot=calculate_storage_slot(f"subnet-{s}", 0))
        for s in range(n_subnets)
    ]
    event_specs = [
        EventProofSpec(event_signature=SIG_TOPDOWN, topic_1=f"subnet-{s}", actor_id_filter=actor)
        for s in range(n_subnets)
    ]

    start = time.perf_counter()
    bundle = generate_proof_bundle(
        world.store, world.parent, world.child, storage_specs, event_specs
    )
    t_gen = time.perf_counter() - start

    start = time.perf_counter()
    result = verify_proof_bundle(
        bundle, TrustPolicy.accept_all(),
        event_filter=None, verify_witness_cids=True,
    )
    t_verify = time.perf_counter() - start
    assert result.all_valid()
    assert len(bundle.storage_proofs) == n_subnets
    assert len(bundle.event_proofs) == n_subnets

    _log(
        f"config5: {n_subnets} subnets, {len(bundle.blocks)} witness blocks "
        f"({bundle.witness_bytes()} B), gen {t_gen*1000:.1f} ms, verify {t_verify*1000:.1f} ms"
    )
    _emit("topdown_checkpoint_bundle_ms", (t_gen + t_verify) * 1000, "ms",
          subnets=n_subnets, witness_bytes=bundle.witness_bytes())


CONFIGS = {
    1: config1_single_tipset,
    2: config2_batch_events,
    3: config3_storage_slots,
    4: config4_witness_cids,
    5: config5_topdown_e2e,
}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", type=int, default=None, help="1-5; default all")
    parser.add_argument("--platform", default="auto", help="auto|default|cpu")
    parser.add_argument("--quick", action="store_true")
    args = parser.parse_args()

    if args.platform == "auto":
        from ipc_proofs_tpu.utils.platform import pick_platform

        args.platform = pick_platform("auto", log=_log)
        _log(f"platform probe → {args.platform}")
    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    os.environ.setdefault("IPC_BENCH_PLATFORM", args.platform)

    targets = [args.config] if args.config else sorted(CONFIGS)
    for n in targets:
        _log(f"=== config {n} ===")
        CONFIGS[n](args.quick)


if __name__ == "__main__":
    main()
