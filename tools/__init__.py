"""Repo tooling: lint, bench-schema validation, chaos/crash harnesses.

Package marker so ``python -m tools.ipclint`` and ``python -m
tools.check_all`` resolve from the repo root without installation.
"""
