"""Sanitizer-hardened build + test of the native C extensions.

Builds ``scan_ext.c`` and ``dagcbor_ext.c`` with ASan+UBSan and the full
warning set promoted to errors (``-fsanitize=address,undefined -Wall
-Wextra -Werror``), then runs the native test subset against the
sanitized modules. Memory errors (heap overflow, use-after-free) and
undefined behavior (signed overflow, misaligned loads, bad shifts) in the
C scanner/codec become hard test failures instead of silent corruption.

Mechanics: the sanitized ``.so``s are cached under distinct names
(``*.san.so`` — see ``core._cid_native.build_cpython_ext``) so they never
poison the fast-path build cache. The test subprocess runs with
``IPC_PROOFS_SAN=1`` (builder picks the sanitized cache) and
``LD_PRELOAD=libasan.so`` (the Python binary itself is uninstrumented, so
the ASan runtime must be first in the process; ``detect_leaks=0`` because
CPython's interned objects look like leaks to lsan).

Exit codes: 0 = clean run *or* graceful skip (no gcc / no libasan — CI
images without the toolchain shouldn't fail tier-1); 1 = compile warning,
sanitizer report, or test failure. ``--strict`` turns a skip into a
failure for environments that must have the toolchain.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
NATIVE_DIR = REPO_ROOT / "ipc_proofs_tpu" / "backend" / "native"
SOURCES = ("scan_ext.c", "dagcbor_ext.c")
MODULES = ("ipc_scan_ext", "ipc_dagcbor_ext")

# the tests that exercise the C extensions end-to-end, including the
# malformed-input fuzz corpora (exactly where ASan/UBSan pay off)
NATIVE_TESTS = (
    "tests/test_scan_native.py",
    "tests/test_native_dagcbor.py",
    "tests/test_native_cid_type.py",
    "tests/test_codec_exec_fuzz.py",
    "tests/test_batch_verifier_fuzz.py",
)

_PROBE_C = "int main(void) { return 0; }\n"


def _gcc_file(name: str) -> "str | None":
    """Resolve a runtime library through gcc; None when not installed."""
    try:
        out = subprocess.run(
            ["gcc", "-print-file-name=" + name],
            capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    path = out.stdout.strip()
    return path if os.path.isabs(path) and os.path.exists(path) else None


def probe_toolchain() -> "tuple[bool, str]":
    """Can this host compile AND run sanitized code?

    Returns (ok, detail) — detail is the LD_PRELOAD string on success, a
    human-readable skip reason on failure.
    """
    try:
        out = subprocess.run(
            ["gcc", "-print-file-name=libasan.so"],
            capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.SubprocessError):
        return False, "gcc not found"
    libasan = out.stdout.strip()
    # gcc echoes the bare name back when the runtime isn't installed
    if not libasan or not os.path.isabs(libasan) or not os.path.exists(libasan):
        return False, "libasan runtime not installed"
    # libstdc++ must ride along in LD_PRELOAD: python doesn't link it, so
    # when ASan initializes its __cxa_throw interceptor the real symbol is
    # absent, and the first C++ throw from a later-dlopened lib (jaxlib's
    # MLIR bindings) hits an AddressSanitizer CHECK instead of unwinding
    libstdcpp = _gcc_file("libstdc++.so.6")
    preload = f"{libasan} {libstdcpp}" if libstdcpp else libasan
    with tempfile.TemporaryDirectory(prefix="san_probe_") as td:
        src = Path(td) / "probe.c"
        exe = Path(td) / "probe"
        src.write_text(_PROBE_C)
        try:
            subprocess.run(
                ["gcc", "-fsanitize=address,undefined", str(src), "-o", str(exe)],
                check=True, capture_output=True, timeout=60,
            )
            subprocess.run(
                [str(exe)], check=True, capture_output=True, timeout=30,
                env={**os.environ, "ASAN_OPTIONS": "detect_leaks=0"},
            )
        except (OSError, subprocess.SubprocessError):
            return False, "sanitized probe failed to compile/run"
    return True, preload


def build_sanitized(preload: str, verbose: bool = True) -> int:
    """Compile both extensions sanitized + warning-clean; 0 on success.

    Builds through the shared builder (with IPC_PROOFS_SAN=1) so the
    ``.san.so`` names, host stamps, and flag set stay in one place — but in
    a SUBPROCESS, because the builder imports the module it built and the
    sanitized .so cannot load into this (unpreloaded) interpreter.
    """
    code = (
        "from pathlib import Path\n"
        "from ipc_proofs_tpu.core import _cid_native as n\n"
        f"native = Path({str(NATIVE_DIR)!r})\n"
        f"for src, mod in zip({SOURCES!r}, {MODULES!r}):\n"
        "    n.build_cpython_ext(native / src, n.BUILD_DIR / (mod + '.so'), mod)\n"
    )
    # the builder imports each module right after compiling it, so the
    # build subprocess needs the ASan runtime preloaded too; detect_leaks=0
    # also keeps LSan from failing the gcc child processes at exit
    env = {
        **os.environ,
        "IPC_PROOFS_SAN": "1",
        "JAX_PLATFORMS": "cpu",
        "LD_PRELOAD": preload,
        "ASAN_OPTIONS": "detect_leaks=0",
    }
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO_ROOT,
        capture_output=True, text=True, timeout=600, env=env,
    )
    if proc.returncode != 0:
        if verbose:
            sys.stderr.write(proc.stderr)
            print("build_native_san: sanitized build FAILED", file=sys.stderr)
        return 1
    if verbose:
        for mod in MODULES:
            so = NATIVE_DIR / "build" / f"{mod}.san.so"
            print(f"build_native_san: built {so.relative_to(REPO_ROOT)}")
    return 0


def run_tests(preload: str, extra_pytest_args: "list[str] | None" = None) -> int:
    """Run the native test subset against the sanitized extensions."""
    env = {
        **os.environ,
        "IPC_PROOFS_SAN": "1",
        "LD_PRELOAD": preload,
        # CPython's arenas/interned strings read as leaks; everything else
        # (overflow, UAF) still aborts the run
        "ASAN_OPTIONS": "detect_leaks=0",
        "UBSAN_OPTIONS": "print_stacktrace=1:halt_on_error=1",
        "JAX_PLATFORMS": "cpu",
    }
    # -s: sanitizer reports print to the real stderr as the process dies —
    # pytest's fd capture would swallow them along with the crashed test
    cmd = [
        sys.executable, "-m", "pytest", "-q", "-s", "-m", "not slow",
        "-p", "no:cacheprovider",
        *NATIVE_TESTS,
        *(extra_pytest_args or []),
    ]
    proc = subprocess.run(cmd, cwd=REPO_ROOT, timeout=1800, env=env)
    return proc.returncode


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.build_native_san",
        description="ASan/UBSan build + native test subset for the C extensions",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="fail (exit 1) instead of skipping when the toolchain is missing",
    )
    ap.add_argument(
        "--build-only", action="store_true",
        help="compile the sanitized extensions but skip the test run",
    )
    ap.add_argument(
        "pytest_args", nargs="*",
        help="extra args forwarded to pytest (e.g. -k decode)",
    )
    args = ap.parse_args(argv)

    ok, detail = probe_toolchain()
    if not ok:
        print(f"build_native_san: SKIP ({detail})", file=sys.stderr)
        return 1 if args.strict else 0
    preload = detail

    rc = build_sanitized(preload)
    if rc != 0:
        return rc
    if args.build_only:
        return 0
    rc = run_tests(preload, args.pytest_args)
    if rc != 0:
        print("build_native_san: sanitized tests FAILED", file=sys.stderr)
        return rc
    print("build_native_san: sanitized build + native tests clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
