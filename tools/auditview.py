#!/usr/bin/env python
"""Offline auditor for provenance registry logs (``--registry-dir``).

The serve daemon answers `/v1/registry/*` about its own chain; this tool
answers the auditor's side of the contract without a live daemon — from
nothing but the log file and, optionally, a previously pinned checkpoint:

- **verify** — walk one ``reg-<owner>.log`` end to end: every frame's
  CRC, every prev-link of the hash chain, and the Merkle root over all
  records. A torn tail (crash residue) is reported but passes; any other
  defect — one flipped bit anywhere — fails with the typed reason.
- **prove** — check an inclusion proof for a bundle digest: find its
  serve record, rebuild the proof from the log, verify it against the
  recomputed root (or against ``--root`` as served by the daemon).
- **diff** — consistency between two checkpoints of the SAME log: given
  an old size (and optionally the old root you pinned back then), prove
  the current tree is an append-only extension and list the records
  appended since.

Usage::

    python tools/auditview.py verify REG.log
    python tools/auditview.py prove REG.log --digest <bundle-digest> [--root HEX]
    python tools/auditview.py diff REG.log --old-size N [--old-root HEX]
    ... --json        # machine-readable verdicts

Exit code 0 = everything checked out; 1 = any integrity or proof
failure. Never modifies the log.
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, ".")  # repo-root invocation, like the other tools

from ipc_proofs_tpu.registry.log import (  # noqa: E402
    RegistryError,
    read_registry_frames,
    record_digest,
)
from ipc_proofs_tpu.registry.mmr import (  # noqa: E402
    MerkleLog,
    leaf_hash,
    verify_consistency,
    verify_inclusion,
)

__all__ = ["load_log", "verify_log", "prove_digest", "diff_checkpoints", "main"]


def load_log(path: str) -> "tuple[list, bool]":
    """All complete frames + torn flag; typed RegistryError propagates."""
    entries, _good, torn = read_registry_frames(path)
    return entries, torn


def verify_log(path: str) -> dict:
    """Full-chain verdict: frame CRCs (the reader enforces them),
    prev-links, record count, Merkle root, chain tip."""
    try:
        entries, torn = load_log(path)
    except RegistryError as exc:
        return {"ok": False, "error": str(exc)}
    prev = ""
    for i, (rec, payload, off) in enumerate(entries):
        got = rec.get("prev") if isinstance(rec, dict) else None
        if got != prev:
            return {
                "ok": False,
                "error": f"chain broken at record {i} (offset {off}): "
                f"prev={got!r}, expected {prev!r}",
            }
        prev = record_digest(payload)
    tree = MerkleLog([leaf_hash(payload) for _rec, payload, _off in entries])
    kinds: dict = {}
    for rec, _payload, _off in entries:
        k = rec.get("kind") or "?"
        kinds[k] = kinds.get(k, 0) + 1
    return {
        "ok": True,
        "records": len(entries),
        "kinds": kinds,
        "root": tree.root().hex(),
        "tip": prev,
        "torn_tail": torn,
    }


def prove_digest(path: str, digest: str, root_hex: str = "") -> dict:
    """Inclusion verdict for the (latest) serve record of ``digest``.
    With ``root_hex`` the proof verifies against the daemon's published
    root — binding the log file to the checkpoint clients pinned."""
    try:
        entries, _torn = load_log(path)
    except RegistryError as exc:
        return {"ok": False, "error": str(exc)}
    seq = None
    for i, (rec, _payload, _off) in enumerate(entries):
        if rec.get("kind") == "serve" and rec.get("digest") == digest:
            seq = i
    if seq is None:
        return {"ok": False, "error": f"no serve record for digest {digest}"}
    leaves = [leaf_hash(payload) for _rec, payload, _off in entries]
    tree = MerkleLog(leaves)
    root = bytes.fromhex(root_hex) if root_hex else tree.root()
    path_hashes = tree.inclusion_path(seq)
    ok = verify_inclusion(leaves[seq], seq, len(leaves), path_hashes, root)
    return {
        "ok": ok,
        "seq": seq,
        "size": len(leaves),
        "root": root.hex(),
        "path": [h.hex() for h in path_hashes],
        **({} if ok else {"error": "inclusion proof did not verify"}),
    }


def diff_checkpoints(path: str, old_size: int, old_root_hex: str = "") -> dict:
    """Append-only verdict between checkpoints: old (size[, root]) vs
    the log's current head, plus the records appended between them."""
    try:
        entries, _torn = load_log(path)
    except RegistryError as exc:
        return {"ok": False, "error": str(exc)}
    n = len(entries)
    if not 0 <= old_size <= n:
        return {"ok": False, "error": f"old size {old_size} not in [0, {n}]"}
    tree = MerkleLog([leaf_hash(payload) for _rec, payload, _off in entries])
    old_root = (
        bytes.fromhex(old_root_hex) if old_root_hex else tree.root_at(old_size)
    )
    proof = tree.consistency_path(old_size) if 0 < old_size < n else []
    ok = verify_consistency(old_size, n, old_root, tree.root(), proof)
    out = {
        "ok": ok,
        "old_size": old_size,
        "old_root": old_root.hex(),
        "size": n,
        "root": tree.root().hex(),
        "proof": [h.hex() for h in proof],
        "appended": [
            dict(rec, seq=old_size + i)
            for i, (rec, _payload, _off) in enumerate(entries[old_size:])
        ],
    }
    if not ok:
        out["error"] = (
            "consistency proof did not verify — the log is NOT an "
            "append-only extension of that checkpoint"
        )
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("cmd", choices=["verify", "prove", "diff"])
    ap.add_argument("log", help="path to a reg-<owner>.log file")
    ap.add_argument("--digest", default="", help="bundle digest (prove)")
    ap.add_argument(
        "--root", default="", help="published head root to prove against (hex)"
    )
    ap.add_argument(
        "--old-size", type=int, default=None, help="old checkpoint size (diff)"
    )
    ap.add_argument(
        "--old-root", default="", help="old checkpoint root to pin (hex, diff)"
    )
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if args.cmd == "verify":
        out = verify_log(args.log)
    elif args.cmd == "prove":
        if not args.digest:
            ap.error("prove requires --digest")
        out = prove_digest(args.log, args.digest, root_hex=args.root)
    else:
        if args.old_size is None:
            ap.error("diff requires --old-size")
        out = diff_checkpoints(
            args.log, args.old_size, old_root_hex=args.old_root
        )

    if args.as_json:
        print(json.dumps(out, indent=2, sort_keys=True))
    elif out["ok"]:
        if args.cmd == "verify":
            print(
                f"OK: {out['records']} record(s) {out['kinds']}, chain + "
                f"CRC verified, root {out['root'][:16]}…"
                + (" (torn tail truncatable)" if out["torn_tail"] else "")
            )
        elif args.cmd == "prove":
            print(
                f"OK: digest included at seq {out['seq']} of {out['size']} "
                f"under root {out['root'][:16]}…"
            )
        else:
            print(
                f"OK: head ({out['size']}) extends checkpoint "
                f"({out['old_size']}); {len(out['appended'])} record(s) "
                "appended"
            )
    else:
        print(f"FAIL: {out['error']}")
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
