"""One-shot static gate: every repo-native checker in sequence.

Chains the analyzers that guard invariants tests can't see directly:

1. **ipclint** — lock discipline, determinism, error taxonomy, metrics
   vocabulary over ``ipc_proofs_tpu`` + ``tools`` (AST-level, fast);
2. **bench schema** — every ``BENCH_*.json`` artifact still parses against
   the reporting contract;
3. **sanitizer probe** — the ASan/UBSan toolchain is present and a probe
   binary compiles and runs (reported, never fatal: images without the
   toolchain run the first two gates and skip the third). Pass ``--san``
   to run the full sanitized build + native test subset instead of the
   probe.

Exit 0 only when every gate passes. Designed for pre-commit / CI::

    python -m tools.check_all          # lint + schema + toolchain probe
    python -m tools.check_all --san    # …with the full sanitizer run
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent


def _gate(name: str, argv: "list[str]") -> bool:
    print(f"check_all: [{name}] {' '.join(argv)}", flush=True)
    proc = subprocess.run([sys.executable, *argv], cwd=REPO_ROOT, timeout=1800)
    ok = proc.returncode == 0
    print(f"check_all: [{name}] {'ok' if ok else f'FAILED (exit {proc.returncode})'}")
    return ok


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.check_all", description="run every repo-native static gate"
    )
    ap.add_argument(
        "--san", action="store_true",
        help="run the full sanitizer build + native tests, not just the probe",
    )
    args = ap.parse_args(argv)

    ok = _gate("ipclint", ["-m", "tools.ipclint", "ipc_proofs_tpu", "tools"])

    artifacts = sorted(str(p.name) for p in REPO_ROOT.glob("BENCH_*.json"))
    if artifacts:
        ok &= _gate("bench-schema", ["tools/check_bench_schema.py", *artifacts])
    else:
        print("check_all: [bench-schema] no BENCH_*.json artifacts — skipped")

    if args.san:
        ok &= _gate("sanitizer", ["-m", "tools.build_native_san"])
    else:
        from tools.build_native_san import probe_toolchain

        available, detail = probe_toolchain()
        if available:
            print("check_all: [sanitizer] toolchain available (probe compiled+ran)")
        else:
            print(f"check_all: [sanitizer] SKIP ({detail})")

    print("check_all: " + ("all gates passed" if ok else "FAILED"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
