"""One-shot static gate: every repo-native checker in sequence.

Chains the analyzers that guard invariants tests can't see directly:

1. **ipclint** — lock discipline, determinism, error taxonomy, metrics
   vocabulary over ``ipc_proofs_tpu`` + ``tools`` (AST-level, fast);
2. **bench schema** — every ``BENCH_*.json`` artifact still parses against
   the reporting contract;
3. **sanitizer probe** — the ASan/UBSan toolchain is present and a probe
   binary compiles and runs (reported, never fatal: images without the
   toolchain run the first two gates and skip the third). Pass ``--san``
   to run the full sanitized build + native test subset instead of the
   probe.

Pass ``--lockdep`` to add a fourth, *dynamic* gate: the lock-heavy
tier-1 test files re-run under ``IPC_LOCKDEP=1`` (strict runtime
lock-order witness, see ``ipc_proofs_tpu/utils/lockdep.py``). Any
acquisition-order inversion, non-reentrant re-entry, or flock/thread
mixed-order violation the tests actually exercise raises
``LockOrderError`` and fails the gate — the static lint proves the
declared order is acyclic, this gate proves the executed order matches.

Exit 0 only when every gate passes. Designed for pre-commit / CI::

    python -m tools.check_all            # lint + schema + toolchain probe
    python -m tools.check_all --san      # …with the full sanitizer run
    python -m tools.check_all --lockdep  # …plus the runtime lockdep sweep
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent

# The tier-1 files whose tests exercise real cross-thread / cross-process
# locking: serve plane, durable admission, tiered store + flocked segment
# eviction, job journal, parallel pipeline, cluster router, thread pools.
# Pure-math and codec suites add wall-clock but no lock edges, so the
# lockdep sweep stays a sub-minute gate instead of a full tier-1 re-run.
LOCKDEP_TEST_FILES = (
    "tests/test_auditview.py",
    "tests/test_backfill.py",
    "tests/test_cluster.py",
    "tests/test_cluster_replica.py",
    "tests/test_crash_recovery.py",
    "tests/test_fetchplane.py",
    "tests/test_fleet.py",
    "tests/test_jobs.py",
    "tests/test_lockdep.py",
    "tests/test_parallel.py",
    "tests/test_range_pipeline.py",
    "tests/test_registry.py",
    "tests/test_replica.py",
    "tests/test_serve.py",
    "tests/test_serve_durable.py",
    "tests/test_slo.py",
    "tests/test_store.py",
    "tests/test_stream_qos.py",
    "tests/test_storex.py",
    "tests/test_subs.py",
    "tests/test_threads.py",
)


def _gate(
    name: str, argv: "list[str]", env: "dict[str, str] | None" = None
) -> bool:
    print(f"check_all: [{name}] {' '.join(argv)}", flush=True)
    run_env = None
    if env:
        run_env = dict(os.environ)
        run_env.update(env)
    proc = subprocess.run(
        [sys.executable, *argv], cwd=REPO_ROOT, timeout=1800, env=run_env
    )
    ok = proc.returncode == 0
    print(f"check_all: [{name}] {'ok' if ok else f'FAILED (exit {proc.returncode})'}")
    return ok


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.check_all", description="run every repo-native static gate"
    )
    ap.add_argument(
        "--san", action="store_true",
        help="run the full sanitizer build + native tests, not just the probe",
    )
    ap.add_argument(
        "--lockdep", action="store_true",
        help="re-run the lock-heavy tier-1 test files under IPC_LOCKDEP=1 "
        "(strict runtime lock-order witness; any inversion fails the gate)",
    )
    args = ap.parse_args(argv)

    ok = _gate("ipclint", ["-m", "tools.ipclint", "ipc_proofs_tpu", "tools"])

    artifacts = sorted(str(p.name) for p in REPO_ROOT.glob("BENCH_*.json"))
    if artifacts:
        ok &= _gate("bench-schema", ["tools/check_bench_schema.py", *artifacts])
    else:
        print("check_all: [bench-schema] no BENCH_*.json artifacts — skipped")

    if args.san:
        ok &= _gate("sanitizer", ["-m", "tools.build_native_san"])
    else:
        from tools.build_native_san import probe_toolchain

        available, detail = probe_toolchain()
        if available:
            print("check_all: [sanitizer] toolchain available (probe compiled+ran)")
        else:
            print(f"check_all: [sanitizer] SKIP ({detail})")

    if args.lockdep:
        present = [f for f in LOCKDEP_TEST_FILES if (REPO_ROOT / f).exists()]
        ok &= _gate(
            "lockdep",
            [
                "-m", "pytest", *present, "-q", "-m", "not slow",
                "-p", "no:cacheprovider", "-p", "no:randomly",
            ],
            env={"IPC_LOCKDEP": "1", "JAX_PLATFORMS": "cpu"},
        )

    print("check_all: " + ("all gates passed" if ok else "FAILED"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
