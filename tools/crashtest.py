"""Crash-recovery harness: SIGKILL the range driver, resume, demand bit-identity.

The chaos invariant (tools/chaos.py) extended to process death: for every
kill point the journaled range job must resume to a final bundle
**byte-identical** to an uninterrupted run. The harness forks the REAL
driver (`generate_event_proofs_for_range_pipelined` with ``job_dir``) as a
child process and kills it via the journal writer's env fault hook
(`ipc_proofs_tpu.jobs.journal.JournalWriter`):

- ``IPC_JOURNAL_CRASH_AT=N`` — SIGKILL at the N-th journal append,
  *after* the record is fully fsync'd (chunk-boundary kill);
- ``+ IPC_JOURNAL_CRASH_TORN=K`` — SIGKILL after only the first K bytes
  of the frame reach disk (torn mid-record write — the resume must
  discard the tail and regenerate that chunk).

A real ``os.kill(getpid(), SIGKILL)``: no destructors, no atexit, no
buffered-file flush — exactly a preemption or OOM kill. The parent
observes rc ``-SIGKILL``, re-runs the child with the same job dir and no
crash env, and compares the final bundle bytes against the reference.

Compaction kills (``--compaction`` / `run_compaction_grid`): with
``IPC_JOURNAL_COMPACT_BYTES=1`` arming auto-compaction on the first
commit, ``IPC_COMPACT_CRASH_BYTES=K`` tears the snapshot sidecar at byte
K and dies before the atomic swap (live journal must be untouched), and
``IPC_COMPACT_CRASH_POST=1`` dies right after ``os.replace`` (the
journal IS the snapshot). Every point must resume byte-identical.

Usage:
    python tools/crashtest.py SEED [--points N] [--pairs P] [--chunk-size C]
                                   [--record-workers W] [--quick]

Importable: `run_grid(base_seed, ...)` backs tests/test_crash_recovery.py
(pinned seeds) and the `tools/soak.py` crash phase. The ``--child``
entrypoint is the forked driver — not for interactive use.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

SIG, SUBNET, ACTOR = "NewTopDownMessage(bytes32,uint256)", "calib-subnet-1", 1001


def _build_world(n_pairs: int, receipts: int, events: int, match_rate: float):
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec

    store, pairs, _ = build_range_world(
        n_pairs, receipts, events, match_rate,
        signature=SIG, topic1=SUBNET, actor_id=ACTOR,
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
    return store, pairs, spec


def backfill_child_main(args) -> int:
    """Forked backfill driver: deterministic world → journaled
    `BackfillEngine` job at ``--chunk-size`` epochs per window.

    The engine journals under ``--job-dir/<job-id>`` through the same
    IPJ1 writer as the range driver, so the ``IPC_JOURNAL_CRASH_AT`` /
    ``IPC_JOURNAL_CRASH_TORN`` hooks SIGKILL it at exactly the same
    commit points — window boundary or torn mid-record."""
    from ipc_proofs_tpu.backfill import BackfillEngine, local_window_runner
    from ipc_proofs_tpu.utils.metrics import Metrics

    store, pairs, spec = _build_world(
        args.pairs, args.receipts, args.events, args.match_rate
    )
    metrics = Metrics()
    engine = BackfillEngine(
        pairs,
        spec,
        local_window_runner(store, spec, metrics=metrics),
        jobs_dir=args.job_dir,
        window_size=args.chunk_size,
        metrics=metrics,
    )
    try:
        bundle = engine.submit(0, len(pairs)).result(timeout=600.0)
    finally:
        engine.close()
    tmp = args.out + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(bundle.to_json())
    os.replace(tmp, args.out)
    if args.metrics_out:
        with open(args.metrics_out, "w") as fh:
            json.dump({"counters": metrics.snapshot()["counters"]}, fh)
    return 0


def rebalance_child_main(args) -> int:
    """Forked rebalance handoff: deterministic source segments → journaled
    `RebalanceJob` (storex.replica) pushing whole segment files into a
    destination directory. The journal lives under ``--job-dir`` through
    the same IPJ1 writer as the range driver, so the
    ``IPC_JOURNAL_CRASH_AT`` / ``IPC_JOURNAL_CRASH_TORN`` hooks SIGKILL
    it at every plan/pushed/commit append boundary. ``--pairs`` is reused
    as the segment count (one block per segment via a 1-byte roll
    threshold); the final placement manifest (name → sha256 of the pushed
    file) is what the parent compares across kill points."""
    import hashlib

    from ipc_proofs_tpu.core.cid import CID
    from ipc_proofs_tpu.storex import RebalanceJob, SegmentStore
    from ipc_proofs_tpu.utils.metrics import Metrics

    src_dir = os.path.join(args.job_dir, "src")
    dest_dir = os.path.join(args.job_dir, "dest")
    os.makedirs(dest_dir, exist_ok=True)
    metrics = Metrics()
    store = SegmentStore(src_dir, owner="a", segment_max_bytes=1, metrics=metrics)
    if len(store) == 0:
        for i in range(args.pairs):
            data = (b"rebalance-%04d-" % i) * (i + 2)
            store.put(CID.hash_of(data), data)
    segments = [d["name"] for d in store.segment_files() if not d["active"]]

    def push(name: str, data: bytes) -> None:
        tmp = os.path.join(dest_dir, name + ".tmp")
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, os.path.join(dest_dir, name))

    def read_segment(name: str):
        path = store.segment_path(name)
        if path is None:
            return None
        with open(path, "rb") as fh:
            return fh.read()

    job = RebalanceJob(
        os.path.join(args.job_dir, "rebalance.journal"),
        "dest", segments, push, read_segment, metrics=metrics,
    )
    committed = job.run()
    store.close()
    placement = {}
    for name in sorted(os.listdir(dest_dir)):
        if name.endswith(".tmp"):
            continue
        with open(os.path.join(dest_dir, name), "rb") as fh:
            placement[name] = hashlib.sha256(fh.read()).hexdigest()
    tmp = args.out + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(
            {"committed": committed, "placement": placement}, fh, sort_keys=True
        )
    os.replace(tmp, args.out)
    if args.metrics_out:
        with open(args.metrics_out, "w") as fh:
            json.dump({"counters": metrics.snapshot()["counters"]}, fh)
    return 0


def stream_child_main(args) -> int:
    """Forked IPBS streamer: deterministic world → one bundle streamed
    through `BundleStreamWriter` into ``--out``, fsync'd per send.

    ``IPC_STREAM_TERM_AT_CHUNK=N`` raises SIGTERM against the process
    right after the N-th send callback lands on disk — a mid-stream
    death with a committed prefix of the IPBS frame sequence, exactly
    what a preempted serve process leaves on a client's socket. The
    parent then demands the truncated stream be DETECTABLY torn (typed
    `WitnessError` from the decoder), never a silently-short document."""
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_chunked
    from ipc_proofs_tpu.witness.stream import BundleStreamWriter
    from ipc_proofs_tpu.witness.wire import WitnessOptions
    from ipc_proofs_tpu.witness.stream import stream_bundle_doc

    store, pairs, spec = _build_world(
        args.pairs, args.receipts, args.events, args.match_rate
    )
    bundle = generate_event_proofs_for_range_chunked(
        store, pairs, spec, chunk_size=args.chunk_size
    )
    term_at = int(os.environ.get("IPC_STREAM_TERM_AT_CHUNK", "0") or 0)
    sends = 0
    fh = open(args.out, "wb")

    def sink(bufs):
        nonlocal sends
        for b in bufs:
            fh.write(bytes(b))
        fh.flush()
        os.fsync(fh.fileno())
        sends += 1
        if term_at and sends >= term_at:
            os.kill(os.getpid(), signal.SIGTERM)

    stream_bundle_doc(BundleStreamWriter(sink), bundle, WitnessOptions())
    fh.close()
    return 0


def child_main(args) -> int:
    """Forked driver: deterministic world → journaled pipelined range run.

    The world is a pure function of the shape arguments, so the crashed
    child, the resumed child, and the parent's reference all see the same
    blocks — any byte divergence is the journal's fault, never the data's.
    """
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined
    from ipc_proofs_tpu.utils.metrics import Metrics

    store, pairs, spec = _build_world(
        args.pairs, args.receipts, args.events, args.match_rate
    )
    metrics = Metrics()
    bundle = generate_event_proofs_for_range_pipelined(
        store,
        pairs,
        spec,
        chunk_size=args.chunk_size,
        metrics=metrics,
        scan_threads=2,
        record_workers=args.record_workers,
        force_pipeline=True,
        job_dir=args.job_dir,
    )
    tmp = args.out + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(bundle.to_json())
    os.replace(tmp, args.out)
    if args.metrics_out:
        with open(args.metrics_out, "w") as fh:
            json.dump({"counters": metrics.snapshot()["counters"]}, fh)
    return 0


def registry_child_main(args) -> int:
    """Forked provenance appender: a `ProvenanceRegistry` at ``--job-dir``
    receives ``--pairs`` deterministic serve records (digest, trace, CID
    set all pure functions of the index), then writes its published head
    to ``--out``.

    ``IPC_REGISTRY_CRASH_AT=N`` SIGKILLs at the N-th append after the
    frame is fully on disk (boundary kill → N+1 committed records);
    ``+ IPC_REGISTRY_CRASH_TORN=K`` persists only the first K bytes of
    that frame (torn kill → N committed records plus residue the reopen
    must truncate). A clean (resume) run reopens the crashed log —
    truncating residue, re-verifying the chain — and appends the same
    ``--pairs`` records again, so the parent knows the exact expected
    record count at every step."""
    import hashlib

    from ipc_proofs_tpu.registry import ProvenanceRegistry
    from ipc_proofs_tpu.utils.metrics import Metrics

    metrics = Metrics()
    reg = ProvenanceRegistry(args.job_dir, owner="crash", metrics=metrics)
    for i in range(args.pairs):
        digest = hashlib.sha256(f"bundle-{i}".encode()).hexdigest()
        reg.append_served(
            digest,
            trace=f"trace-{i}",
            tenant="crashtest",
            key=f"pair:{i}",
            verdict="valid",
            cids=frozenset(
                hashlib.sha256(f"cid-{i}-{j}".encode()).digest()
                for j in range(2)
            ),
            t=float(i),
        )
    head = reg.head()
    reg.close()
    tmp = args.out + ".tmp"
    with open(tmp, "w") as fh:
        json.dump({"head": head}, fh, sort_keys=True)
    os.replace(tmp, args.out)
    if args.metrics_out:
        with open(args.metrics_out, "w") as fh:
            json.dump({"counters": metrics.snapshot()["counters"]}, fh)
    return 0


def _spawn_child(
    job_dir: str,
    out: str,
    shape: dict,
    crash_at: "int | None" = None,
    torn: "int | None" = None,
    metrics_out: "str | None" = None,
    timeout_s: float = 300.0,
    extra_env: "dict | None" = None,
    backfill: bool = False,
    rebalance: bool = False,
    stream: bool = False,
    registry: bool = False,
) -> subprocess.CompletedProcess:
    cmd = [
        sys.executable, os.path.abspath(__file__), "--child",
        "--job-dir", job_dir, "--out", out,
        "--pairs", str(shape["pairs"]), "--chunk-size", str(shape["chunk_size"]),
        "--receipts", str(shape["receipts"]), "--events", str(shape["events"]),
        "--match-rate", str(shape["match_rate"]),
        "--record-workers", str(shape.get("record_workers") or 1),
    ]
    if backfill:
        cmd.append("--backfill")
    if rebalance:
        cmd.append("--rebalance")
    if stream:
        cmd.append("--stream")
    if registry:
        cmd.append("--registry")
    if metrics_out:
        cmd += ["--metrics-out", metrics_out]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["IPC_FORCE_PIPELINE"] = "1"
    for key in (
        "IPC_JOURNAL_CRASH_AT",
        "IPC_JOURNAL_CRASH_TORN",
        "IPC_JOURNAL_CRASH_SIGNAL",
        "IPC_JOURNAL_COMPACT_BYTES",
        "IPC_COMPACT_CRASH_BYTES",
        "IPC_COMPACT_CRASH_POST",
        "IPC_STREAM_TERM_AT_CHUNK",
        "IPC_REGISTRY_CRASH_AT",
        "IPC_REGISTRY_CRASH_TORN",
    ):
        env.pop(key, None)
    if crash_at is not None:
        prefix = "IPC_REGISTRY" if registry else "IPC_JOURNAL"
        env[f"{prefix}_CRASH_AT"] = str(crash_at)
        if torn is not None:
            env[f"{prefix}_CRASH_TORN"] = str(torn)
    if extra_env:
        env.update(extra_env)
    return subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=timeout_s
    )


def crash_run(
    reference: str,
    shape: dict,
    crash_at: int,
    torn: "int | None",
    workdir: str,
    tag: "str | int" = 0,
) -> dict:
    """One kill point: crash the child at ``crash_at`` (optionally torn at
    byte ``torn``), resume it, and check the final bundle bytes.

    ``tag`` must be unique per call — it keys the job dir, and a repeated
    (crash_at, torn) draw must NOT resume the earlier call's journal (a
    fully-committed job never appends, so the crash hook would never fire).
    """
    from ipc_proofs_tpu.jobs import JOBS_JOURNAL_NAME, read_journal

    job_dir = os.path.join(workdir, f"job_{tag}_at{crash_at}_torn{torn}")
    out = os.path.join(workdir, f"out_{tag}_at{crash_at}_torn{torn}.json")
    metrics_out = out + ".metrics"
    res = {"crash_at": crash_at, "torn": torn}

    crashed = _spawn_child(job_dir, out, shape, crash_at=crash_at, torn=torn)
    if crashed.returncode != -signal.SIGKILL:
        res["outcome"] = "no_crash"
        res["rc"] = crashed.returncode
        res["stderr"] = crashed.stderr[-2000:]
        return res

    # post-mortem: the journal must hold exactly the committed prefix —
    # crash_at records for a torn kill (+1 when the frame fully landed)
    jpath = os.path.join(job_dir, JOBS_JOURNAL_NAME)
    n_records, torn_tail = 0, False
    if os.path.exists(jpath):
        records, _, torn_tail = read_journal(jpath)
        n_records = len(records)
    res["records_after_crash"] = n_records
    res["torn_tail"] = torn_tail
    expect = crash_at if torn is not None else crash_at + 1
    if n_records != expect:
        res["outcome"] = "journal_mismatch"
        res["expected_records"] = expect
        return res

    resumed = _spawn_child(job_dir, out, shape, metrics_out=metrics_out)
    if resumed.returncode != 0:
        res["outcome"] = "resume_failed"
        res["rc"] = resumed.returncode
        res["stderr"] = resumed.stderr[-2000:]
        return res
    with open(out) as fh:
        final = fh.read()
    with open(metrics_out) as fh:
        counters = json.load(fh)["counters"]
    res["chunks_replayed"] = counters.get("jobs.chunks_replayed", 0)
    res["chunks_resumed"] = counters.get("range_chunks_resumed", 0)
    res["outcome"] = "identical" if final == reference else "divergent"
    if res["outcome"] == "identical" and res["chunks_replayed"] != n_records:
        res["outcome"] = "replay_miscount"  # resumed run must reuse every commit
    return res


def _find_backfill_journal(jobs_dir: str) -> "str | None":
    """The backfill engine journals under ``jobs_dir/<bf-...>/`` — one
    subdirectory per deterministic job id. Locate the journal post-mortem."""
    from ipc_proofs_tpu.jobs import JOBS_JOURNAL_NAME

    if not os.path.isdir(jobs_dir):
        return None
    for name in sorted(os.listdir(jobs_dir)):
        jpath = os.path.join(jobs_dir, name, JOBS_JOURNAL_NAME)
        if os.path.exists(jpath):
            return jpath
    return None


def backfill_crash_run(
    reference: str,
    shape: dict,
    crash_at: int,
    torn: "int | None",
    workdir: str,
    tag: "str | int" = 0,
    term: bool = False,
) -> dict:
    """One backfill kill point: kill the `BackfillEngine` child at the
    ``crash_at``-th window commit (optionally torn at byte ``torn``),
    resume it from the same jobs dir, and demand the final bundle be
    byte-identical to the reference. The resumed run must replay every
    committed window from the journal (``jobs.chunks_replayed`` at the
    journal layer, ``backfill.windows_replayed`` at the engine).

    ``term=True`` delivers SIGTERM instead of SIGKILL — the
    orchestrator-preemption flavor, landing while later windows are
    still in flight. Recovery must be indistinguishable from a SIGKILL."""
    jobs_dir = os.path.join(workdir, f"bfjob_{tag}_at{crash_at}_torn{torn}")
    out = os.path.join(workdir, f"bfout_{tag}_at{crash_at}_torn{torn}.json")
    metrics_out = out + ".metrics"
    res = {"crash_at": crash_at, "torn": torn, "signal": "TERM" if term else "KILL"}

    crashed = _spawn_child(
        jobs_dir, out, shape, crash_at=crash_at, torn=torn, backfill=True,
        extra_env={"IPC_JOURNAL_CRASH_SIGNAL": "TERM"} if term else None,
    )
    if crashed.returncode != -(signal.SIGTERM if term else signal.SIGKILL):
        res["outcome"] = "no_crash"
        res["rc"] = crashed.returncode
        res["stderr"] = crashed.stderr[-2000:]
        return res

    from ipc_proofs_tpu.jobs import read_journal

    jpath = _find_backfill_journal(jobs_dir)
    n_records, torn_tail = 0, False
    if jpath is not None:
        records, _, torn_tail = read_journal(jpath)
        n_records = len(records)
    res["records_after_crash"] = n_records
    res["torn_tail"] = torn_tail
    expect = crash_at if torn is not None else crash_at + 1
    if n_records != expect:
        res["outcome"] = "journal_mismatch"
        res["expected_records"] = expect
        return res

    resumed = _spawn_child(
        jobs_dir, out, shape, metrics_out=metrics_out, backfill=True
    )
    if resumed.returncode != 0:
        res["outcome"] = "resume_failed"
        res["rc"] = resumed.returncode
        res["stderr"] = resumed.stderr[-2000:]
        return res
    with open(out) as fh:
        final = fh.read()
    with open(metrics_out) as fh:
        counters = json.load(fh)["counters"]
    res["chunks_replayed"] = counters.get("jobs.chunks_replayed", 0)
    res["windows_replayed"] = counters.get("backfill.windows_replayed", 0)
    res["outcome"] = "identical" if final == reference else "divergent"
    if res["outcome"] == "identical" and (
        res["chunks_replayed"] != n_records
        or res["windows_replayed"] != n_records
    ):
        res["outcome"] = "replay_miscount"  # resumed run must reuse every commit
    return res


def rebalance_crash_run(
    reference: dict,
    n_segments: int,
    crash_at: int,
    torn: "int | None",
    workdir: str,
    tag: "str | int" = 0,
) -> dict:
    """One rebalance kill point: SIGKILL the `RebalanceJob` child at the
    ``crash_at``-th journal append (plan / pushed / commit boundary,
    optionally torn at byte ``torn``), resume it, and demand the final
    destination placement — file names AND bytes — match the
    uninterrupted reference, with the resume actually detected
    (``storex.rebalance_resumes``) whenever the crash left records."""
    from ipc_proofs_tpu.jobs import read_journal

    job_dir = os.path.join(workdir, f"rbjob_{tag}_at{crash_at}_torn{torn}")
    out = os.path.join(workdir, f"rbout_{tag}_at{crash_at}_torn{torn}.json")
    metrics_out = out + ".metrics"
    shape = {
        "pairs": n_segments, "chunk_size": 1,
        "receipts": 1, "events": 1, "match_rate": 0.0,
    }
    res = {"crash_at": crash_at, "torn": torn}

    crashed = _spawn_child(
        job_dir, out, shape, crash_at=crash_at, torn=torn, rebalance=True
    )
    if crashed.returncode != -signal.SIGKILL:
        res["outcome"] = "no_crash"
        res["rc"] = crashed.returncode
        res["stderr"] = crashed.stderr[-2000:]
        return res

    jpath = os.path.join(job_dir, "rebalance.journal")
    n_records = 0
    already_committed = False
    if os.path.exists(jpath):
        records, _, _torn_tail = read_journal(jpath)
        n_records = len(records)
        already_committed = any(
            isinstance(r, dict) and r.get("kind") == "commit" for r in records
        )
    res["records_after_crash"] = n_records
    expect = crash_at if torn is not None else crash_at + 1
    if n_records != expect:
        res["outcome"] = "journal_mismatch"
        res["expected_records"] = expect
        return res

    resumed = _spawn_child(
        job_dir, out, shape, metrics_out=metrics_out, rebalance=True
    )
    if resumed.returncode != 0:
        res["outcome"] = "resume_failed"
        res["rc"] = resumed.returncode
        res["stderr"] = resumed.stderr[-2000:]
        return res
    with open(out) as fh:
        final = json.load(fh)
    with open(metrics_out) as fh:
        counters = json.load(fh)["counters"]
    res["resumes"] = counters.get("storex.rebalance_resumes", 0)
    ok = final["committed"] and final["placement"] == reference["placement"]
    res["outcome"] = "identical" if ok else "divergent"
    # a crash that left records but no commit must be DETECTED as a resume;
    # a post-commit kill replays to a no-op and counts nothing
    expect_resumes = 1 if (n_records and not already_committed) else 0
    if res["outcome"] == "identical" and res["resumes"] != expect_resumes:
        res["outcome"] = "resume_miscount"  # committed prefix must be detected
    return res


def run_rebalance_grid(
    base_seed: int, n_segments: int = 3, log=lambda msg: None
) -> dict:
    """Exhaustive rebalance kill grid: every append boundary (plan, each
    pushed record, commit — ``n_segments + 2`` points) plus two seeded
    torn mid-record writes. ``ok`` iff every point crashed, resumed, and
    converged on the byte-identical reference placement."""
    with tempfile.TemporaryDirectory(prefix="crashtest_rebalance_") as workdir:
        ref_dir = os.path.join(workdir, "reference")
        ref_out = os.path.join(workdir, "reference.json")
        shape = {
            "pairs": n_segments, "chunk_size": 1,
            "receipts": 1, "events": 1, "match_rate": 0.0,
        }
        ref = _spawn_child(ref_dir, ref_out, shape, rebalance=True)
        if ref.returncode != 0:
            return {
                "ok": False, "points": 0,
                "violations": [{"outcome": "reference_failed",
                                "stderr": ref.stderr[-2000:]}],
                "counts": {},
            }
        with open(ref_out) as fh:
            reference = json.load(fh)
        if len(reference["placement"]) != n_segments:
            return {
                "ok": False, "points": 0,
                "violations": [{"outcome": "reference_incomplete",
                                "placement": reference["placement"]}],
                "counts": {},
            }

        rng = random.Random(base_seed)
        n_appends = n_segments + 2  # plan + one per segment + commit
        kill_points = [(at, None) for at in range(n_appends)]
        kill_points += [
            (rng.randrange(n_appends), rng.choice([1, 5, 11, 13, 64]))
            for _ in range(2)
        ]
        counts: "dict[str, int]" = {}
        violations = []
        for i, (crash_at, torn) in enumerate(kill_points):
            res = rebalance_crash_run(
                reference, n_segments, crash_at, torn, workdir, tag=i
            )
            counts[res["outcome"]] = counts.get(res["outcome"], 0) + 1
            if res["outcome"] != "identical":
                violations.append(res)
            log(
                f"rebalance kill at append {crash_at}"
                + (f" torn@{torn}B" if torn is not None else " (boundary)")
                + f": {res['outcome']}"
            )
    return {
        "ok": not violations,
        "points": len(kill_points),
        "kill_points": kill_points,
        "counts": counts,
        "violations": violations,
    }


def run_backfill_grid(
    base_seed: int,
    points: int = 6,
    n_pairs: int = 12,
    window_size: int = 2,
    receipts: int = 4,
    events: int = 2,
    match_rate: float = 0.2,
    log=lambda msg: None,
) -> dict:
    """Seeded kill-point grid over the backfill engine: half
    window-boundary kills, half torn mid-record writes. The reference is
    the CHUNKED RANGE DRIVER over the same world at the same chunking —
    so the grid also re-asserts the backfill/driver byte-identity law
    under crash-resume, not just on the happy path."""
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_chunked

    shape = {
        "pairs": n_pairs, "chunk_size": window_size,
        "receipts": receipts, "events": events, "match_rate": match_rate,
        "record_workers": 1,
    }
    n_windows = (n_pairs + window_size - 1) // window_size
    store, pairs, spec = _build_world(n_pairs, receipts, events, match_rate)
    reference = generate_event_proofs_for_range_chunked(
        store, pairs, spec, chunk_size=window_size
    ).to_json()

    rng = random.Random(base_seed)
    kill_points = []
    for i in range(points):
        crash_at = rng.randrange(n_windows - 1) if n_windows > 1 else 0
        if i % 2 == 0:
            kill_points.append((crash_at, None))  # window-boundary kill
        else:
            kill_points.append((crash_at, rng.choice([1, 5, 11, 13, 64, 4096])))

    counts: dict[str, int] = {}
    violations = []
    with tempfile.TemporaryDirectory(prefix="crashtest_backfill_") as workdir:
        for i, (crash_at, torn) in enumerate(kill_points):
            res = backfill_crash_run(
                reference, shape, crash_at, torn, workdir, tag=i
            )
            counts[res["outcome"]] = counts.get(res["outcome"], 0) + 1
            if res["outcome"] != "identical":
                violations.append(res)
            log(
                f"backfill kill at window {crash_at}"
                + (f" torn@{torn}B" if torn is not None else " (boundary)")
                + f": {res['outcome']}"
                + (
                    f" ({res.get('records_after_crash')} committed, "
                    f"{res.get('windows_replayed')} replayed)"
                    if "records_after_crash" in res else ""
                )
            )
    boundary = sum(1 for _, t in kill_points if t is None)
    ok = (
        not violations
        and boundary > 0
        and boundary < len(kill_points)  # both flavors exercised
    )
    return {
        "ok": ok,
        "points": len(kill_points),
        "kill_points": kill_points,
        "n_windows": n_windows,
        "counts": counts,
        "violations": violations,
    }


def sigterm_stream_run(
    reference: bytes,
    shape: dict,
    term_at: int,
    workdir: str,
    tag: "str | int" = 0,
) -> dict:
    """One mid-IPBS-stream SIGTERM: the stream child dies right after its
    ``term_at``-th send callback hits disk, leaving a committed prefix of
    the frame sequence — what a preempted serve process leaves on a
    client socket. The invariant is DETECTABILITY: the truncated bytes
    must raise a typed `WitnessError` from the client decoder (torn
    frame / open document / missing trailer), never parse as a complete
    document ("silent_partial" = violation)."""
    from ipc_proofs_tpu.witness.errors import WitnessError
    from ipc_proofs_tpu.witness.stream import decode_bundle_stream

    job_dir = os.path.join(workdir, f"stjob_{tag}_term{term_at}")
    out = os.path.join(workdir, f"stout_{tag}_term{term_at}.ipbs")
    res: dict = {"term_at": term_at}

    crashed = _spawn_child(
        job_dir, out, shape, stream=True,
        extra_env={"IPC_STREAM_TERM_AT_CHUNK": str(term_at)},
    )
    if crashed.returncode != -signal.SIGTERM:
        res["outcome"] = "no_crash"
        res["rc"] = crashed.returncode
        res["stderr"] = crashed.stderr[-2000:]
        return res
    partial = b""
    if os.path.exists(out):
        with open(out, "rb") as fh:
            partial = fh.read()
    res["partial_bytes"] = len(partial)
    res["reference_bytes"] = len(reference)
    if not partial:
        res["outcome"] = "empty_prefix"  # term_at ≥ 1 ⇒ one send committed
        return res
    if partial == reference:
        # the kill landed on the very last send: nothing was torn
        res["outcome"] = "complete_before_term"
        return res
    if not reference.startswith(partial):
        res["outcome"] = "divergent"  # the prefix itself must be honest bytes
        return res
    try:
        decode_bundle_stream(partial)
    except WitnessError as exc:
        res["outcome"] = "typed_tear"
        res["error"] = f"{type(exc).__name__}: {exc}"
        return res
    res["outcome"] = "silent_partial"  # decoder accepted a torn stream
    return res


def run_sigterm_grid(
    base_seed: int,
    n_pairs: int = 8,
    window_size: int = 2,
    receipts: int = 3,
    events: int = 2,
    match_rate: float = 0.25,
    log=lambda msg: None,
) -> dict:
    """SIGTERM (orchestrator-preemption) grid, two surfaces:

    - **in-flight backfill window**: TERM at a window-commit append while
      later windows are still un-run — resume must be byte-identical to
      the chunked-driver reference, replaying every committed window;
    - **mid-IPBS-stream**: TERM between stream sends — the committed
      prefix must decode to a typed `WitnessError`, never a document.

    ``ok`` iff every backfill point resumed identical AND every stream
    point tore typed, with at least one point per surface."""
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_chunked

    shape = {
        "pairs": n_pairs, "chunk_size": window_size,
        "receipts": receipts, "events": events, "match_rate": match_rate,
        "record_workers": 1,
    }
    n_windows = (n_pairs + window_size - 1) // window_size
    store, pairs, spec = _build_world(n_pairs, receipts, events, match_rate)
    reference = generate_event_proofs_for_range_chunked(
        store, pairs, spec, chunk_size=window_size
    ).to_json()

    rng = random.Random(base_seed)
    backfill_points = sorted(
        rng.sample(range(max(1, n_windows - 1)), k=min(2, max(1, n_windows - 1)))
    )
    counts: "dict[str, int]" = {}
    violations = []
    stream_points = []
    with tempfile.TemporaryDirectory(prefix="crashtest_sigterm_") as workdir:
        for i, crash_at in enumerate(backfill_points):
            res = backfill_crash_run(
                reference, shape, crash_at, None, workdir, tag=f"term{i}",
                term=True,
            )
            counts[res["outcome"]] = counts.get(res["outcome"], 0) + 1
            if res["outcome"] != "identical":
                violations.append(res)
            log(
                f"SIGTERM backfill at window {crash_at}: {res['outcome']}"
                + (
                    f" ({res.get('records_after_crash')} committed, "
                    f"{res.get('windows_replayed')} replayed)"
                    if "records_after_crash" in res else ""
                )
            )

        # fault-free stream reference (also proves the stream child works)
        ref_dir = os.path.join(workdir, "stream_ref")
        ref_out = os.path.join(workdir, "stream_ref.ipbs")
        ref = _spawn_child(ref_dir, ref_out, shape, stream=True)
        if ref.returncode != 0:
            violations.append(
                {"outcome": "stream_reference_failed",
                 "stderr": ref.stderr[-2000:]}
            )
        else:
            with open(ref_out, "rb") as fh:
                stream_reference = fh.read()
            stream_points = [1, 3, 5]
            for i, term_at in enumerate(stream_points):
                res = sigterm_stream_run(
                    stream_reference, shape, term_at, workdir, tag=i
                )
                counts[res["outcome"]] = counts.get(res["outcome"], 0) + 1
                if res["outcome"] != "typed_tear":
                    violations.append(res)
                log(
                    f"SIGTERM stream at send {term_at}: {res['outcome']}"
                    + (
                        f" ({res.get('partial_bytes')}/"
                        f"{res.get('reference_bytes')} bytes)"
                        if "partial_bytes" in res else ""
                    )
                )
    ok = (
        not violations
        and counts.get("identical", 0) >= 1
        and counts.get("typed_tear", 0) >= 1
    )
    return {
        "ok": ok,
        "backfill_points": backfill_points,
        "stream_points": stream_points,
        "counts": counts,
        "violations": violations,
    }


def compaction_crash_run(
    reference: str,
    shape: dict,
    mode: str,
    workdir: str,
    tag: "str | int" = 0,
    torn_bytes: int = 7,
) -> dict:
    """One kill-during-compaction point.

    The child runs with ``IPC_JOURNAL_COMPACT_BYTES=1`` so the very first
    chunk commit triggers a compaction, which the crash hook then kills:

    - ``mode="torn_tmp"``: ``IPC_COMPACT_CRASH_BYTES`` tears the snapshot
      sidecar at ``torn_bytes`` and SIGKILLs BEFORE the atomic swap — the
      live journal must be untouched (the torn sidecar is crash residue);
    - ``mode="post_swap"``: ``IPC_COMPACT_CRASH_POST`` SIGKILLs right
      AFTER ``os.replace`` — the journal now IS the snapshot and must
      replay to the same committed set.

    Either way the resumed run must reproduce the reference bundle
    byte-for-byte, and the post-crash journal must parse with no
    integrity error at any byte.
    """
    from ipc_proofs_tpu.jobs import JOBS_JOURNAL_NAME, read_journal

    job_dir = os.path.join(workdir, f"compact_{tag}_{mode}")
    out = os.path.join(workdir, f"compact_out_{tag}_{mode}.json")
    res: dict = {"mode": mode}
    extra = {"IPC_JOURNAL_COMPACT_BYTES": "1"}
    if mode == "torn_tmp":
        extra["IPC_COMPACT_CRASH_BYTES"] = str(torn_bytes)
    elif mode == "post_swap":
        extra["IPC_COMPACT_CRASH_POST"] = "1"
    else:
        raise ValueError(f"unknown compaction crash mode {mode!r}")

    crashed = _spawn_child(job_dir, out, shape, extra_env=extra)
    if crashed.returncode != -signal.SIGKILL:
        res["outcome"] = "no_crash"
        res["rc"] = crashed.returncode
        res["stderr"] = crashed.stderr[-2000:]
        return res

    jpath = os.path.join(job_dir, JOBS_JOURNAL_NAME)
    try:
        records, _, torn_tail = read_journal(jpath)
    except Exception as exc:  # fail-soft: a corrupt journal is the grid's FINDING, reported as a violation, not a harness crash
        res["outcome"] = "journal_corrupt"
        res["error"] = f"{type(exc).__name__}: {exc}"
        return res
    res["records_after_crash"] = len(records)
    res["torn_tail"] = torn_tail
    if mode == "torn_tmp":
        # swap never happened: the torn sidecar must still be sitting there
        # and the live journal must hold the committed records untouched
        res["sidecar_left"] = os.path.exists(jpath + ".compact")
        if not res["sidecar_left"]:
            res["outcome"] = "sidecar_missing"
            return res
    if not records:
        res["outcome"] = "journal_empty"  # compaction fired after ≥1 commit
        return res

    resumed = _spawn_child(job_dir, out, shape)
    if resumed.returncode != 0:
        res["outcome"] = "resume_failed"
        res["rc"] = resumed.returncode
        res["stderr"] = resumed.stderr[-2000:]
        return res
    with open(out) as fh:
        final = fh.read()
    res["outcome"] = "identical" if final == reference else "divergent"
    return res


def run_compaction_grid(
    base_seed: int,
    n_pairs: int = 12,
    chunk_size: int = 2,
    receipts: int = 4,
    events: int = 2,
    match_rate: float = 0.2,
    log=lambda msg: None,
) -> dict:
    """Kill-during-compaction grid: torn-sidecar kills at several byte
    offsets plus the post-swap kill. ``ok`` iff every point crashed,
    left a parseable journal, resumed, and reproduced the reference."""
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined

    shape = {
        "pairs": n_pairs, "chunk_size": chunk_size,
        "receipts": receipts, "events": events, "match_rate": match_rate,
        "record_workers": 1,
    }
    store, pairs, spec = _build_world(n_pairs, receipts, events, match_rate)
    reference = generate_event_proofs_for_range_pipelined(
        store, pairs, spec, chunk_size=chunk_size, scan_threads=2,
        force_pipeline=True,
    ).to_json()

    rng = random.Random(base_seed)
    points = [
        ("torn_tmp", rng.choice([1, 5, 11])),  # inside the first frame header
        ("torn_tmp", rng.choice([13, 64, 200])),  # inside a payload
        ("post_swap", 0),
    ]
    counts: dict[str, int] = {}
    violations = []
    with tempfile.TemporaryDirectory(prefix="crashtest_compact_") as workdir:
        for i, (mode, torn_bytes) in enumerate(points):
            res = compaction_crash_run(
                reference, shape, mode, workdir, tag=i, torn_bytes=torn_bytes
            )
            counts[res["outcome"]] = counts.get(res["outcome"], 0) + 1
            if res["outcome"] != "identical":
                violations.append(res)
            log(
                f"compaction kill [{mode}"
                + (f" torn@{torn_bytes}B" if mode == "torn_tmp" else "")
                + f"]: {res['outcome']}"
            )
    return {
        "ok": not violations,
        "points": len(points),
        "kill_points": points,
        "counts": counts,
        "violations": violations,
    }


def registry_crash_run(
    shape: dict,
    crash_at: int,
    torn: "int | None",
    workdir: str,
    tag: "str | int" = 0,
) -> dict:
    """One provenance-registry kill point. The invariant is NOT
    byte-identity (the registry is append-only, not replayed) but the
    audit chain's crash contract:

    - the committed prefix is exact — ``crash_at + 1`` records for a
      boundary kill, ``crash_at`` for a torn one (residue truncatable);
    - the survivor log re-verifies: every CRC, every prev-link;
    - the resumed process reopens the SAME log and its appends extend the
      same head — the old root is a proven consistency prefix of the new.
    """
    from ipc_proofs_tpu.registry.log import read_registry_frames, verify_chain
    from ipc_proofs_tpu.registry.mmr import (
        MerkleLog,
        leaf_hash,
        verify_consistency,
    )

    job_dir = os.path.join(workdir, f"reg_{tag}")
    out = os.path.join(workdir, f"reg_out_{tag}.json")
    log_path = os.path.join(job_dir, "reg-crash.log")
    res: dict = {"crash_at": crash_at, "torn": torn}

    crashed = _spawn_child(
        job_dir, out, shape, crash_at=crash_at, torn=torn, registry=True
    )
    if crashed.returncode != -signal.SIGKILL:
        res["outcome"] = "no_crash"
        res["rc"] = crashed.returncode
        res["stderr"] = crashed.stderr[-2000:]
        return res

    # post-mortem: survivor log must hold the exact committed prefix,
    # chain-verified, with torn residue iff the kill was torn
    try:
        entries, _good, torn_tail = read_registry_frames(log_path)
        verify_chain(entries)
    except Exception as exc:  # fail-soft: any reopen failure IS the grid outcome under test
        res["outcome"] = "chain_corrupt"
        res["error"] = f"{type(exc).__name__}: {exc}"
        return res
    expect = crash_at if torn is not None else crash_at + 1
    res["records_after_crash"] = len(entries)
    res["torn_tail"] = torn_tail
    if len(entries) != expect:
        res["outcome"] = "commit_count_wrong"
        res["expected"] = expect
        return res
    if torn_tail != (torn is not None):
        res["outcome"] = "torn_flag_wrong"
        return res
    old_tree = MerkleLog([leaf_hash(p) for _rec, p, _off in entries])
    old_size, old_root = old_tree.size, old_tree.root()

    # resume: reopen (truncates residue, replays chain), append more
    resumed = _spawn_child(job_dir, out, shape, registry=True)
    if resumed.returncode != 0:
        res["outcome"] = "resume_failed"
        res["rc"] = resumed.returncode
        res["stderr"] = resumed.stderr[-2000:]
        return res
    try:
        entries2, _good2, torn2 = read_registry_frames(log_path)
        verify_chain(entries2)
    except Exception as exc:  # fail-soft: any reopen failure IS the grid outcome under test
        res["outcome"] = "post_resume_corrupt"
        res["error"] = f"{type(exc).__name__}: {exc}"
        return res
    res["records_after_resume"] = len(entries2)
    if torn2 or len(entries2) != old_size + shape["pairs"]:
        res["outcome"] = "resume_count_wrong"
        res["expected"] = old_size + shape["pairs"]
        return res
    new_tree = MerkleLog([leaf_hash(p) for _rec, p, _off in entries2])
    proof = (
        new_tree.consistency_path(old_size)
        if 0 < old_size < new_tree.size
        else []
    )
    if not verify_consistency(
        old_size, new_tree.size, old_root, new_tree.root(), proof
    ):
        res["outcome"] = "head_diverged"
        return res
    # the child's published head must match the auditor's recomputation
    with open(out) as fh:
        head = json.load(fh)["head"]
    if head["root"] != new_tree.root().hex() or head["size"] != new_tree.size:
        res["outcome"] = "head_mismatch"
        res["head"] = head
        return res
    res["outcome"] = "identical"
    return res


def run_registry_grid(
    base_seed: int,
    points: int = 8,
    n_records: int = 12,
    log=lambda msg: None,
) -> dict:
    """Seeded kill grid over the provenance registry writer: half
    boundary kills (frame fully fsync'd), half torn mid-record writes,
    kill indices drawn over the whole append range. ``ok`` iff every
    point crashed, reopened with the exact committed prefix, re-verified
    the chain, and extended the same head — and both flavors occurred."""
    shape = {
        "pairs": n_records, "chunk_size": 2, "receipts": 1, "events": 1,
        "match_rate": 0.0, "record_workers": 1,
    }
    rng = random.Random(base_seed)
    kill_points = []
    for i in range(points):
        crash_at = rng.randrange(n_records)
        if i % 2 == 0:
            kill_points.append((crash_at, None))  # boundary kill
        else:
            # torn write: tear inside the 12-byte header or the payload
            kill_points.append((crash_at, rng.choice([1, 5, 11, 13, 64, 4096])))

    counts: dict[str, int] = {}
    violations = []
    with tempfile.TemporaryDirectory(prefix="crashtest_registry_") as workdir:
        for i, (crash_at, torn) in enumerate(kill_points):
            res = registry_crash_run(shape, crash_at, torn, workdir, tag=i)
            counts[res["outcome"]] = counts.get(res["outcome"], 0) + 1
            if res["outcome"] != "identical":
                violations.append(res)
            log(
                f"registry kill at append {crash_at}"
                + (f" torn@{torn}B" if torn is not None else " (boundary)")
                + f": {res['outcome']}"
                + (
                    f" ({res.get('records_after_crash')} committed, "
                    f"{res.get('records_after_resume')} after resume)"
                    if "records_after_crash" in res else ""
                )
            )
    boundary = sum(1 for _, t in kill_points if t is None)
    ok = (
        not violations
        and boundary > 0
        and boundary < len(kill_points)  # both flavors exercised
    )
    return {
        "ok": ok,
        "points": len(kill_points),
        "kill_points": kill_points,
        "counts": counts,
        "violations": violations,
    }


def run_grid(
    base_seed: int,
    points: int = 8,
    n_pairs: int = 12,
    chunk_size: int = 2,
    receipts: int = 4,
    events: int = 2,
    match_rate: float = 0.2,
    record_workers: int = 1,
    log=lambda msg: None,
) -> dict:
    """Seeded kill-point grid: half chunk-boundary kills, half torn
    mid-record writes, kill indices drawn over the whole chunk range.
    ``ok`` iff every point crashed, resumed, and reproduced the reference
    byte-for-byte — and both kill flavors actually occurred.

    ``record_workers > 1`` kills the child while several record workers
    are committing concurrently: the journal's count-clock (serialized
    under the job lock) still fires at the N-th append, but WHICH chunk
    indices made it in is scheduling-dependent — the count-based
    post-mortem and replay checks are deliberately order-agnostic."""
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined

    shape = {
        "pairs": n_pairs, "chunk_size": chunk_size,
        "receipts": receipts, "events": events, "match_rate": match_rate,
        "record_workers": record_workers,
    }
    n_chunks = (n_pairs + chunk_size - 1) // chunk_size
    store, pairs, spec = _build_world(n_pairs, receipts, events, match_rate)
    reference = generate_event_proofs_for_range_pipelined(
        store, pairs, spec, chunk_size=chunk_size, scan_threads=2,
        record_workers=record_workers, force_pipeline=True,
    ).to_json()

    rng = random.Random(base_seed)
    kill_points = []
    for i in range(points):
        crash_at = rng.randrange(n_chunks - 1) if n_chunks > 1 else 0
        if i % 2 == 0:
            kill_points.append((crash_at, None))  # boundary kill
        else:
            # torn write: tear inside the 12-byte header or the payload
            kill_points.append((crash_at, rng.choice([1, 5, 11, 13, 64, 4096])))

    counts: dict[str, int] = {}
    violations = []
    with tempfile.TemporaryDirectory(prefix="crashtest_") as workdir:
        for i, (crash_at, torn) in enumerate(kill_points):
            res = crash_run(reference, shape, crash_at, torn, workdir, tag=i)
            counts[res["outcome"]] = counts.get(res["outcome"], 0) + 1
            if res["outcome"] != "identical":
                violations.append(res)
            log(
                f"kill at record {crash_at}"
                + (f" torn@{torn}B" if torn is not None else " (boundary)")
                + f": {res['outcome']}"
                + (
                    f" ({res.get('records_after_crash')} committed, "
                    f"{res.get('chunks_replayed')} replayed)"
                    if "records_after_crash" in res else ""
                )
            )
    boundary = sum(1 for _, t in kill_points if t is None)
    ok = (
        not violations
        and boundary > 0
        and boundary < len(kill_points)  # both flavors exercised
    )
    return {
        "ok": ok,
        "points": len(kill_points),
        "kill_points": kill_points,
        "n_chunks": n_chunks,
        "counts": counts,
        "violations": violations,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("seed", nargs="?", type=int, help="base seed for the kill grid")
    ap.add_argument("--points", type=int, default=8, help="kill points to test")
    ap.add_argument("--pairs", type=int, default=12)
    ap.add_argument("--chunk-size", type=int, default=2)
    ap.add_argument("--receipts", type=int, default=4)
    ap.add_argument("--events", type=int, default=2)
    ap.add_argument("--match-rate", type=float, default=0.2)
    ap.add_argument(
        "--record-workers", type=int, default=1,
        help="record-stage workers in the child (>1 = concurrent commits)",
    )
    ap.add_argument("--quick", action="store_true", help="fewer kill points")
    ap.add_argument(
        "--compaction", action="store_true",
        help="also run the kill-during-compaction grid (torn snapshot "
        "sidecar + post-swap kills via IPC_COMPACT_CRASH_*)",
    )
    ap.add_argument(
        "--backfill", action="store_true",
        help="run the kill grid against the backfill engine instead of "
        "the range driver (reference = chunked driver; in --child mode, "
        "selects the backfill child)",
    )
    ap.add_argument(
        "--rebalance", action="store_true",
        help="run the kill grid against the journaled segment-rebalance "
        "handoff (storex.RebalanceJob) instead of the range driver (in "
        "--child mode, selects the rebalance child)",
    )
    ap.add_argument(
        "--sigterm", action="store_true",
        help="run the SIGTERM (preemption) grid: TERM at an in-flight "
        "backfill window commit (resume must be byte-identical) and TERM "
        "mid-IPBS-stream (the torn prefix must decode to a typed error)",
    )
    ap.add_argument(
        "--registry", action="store_true",
        help="run the kill grid against the provenance registry writer "
        "(IPC_REGISTRY_CRASH_AT/TORN): reopen must truncate residue, "
        "re-verify the hash chain, and extend the same head (in --child "
        "mode, selects the registry child)",
    )
    ap.add_argument(
        "--stream", action="store_true",
        help=argparse.SUPPRESS,  # internal: selects the IPBS stream child
    )
    # --child: the forked driver entrypoint (internal)
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--job-dir", help=argparse.SUPPRESS)
    ap.add_argument("--out", help=argparse.SUPPRESS)
    ap.add_argument("--metrics-out", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.child:
        if not args.job_dir or not args.out:
            ap.error("--child needs --job-dir and --out")
        if args.rebalance:
            return rebalance_child_main(args)
        if args.stream:
            return stream_child_main(args)
        if args.registry:
            return registry_child_main(args)
        return backfill_child_main(args) if args.backfill else child_main(args)
    if args.seed is None:
        ap.error("seed is required")

    points = 4 if args.quick and args.points == 8 else args.points
    t0 = time.time()
    if args.registry:
        summary = run_registry_grid(
            args.seed, points=points, n_records=args.pairs,
            log=lambda m: print(f"[{time.time()-t0:6.1f}s] {m}", flush=True),
        )
        print(json.dumps(summary, indent=2))
        if not summary["ok"]:
            print("CRASH-RECOVERY INVARIANT VIOLATED", file=sys.stderr)
            return 1
        print("CRASH RECOVERY CLEAN")
        return 0
    if args.sigterm:
        summary = run_sigterm_grid(
            args.seed,
            log=lambda m: print(f"[{time.time()-t0:6.1f}s] {m}", flush=True),
        )
        print(json.dumps(summary, indent=2))
        if not summary["ok"]:
            print("CRASH-RECOVERY INVARIANT VIOLATED", file=sys.stderr)
            return 1
        print("CRASH RECOVERY CLEAN")
        return 0
    if args.rebalance:
        summary = run_rebalance_grid(
            args.seed, n_segments=max(1, args.pairs if args.pairs != 12 else 3),
            log=lambda m: print(f"[{time.time()-t0:6.1f}s] {m}", flush=True),
        )
        print(json.dumps(summary, indent=2))
        if not summary["ok"]:
            print("CRASH-RECOVERY INVARIANT VIOLATED", file=sys.stderr)
            return 1
        print("CRASH RECOVERY CLEAN")
        return 0
    if args.backfill:
        summary = run_backfill_grid(
            args.seed, points=points, n_pairs=args.pairs,
            window_size=args.chunk_size, receipts=args.receipts,
            events=args.events, match_rate=args.match_rate,
            log=lambda m: print(f"[{time.time()-t0:6.1f}s] {m}", flush=True),
        )
        print(json.dumps(summary, indent=2))
        if not summary["ok"]:
            print("CRASH-RECOVERY INVARIANT VIOLATED", file=sys.stderr)
            return 1
        print("CRASH RECOVERY CLEAN")
        return 0
    summary = run_grid(
        args.seed, points=points, n_pairs=args.pairs,
        chunk_size=args.chunk_size, receipts=args.receipts,
        events=args.events, match_rate=args.match_rate,
        record_workers=args.record_workers,
        log=lambda m: print(f"[{time.time()-t0:6.1f}s] {m}", flush=True),
    )
    if args.compaction:
        summary["compaction"] = run_compaction_grid(
            args.seed, n_pairs=args.pairs, chunk_size=args.chunk_size,
            receipts=args.receipts, events=args.events,
            match_rate=args.match_rate,
            log=lambda m: print(f"[{time.time()-t0:6.1f}s] {m}", flush=True),
        )
        summary["ok"] = summary["ok"] and summary["compaction"]["ok"]
    print(json.dumps(summary, indent=2))
    if not summary["ok"]:
        print("CRASH-RECOVERY INVARIANT VIOLATED", file=sys.stderr)
        return 1
    print("CRASH RECOVERY CLEAN")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
