"""Chaos differential driver: the byte-identity-or-typed-error invariant.

Runs the pipelined range driver through the REAL client stack —
`LotusClient` (retries, jitter, retryable codes) → `EndpointPool`
(failover, breakers, integrity verification) → `RpcBlockstore` — against
hermetic in-process "Lotus nodes" (`store.faults.LocalLotusSession`)
wrapped in seeded fault injectors (`FaultySession`). For every fault seed
the run must either:

- produce a bundle **byte-identical** to the fault-free reference, or
- raise a **typed error** (`IntegrityError` / `RpcError` / `RuntimeError`
  / transport errors).

A bundle that differs from the reference ("divergent") or an exception
outside the typed set ("untyped") is a real bug — most critically, a
bit-flipped block that slipped past CID verification into a witness.

Usage:
    python tools/chaos.py SEED [--runs N] [--pairs P] [--fault-rate R ...]
                               [--quick]

Importable: `tools/soak.py` registers `phase_chaos`, and
tests/test_chaos.py drives `chaos_run`/`run_grid` over a pinned seed grid.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import (
    generate_event_proofs_for_range,
    generate_event_proofs_for_range_pipelined,
)
from ipc_proofs_tpu.store.failover import EndpointPool
from ipc_proofs_tpu.store.faults import FaultPlan, FaultySession, LocalLotusSession
from ipc_proofs_tpu.store.rpc import IntegrityError, LotusClient, RpcBlockstore, RpcError
from ipc_proofs_tpu.utils.metrics import Metrics

SIG, SUBNET, ACTOR = "NewTopDownMessage(bytes32,uint256)", "calib-subnet-1", 1001

# The complete set of acceptable failure types under fault injection.
# Anything else escaping the driver is an invariant violation.
TYPED_ERRORS = (
    IntegrityError,
    RpcError,
    RuntimeError,
    ConnectionError,
    TimeoutError,
    OSError,
)


def build_world(n_pairs: int = 12, receipts_per_pair: int = 4,
                events_per_receipt: int = 2, match_rate: float = 0.2):
    """Hermetic range world + spec + fault-free reference bundle JSON."""
    store, pairs, _ = build_range_world(
        n_pairs, receipts_per_pair, events_per_receipt, match_rate,
        signature=SIG, topic1=SUBNET, actor_id=ACTOR,
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
    reference = generate_event_proofs_for_range(store, pairs, spec).to_json()
    return store, pairs, spec, reference


def chaos_run(
    store,
    pairs,
    spec,
    reference: str,
    seed: int,
    fault_rate: float = 0.2,
    n_endpoints: int = 2,
    chunk_size: int = 4,
    hedge_ms: "float | None" = None,
    max_retries: int = 3,
) -> dict:
    """One seeded chaos run; returns {"outcome": ..., ...} where outcome is
    "identical" | "typed_error" | "divergent" | "untyped_error" (the last
    two are invariant violations)."""
    metrics = Metrics()
    plans = [
        FaultPlan(seed * 101 + i, fault_rate=fault_rate) for i in range(n_endpoints)
    ]
    clients = [
        LotusClient(
            f"http://chaos-{i}",
            session=FaultySession(LocalLotusSession(store), plans[i], sleep=lambda s: None),
            metrics=metrics,
            max_retries=max_retries,
            backoff_base_s=0.0005,
            backoff_max_s=0.002,
            rng=random.Random(seed + i),
        )
        for i in range(n_endpoints)
    ]
    pool = EndpointPool(
        clients,
        breaker_threshold=3,
        breaker_reset_s=0.01,
        hedge_ms=hedge_ms,
        metrics=metrics,
    )
    rpc_store = RpcBlockstore(pool, metrics=metrics)
    try:
        bundle = generate_event_proofs_for_range_pipelined(
            rpc_store,
            pairs,
            spec,
            chunk_size=chunk_size,
            metrics=metrics,
            scan_threads=1,  # deterministic fault-draw order
            scan_retries=2,
            force_pipeline=True,
        )
    except TYPED_ERRORS as exc:
        return {
            "outcome": "typed_error",
            "error": type(exc).__name__,
            "faults": [p.snapshot() for p in plans],
            "counters": metrics.snapshot()["counters"],
        }
    except Exception as exc:  # fail-soft: an untyped escape IS the harness finding — reported as outcome=untyped_error
        return {
            "outcome": "untyped_error",
            "error": f"{type(exc).__name__}: {exc}",
            "faults": [p.snapshot() for p in plans],
        }
    finally:
        pool.close()
    outcome = "identical" if bundle.to_json() == reference else "divergent"
    return {
        "outcome": outcome,
        "faults": [p.snapshot() for p in plans],
        "counters": metrics.snapshot()["counters"],
    }


def run_grid(
    base_seed: int,
    runs: int = 20,
    fault_rates=(0.05, 0.3, 0.6),
    n_pairs: int = 12,
    log=lambda msg: None,
) -> dict:
    """Seed × fault-rate grid; returns a summary with per-outcome counts.

    ``ok`` is True iff no run was divergent or untyped AND at least one
    run in each regime occurred somewhere (identical + typed/absorbed),
    so a vacuous all-crash or all-clean grid does not silently pass."""
    store, pairs, spec, reference = build_world(n_pairs=n_pairs)
    counts = {"identical": 0, "typed_error": 0, "divergent": 0, "untyped_error": 0}
    violations = []
    total_faults = 0
    bitflips = 0
    for rate in fault_rates:
        for k in range(runs):
            seed = base_seed + k
            res = chaos_run(store, pairs, spec, reference, seed, fault_rate=rate)
            counts[res["outcome"]] += 1
            for f in res["faults"]:
                total_faults += f["faults_injected"]
                bitflips += f["by_kind"].get("bitflip", 0)
            if res["outcome"] in ("divergent", "untyped_error"):
                violations.append({"seed": seed, "fault_rate": rate, **res})
            log(
                f"chaos seed={seed} rate={rate}: {res['outcome']} "
                f"({sum(f['faults_injected'] for f in res['faults'])} faults)"
            )
    ok = (
        not violations
        and counts["identical"] > 0  # faults absorbed at least once
        and total_faults > 0  # the schedule actually injected something
    )
    return {
        "ok": ok,
        "runs": runs * len(fault_rates),
        "counts": counts,
        "total_faults_injected": total_faults,
        "bitflips_injected": bitflips,
        "violations": violations,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("seed", type=int, help="base seed for the fault grid")
    ap.add_argument("--runs", type=int, default=20, help="seeds per fault rate")
    ap.add_argument("--pairs", type=int, default=12)
    ap.add_argument(
        "--fault-rate", type=float, action="append", default=None,
        help="fault rates to sweep (repeatable; default 0.05 0.3 0.6)",
    )
    ap.add_argument("--quick", action="store_true", help="small world, fewer runs")
    args = ap.parse_args(argv)

    runs = 5 if args.quick and args.runs == 20 else args.runs
    n_pairs = 6 if args.quick else args.pairs
    rates = tuple(args.fault_rate) if args.fault_rate else (0.05, 0.3, 0.6)

    t0 = time.time()
    summary = run_grid(
        args.seed, runs=runs, fault_rates=rates, n_pairs=n_pairs,
        log=lambda m: print(f"[{time.time()-t0:6.1f}s] {m}", flush=True),
    )
    print(json.dumps(summary, indent=2))
    if not summary["ok"]:
        print("CHAOS INVARIANT VIOLATED", file=sys.stderr)
        return 1
    print("CHAOS CLEAN")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
