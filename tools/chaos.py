"""Chaos differential driver: the byte-identity-or-typed-error invariant.

Runs the pipelined range driver through the REAL client stack —
`LotusClient` (retries, jitter, retryable codes) → `EndpointPool`
(failover, breakers, integrity verification) → `RpcBlockstore` — against
hermetic in-process "Lotus nodes" (`store.faults.LocalLotusSession`)
wrapped in seeded fault injectors (`FaultySession`). For every fault seed
the run must either:

- produce a bundle **byte-identical** to the fault-free reference, or
- raise a **typed error** (`IntegrityError` / `RpcError` / `RuntimeError`
  / transport errors).

A bundle that differs from the reference ("divergent") or an exception
outside the typed set ("untyped") is a real bug — most critically, a
bit-flipped block that slipped past CID verification into a witness.

Usage:
    python tools/chaos.py SEED [--runs N] [--pairs P] [--fault-rate R ...]
                               [--quick]

Importable: `tools/soak.py` registers `phase_chaos`, and
tests/test_chaos.py drives `chaos_run`/`run_grid` over a pinned seed grid.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import random
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from ipc_proofs_tpu.cluster import (
    ClusterRouter,
    LocalShard,
    ShardClient,
    ShardUnavailable,
)
from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import (
    generate_event_proofs_for_range,
    generate_event_proofs_for_range_chunked,
    generate_event_proofs_for_range_pipelined,
)
from ipc_proofs_tpu.store.failover import EndpointPool
from ipc_proofs_tpu.store.faults import FaultPlan, FaultySession, LocalLotusSession
from ipc_proofs_tpu.store.rpc import IntegrityError, LotusClient, RpcBlockstore, RpcError
from ipc_proofs_tpu.utils.metrics import Metrics
from ipc_proofs_tpu.witness.errors import StreamAbortError
from ipc_proofs_tpu.witness.stream import BundleStreamWriter, decode_bundle_stream

SIG, SUBNET, ACTOR = "NewTopDownMessage(bytes32,uint256)", "calib-subnet-1", 1001

# The complete set of acceptable failure types under fault injection.
# Anything else escaping the driver is an invariant violation.
TYPED_ERRORS = (
    IntegrityError,
    RpcError,
    RuntimeError,
    ConnectionError,
    TimeoutError,
    OSError,
)


def build_world(n_pairs: int = 12, receipts_per_pair: int = 4,
                events_per_receipt: int = 2, match_rate: float = 0.2):
    """Hermetic range world + spec + fault-free reference bundle JSON."""
    store, pairs, _ = build_range_world(
        n_pairs, receipts_per_pair, events_per_receipt, match_rate,
        signature=SIG, topic1=SUBNET, actor_id=ACTOR,
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
    reference = generate_event_proofs_for_range(store, pairs, spec).to_json()
    return store, pairs, spec, reference


def chaos_run(
    store,
    pairs,
    spec,
    reference: str,
    seed: int,
    fault_rate: float = 0.2,
    n_endpoints: int = 2,
    chunk_size: int = 4,
    hedge_ms: "float | None" = None,
    max_retries: int = 3,
) -> dict:
    """One seeded chaos run; returns {"outcome": ..., ...} where outcome is
    "identical" | "typed_error" | "divergent" | "untyped_error" (the last
    two are invariant violations)."""
    metrics = Metrics()
    plans = [
        FaultPlan(seed * 101 + i, fault_rate=fault_rate) for i in range(n_endpoints)
    ]
    clients = [
        LotusClient(
            f"http://chaos-{i}",
            session=FaultySession(LocalLotusSession(store), plans[i], sleep=lambda s: None),
            metrics=metrics,
            max_retries=max_retries,
            backoff_base_s=0.0005,
            backoff_max_s=0.002,
            rng=random.Random(seed + i),
        )
        for i in range(n_endpoints)
    ]
    pool = EndpointPool(
        clients,
        breaker_threshold=3,
        breaker_reset_s=0.01,
        hedge_ms=hedge_ms,
        metrics=metrics,
    )
    rpc_store = RpcBlockstore(pool, metrics=metrics)
    try:
        bundle = generate_event_proofs_for_range_pipelined(
            rpc_store,
            pairs,
            spec,
            chunk_size=chunk_size,
            metrics=metrics,
            scan_threads=1,  # deterministic fault-draw order
            scan_retries=2,
            force_pipeline=True,
        )
    except TYPED_ERRORS as exc:
        return {
            "outcome": "typed_error",
            "error": type(exc).__name__,
            "faults": [p.snapshot() for p in plans],
            "counters": metrics.snapshot()["counters"],
        }
    except Exception as exc:  # fail-soft: an untyped escape IS the harness finding — reported as outcome=untyped_error
        return {
            "outcome": "untyped_error",
            "error": f"{type(exc).__name__}: {exc}",
            "faults": [p.snapshot() for p in plans],
        }
    finally:
        pool.close()
    outcome = "identical" if bundle.to_json() == reference else "divergent"
    return {
        "outcome": outcome,
        "faults": [p.snapshot() for p in plans],
        "counters": metrics.snapshot()["counters"],
    }


def run_grid(
    base_seed: int,
    runs: int = 20,
    fault_rates=(0.05, 0.3, 0.6),
    n_pairs: int = 12,
    log=lambda msg: None,
) -> dict:
    """Seed × fault-rate grid; returns a summary with per-outcome counts.

    ``ok`` is True iff no run was divergent or untyped AND at least one
    run in each regime occurred somewhere (identical + typed/absorbed),
    so a vacuous all-crash or all-clean grid does not silently pass."""
    store, pairs, spec, reference = build_world(n_pairs=n_pairs)
    counts = {"identical": 0, "typed_error": 0, "divergent": 0, "untyped_error": 0}
    violations = []
    total_faults = 0
    bitflips = 0
    for rate in fault_rates:
        for k in range(runs):
            seed = base_seed + k
            res = chaos_run(store, pairs, spec, reference, seed, fault_rate=rate)
            counts[res["outcome"]] += 1
            for f in res["faults"]:
                total_faults += f["faults_injected"]
                bitflips += f["by_kind"].get("bitflip", 0)
            if res["outcome"] in ("divergent", "untyped_error"):
                violations.append({"seed": seed, "fault_rate": rate, **res})
            log(
                f"chaos seed={seed} rate={rate}: {res['outcome']} "
                f"({sum(f['faults_injected'] for f in res['faults'])} faults)"
            )
    ok = (
        not violations
        and counts["identical"] > 0  # faults absorbed at least once
        and total_faults > 0  # the schedule actually injected something
    )
    return {
        "ok": ok,
        "runs": runs * len(fault_rates),
        "counts": counts,
        "total_faults_injected": total_faults,
        "bitflips_injected": bitflips,
        "violations": violations,
    }


# ---------------------------------------------------------------------------
# Remote shard transport chaos: the same identical-or-typed invariant,
# pushed through the CLUSTER stack — ClusterRouter scatter/gather (both
# the buffered and the cut-through streamed door) over shard HTTP with a
# seeded schedule of drops, delays, and mid-chunk-stream truncations.
# ---------------------------------------------------------------------------


class ShardFaultPlan:
    """Seeded fault schedule for one shard's HTTP transport.

    Draw kinds: ``drop`` (connection never completes), ``delay`` (slow
    but correct answer), ``truncate`` (the response dies mid-flight —
    for a chunk stream, cut at a seeded byte offset so the router sees a
    torn frame or a missing trailer)."""

    KINDS = ("drop", "delay", "truncate")

    def __init__(self, seed: int, fault_rate: float = 0.2):
        self._rng = random.Random(seed)
        self.fault_rate = fault_rate
        self.faults_injected = 0
        self.by_kind: "dict[str, int]" = {}

    def draw(self) -> "str | None":
        if self._rng.random() >= self.fault_rate:
            return None
        kind = self._rng.choice(self.KINDS)
        self.faults_injected += 1
        self.by_kind[kind] = self.by_kind.get(kind, 0) + 1
        return kind

    def cut_point(self, n: int) -> int:
        # land INSIDE the stream (never 0 = before the magic, never n =
        # clean EOF at the trailer) so the relay must detect the tear
        return self._rng.randrange(1, n) if n > 1 else 0

    def snapshot(self) -> dict:
        return {
            "faults_injected": self.faults_injected,
            "by_kind": dict(self.by_kind),
        }


class ChaosShardClient(ShardClient):
    """`ShardClient` with a seeded fault plan on every round-trip.

    Faults surface exactly the way the real transport surfaces them:
    drops and buffered-body truncations raise `ShardUnavailable` (what
    `ShardClient` maps refused/reset/short-read sockets to); a streamed
    truncation hands the router a prefix of the real chunk stream, which
    the relay must classify as torn (integrity error or missing
    trailer), never forward as a complete document."""

    def __init__(self, name, base_url, plan: ShardFaultPlan, **kw):
        super().__init__(name, base_url, **kw)
        self.plan = plan

    def _pre(self, path: str) -> None:
        kind = self.plan.draw()
        if kind == "drop":
            raise ShardUnavailable(f"shard {self.name}: chaos drop {path}")
        if kind == "delay":
            time.sleep(0.002)
        self._pending_truncate = kind == "truncate"

    def post(self, path, body):
        self._pre(path)
        if self._pending_truncate:
            raise ShardUnavailable(
                f"shard {self.name}: chaos truncated response body {path}"
            )
        return super().post(path, body)

    def post_stream(self, path, body):
        self._pre(path)
        kind, payload = super().post_stream(path, body)
        if kind != "stream" or not self._pending_truncate:
            return kind, payload
        raw = payload.read()
        try:
            payload.close()
        except OSError:
            pass
        return "stream", io.BytesIO(raw[: self.plan.cut_point(len(raw))])


def build_shard_world(n_pairs: int = 6, n_shards: int = 2):
    """Hermetic cluster world: live in-process shards + the fault-free
    chunked reference (canonical JSON)."""
    store, pairs, _ = build_range_world(
        n_pairs, 4, 2, 0.3, signature=SIG, topic1=SUBNET, actor_id=ACTOR,
    )
    spec = EventProofSpec(
        event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR
    )
    shards = [
        LocalShard(f"s{i}", store, pairs, spec).start() for i in range(n_shards)
    ]
    reference = json.dumps(
        generate_event_proofs_for_range_chunked(
            store, list(pairs), spec, chunk_size=3
        ).to_json_obj(),
        sort_keys=True,
    )
    return shards, pairs, reference


def chaos_shard_run(
    shards, pairs, reference: str, seed: int,
    fault_rate: float = 0.2, door: str = "buffered",
) -> dict:
    """One seeded run through a fresh router over the live shards.

    ``door`` is "buffered" (JSON scatter/gather) or "streamed" (the
    cut-through relay door, reassembled with the digest-checking client
    decoder)."""
    metrics = Metrics()
    plans = {
        s.name: ShardFaultPlan(seed * 211 + i, fault_rate=fault_rate)
        for i, s in enumerate(shards)
    }
    router = ClusterRouter(
        {s.name: ChaosShardClient(s.name, s.url, plans[s.name]) for s in shards},
        pairs, metrics=metrics, scrape_interval_s=60.0,
    )
    faults = [p.snapshot for p in plans.values()]  # bound methods: late snap
    idxs = list(range(len(pairs)))
    try:
        if door == "buffered":
            status, obj = router.generate_range(idxs, chunk_size=3)
            if status != 200:
                # the router typed the failure on the wire (503 + error)
                return {
                    "outcome": "typed_error",
                    "error": f"http {status}: {obj.get('error', '?')}",
                    "faults": [f() for f in faults],
                }
            got = json.dumps(obj["bundle"], sort_keys=True)
        else:
            chunks: "list[bytes]" = []
            out = router.generate_range(
                idxs, chunk_size=3,
                writer_factory=lambda: BundleStreamWriter(
                    lambda bufs: chunks.extend(bytes(b) for b in bufs),
                    metrics=metrics,
                ),
            )
            assert out is None
            fields = decode_bundle_stream(b"".join(chunks))
            got = json.dumps(fields["bundle"], sort_keys=True)
    except (StreamAbortError,) + TYPED_ERRORS as exc:
        return {
            "outcome": "typed_error",
            "error": type(exc).__name__,
            "faults": [f() for f in faults],
        }
    except Exception as exc:  # fail-soft: an untyped escape IS the harness finding — reported as outcome=untyped_error
        return {
            "outcome": "untyped_error",
            "error": f"{type(exc).__name__}: {exc}",
            "faults": [f() for f in faults],
        }
    finally:
        router.close()
    outcome = "identical" if got == reference else "divergent"
    return {
        "outcome": outcome,
        "faults": [f() for f in faults],
        "counters": metrics.snapshot()["counters"],
    }


def run_shard_grid(
    base_seed: int,
    runs: int = 5,
    fault_rates=(0.1, 0.3, 0.6),
    n_pairs: int = 6,
    log=lambda msg: None,
) -> dict:
    """Seed × fault-rate × door grid over the cluster transport."""
    shards, pairs, reference = build_shard_world(n_pairs=n_pairs)
    counts = {"identical": 0, "typed_error": 0, "divergent": 0,
              "untyped_error": 0}
    violations = []
    total_faults = 0
    try:
        for rate in fault_rates:
            for k in range(runs):
                for door in ("buffered", "streamed"):
                    seed = base_seed + k
                    res = chaos_shard_run(
                        shards, pairs, reference, seed,
                        fault_rate=rate, door=door,
                    )
                    counts[res["outcome"]] += 1
                    n = sum(f["faults_injected"] for f in res["faults"])
                    total_faults += n
                    if res["outcome"] in ("divergent", "untyped_error"):
                        violations.append(
                            {"seed": seed, "fault_rate": rate, "door": door,
                             **res}
                        )
                    log(
                        f"shard-chaos seed={seed} rate={rate} door={door}: "
                        f"{res['outcome']} ({n} faults)"
                    )
    finally:
        for s in shards:
            try:
                s.stop(timeout=10)
            except Exception:  # fail-soft: best-effort teardown; a shard that won't stop must not mask the grid verdict
                pass
    ok = (
        not violations
        and counts["identical"] > 0  # failover absorbed faults at least once
        and total_faults > 0  # the schedule actually injected something
    )
    return {
        "ok": ok,
        "runs": runs * len(fault_rates) * 2,
        "counts": counts,
        "total_faults_injected": total_faults,
        "violations": violations,
    }


# ---------------------------------------------------------------------------
# Slow-not-dead shard: a member that answers CORRECTLY but slowly must be
# quarantined by the router's latency-EWMA placement penalty (traffic
# steered away, `cluster.slow_quarantines` counted) without ever being
# marked dead — and every response must stay byte-identical (no faults
# are injected, only delay).
# ---------------------------------------------------------------------------


class SlowShardClient(ShardClient):
    """`ShardClient` that answers correctly after a fixed delay —
    slow-not-dead. The router must learn this through its dispatch-latency
    EWMA, not through failures (there are none)."""

    def __init__(self, name, base_url, delay_s: float, **kw):
        super().__init__(name, base_url, **kw)
        self.delay_s = delay_s
        self.calls = 0

    def post(self, path, body):
        self.calls += 1
        time.sleep(self.delay_s)
        return super().post(path, body)

    def post_stream(self, path, body):
        self.calls += 1
        time.sleep(self.delay_s)
        return super().post_stream(path, body)


def run_slow_shard_grid(
    base_seed: int,
    rounds: int = 10,
    n_pairs: int = 6,
    delay_s: float = 0.02,
    log=lambda msg: None,
) -> dict:
    """Repeated buffered range requests against a 2-shard cluster where one
    shard is slow-not-dead. Verdict requires all three:

    - every response byte-identical to the fault-free reference (delay is
      not a fault — nothing may diverge or error),
    - ``cluster.slow_quarantines`` > 0 (the latency-EWMA term, not raw
      queue depth, drove placement off the slow shard at least once),
    - the slow shard is still alive at the end (quarantine ≠ death)."""
    shards, pairs, reference = build_shard_world(n_pairs=n_pairs, n_shards=2)
    metrics = Metrics()
    slow_name = shards[0].name
    clients = {
        s.name: (
            SlowShardClient(s.name, s.url, delay_s)
            if s.name == slow_name
            else ShardClient(s.name, s.url)
        )
        for s in shards
    }
    router = ClusterRouter(
        clients,
        pairs,
        metrics=metrics,
        scrape_interval_s=60.0,
        # one queue slot ≈ 2ms of latency: a 20ms-slow shard looks ~10
        # slots deep, comfortably past the steal threshold, while its raw
        # inflight stays 0 in this sequential driver — exactly the
        # EWMA-driven quarantine signature
        steal_threshold=3,
        steal_latency_unit_s=delay_s / 10.0,
    )
    idxs = list(range(len(pairs)))
    divergent = 0
    errors = []
    try:
        for r in range(rounds):
            try:
                status, obj = router.generate_range(idxs, chunk_size=2)
            except TYPED_ERRORS as exc:
                errors.append(f"{type(exc).__name__}: {exc}")
                continue
            if status != 200:
                errors.append(f"http {status}: {obj.get('error', '?')}")
                continue
            if json.dumps(obj["bundle"], sort_keys=True) != reference:
                divergent += 1
            snap = metrics.snapshot()["counters"]
            log(
                f"slow-shard round={r}: quarantines="
                f"{snap.get('cluster.slow_quarantines', 0)} "
                f"steals={snap.get('cluster.steals', 0)}"
            )
        _, health = router.cluster_status()
        slow_alive = bool(health["ring"].get(slow_name, {}).get("alive"))
    finally:
        router.close()
        for s in shards:
            try:
                s.stop(timeout=10)
            except Exception:  # fail-soft: best-effort teardown must not mask the verdict
                pass
    counters = metrics.snapshot()["counters"]
    quarantines = counters.get("cluster.slow_quarantines", 0)
    ok = (
        divergent == 0
        and not errors
        and quarantines > 0
        and slow_alive
    )
    return {
        "ok": ok,
        "rounds": rounds,
        "divergent": divergent,
        "errors": errors,
        "slow_quarantines": quarantines,
        "steals": counters.get("cluster.steals", 0),
        "slow_shard_alive": slow_alive,
        "slow_shard_calls": clients[slow_name].calls,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("seed", type=int, help="base seed for the fault grid")
    ap.add_argument("--runs", type=int, default=20, help="seeds per fault rate")
    ap.add_argument("--pairs", type=int, default=12)
    ap.add_argument(
        "--fault-rate", type=float, action="append", default=None,
        help="fault rates to sweep (repeatable; default 0.05 0.3 0.6)",
    )
    ap.add_argument("--quick", action="store_true", help="small world, fewer runs")
    ap.add_argument(
        "--shards", action="store_true",
        help="chaos the CLUSTER shard transport (drop/delay/truncate over "
        "shard HTTP, buffered and streamed doors) instead of the RPC stack",
    )
    ap.add_argument(
        "--slow-shard", action="store_true",
        help="slow-not-dead shard: verify the router's latency-EWMA "
        "quarantine steers traffic away (cluster.slow_quarantines) while "
        "every response stays byte-identical and the shard stays alive",
    )
    args = ap.parse_args(argv)

    runs = 5 if args.quick and args.runs == 20 else args.runs
    n_pairs = 6 if args.quick else args.pairs
    rates = tuple(args.fault_rate) if args.fault_rate else (0.05, 0.3, 0.6)

    t0 = time.time()
    if args.slow_shard:
        summary = run_slow_shard_grid(
            args.seed, rounds=max(4, min(runs, 10)), n_pairs=6,
            log=lambda m: print(f"[{time.time()-t0:6.1f}s] {m}", flush=True),
        )
    elif args.shards:
        summary = run_shard_grid(
            args.seed, runs=min(runs, 5), fault_rates=rates, n_pairs=6,
            log=lambda m: print(f"[{time.time()-t0:6.1f}s] {m}", flush=True),
        )
    else:
        summary = run_grid(
            args.seed, runs=runs, fault_rates=rates, n_pairs=n_pairs,
            log=lambda m: print(f"[{time.time()-t0:6.1f}s] {m}", flush=True),
        )
    print(json.dumps(summary, indent=2))
    if not summary["ok"]:
        print("CHAOS INVARIANT VIOLATED", file=sys.stderr)
        return 1
    print("CHAOS CLEAN")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
