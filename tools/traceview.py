#!/usr/bin/env python
"""Offline summarizer for ``--trace-out`` Chrome trace JSON.

Perfetto answers "show me everything"; this answers the two questions an
operator actually asks a trace first, without leaving the terminal:

- **where did the time go** — per-stage aggregate (count / total / mean /
  max) over every complete span, plus each trace's *critical path*: the
  chain from the root through its widest child at every level, with the
  unattributed self-time gap at each hop;
- **what was slow** — the top-5 widest spans per trace.

Usage::

    python tools/traceview.py trace.json            # human summary
    python tools/traceview.py trace.json --json     # machine-readable
    python tools/traceview.py trace.json --trace ID # one trace only
    python tools/traceview.py --stitch router.json shard0.json shard1.json \
        --out fleet.json                            # merge captures by trace_id

The input is the Chrome trace-event JSON written by
``ipc_proofs_tpu.obs.export.write_chrome_trace`` (``--trace-out`` on
``generate`` / ``range`` / ``serve``); any trace-event file whose ``X``
events carry ``args.trace_id`` / ``args.span_id`` works.

``--stitch`` merges captures from DIFFERENT processes of one distributed
request (router + shards) into a single coherent file: span ids are
process-local counters, so each file's ids get a ``f<k>:`` namespace
prefix — except references to span ids that exist in another capture
(the cross-process graft points), which are remapped to THAT capture's
namespace so the subtrees join up under one root per trace.
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["load_events", "stitch", "summarize", "main"]

TOP_WIDEST = 5


def load_events(path: str) -> "list[dict]":
    """Complete (``ph == "X"``) events from a trace file; accepts both the
    ``{"traceEvents": [...]}`` object form and a bare JSON array."""
    with open(path) as fh:
        obj = json.load(fh)
    events = obj.get("traceEvents", obj) if isinstance(obj, dict) else obj
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a Chrome trace-event file")
    return [e for e in events if isinstance(e, dict) and e.get("ph") == "X"]


def stitch(event_lists: "list[list[dict]]") -> "list[dict]":
    """Merge per-process captures of one distributed request.

    ``event_lists[k]`` is one file's ``X`` events. Span ids are
    process-local counters, so ids from file ``k`` are namespaced
    ``f"f{k}:<id>"``. A ``parent_id`` resolves within the SAME trace_id
    (trace ids are globally unique; span ids are not): same-file first —
    excluding a self-reference, which can only be an adopted span whose
    wire parent happens to collide with its own local id — then the
    first OTHER file holding that span id in the trace (the
    cross-process graft point: a shard's request span parents to the
    router span id it adopted from the wire carrier). Pass the router's
    capture first so ambiguous graft points resolve toward it. Parents
    found nowhere stay verbatim (those spans remain roots).
    """
    ids_by_file: "list[dict]" = []
    for evs in event_lists:
        per_trace: "dict[str, set]" = {}
        for e in evs:
            a = e.get("args", {})
            per_trace.setdefault(a.get("trace_id"), set()).add(a.get("span_id"))
        ids_by_file.append(per_trace)

    def resolve(parent, tid, own, k: int):
        if parent is None:
            return None
        if parent != own and parent in ids_by_file[k].get(tid, ()):
            return f"f{k}:{parent}"
        for j, per in enumerate(ids_by_file):
            if j != k and parent in per.get(tid, ()):
                return f"f{j}:{parent}"
        return parent

    merged: "list[dict]" = []
    for k, evs in enumerate(event_lists):
        for e in evs:
            out = dict(e)
            args = dict(e.get("args", {}))
            sid = args.get("span_id")
            args["parent_id"] = resolve(
                args.get("parent_id"), args.get("trace_id"), sid, k
            )
            if sid is not None:
                args["span_id"] = f"f{k}:{sid}"
            args["capture"] = f"f{k}"
            out["args"] = args
            merged.append(out)
    merged.sort(key=lambda e: e.get("ts", 0))
    return merged


def _critical_path(root: dict, children: "dict[str, list[dict]]") -> "list[dict]":
    """Root → widest child at every level. ``self_us`` is the hop's
    unattributed gap: its duration minus the widest child's — time spent
    in the span itself (or in siblings the path doesn't descend into)."""
    path = []
    node = root
    while node is not None:
        kids = children.get(node["args"]["span_id"], [])
        widest = max(kids, key=lambda e: e.get("dur", 0), default=None)
        path.append(
            {
                "name": node["name"],
                "dur_us": node.get("dur", 0),
                "self_us": node.get("dur", 0)
                - (widest.get("dur", 0) if widest is not None else 0),
            }
        )
        node = widest
    return path


def summarize(events: "list[dict]", trace_id: "str | None" = None) -> dict:
    """Aggregate a list of ``X`` events (see `load_events`)."""
    if trace_id is not None:
        events = [e for e in events if e.get("args", {}).get("trace_id") == trace_id]

    stages: "dict[str, dict]" = {}
    for e in events:
        st = stages.setdefault(
            e["name"], {"count": 0, "total_us": 0, "max_us": 0}
        )
        st["count"] += 1
        st["total_us"] += e.get("dur", 0)
        st["max_us"] = max(st["max_us"], e.get("dur", 0))
    for st in stages.values():
        st["mean_us"] = round(st["total_us"] / st["count"], 1)

    by_trace: "dict[str, list[dict]]" = {}
    for e in events:
        tid = e.get("args", {}).get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(e)

    traces = []
    for tid, evs in by_trace.items():
        ids = {e["args"]["span_id"] for e in evs}
        children: "dict[str, list[dict]]" = {}
        roots = []
        for e in evs:
            parent = e["args"].get("parent_id")
            if parent in ids:
                children.setdefault(parent, []).append(e)
            else:
                roots.append(e)
        root = max(roots, key=lambda e: e.get("dur", 0), default=None)
        widest = sorted(evs, key=lambda e: e.get("dur", 0), reverse=True)
        traces.append(
            {
                "trace_id": tid,
                "spans": len(evs),
                "root": root["name"] if root is not None else None,
                "wall_us": root.get("dur", 0) if root is not None else None,
                "critical_path": (
                    _critical_path(root, children) if root is not None else []
                ),
                "widest": [
                    {"name": e["name"], "dur_us": e.get("dur", 0)}
                    for e in widest[:TOP_WIDEST]
                ],
            }
        )
    traces.sort(key=lambda t: t["wall_us"] or 0, reverse=True)
    return {"n_events": len(events), "n_traces": len(traces), "stages": stages,
            "traces": traces}


def _fmt_us(us) -> str:
    return f"{us / 1000:.2f}ms" if us is not None else "?"


def _print_human(summary: dict) -> None:
    print(f"{summary['n_events']} spans, {summary['n_traces']} traces")
    print("\nper-stage totals (busiest first):")
    order = sorted(
        summary["stages"].items(), key=lambda kv: kv[1]["total_us"], reverse=True
    )
    for name, st in order:
        print(
            f"  {name:<28} x{st['count']:<5} total {_fmt_us(st['total_us']):>10}"
            f"  mean {_fmt_us(st['mean_us']):>9}  max {_fmt_us(st['max_us']):>9}"
        )
    for t in summary["traces"]:
        print(
            f"\ntrace {t['trace_id']}  ({t['spans']} spans, "
            f"root {t['root']}, wall {_fmt_us(t['wall_us'])})"
        )
        print("  critical path:")
        for hop in t["critical_path"]:
            print(
                f"    {hop['name']:<28} {_fmt_us(hop['dur_us']):>10}"
                f"  (self {_fmt_us(hop['self_us'])})"
            )
        print(f"  top-{TOP_WIDEST} widest:")
        for w in t["widest"]:
            print(f"    {w['name']:<28} {_fmt_us(w['dur_us']):>10}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="traceview", description=__doc__.splitlines()[0]
    )
    parser.add_argument(
        "trace", nargs="+",
        help="Chrome trace JSON (--trace-out output); several with --stitch",
    )
    parser.add_argument("--trace-id", default=None, help="summarize one trace only")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    parser.add_argument(
        "--stitch", action="store_true",
        help="merge multiple per-process captures (router first, then "
        "shards) into one coherent trace before summarizing",
    )
    parser.add_argument(
        "--out", default=None, metavar="PATH",
        help="with --stitch: also write the merged trace-event JSON here",
    )
    args = parser.parse_args(argv)

    if args.stitch:
        events = stitch([load_events(p) for p in args.trace])
        if args.out:
            with open(args.out, "w") as fh:
                json.dump({"traceEvents": events}, fh)
    elif len(args.trace) > 1:
        parser.error("multiple trace files need --stitch")
        return 2
    else:
        events = load_events(args.trace[0])

    summary = summarize(events, trace_id=args.trace_id)
    if args.json:
        print(json.dumps(summary))
    else:
        _print_human(summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
