#!/usr/bin/env python
"""Offline summarizer for ``--trace-out`` Chrome trace JSON.

Perfetto answers "show me everything"; this answers the two questions an
operator actually asks a trace first, without leaving the terminal:

- **where did the time go** — per-stage aggregate (count / total / mean /
  max) over every complete span, plus each trace's *critical path*: the
  chain from the root through its widest child at every level, with the
  unattributed self-time gap at each hop;
- **what was slow** — the top-5 widest spans per trace.

Usage::

    python tools/traceview.py trace.json            # human summary
    python tools/traceview.py trace.json --json     # machine-readable
    python tools/traceview.py trace.json --trace ID # one trace only

The input is the Chrome trace-event JSON written by
``ipc_proofs_tpu.obs.export.write_chrome_trace`` (``--trace-out`` on
``generate`` / ``range`` / ``serve``); any trace-event file whose ``X``
events carry ``args.trace_id`` / ``args.span_id`` works.
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["load_events", "summarize", "main"]

TOP_WIDEST = 5


def load_events(path: str) -> "list[dict]":
    """Complete (``ph == "X"``) events from a trace file; accepts both the
    ``{"traceEvents": [...]}`` object form and a bare JSON array."""
    with open(path) as fh:
        obj = json.load(fh)
    events = obj.get("traceEvents", obj) if isinstance(obj, dict) else obj
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a Chrome trace-event file")
    return [e for e in events if isinstance(e, dict) and e.get("ph") == "X"]


def _critical_path(root: dict, children: "dict[str, list[dict]]") -> "list[dict]":
    """Root → widest child at every level. ``self_us`` is the hop's
    unattributed gap: its duration minus the widest child's — time spent
    in the span itself (or in siblings the path doesn't descend into)."""
    path = []
    node = root
    while node is not None:
        kids = children.get(node["args"]["span_id"], [])
        widest = max(kids, key=lambda e: e.get("dur", 0), default=None)
        path.append(
            {
                "name": node["name"],
                "dur_us": node.get("dur", 0),
                "self_us": node.get("dur", 0)
                - (widest.get("dur", 0) if widest is not None else 0),
            }
        )
        node = widest
    return path


def summarize(events: "list[dict]", trace_id: "str | None" = None) -> dict:
    """Aggregate a list of ``X`` events (see `load_events`)."""
    if trace_id is not None:
        events = [e for e in events if e.get("args", {}).get("trace_id") == trace_id]

    stages: "dict[str, dict]" = {}
    for e in events:
        st = stages.setdefault(
            e["name"], {"count": 0, "total_us": 0, "max_us": 0}
        )
        st["count"] += 1
        st["total_us"] += e.get("dur", 0)
        st["max_us"] = max(st["max_us"], e.get("dur", 0))
    for st in stages.values():
        st["mean_us"] = round(st["total_us"] / st["count"], 1)

    by_trace: "dict[str, list[dict]]" = {}
    for e in events:
        tid = e.get("args", {}).get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(e)

    traces = []
    for tid, evs in by_trace.items():
        ids = {e["args"]["span_id"] for e in evs}
        children: "dict[str, list[dict]]" = {}
        roots = []
        for e in evs:
            parent = e["args"].get("parent_id")
            if parent in ids:
                children.setdefault(parent, []).append(e)
            else:
                roots.append(e)
        root = max(roots, key=lambda e: e.get("dur", 0), default=None)
        widest = sorted(evs, key=lambda e: e.get("dur", 0), reverse=True)
        traces.append(
            {
                "trace_id": tid,
                "spans": len(evs),
                "root": root["name"] if root is not None else None,
                "wall_us": root.get("dur", 0) if root is not None else None,
                "critical_path": (
                    _critical_path(root, children) if root is not None else []
                ),
                "widest": [
                    {"name": e["name"], "dur_us": e.get("dur", 0)}
                    for e in widest[:TOP_WIDEST]
                ],
            }
        )
    traces.sort(key=lambda t: t["wall_us"] or 0, reverse=True)
    return {"n_events": len(events), "n_traces": len(traces), "stages": stages,
            "traces": traces}


def _fmt_us(us) -> str:
    return f"{us / 1000:.2f}ms" if us is not None else "?"


def _print_human(summary: dict) -> None:
    print(f"{summary['n_events']} spans, {summary['n_traces']} traces")
    print("\nper-stage totals (busiest first):")
    order = sorted(
        summary["stages"].items(), key=lambda kv: kv[1]["total_us"], reverse=True
    )
    for name, st in order:
        print(
            f"  {name:<28} x{st['count']:<5} total {_fmt_us(st['total_us']):>10}"
            f"  mean {_fmt_us(st['mean_us']):>9}  max {_fmt_us(st['max_us']):>9}"
        )
    for t in summary["traces"]:
        print(
            f"\ntrace {t['trace_id']}  ({t['spans']} spans, "
            f"root {t['root']}, wall {_fmt_us(t['wall_us'])})"
        )
        print("  critical path:")
        for hop in t["critical_path"]:
            print(
                f"    {hop['name']:<28} {_fmt_us(hop['dur_us']):>10}"
                f"  (self {_fmt_us(hop['self_us'])})"
            )
        print(f"  top-{TOP_WIDEST} widest:")
        for w in t["widest"]:
            print(f"    {w['name']:<28} {_fmt_us(w['dur_us']):>10}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="traceview", description=__doc__.splitlines()[0]
    )
    parser.add_argument("trace", help="Chrome trace JSON (--trace-out output)")
    parser.add_argument("--trace-id", default=None, help="summarize one trace only")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)

    summary = summarize(load_events(args.trace), trace_id=args.trace_id)
    if args.json:
        print(json.dumps(summary))
    else:
        _print_human(summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
