"""Error-taxonomy lint (``err-bare`` / ``err-swallow``).

The product is a byte-exact witness: a swallowed exception doesn't crash
the run, it silently produces a *different answer* (missing chunk,
un-demoted endpoint, un-journaled record).  So:

* ``err-bare`` — bare ``except:`` is never allowed; it catches
  ``KeyboardInterrupt``/``SystemExit`` and masks the crash-fault hooks
  the crashtest harness relies on.
* ``err-swallow`` — an ``except Exception:`` (or ``BaseException``)
  handler must either contain a ``raise`` (re-raise or conversion to a
  typed error such as ``JournalError``/``IntegrityError``/``RpcError``)
  or carry a ``# fail-soft: <why>`` justification on the ``except`` line
  (or the line directly above) explaining why degrading is correct.
"""

from __future__ import annotations

import ast

from tools.ipclint.engine import LintRun, SourceFile

__all__ = ["check"]

_BROAD = frozenset({"Exception", "BaseException"})


def _broad_name(type_node: ast.expr) -> str:
    """'Exception'/'BaseException' when the handler catches one, else ''."""
    candidates = (
        type_node.elts if isinstance(type_node, ast.Tuple) else [type_node]
    )
    for cand in candidates:
        if isinstance(cand, ast.Name) and cand.id in _BROAD:
            return cand.id
        if isinstance(cand, ast.Attribute) and cand.attr in _BROAD:
            return cand.attr
    return ""


def check(run: LintRun, sf: SourceFile) -> None:
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            run.add(sf, node.lineno, "err-bare",
                    "bare `except:` — catch a concrete type, or at minimum "
                    "`except Exception` with a fail-soft justification")
            continue
        broad = _broad_name(node.type)
        if not broad:
            continue
        if any(isinstance(n, ast.Raise) for n in ast.walk(node)):
            continue  # re-raises or converts to a typed error
        if sf.fail_soft(node.lineno):
            continue
        run.add(sf, node.lineno, "err-swallow",
                f"`except {broad}` swallows the error — re-raise, convert to "
                f"a typed error, or justify with `# fail-soft: <why>`")
