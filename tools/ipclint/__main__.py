"""CLI: ``python -m tools.ipclint [paths...]`` — exit 0 iff clean.

Defaults to linting ``ipc_proofs_tpu tools`` from the repo root, which
is the invocation pinned by ``tests/test_lint.py``.
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.ipclint import lint_paths


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.ipclint",
        description="Project-native static analysis for ipc-proofs-tpu.",
    )
    parser.add_argument(
        "paths", nargs="*", default=["ipc_proofs_tpu", "tools"],
        help="files or directories to lint (default: ipc_proofs_tpu tools)",
    )
    parser.add_argument(
        "--no-vocab", action="store_true",
        help="skip the cross-file metrics-vocabulary rules",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="emit findings as one JSON object per line "
             "(keys: rule, path, line, message)",
    )
    args = parser.parse_args(argv)

    run = lint_paths(args.paths, check_vocab=not args.no_vocab)
    for finding in run.findings:
        if args.json:
            print(json.dumps(
                {
                    "rule": finding.rule,
                    "path": finding.path,
                    "line": finding.line,
                    "message": finding.message,
                },
                sort_keys=True,
            ))
        else:
            print(finding.render())
    n_files = len(run.files)
    if run.findings:
        print(f"ipclint: {len(run.findings)} finding(s) in {n_files} file(s)",
              file=sys.stderr)
        return 1
    print(f"ipclint: clean ({n_files} files)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
