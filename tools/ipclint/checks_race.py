"""Lock-discipline race lint (``race-guard`` / ``race-unannotated``).

Convention: a shared attribute of a class is annotated at its
``__init__`` assignment (or any assignment) with a trailing
``# guarded-by: <lockattr>`` comment.  The checker then verifies every
``self.<attr>`` read or write in the class body is *lexically* inside a
``with self.<lockattr>:`` block (``threading.Lock``, ``RLock`` and
``Condition`` are all used directly as context managers in this tree),
or inside a method marked with a ``@locked`` decorator (meaning: the
caller must already hold the lock).

``__init__`` is exempt — construction happens-before publication to
other threads.  The check is lexical, not interprocedural: a closure
*defined* inside a ``with`` block counts as lock-held even though it may
run later; that approximation is deliberate (this tree's worker
closures capture the lock discipline of their definition site).

``race-unannotated`` is the discovery half: in a class that spawns
threads (creates ``threading.Thread``/``Timer`` or a
``ThreadPoolExecutor`` anywhere in its body), any attribute mutated
outside ``__init__`` from two or more distinct methods must carry a
``guarded-by`` annotation (or an explicit suppression explaining why it
is safe).
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Set

from tools.ipclint.engine import LintRun, SourceFile

__all__ = ["check"]

_SPAWNER_NAMES = frozenset({"Thread", "ThreadPoolExecutor", "Timer"})


def _terminal_name(func: ast.expr) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _spawns_threads(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) and _terminal_name(node.func) in _SPAWNER_NAMES:
            return True
    return False


def _is_locked_decorator(dec: ast.expr) -> bool:
    if isinstance(dec, ast.Call):
        dec = dec.func
    return _terminal_name(dec) == "locked" or (
        isinstance(dec, ast.Name) and dec.id == "locked"
    )


def _self_attr(node: ast.expr) -> str:
    """Return the attribute name when ``node`` is ``self.<attr>``."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


def _collect_guarded(sf: SourceFile, cls: ast.ClassDef) -> Dict[str, str]:
    """attr -> lock attr, from ``# guarded-by:`` comments on assignments."""
    guarded: Dict[str, str] = {}
    for node in ast.walk(cls):
        targets: List[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for tgt in targets:
            attr = _self_attr(tgt)
            if attr and attr not in guarded:
                lock = sf.guarded_by(node.lineno)
                if lock:
                    guarded[attr] = lock
    return guarded


def _check_method(
    run: LintRun,
    sf: SourceFile,
    cls: ast.ClassDef,
    method: ast.AST,
    guarded: Dict[str, str],
) -> None:
    all_held = any(_is_locked_decorator(d) for d in method.decorator_list)
    flagged: Set[int] = set()

    def walk(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly = set()
            for item in node.items:
                walk(item.context_expr, held)
                lock = _self_attr(item.context_expr)
                if lock:
                    newly.add(lock)
            inner = held | newly
            for child in node.body:
                walk(child, inner)
            return
        attr = _self_attr(node)
        if attr and attr in guarded:
            lock = guarded[attr]
            if not all_held and lock not in held and node.lineno not in flagged:
                flagged.add(node.lineno)
                run.add(
                    sf, node.lineno, "race-guard",
                    f"'{cls.name}.{attr}' is guarded-by '{lock}' but accessed "
                    f"outside `with self.{lock}:` in {method.name}()",
                )
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for stmt in method.body:
        walk(stmt, frozenset())


def _check_unannotated(
    run: LintRun,
    sf: SourceFile,
    cls: ast.ClassDef,
    methods: List[ast.AST],
    guarded: Dict[str, str],
) -> None:
    # A data race needs a writer and a second thread touching the same
    # attribute: flag attrs mutated outside __init__ that at least one
    # *other* method also reads or writes (each public method of a
    # thread-spawning class is a potential thread entry point).
    mutators: Dict[str, Set[str]] = {}
    accessors: Dict[str, Set[str]] = {}
    first_site: Dict[str, int] = {}
    for method in methods:
        if method.name == "__init__":
            continue
        for node in ast.walk(method):
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr and attr not in guarded:
                    mutators.setdefault(attr, set()).add(method.name)
                    first_site.setdefault(attr, node.lineno)
            attr = _self_attr(node)
            if attr and attr not in guarded:
                accessors.setdefault(attr, set()).add(method.name)
    for attr, writer_names in sorted(mutators.items()):
        touching = accessors.get(attr, set()) | writer_names
        if len(touching) >= 2:
            run.add(
                sf, first_site[attr], "race-unannotated",
                f"'{cls.name}.{attr}' is mutated in "
                f"{', '.join(sorted(writer_names))}() and touched from "
                f"{len(touching)} methods of a thread-spawning class but has "
                f"no `# guarded-by:` annotation",
            )


def check(run: LintRun, sf: SourceFile) -> None:
    for cls in ast.walk(sf.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guarded = _collect_guarded(sf, cls)
        methods = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for method in methods:
            if method.name == "__init__":
                continue
            if guarded:
                _check_method(run, sf, cls, method, guarded)
        if _spawns_threads(cls):
            _check_unannotated(run, sf, cls, methods, guarded)
