"""Metrics/trace vocabulary lint (``vocab-unknown`` / ``vocab-dead``).

``utils/metrics.py`` declares the full counter/stage/gauge/histogram
vocabulary as module-level tuples named ``*_COUNTERS`` / ``*_STAGES`` /
``*_GAUGES`` / ``*_HISTOGRAMS``.  Entries ending in ``.*`` are prefix
wildcards for per-instance families built with f-strings (e.g.
``serve.accepted.*`` covers ``f"serve.accepted.{name}"``).

* ``vocab-unknown`` — a string literal passed to ``metrics.count()`` /
  ``stage()`` / ``set_gauge()`` / ``observe()`` that matches no declared
  entry of that kind.  This is the typo catcher: a misspelt counter name
  doesn't error at runtime, it silently mints a new series that never
  shows up where dashboards look.
* ``vocab-dead`` — a declared entry no call site references: stale
  vocabulary reads as live telemetry to operators.  A wildcard entry is
  only kept alive by a *wildcard-form* (f-string) call site — a concrete
  literal under the prefix belongs in the vocabulary literally, so a
  family whose dynamic call sites were all removed goes dead even if
  stray literals still match it.

Only calls on receivers named ``metrics`` / ``_metrics`` / ``m`` are
inspected (that is the project-wide naming convention for the
:class:`Metrics` handle); non-literal name arguments are skipped.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from tools.ipclint.engine import LintRun, SourceFile

__all__ = ["check"]

_KIND_BY_METHOD = {
    "count": "counter",
    "stage": "stage",
    "set_gauge": "gauge",
    "observe": "histogram",
}
_KIND_BY_SUFFIX = {
    "_COUNTERS": "counter",
    "_STAGES": "stage",
    "_GAUGES": "gauge",
    "_HISTOGRAMS": "histogram",
}
_METRICS_RECEIVERS = frozenset({"metrics", "_metrics", "m"})


def _terminal(node: ast.expr) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _load_vocab(vocab_sf: SourceFile) -> Dict[str, List[Tuple[str, int]]]:
    """kind -> [(entry, lineno)] from module-level tuple assignments."""
    vocab: Dict[str, List[Tuple[str, int]]] = {
        k: [] for k in _KIND_BY_SUFFIX.values()
    }
    for node in vocab_sf.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        kind = next(
            (k for suf, k in _KIND_BY_SUFFIX.items() if target.id.endswith(suf)),
            None,
        )
        if kind is None or not isinstance(node.value, (ast.Tuple, ast.List)):
            continue
        for elt in node.value.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                vocab[kind].append((elt.value, elt.lineno))
    return vocab


def _name_arg(call: ast.Call) -> Optional[ast.expr]:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


def _literal_forms(node: ast.expr) -> List[str]:
    """Concrete name strings (or ``prefix.*`` patterns for f-strings)
    denoted by a metric-name expression; [] when non-literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.IfExp):  # e.g. count("a" if cond else "b")
        return _literal_forms(node.body) + _literal_forms(node.orelse)
    if isinstance(node, ast.JoinedStr):
        prefix = ""
        for part in node.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                prefix += part.value
            else:
                break
        if prefix:
            return [prefix + "*" if not prefix.endswith("*") else prefix]
        return []
    return []


def _matches(entry: str, form: str) -> bool:
    if entry.endswith(".*"):
        prefix = entry[:-1]  # "serve.accepted."
        if form.endswith("*"):
            return form[:-1] == prefix
        return form.startswith(prefix)
    if form.endswith("*"):
        return False  # f-string can only satisfy a wildcard entry
    return form == entry


def check(run: LintRun, vocab_sf: SourceFile) -> None:
    vocab = _load_vocab(vocab_sf)
    used: Dict[str, set] = {k: set() for k in vocab}

    for sf in run.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            forms_here: List[Tuple[str, List[str], int]] = []
            method = (
                node.func.attr if isinstance(node.func, ast.Attribute) else ""
            )
            if (
                method in _KIND_BY_METHOD
                and isinstance(node.func, ast.Attribute)
                and _terminal(node.func.value) in _METRICS_RECEIVERS
            ):
                arg = _name_arg(node)
                if arg is not None:
                    forms_here.append(
                        (_KIND_BY_METHOD[method], _literal_forms(arg), node.lineno)
                    )
            # PipelineStage(..., metrics_stage="...") names a stage too
            for kw in node.keywords:
                if kw.arg == "metrics_stage":
                    forms_here.append(("stage", _literal_forms(kw.value), kw.value.lineno))
            for kind, forms, lineno in forms_here:
                for form in forms:
                    hits = [e for e, _ in vocab[kind] if _matches(e, form)]
                    if hits:
                        # a wildcard entry is only kept ALIVE by a wildcard
                        # (f-string) call site: a concrete literal that
                        # happens to fall under the prefix should be
                        # declared literally, not hide behind the family
                        used[kind].update(
                            e for e in hits
                            if form.endswith("*") or not e.endswith(".*")
                        )
                    else:
                        shown = form[:-1] + "{…}" if form.endswith("*") else form
                        run.add(
                            sf, lineno, "vocab-unknown",
                            f"{kind} name '{shown}' is not declared in any "
                            f"*_{kind.upper()}S vocabulary in utils/metrics.py",
                        )

    for kind, entries in vocab.items():
        for entry, lineno in entries:
            if entry in used[kind]:
                continue
            if entry.endswith(".*"):
                run.add(
                    vocab_sf, lineno, "vocab-dead",
                    f"wildcard {kind} vocabulary entry '{entry}' has no "
                    f"matching f-string call site — declare the concrete "
                    f"names instead, or remove it",
                )
            else:
                run.add(
                    vocab_sf, lineno, "vocab-dead",
                    f"{kind} vocabulary entry '{entry}' has no call site — "
                    f"remove it or wire it up",
                )
