"""Determinism lint for the proof-path packages (core/ ipld/ state/
proofs/ crypto/) — the packages whose output is the byte-exact witness.

* ``det-wallclock`` — wall-clock reads (``time.time``, ``datetime.now``,
  …).  ``time.monotonic``/``perf_counter``/``thread_time`` are allowed:
  they measure duration, and durations only feed metrics, never witness
  bytes.
* ``det-random`` — module-level ``random.*`` use and unseeded RNG
  construction (``random.Random()`` / ``np.random.default_rng()`` with
  no seed).  Seeded constructors are fine — they are how the fault plan
  and fuzz tests stay reproducible.
* ``det-setiter`` — iterating directly over a set literal, set
  comprehension or ``set(...)``/``frozenset(...)`` call in a ``for`` or
  comprehension: set ordering is salted per process, so any such loop
  feeding witness output diverges between runs.  Wrap in ``sorted()``.
* ``det-float`` — float arithmetic: true division (except ``pathlib``
  ``/`` joins, recognised by a string-literal operand) and float
  constants used in arithmetic.  Consensus values are integers and
  bytes; floats round differently across platforms.
"""

from __future__ import annotations

import ast

from tools.ipclint.engine import LintRun, SourceFile

__all__ = ["check"]

_WALL_TIME_FNS = frozenset(
    {"time", "time_ns", "localtime", "gmtime", "ctime", "strftime", "asctime"}
)
_WALL_DT_FNS = frozenset({"now", "utcnow", "today"})
_SET_MAKERS = frozenset({"set", "frozenset"})
_SEEDED_CTORS = frozenset({"Random", "default_rng", "RandomState", "Generator"})


def _base_name(node: ast.expr) -> str:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _check_call(run: LintRun, sf: SourceFile, node: ast.Call) -> None:
    func = node.func
    if not isinstance(func, ast.Attribute):
        return
    value = func.value

    # time.time() and friends
    if isinstance(value, ast.Name) and value.id == "time" and func.attr in _WALL_TIME_FNS:
        run.add(sf, node.lineno, "det-wallclock",
                f"wall-clock read time.{func.attr}() in a proof-path package")
        return
    # datetime.now()/utcnow()/today() — on datetime/date or datetime.datetime
    if func.attr in _WALL_DT_FNS and _base_name(value) in ("datetime", "date"):
        run.add(sf, node.lineno, "det-wallclock",
                f"wall-clock read {ast.unparse(func)}() in a proof-path package")
        return

    # random module use: random.<fn>(), np.random.<fn>()
    is_random_mod = isinstance(value, ast.Name) and value.id == "random"
    is_np_random = (
        isinstance(value, ast.Attribute)
        and value.attr == "random"
        and _base_name(value) in ("np", "numpy", "jnp", "jax")
    )
    if is_random_mod or is_np_random:
        if func.attr in _SEEDED_CTORS:
            if not node.args and not node.keywords:
                run.add(sf, node.lineno, "det-random",
                        f"unseeded RNG construction {ast.unparse(func)}()")
        else:
            run.add(sf, node.lineno, "det-random",
                    f"module-level RNG call {ast.unparse(func)}() "
                    f"(process-global state; use a seeded instance)")


def _is_set_expr(node: ast.expr) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id in _SET_MAKERS
    )


def _check_iter(run: LintRun, sf: SourceFile, it: ast.expr) -> None:
    if _is_set_expr(it):
        run.add(sf, it.lineno, "det-setiter",
                "iteration order over a set is salted per process — wrap in "
                "sorted() so downstream output is byte-stable")


def _check_float(run: LintRun, sf: SourceFile, node: ast.BinOp) -> None:
    if isinstance(node.op, ast.Div):
        # pathlib's `/` join always has a string-literal operand somewhere
        for side in (node.left, node.right):
            if isinstance(side, ast.Constant) and isinstance(side.value, str):
                return
        run.add(sf, node.lineno, "det-float",
                "true division produces floats — consensus values are "
                "integers (use // or Fraction)")
        return
    for side in (node.left, node.right):
        if isinstance(side, ast.Constant) and isinstance(side.value, float):
            run.add(sf, node.lineno, "det-float",
                    "float constant in arithmetic in a proof-path package")
            return


def check(run: LintRun, sf: SourceFile) -> None:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            _check_call(run, sf, node)
        elif isinstance(node, ast.For):
            _check_iter(run, sf, node.iter)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            for gen in node.generators:
                _check_iter(run, sf, gen.iter)
        elif isinstance(node, ast.BinOp):
            _check_float(run, sf, node)
