"""Interprocedural lock-order lint (``lock-order-cycle`` /
``lock-held-blocking`` / ``lock-order-undeclared``).

The ``guarded-by:`` rules (checks_race) prove each access is under *a*
lock; this family proves the locks themselves are acquired in ONE global
order — the invariant deadlocks actually violate. Three passes:

1. **Model.** Every lock construction site is identified — ``self._x =
   threading.Lock()`` (or the lockdep ``named_lock`` / ``named_rlock`` /
   ``named_condition`` factories, or a dataclass
   ``field(default_factory=...)``) — and given a stable id:
   ``ClassName.attr`` for instance locks, ``modbase.var`` for
   module-level locks, or the literal handed to a ``named_*`` factory.
   File locks appear as ``flock:<name>`` via the ``flock_frame(path,
   "name")`` wrapper; a raw blocking ``fcntl.flock`` falls back to
   ``flock:<modbase>``, and ``LOCK_NB`` trylocks never create incoming
   edges (a trylock cannot wait, so it cannot deadlock).
2. **Extract.** Each function body is walked lexically: ``with
   self._x:`` nesting yields order edges ``outer < inner``; ``@locked``
   methods start with the instance lock held; blocking primitives
   (``time.sleep``, ``os.fsync``, subprocess waits, unbounded
   ``Queue.get`` / ``.join()`` / ``.result()``, ``urlopen``, socket
   reads) are recorded with the locks held at the call site.  Call
   edges — ``self.m()``, bare same-module calls, constructor-typed
   ``self.attr.m()``, and the ``metrics``/``_metrics``/``m`` receiver
   convention — propagate acquisitions and blocking reachability
   interprocedurally to a fixpoint.
3. **Judge.** ``lock-order-cycle``: some path acquires ``A`` before
   ``B`` and some path the reverse (or a non-reentrant lock re-enters
   itself — a guaranteed self-deadlock).  ``lock-held-blocking``: a
   blocking primitive runs (or is reachable through resolved calls)
   while any lock is held.  ``lock-order-undeclared``: an observed
   ``A < B`` nesting with no covering ``# lock-order: A < B``
   declaration — chains (``A < B < C``) declare each adjacent pair,
   coverage is transitive, and ``# lock-order: * < X`` declares ``X`` a
   terminal *leaf* lock (anything may hold while taking ``X``).
   Declarations that stop matching any observed nesting are flagged
   ``stale-suppression`` — the same can't-outlive-its-reason contract as
   ``ipclint: disable`` comments.

``Condition.wait(...)`` releases the condition it waits on, so a bare
``cond.wait()`` under ``with cond:`` is exempt — it is flagged only when
*other* locks are held across the wait.  Reporting is per ordered pair
(first site in path/line order), so one declaration covers every site
that nests the same two locks.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from tools.ipclint.engine import LintRun, SourceFile

__all__ = ["check"]

#: threading constructor terminal name -> (reentrant, is_condition)
_LOCK_CTORS = {
    "Lock": (False, False),
    "RLock": (True, False),
    "Condition": (False, True),
}
#: lockdep factory terminal name -> (reentrant, is_condition)
_NAMED_CTORS = {
    "named_lock": (False, False),
    "named_rlock": (True, False),
    "named_condition": (False, True),
}
#: receiver names conventionally bound to the Metrics handle (kept in
#: sync with checks_vocab._METRICS_RECEIVERS)
_METRICS_RECEIVERS = frozenset({"metrics", "_metrics", "m"})

_LOCK_ORDER_RE = re.compile(r"lock-order:\s*(.+)")
_ORDER_TOKEN_RE = re.compile(r"^[A-Za-z0-9_.:\-]+$")

# interprocedural blocking-reachability chains are capped for message
# sanity; the fixpoint itself is exact
_MAX_VIA_CHAIN = 3


def _terminal(node: Optional[ast.expr]) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _self_attr(node: ast.expr) -> str:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


def _is_locked_decorator(dec: ast.expr) -> bool:
    if isinstance(dec, ast.Call):
        dec = dec.func
    return _terminal(dec) == "locked"


def _str_const(node: Optional[ast.expr]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _none_const(node: ast.expr) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


@dataclass
class _Lock:
    lock_id: str
    reentrant: bool = False
    condition: bool = False


@dataclass
class _Func:
    qualname: str
    sf: SourceFile
    node: ast.AST
    owner: Optional["_Class"] = None
    module: Optional["_Module"] = None
    entry_held: FrozenSet[str] = frozenset()
    #: lock ids blocking-acquired anywhere inside (lexically or, after
    #: the fixpoint, through resolved calls)
    acquires: Set[str] = field(default_factory=set)
    #: (outer_id, inner_id, line) — outer held when inner was acquired;
    #: outer == inner records a non-reentrant self re-entry
    edges: List[Tuple[str, str, int]] = field(default_factory=list)
    #: (description, line, locks held at the site)
    blocking: List[Tuple[str, int, FrozenSet[str]]] = field(default_factory=list)
    #: (ref, line, locks held at the site)
    calls: List[Tuple[tuple, int, FrozenSet[str]]] = field(default_factory=list)
    #: resolved call targets, same order as matching `calls` entries
    resolved: List[Tuple["_Func", int, FrozenSet[str]]] = field(default_factory=list)
    #: blocking description -> call chain (qualnames) it is reached through
    blk: Dict[str, Tuple[str, ...]] = field(default_factory=dict)


@dataclass
class _Class:
    name: str
    modkey: str
    locks: Dict[str, _Lock] = field(default_factory=dict)
    attr_types: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, _Func] = field(default_factory=dict)
    entry_lock: Optional[str] = None  # lock id @locked methods start holding


@dataclass
class _Module:
    modkey: str
    sf: SourceFile
    locks: Dict[str, _Lock] = field(default_factory=dict)
    functions: Dict[str, _Func] = field(default_factory=dict)
    classes: Dict[str, _Class] = field(default_factory=dict)


def _modkey(rel: str) -> str:
    parts = rel.replace("\\", "/").split("/")
    base = parts[-1]
    if base.endswith(".py"):
        base = base[:-3]
    if base == "__init__" and len(parts) >= 2:
        base = parts[-2]
    return base


def _lock_ctor(value: ast.expr) -> Optional[Tuple[Optional[str], bool, bool]]:
    """(explicit_name, reentrant, is_condition) when ``value`` constructs
    a lock; handles ``x if c else y`` arms, ``named_*`` factories and
    dataclass ``field(default_factory=...)`` (plain or lambda)."""
    if isinstance(value, ast.IfExp):
        return _lock_ctor(value.body) or _lock_ctor(value.orelse)
    if not isinstance(value, ast.Call):
        return None
    t = _terminal(value.func)
    if t in _LOCK_CTORS:
        reent, cond = _LOCK_CTORS[t]
        return (None, reent, cond)
    if t in _NAMED_CTORS:
        reent, cond = _NAMED_CTORS[t]
        name = _str_const(value.args[0]) if value.args else None
        for kw in value.keywords:
            if kw.arg == "name":
                name = _str_const(kw.value) or name
        return (name, reent, cond)
    if t == "field":
        for kw in value.keywords:
            if kw.arg == "default_factory":
                fac = kw.value
                if isinstance(fac, ast.Lambda):
                    return _lock_ctor(fac.body)
                ft = _terminal(fac)
                if ft in _LOCK_CTORS:
                    reent, cond = _LOCK_CTORS[ft]
                    return (None, reent, cond)
    return None


def _ctor_class(value: ast.expr) -> str:
    """Class name when ``value`` is a ``ClassName(...)`` construction."""
    if isinstance(value, ast.IfExp):
        return _ctor_class(value.body) or _ctor_class(value.orelse)
    if isinstance(value, ast.Call):
        t = _terminal(value.func)
        if t and t[0].isupper() and t not in _LOCK_CTORS:
            return t
    return ""


def _blocking_call(call: ast.Call) -> Optional[Tuple[str, Optional[ast.expr]]]:
    """(description, condition_receiver) when ``call`` can block
    indefinitely; the receiver is returned for the wait family so the
    caller can apply the Condition self-release exemption."""
    func = call.func
    name = _terminal(func)
    recv = func.value if isinstance(func, ast.Attribute) else None

    def bounded_by_timeout() -> bool:
        return any(
            kw.arg == "timeout" and not _none_const(kw.value)
            for kw in call.keywords
        )

    if name == "sleep":
        return ("time.sleep()", None)
    if name == "fsync":
        return ("os.fsync()", None)
    if name in ("communicate", "check_output", "check_call"):
        return (f".{name}()", None)
    if name == "run" and _terminal(recv) == "subprocess":
        return ("subprocess.run()", None)
    if name == "urlopen":
        return ("urlopen()", None)
    if name == "recv":
        return (".recv()", None)
    if name == "accept" and not call.args:
        return (".accept()", None)
    if name == "select" and _terminal(recv) == "select":
        return ("select.select()", None)
    if name in ("wait", "wait_for"):
        positional_timeout = len(call.args) >= (1 if name == "wait" else 2)
        if positional_timeout or bounded_by_timeout():
            return None
        return (f".{name}() with no timeout", recv)
    if name == "join" and not call.args and not bounded_by_timeout():
        # str.join / os.path.join always carry arguments
        return (".join() with no timeout", None)
    if name == "result" and not call.args and not bounded_by_timeout():
        return (".result() with no timeout", None)
    if name == "get":
        if bounded_by_timeout():
            return None
        if not call.args and not call.keywords:
            return ("Queue.get() with no timeout", None)
        block_true = any(
            kw.arg == "block"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
            for kw in call.keywords
        )
        if block_true or (
            call.args
            and isinstance(call.args[0], ast.Constant)
            and call.args[0].value is True
        ):
            return ("Queue.get(block=True) with no timeout", None)
    return None


def _call_ref(call: ast.Call) -> Optional[tuple]:
    func = call.func
    if isinstance(func, ast.Name):
        return ("mod", func.id)
    if not isinstance(func, ast.Attribute):
        return None
    base = func.value
    if isinstance(base, ast.Name):
        if base.id == "self":
            return ("self", func.attr)
        if base.id in _METRICS_RECEIVERS:
            return ("class", "Metrics", func.attr)
        return None
    if (
        isinstance(base, ast.Attribute)
        and isinstance(base.value, ast.Name)
        and base.value.id == "self"
    ):
        return ("attr", base.attr, func.attr)
    if isinstance(base, ast.Call) and _terminal(base.func) == "get_metrics":
        return ("class", "Metrics", func.attr)
    return None


def _flock_arg_names(op: ast.expr) -> Set[str]:
    return {_terminal(n) for n in ast.walk(op) if isinstance(n, (ast.Name, ast.Attribute))}


def _build_class(sf: SourceFile, modkey: str, cls: ast.ClassDef) -> _Class:
    model = _Class(name=cls.name, modkey=modkey)
    # class-level lock attributes: dataclass fields and shared class attrs
    for stmt in cls.body:
        target: Optional[ast.expr] = None
        value: Optional[ast.expr] = None
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            target, value = stmt.target, stmt.value
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target, value = stmt.targets[0], stmt.value
        if isinstance(target, ast.Name) and value is not None:
            got = _lock_ctor(value)
            if got:
                name, reent, cond = got
                model.locks[target.id] = _Lock(
                    name or f"{cls.name}.{target.id}", reent, cond
                )
    # instance attributes assigned in any method (canonically __init__)
    for node in ast.walk(cls):
        target = None
        value = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value = node.target, node.value
        if target is None or value is None:
            continue
        attr = _self_attr(target)
        if not attr:
            continue
        got = _lock_ctor(value)
        if got:
            name, reent, cond = got
            model.locks.setdefault(
                attr, _Lock(name or f"{cls.name}.{attr}", reent, cond)
            )
            continue
        cname = _ctor_class(value)
        if cname:
            model.attr_types.setdefault(attr, cname)
    # the project-wide naming convention for the Metrics handle
    for conv in ("metrics", "_metrics"):
        model.attr_types.setdefault(conv, "Metrics")
    if "_lock" in model.locks:
        model.entry_lock = model.locks["_lock"].lock_id
    elif len(model.locks) == 1:
        model.entry_lock = next(iter(model.locks.values())).lock_id
    return model


def _analyze_func(func: _Func) -> None:
    owner, module = func.owner, func.module

    def lock_of_expr(expr: ast.expr) -> Optional[Tuple[str, bool, bool]]:
        """(lock_id, reentrant, blocking_acquire) when ``expr`` denotes a
        lock acquisition usable as a `with` item."""
        attr = _self_attr(expr)
        if attr and owner is not None and attr in owner.locks:
            lk = owner.locks[attr]
            return (lk.lock_id, lk.reentrant, True)
        if isinstance(expr, ast.Name) and expr.id in module.locks:
            lk = module.locks[expr.id]
            return (lk.lock_id, lk.reentrant, True)
        if isinstance(expr, ast.Call) and _terminal(expr.func) == "flock_frame":
            name = _str_const(expr.args[1]) if len(expr.args) >= 2 else None
            blocking = True
            for kw in expr.keywords:
                if kw.arg == "name":
                    name = _str_const(kw.value) or name
                if kw.arg == "blocking" and isinstance(kw.value, ast.Constant):
                    blocking = bool(kw.value.value)
            lock_id = f"flock:{name}" if name else f"flock:{module.modkey}"
            return (lock_id, False, blocking)
        return None

    def visit_call(node: ast.Call, held: Tuple[str, ...]) -> None:
        # raw fcntl.flock: LOCK_UN releases, LOCK_NB trylocks (no edge);
        # a blocking exclusive/shared flock orders after every held lock
        if _terminal(node.func) == "flock" and _terminal(
            getattr(node.func, "value", None)
        ) == "fcntl" and len(node.args) >= 2:
            names = _flock_arg_names(node.args[1])
            if "LOCK_UN" not in names and "LOCK_NB" not in names:
                lock_id = f"flock:{module.modkey}"
                for h in held:
                    func.edges.append((h, lock_id, node.lineno))
                func.acquires.add(lock_id)
            return
        blocking = _blocking_call(node)
        if blocking is not None:
            desc, cond_recv = blocking
            held_eff = held
            if cond_recv is not None:
                got = lock_of_expr(cond_recv)
                if got is not None and got[0] in held:
                    # cond.wait() releases the condition itself; only
                    # OTHER locks are held across the wait
                    held_eff = tuple(h for h in held if h != got[0])
            func.blocking.append((desc, node.lineno, frozenset(held_eff)))
        ref = _call_ref(node)
        if ref is not None:
            func.calls.append((ref, node.lineno, frozenset(held)))

    def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            cur = held
            for item in node.items:
                walk(item.context_expr, cur)
                got = lock_of_expr(item.context_expr)
                if got is None:
                    continue
                lock_id, reent, blocking_acq = got
                if lock_id in cur:
                    if not reent:
                        func.edges.append(
                            (lock_id, lock_id, item.context_expr.lineno)
                        )
                    continue
                if blocking_acq:
                    for h in cur:
                        func.edges.append((h, lock_id, item.context_expr.lineno))
                    func.acquires.add(lock_id)
                cur = cur + (lock_id,)
            for child in node.body:
                walk(child, cur)
            return
        if isinstance(node, ast.Call):
            visit_call(node, held)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # definition-site discipline, matching checks_race: a worker
            # closure defined under a lock inherits that lock's context
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                walk(child, held)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    entry = tuple(sorted(func.entry_held))
    body = getattr(func.node, "body", [])
    for stmt in body:
        walk(stmt, entry)


def _build_module(sf: SourceFile) -> _Module:
    modkey = _modkey(sf.rel)
    module = _Module(modkey=modkey, sf=sf)
    for stmt in sf.tree.body:
        target: Optional[ast.expr] = None
        value: Optional[ast.expr] = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target, value = stmt.targets[0], stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            target, value = stmt.target, stmt.value
        if isinstance(target, ast.Name) and value is not None:
            got = _lock_ctor(value)
            if got:
                name, reent, cond = got
                module.locks[target.id] = _Lock(
                    name or f"{modkey}.{target.id}", reent, cond
                )
    for cls in [n for n in ast.walk(sf.tree) if isinstance(n, ast.ClassDef)]:
        cmodel = _build_class(sf, modkey, cls)
        module.classes.setdefault(cls.name, cmodel)
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            entry: FrozenSet[str] = frozenset()
            if cmodel.entry_lock and any(
                _is_locked_decorator(d) for d in meth.decorator_list
            ):
                entry = frozenset({cmodel.entry_lock})
            fn = _Func(
                qualname=f"{cls.name}.{meth.name}",
                sf=sf,
                node=meth,
                owner=cmodel,
                module=module,
                entry_held=entry,
            )
            cmodel.methods[meth.name] = fn
    for stmt in sf.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            module.functions[stmt.name] = _Func(
                qualname=f"{modkey}.{stmt.name}",
                sf=sf,
                node=stmt,
                module=module,
            )
    return module


def _fmt_locks(held: FrozenSet[str]) -> str:
    return ", ".join(f"'{h}'" for h in sorted(held))


def _parse_declarations(
    run: LintRun,
) -> Tuple[Dict[Tuple[str, str], Tuple[SourceFile, int]], Dict[str, Tuple[SourceFile, int]]]:
    """Collect ``# lock-order: A < B [< C ...]`` and ``# lock-order: * <
    X`` declarations from every linted file."""
    pairs: Dict[Tuple[str, str], Tuple[SourceFile, int]] = {}
    leaves: Dict[str, Tuple[SourceFile, int]] = {}
    for sf in run.files:
        for line in sorted(sf.comments):
            m = _LOCK_ORDER_RE.search(sf.comments[line])
            if not m:
                continue
            tokens = [t.strip() for t in m.group(1).split("<")]
            if len(tokens) == 2 and tokens[0] == "*" and _ORDER_TOKEN_RE.match(tokens[1]):
                leaves.setdefault(tokens[1], (sf, line))
                continue
            if len(tokens) < 2 or not all(_ORDER_TOKEN_RE.match(t) for t in tokens):
                continue  # malformed: the uncovered edge keeps its finding
            for a, b in zip(tokens, tokens[1:]):
                pairs.setdefault((a, b), (sf, line))
    return pairs, leaves


def _closure_path(
    decl: Dict[Tuple[str, str], Tuple[SourceFile, int]], a: str, b: str
) -> Optional[List[Tuple[str, str]]]:
    """Shortest chain of declared pairs deriving ``a < b`` (BFS), or None."""
    succ: Dict[str, List[str]] = {}
    for (x, y) in decl:
        succ.setdefault(x, []).append(y)
    seen = {a}
    frontier: List[Tuple[str, List[Tuple[str, str]]]] = [(a, [])]
    while frontier:
        node, path = frontier.pop(0)
        for nxt in sorted(succ.get(node, ())):
            if nxt in seen:
                continue
            step = path + [(node, nxt)]
            if nxt == b:
                return step
            seen.add(nxt)
            frontier.append((nxt, step))
    return None


def check(run: LintRun) -> None:
    modules = [_build_module(sf) for sf in run.files]

    class_index: Dict[str, List[_Class]] = {}
    funcs: List[_Func] = []
    for module in modules:
        for cmodel in module.classes.values():
            class_index.setdefault(cmodel.name, []).append(cmodel)
            funcs.extend(cmodel.methods.values())
        funcs.extend(module.functions.values())

    for fn in funcs:
        _analyze_func(fn)

    def unique_class(name: str, prefer_module: _Module) -> Optional[_Class]:
        local = prefer_module.classes.get(name)
        if local is not None:
            return local
        cands = class_index.get(name, [])
        return cands[0] if len(cands) == 1 else None

    for fn in funcs:
        for ref, line, held in fn.calls:
            target: Optional[_Func] = None
            if ref[0] == "self" and fn.owner is not None:
                target = fn.owner.methods.get(ref[1])
            elif ref[0] == "mod":
                target = fn.module.functions.get(ref[1])
            elif ref[0] == "attr" and fn.owner is not None:
                cname = fn.owner.attr_types.get(ref[1])
                if cname:
                    cls = unique_class(cname, fn.module)
                    if cls is not None:
                        target = cls.methods.get(ref[2])
            elif ref[0] == "class":
                cls = unique_class(ref[1], fn.module)
                if cls is not None:
                    target = cls.methods.get(ref[2])
            if target is not None and target is not fn:
                fn.resolved.append((target, line, held))

    # fixpoint: transitive blocking-acquisition sets and blocking
    # reachability over the resolved call graph (cycles converge because
    # both propagations are monotone over finite sets)
    for fn in funcs:
        for desc, _line, _held in fn.blocking:
            fn.blk.setdefault(desc, ())
    changed = True
    while changed:
        changed = False
        for fn in funcs:
            for callee, _line, _held in fn.resolved:
                if not callee.acquires <= fn.acquires:
                    fn.acquires |= callee.acquires
                    changed = True
                for desc, path in callee.blk.items():
                    if desc not in fn.blk and len(path) < _MAX_VIA_CHAIN:
                        fn.blk[desc] = (callee.qualname,) + path
                        changed = True

    # ---- lock-held-blocking ------------------------------------------------
    flagged: Set[Tuple[str, int]] = set()
    for fn in funcs:
        for desc, line, held in fn.blocking:
            if held and (fn.sf.rel, line) not in flagged:
                flagged.add((fn.sf.rel, line))
                run.add(
                    fn.sf, line, "lock-held-blocking",
                    f"blocking {desc} while holding {_fmt_locks(held)}",
                )
        for callee, line, held in fn.resolved:
            if not held or not callee.blk:
                continue
            if callee.entry_held and held <= callee.entry_held:
                continue  # @locked callee: reported at its own site
            if (fn.sf.rel, line) in flagged:
                continue
            desc = sorted(callee.blk)[0]
            chain = " -> ".join((callee.qualname,) + callee.blk[desc])
            flagged.add((fn.sf.rel, line))
            run.add(
                fn.sf, line, "lock-held-blocking",
                f"blocking {desc} is reachable through {chain}() while "
                f"holding {_fmt_locks(held)}",
            )

    # ---- observed order edges ---------------------------------------------
    edge_sites: List[Tuple[str, str, SourceFile, int, str]] = []
    for fn in funcs:
        for outer, inner, line in fn.edges:
            edge_sites.append((outer, inner, fn.sf, line, ""))
        for callee, line, held in fn.resolved:
            for inner in sorted(callee.acquires):
                for outer in sorted(held):
                    if outer != inner:
                        edge_sites.append((
                            outer, inner, fn.sf, line,
                            f" via call to {callee.qualname}()",
                        ))

    site_of: Dict[Tuple[str, str], Tuple[SourceFile, int, str]] = {}
    for outer, inner, sf, line, note in sorted(
        edge_sites, key=lambda e: (e[0], e[1], e[2].rel, e[3], e[4])
    ):
        key = (outer, inner)
        prev = site_of.get(key)
        if prev is None or (sf.rel, line) < (prev[0].rel, prev[1]):
            site_of[key] = (sf, line, note)

    graph: Dict[str, Set[str]] = {}
    for (outer, inner) in site_of:
        graph.setdefault(outer, set()).add(inner)

    reach_memo: Dict[str, Set[str]] = {}

    def reachable_from(src: str) -> Set[str]:
        if src not in reach_memo:
            seen: Set[str] = set()
            stack = [src]
            while stack:
                node = stack.pop()
                for nxt in graph.get(node, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
            reach_memo[src] = seen
        return reach_memo[src]

    decl_pairs, leaves = _parse_declarations(run)
    used_decl: Set[Tuple[str, str]] = set()
    used_leaves: Set[str] = set()

    for (outer, inner), (sf, line, note) in sorted(
        site_of.items(), key=lambda kv: (kv[1][0].rel, kv[1][1], kv[0])
    ):
        if outer == inner:
            run.add(
                sf, line, "lock-order-cycle",
                f"non-reentrant lock '{outer}' is acquired while already "
                f"held{note} — guaranteed self-deadlock",
            )
            continue
        if outer in reachable_from(inner):
            rev = site_of.get((inner, outer))
            where = (
                f" (reverse order at {rev[0].rel}:{rev[1]})"
                if rev is not None
                else " (reverse order through intermediate locks)"
            )
            run.add(
                sf, line, "lock-order-cycle",
                f"'{inner}' is acquired while '{outer}' is held{note}, but "
                f"the opposite order also occurs{where} — ABBA deadlock",
            )
            continue
        path = _closure_path(decl_pairs, outer, inner)
        if path is not None:
            used_decl.update(path)
            continue
        if inner in leaves:
            used_leaves.add(inner)
            continue
        run.add(
            sf, line, "lock-order-undeclared",
            f"'{inner}' is acquired while '{outer}' is held{note} but no "
            f"`# lock-order: {outer} < {inner}` declaration covers it "
            f"(use `# lock-order: * < {inner}` for a leaf lock)",
        )

    # declarations must not outlive the nesting they bless
    for (a, b), (sf, line) in sorted(
        decl_pairs.items(), key=lambda kv: (kv[1][0].rel, kv[1][1], kv[0])
    ):
        if (a, b) not in used_decl:
            run.add(
                sf, line, "stale-suppression",
                f"lock-order declaration '{a} < {b}' matches no observed "
                f"acquisition order — remove it",
            )
    for leaf, (sf, line) in sorted(
        leaves.items(), key=lambda kv: (kv[1][0].rel, kv[1][1], kv[0])
    ):
        if leaf not in used_leaves:
            run.add(
                sf, line, "stale-suppression",
                f"lock-order declaration '* < {leaf}' matches no observed "
                f"acquisition order — remove it",
            )
