"""Lint engine: file discovery, comment maps, suppressions, orchestration.

The checkers work on :class:`SourceFile` objects which pair the parsed
AST with a line → comment map extracted by ``tokenize`` (comments are
invisible to ``ast``, but two of the project conventions —
``# guarded-by: <lock>`` and ``# fail-soft: <why>`` — live in comments,
as do ``# ipclint: disable=<rule>`` suppressions).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["Finding", "SourceFile", "LintRun", "lint_paths"]

#: Proof-path packages subject to the determinism rules (det-*).
DET_PACKAGES = frozenset({"core", "ipld", "state", "proofs", "crypto"})

_DISABLE_RE = re.compile(r"ipclint:\s*disable=([A-Za-z0-9_,\- ]+)")
_GUARDED_BY_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_FAIL_SOFT_RE = re.compile(r"fail-soft:\s*(\S.*)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    path: str  # display (repo-relative) path
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


class SourceFile:
    """A parsed Python file plus its comment/suppression side tables."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.tree = ast.parse(source, filename=rel)
        # line -> comment text (text after '#', stripped); extracted with
        # tokenize so '#' inside string literals is never misread.
        self.comments: Dict[int, str] = {}
        # lines whose comment is the whole line (vs trailing a statement)
        self._own_line: Set[int] = set()
        lines = source.splitlines()
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                row, col = tok.start
                self.comments[row] = tok.string.lstrip("#").strip()
                if not lines[row - 1][:col].strip():
                    self._own_line.add(row)
        # line -> set of rule ids disabled on that line
        self.disables: Dict[int, Set[str]] = {}
        for line, text in self.comments.items():
            m = _DISABLE_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self.disables[line] = rules

    @property
    def in_det_scope(self) -> bool:
        parts = Path(self.rel).parts
        for i, part in enumerate(parts[:-1]):
            if part == "ipc_proofs_tpu" and parts[i + 1] in DET_PACKAGES:
                return True
        return False

    def comment_near(self, line: int) -> str:
        """Comment text attached to ``line``: the trailing comment on the
        line itself plus a *full-line* comment directly above (convention
        for statements too long to carry a trailing annotation) — a
        trailing comment above belongs to that statement, not this one."""
        pieces = []
        if line - 1 in self._own_line:
            pieces.append(self.comments[line - 1])
        here = self.comments.get(line)
        if here is not None:
            pieces.append(here)
        return " ".join(pieces)

    def guarded_by(self, line: int) -> Optional[str]:
        m = _GUARDED_BY_RE.search(self.comment_near(line))
        return m.group(1) if m else None

    def fail_soft(self, line: int) -> Optional[str]:
        m = _FAIL_SOFT_RE.search(self.comment_near(line))
        return m.group(1) if m else None


class LintRun:
    """Collects findings across files, honouring per-line suppressions."""

    def __init__(self, known_rules: Iterable[str]):
        self.known_rules = frozenset(known_rules)
        self.files: List[SourceFile] = []
        self.findings: List[Finding] = []
        # (file, line, rule) suppressions that actually fired
        self._used: Set[Tuple[str, int, str]] = set()

    def add(self, sf: SourceFile, line: int, rule: str, message: str) -> None:
        disabled = sf.disables.get(line, ())
        if rule in disabled:
            self._used.add((sf.rel, line, rule))
            return
        self.findings.append(Finding(sf.rel, line, rule, message))

    def finish(self) -> List[Finding]:
        """Emit stale-suppression findings and return the sorted list."""
        for sf in self.files:
            for line, rules in sf.disables.items():
                for rule in sorted(rules):
                    if rule not in self.known_rules:
                        self.findings.append(Finding(
                            sf.rel, line, "stale-suppression",
                            f"disable names unknown rule '{rule}'",
                        ))
                    elif (sf.rel, line, rule) not in self._used:
                        self.findings.append(Finding(
                            sf.rel, line, "stale-suppression",
                            f"suppression of '{rule}' no longer matches "
                            f"any finding — remove it",
                        ))
        self.findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
        return self.findings


def _iter_py_files(root: Path) -> Iterable[Path]:
    if root.is_file():
        if root.suffix == ".py":
            yield root
        return
    for path in sorted(root.rglob("*.py")):
        parts = path.relative_to(root).parts
        if any(p.startswith(".") or p == "__pycache__" for p in parts):
            continue
        yield path


def _find_vocab_file(repo_root: Path, files: List[SourceFile]) -> Optional[SourceFile]:
    for sf in files:
        if sf.rel.replace("\\", "/").endswith("ipc_proofs_tpu/utils/metrics.py"):
            return sf
    # vocab may live outside the scanned paths (e.g. linting tools/ only)
    cand = repo_root / "ipc_proofs_tpu" / "utils" / "metrics.py"
    if cand.is_file():
        rel = str(cand.relative_to(repo_root))
        return SourceFile(cand, rel, cand.read_text(encoding="utf-8"))
    return None


def lint_paths(
    paths: Iterable[str],
    repo_root: Optional[str] = None,
    known_rules: Optional[Iterable[str]] = None,
    check_vocab: bool = True,
) -> LintRun:
    """Lint every ``*.py`` under ``paths`` and return the finished run.

    ``repo_root`` anchors display paths and the metrics-vocabulary
    lookup; it defaults to the parent of this package's parent (the
    repo checkout). ``check_vocab=False`` skips the cross-file
    vocabulary rules — used by fixture tests that lint snippets with
    no metrics module in scope.
    """
    from tools import ipclint as _pkg
    from tools.ipclint import (
        checks_det,
        checks_err,
        checks_lockorder,
        checks_race,
        checks_vocab,
    )

    root = Path(repo_root) if repo_root else Path(__file__).resolve().parents[2]
    run = LintRun(known_rules if known_rules is not None else _pkg.RULES)

    for raw in paths:
        p = Path(raw)
        if not p.is_absolute():
            p = root / p
        for f in _iter_py_files(p):
            try:
                rel = str(f.resolve().relative_to(root.resolve()))
            except ValueError:
                rel = str(f)
            try:
                sf = SourceFile(f, rel, f.read_text(encoding="utf-8"))
            except (SyntaxError, UnicodeDecodeError, tokenize.TokenError) as exc:
                # an unparseable file must be a loud finding, not a silent
                # skip — CI trusting "clean" needs every file analyzed
                line = getattr(exc, "lineno", None) or 1
                detail = getattr(exc, "msg", None) or str(exc)
                run.findings.append(
                    Finding(rel, line, "parse-error", f"file does not parse: {detail}")
                )
                continue
            run.files.append(sf)

    for sf in run.files:
        checks_race.check(run, sf)
        checks_err.check(run, sf)
        if sf.in_det_scope:
            checks_det.check(run, sf)

    checks_lockorder.check(run)

    if check_vocab:
        vocab_sf = _find_vocab_file(root, run.files)
        if vocab_sf is not None:
            checks_vocab.check(run, vocab_sf)

    run.finish()
    return run
