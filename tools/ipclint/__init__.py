"""ipclint — project-native static analysis for ipc-proofs-tpu.

Encodes this codebase's real invariants as machine-checked AST rules:

* ``race-guard`` / ``race-unannotated`` — lock-discipline lint over the
  ``# guarded-by: <lock>`` annotation convention (checks_race).
* ``det-wallclock`` / ``det-random`` / ``det-setiter`` / ``det-float`` —
  determinism lint for the proof-path packages (checks_det).
* ``err-bare`` / ``err-swallow`` — error-taxonomy lint: no bare
  ``except:``; ``except Exception`` must re-raise or carry a
  ``# fail-soft:`` justification (checks_err).
* ``vocab-unknown`` / ``vocab-dead`` — metrics/trace vocabulary lint
  against the declared ``*_COUNTERS``/``*_STAGES``/``*_GAUGES``/
  ``*_HISTOGRAMS`` tuples in ``utils/metrics.py`` (checks_vocab).
* ``lock-order-cycle`` / ``lock-held-blocking`` /
  ``lock-order-undeclared`` — interprocedural lock-order lint over the
  ``# lock-order: A < B`` declaration convention: the global acquisition
  graph must be acyclic, declared, and never wait on a blocking
  primitive while holding a lock (checks_lockorder).
* ``stale-suppression`` — an ``# ipclint: disable=<rule>`` comment (or
  ``# lock-order:`` declaration) that suppressed/blessed nothing.
* ``parse-error`` — a file the linter could not parse; emitted instead
  of silently skipping so CI can trust a clean run covered every file.

Run as ``python -m tools.ipclint [paths...]`` (defaults to
``ipc_proofs_tpu tools``); exits non-zero iff findings remain after
suppressions.
"""

from tools.ipclint.engine import Finding, LintRun, lint_paths

__all__ = ["Finding", "LintRun", "lint_paths", "RULES"]

#: Every rule id the suite can emit (suppression comments are validated
#: against this set so a typo'd disable is itself an error).
RULES = (
    "race-guard",
    "race-unannotated",
    "det-wallclock",
    "det-random",
    "det-setiter",
    "det-float",
    "err-bare",
    "err-swallow",
    "vocab-unknown",
    "vocab-dead",
    "lock-order-cycle",
    "lock-held-blocking",
    "lock-order-undeclared",
    "stale-suppression",
    "parse-error",
)
