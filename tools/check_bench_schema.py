#!/usr/bin/env python
"""Validate BENCH_*.json artifacts against the bench reporting schema.

Catches bench-reporting regressions at test time instead of at
artifact-consumption time: a leg that silently stops emitting a key, a
type drift (string where a number was), or a headline missing the overlap
flags. Two strictness levels:

- every artifact (any vintage) must carry the CORE keys with sane types;
- the CURRENT artifact (``--require-current`` / ``require_current=True``)
  must carry the full present-day e2e key set — the orchestrator's
  ``_E2E_SCHEMA_KEYS`` contract plus the satellite leg keys — AND pass
  the perf gates: ``pipeline_speedup_vs_serial >= 1.0`` and
  ``cluster_linearity_4shard >= 0.8``, each whenever ``host_cores > 2``
  (hosts without spare cores skip the gates with a printed reason — see
  `speedup_gate_skip_reason` / `cluster_gate_skip_reason`), plus
  ``device_linearity_Nchip >= 0.8`` whenever ``onchip_devices > 1``
  (single-device hosts skip with a printed reason — see
  `onchip_gate_skip_reason`), plus the host-shape-independent standing
  amortization gate ``standing_generations_per_tipset <=
  standing_distinct_filters`` (see `standing_gate_skip_reason`) and the
  fleet-observability overhead gate ``fleetobs_overhead_pct <= 3``
  whenever ``host_cores > 2`` (the scrape/watchdog threads time-slice
  the request loop otherwise — see `fleetobs_gate_skip_reason`; the
  companion span-stitching check IS host-shape independent), the
  same-shaped trace-spine gate ``trace_overhead_pct <= 3`` whenever
  ``host_cores > 2`` (see `trace_gate_skip_reason`), the verify-autotune
  gate — ``verify_tuned_speedup >= 1.0`` unless the tuner honestly
  recorded ``verify_autotune_scalar_only`` (see
  `verify_autotune_gate_skip_reason`) — and the backfill gates
  ``backfill_epochs_per_sec > 0`` and ``backfill_ttfc_ms <
  backfill_total_ms`` (streaming must beat completion — see
  `backfill_gate_skip_reason`), plus the zero-copy gate
  ``warm_block_bytes_copied_per_resp == 0`` (pure accounting over the
  stream writer's own counters — host-shape independent, exactly zero,
  see `zerocopy_gate_skip_reason`) and the QoS fairness gate
  ``qos_light_tenant_p99_ms <= max(10 x p50, 250ms)`` whenever
  ``host_cores > 2`` (on smaller hosts the heavy flood time-slices the
  light tenant's only cores, so the tail measures core contention, not
  queue ordering — see `qos_gate_skip_reason`), and the multi-host
  gates ``kill_recovery_ms <= 10000``, ``replica_repair_hit_rate >=
  0.99``, and ``aggregate_proofs_per_sec_2host > 0`` whenever
  ``host_cores > 2`` (on smaller hosts the shards, load clients, and
  recovery probe time-slice the same core — see
  `hostkill_gate_skip_reason`), and the overload gates
  ``goodput_ratio_at_2x >= 0.8`` and ``cancel_reclaim_pct > 0`` whenever
  ``host_cores > 2`` (on smaller hosts the 2× closed-loop clients
  time-slice the server's only cores — see `overload_gate_skip_reason`),
  and the provenance-registry gates ``registry_append_overhead_pct < 1``
  and ``fleet_delta_hit_rate > fleet_delta_baseline_hit_rate`` (both are
  same-host ratios — host-shape independent, see
  `registry_gate_skip_reason`).

Importable (``check_artifact(obj) -> list[str]`` of problems) and a CLI::

    python tools/check_bench_schema.py BENCH_*.json
    python tools/check_bench_schema.py --require-current BENCH_r07.json
"""

from __future__ import annotations

import argparse
import json
import sys

# round ≤5 artifacts are raw run-capture wrappers: the orchestrator JSON
# (when the run parsed) sits under "parsed"
_WRAPPER_KEYS = {"cmd", "rc", "tail"}

# every orchestrator artifact, any vintage, must have these
_CORE_REQUIRED = {
    "metric": str,
    "value": (int, float),
    "unit": str,
}

# known keys with their expected types; None is always allowed (legs can
# fail and the orchestrator nulls their keys honestly)
_NUM = (int, float)
_KNOWN_TYPES = {
    "platform": str,
    "devices": int,
    "host_cores": int,
    "host_cores_affinity": int,
    "scan_threads": int,
    "record_workers": int,
    "verify_workers": int,
    "effective_threads": int,
    "native_scan_threads": int,
    "pipeline_depth": int,
    "pipeline_chunk": int,
    "verify_chunk_pairs": int,
    "events_per_sec_e2e": _NUM,
    "proofs": int,
    "stages_ms": dict,
    "stages_wall_ms": dict,
    "stages_overlap": bool,
    "gen_verify_overlap": bool,
    "overlap_efficiency": _NUM,
    "serial_proofs_per_sec": _NUM,
    "serial_e2e_reps_s": list,
    "pipeline_speedup_vs_serial": _NUM,
    "e2e_policy": str,
    "e2e_reps_s": list,
    "vs_baseline": _NUM,
    "vs_native_baseline": _NUM,
    "scalar_baseline_proofs_per_sec": _NUM,
    "native_baseline_proofs_per_sec": _NUM,
    "device_mask_kernel_events_per_sec": _NUM,
    "witness_cid_kernel_per_sec": _NUM,
    "witness_cid_kernel": str,
    "serve_batched_rps": _NUM,
    "serve_sequential_rps": _NUM,
    "serve_speedup_vs_sequential": _NUM,
    "serve_concurrency": int,
    "serve_requests": int,
    "serve_p99_latency_ms": _NUM,
    "serve_mean_batch": _NUM,
    "serve_rejections": int,
    "witness_reduction_pct": _NUM,
    "witness_two_pass_bytes": int,
    "witness_single_pass_bytes": int,
    "witness_sample_pairs": int,
    "witness_bytes_per_proof_k1": _NUM,
    "witness_bytes_per_proof_k16": _NUM,
    "witness_bytes_per_proof_k256": _NUM,
    "witness_delta_ratio": _NUM,
    "witness_compressed_ratio": _NUM,
    "resilience_fault_free_proofs_per_sec": _NUM,
    "integrity_overhead_pct": _NUM,
    "proofs_per_sec_at_fault_rate": _NUM,
    "resilience_fault_rate": _NUM,
    "recovery_ms": _NUM,
    "durability_journal_overhead_pct": _NUM,
    "durability_resume_ms": _NUM,
    "durability_replay_chunks_per_sec": _NUM,
    "durability_journal_bytes": int,
    "durability_chunks": int,
    "trace_overhead_pct": _NUM,
    "spans_per_proof": _NUM,
    "observability_spans_recorded": int,
    "observability_spans_dropped": int,
    "observability_pairs": int,
    "cold_vs_warm_speedup": _NUM,
    "disk_hit_ratio": _NUM,
    "prefetch_hit_ratio": _NUM,
    "storage_cold_rpc_calls": int,
    "storage_warm_rpc_calls": int,
    "storage_prefetched_blocks": int,
    "storage_disk_bytes": int,
    "storage_pairs": int,
    "cold_rpc_roundtrips_per_proof": _NUM,
    "sync_rpc_roundtrips_per_proof": _NUM,
    "cold_speedup_vs_sync_walker": _NUM,
    "speculate_waste_pct": _NUM,
    "asyncfetch_batch_calls": int,
    "asyncfetch_cold_rpc_calls": int,
    "asyncfetch_sync_rpc_calls": int,
    "asyncfetch_pairs": int,
    "cluster_linearity_4shard": _NUM,
    "aggregate_proofs_per_sec": _NUM,
    "steal_events": int,
    "cluster_rps_1shard": _NUM,
    "cluster_rps_4shard": _NUM,
    "cluster_pairs": int,
    "cluster_requests": int,
    "device_linearity_Nchip": _NUM,
    "batch_verify_speedup": _NUM,
    "onchip_devices": int,
    "onchip_match_events": int,
    "onchip_verify_blocks": int,
    "onchip_device_calls": int,
    "verify_tuned_speedup": _NUM,
    "verify_autotune_scalar_only": bool,
    "verify_autotuned_min_bytes": int,
    "backfill_epochs_per_sec": _NUM,
    "backfill_epochs_per_sec_1shard": _NUM,
    "backfill_ttfc_ms": _NUM,
    "backfill_total_ms": _NUM,
    "backfill_occupancy_pct": _NUM,
    "backfill_windows": int,
    "backfill_epochs": int,
    "backfill_shards": int,
    "standing_proofs_pushed_per_sec_1k": _NUM,
    "standing_proofs_pushed_per_sec_10k": _NUM,
    "standing_delivery_lag_p50_ms": _NUM,
    "standing_delivery_lag_p99_ms": _NUM,
    "standing_subscriptions": int,
    "standing_tipsets": int,
    "standing_distinct_filters": int,
    "standing_generations_per_tipset": _NUM,
    "fleetobs_overhead_pct": _NUM,
    "fleetobs_rps_plain": _NUM,
    "fleetobs_rps_observed": _NUM,
    "fleetobs_stitched_spans": int,
    "fleetobs_scrapes": int,
    "fleetobs_pairs": int,
    "fleetobs_requests": int,
    "warm_block_bytes_copied_per_resp": _NUM,
    "stream_ttfb_ms": _NUM,
    "qos_light_tenant_p99_ms": _NUM,
    "qos_light_tenant_p50_ms": _NUM,
    "qos_heavy_backlog_drain_ms": _NUM,
    "zerocopy_bytes_per_resp": _NUM,
    "zerocopy_responses": int,
    "qos_heavy_concurrency": int,
    "qos_heavy_requests": int,
    "zerocopy_host_cpus": int,
    "aggregate_proofs_per_sec_2host": _NUM,
    "replica_repair_hit_rate": _NUM,
    "kill_recovery_ms": _NUM,
    "hostkill_pairs": int,
    "hostkill_requests": int,
    "hostkill_failovers": int,
    "goodput_ratio_at_2x": _NUM,
    "shed_rate": _NUM,
    "light_tenant_p99_ms_overload": _NUM,
    "cancel_reclaim_pct": _NUM,
    "overload_capacity_rps": _NUM,
    "overload_goodput_rps": _NUM,
    "overload_requests": int,
    "overload_doomed_requests": int,
    "overload_admit_limit_final": _NUM,
    "overload_host_cpus": int,
    "registry_append_overhead_pct": _NUM,
    "registry_append_us": _NUM,
    "registry_inclusion_proof_ms": _NUM,
    "fleet_delta_hit_rate": _NUM,
    "fleet_delta_baseline_hit_rate": _NUM,
    "registry_chain_records": int,
    "registry_serve_requests": int,
    "registry_shards": int,
    "registry_lookups": int,
    "legs": dict,
    "watchdog_fallback": bool,
}

# the CURRENT artifact must report the full e2e contract: host
# introspection, pipeline knobs, both overlap flags, and the serial
# comparison the speedup ratio is derived from
_CURRENT_REQUIRED = (
    "platform", "devices", "host_cores", "host_cores_affinity",
    "scan_threads", "record_workers", "verify_workers", "effective_threads",
    "native_scan_threads", "pipeline_depth",
    "pipeline_chunk", "events_per_sec_e2e", "proofs", "stages_ms",
    "stages_wall_ms", "stages_overlap", "gen_verify_overlap",
    "overlap_efficiency", "serial_proofs_per_sec", "serial_e2e_reps_s",
    "pipeline_speedup_vs_serial", "e2e_policy", "e2e_reps_s",
    "vs_baseline", "vs_native_baseline",
    "scalar_baseline_proofs_per_sec", "native_baseline_proofs_per_sec",
    "serve_batched_rps", "serve_speedup_vs_sequential",
    "witness_reduction_pct",
    "witness_bytes_per_proof_k1", "witness_bytes_per_proof_k16",
    "witness_bytes_per_proof_k256", "witness_delta_ratio",
    "witness_compressed_ratio",
    "resilience_fault_free_proofs_per_sec", "integrity_overhead_pct",
    "proofs_per_sec_at_fault_rate", "resilience_fault_rate", "recovery_ms",
    "durability_journal_overhead_pct", "durability_resume_ms",
    "durability_replay_chunks_per_sec", "durability_journal_bytes",
    "durability_chunks",
    "trace_overhead_pct", "spans_per_proof",
    "cold_vs_warm_speedup", "disk_hit_ratio", "prefetch_hit_ratio",
    "cold_rpc_roundtrips_per_proof", "sync_rpc_roundtrips_per_proof",
    "cold_speedup_vs_sync_walker", "speculate_waste_pct",
    "cluster_linearity_4shard", "aggregate_proofs_per_sec", "steal_events",
    "device_linearity_Nchip", "batch_verify_speedup",
    "verify_tuned_speedup", "verify_autotune_scalar_only",
    "backfill_epochs_per_sec", "backfill_ttfc_ms", "backfill_total_ms",
    "standing_proofs_pushed_per_sec_1k", "standing_proofs_pushed_per_sec_10k",
    "standing_delivery_lag_p50_ms", "standing_delivery_lag_p99_ms",
    "standing_subscriptions", "standing_tipsets",
    "standing_distinct_filters", "standing_generations_per_tipset",
    "fleetobs_overhead_pct", "fleetobs_rps_plain", "fleetobs_rps_observed",
    "fleetobs_stitched_spans",
    "warm_block_bytes_copied_per_resp", "stream_ttfb_ms",
    "qos_light_tenant_p99_ms",
    "aggregate_proofs_per_sec_2host", "replica_repair_hit_rate",
    "kill_recovery_ms",
    "goodput_ratio_at_2x", "shed_rate", "light_tenant_p99_ms_overload",
    "cancel_reclaim_pct",
    "registry_append_overhead_pct", "registry_inclusion_proof_ms",
    "fleet_delta_hit_rate", "fleet_delta_baseline_hit_rate",
    "legs", "watchdog_fallback",
)


def check_artifact(obj: dict, require_current: bool = False) -> list[str]:
    """Return a list of problems ([] = valid).

    ``require_current`` additionally demands the full present-day key set
    (apply it to the newest artifact only — old vintages legitimately
    predate newer keys).
    """
    problems: list[str] = []
    if not isinstance(obj, dict):
        return [f"artifact is {type(obj).__name__}, expected object"]

    if _WRAPPER_KEYS <= set(obj):
        # legacy run-capture wrapper: validate the parsed payload when the
        # wrapped run succeeded; a failed capture (parsed: null) is honest
        if require_current:
            problems.append("current artifact must be orchestrator JSON, not a run-capture wrapper")
        parsed = obj.get("parsed")
        if parsed is None:
            return problems
        return problems + [f"parsed: {p}" for p in check_artifact(parsed)]

    for key, types in _CORE_REQUIRED.items():
        if key not in obj:
            problems.append(f"missing required key {key!r}")
        elif obj[key] is not None and not isinstance(obj[key], types):
            problems.append(
                f"{key!r} is {type(obj[key]).__name__}, expected {types}"
            )
    # the headline may be null only in the total-failure artifact, which
    # still carries the schema — "value" must then EXIST and be null
    if "value" in obj and obj["value"] is None and obj.get("platform") is not None:
        problems.append("null value with a non-null platform (partial schema)")

    for key, types in _KNOWN_TYPES.items():
        if key in obj and obj[key] is not None and not isinstance(obj[key], types):
            # bool is an int subclass; don't let flags pass as numbers
            problems.append(
                f"{key!r} is {type(obj[key]).__name__}, expected {types}"
            )
        if (
            key in obj
            and isinstance(obj[key], bool)
            and not (types is bool or types == bool)
        ):
            problems.append(f"{key!r} is bool, expected {types}")

    for key in ("stages_ms", "stages_wall_ms"):
        val = obj.get(key)
        if isinstance(val, dict):
            for stage, ms in val.items():
                if not isinstance(ms, (int, float)) or isinstance(ms, bool):
                    problems.append(f"{key}[{stage!r}] is not a number")

    for key in ("e2e_reps_s", "serial_e2e_reps_s"):
        val = obj.get(key)
        if isinstance(val, list) and any(
            not isinstance(v, (int, float)) or isinstance(v, bool) for v in val
        ):
            problems.append(f"{key!r} has non-numeric entries")

    if require_current:
        for key in _CURRENT_REQUIRED:
            if key not in obj:
                problems.append(f"current artifact missing key {key!r}")
        # the perf gate: with spare cores the stage-overlapped engine must
        # actually BEAT the serial engine, not just exist (>2 because two
        # cores barely cover scan+record and the ratio sits at the noise
        # floor; 1-core hosts run the serial fallback by design)
        if speedup_gate_skip_reason(obj) is None:
            speedup = obj.get("pipeline_speedup_vs_serial")
            if not isinstance(speedup, _NUM) or isinstance(speedup, bool):
                problems.append(
                    "speedup gate: pipeline_speedup_vs_serial is "
                    f"{speedup!r} on a {obj.get('host_cores')}-core host "
                    "(pipelined leg did not run?)"
                )
            elif speedup < 1.0:
                problems.append(
                    f"speedup gate: pipeline_speedup_vs_serial={speedup} "
                    f"< 1.0 on a {obj.get('host_cores')}-core host — the "
                    "stage-overlapped engine must beat serial when cores "
                    "are available"
                )
        # the asyncfetch gate: the fetch plane must issue STRICTLY fewer
        # RPC round-trips per proof than the sync walker in the SAME
        # artifact — batching that doesn't collapse round-trips is a
        # regression, regardless of host shape (round-trip counts are
        # deterministic I/O accounting, not scheduling)
        if asyncfetch_gate_skip_reason(obj) is None:
            cold = obj.get("cold_rpc_roundtrips_per_proof")
            sync = obj.get("sync_rpc_roundtrips_per_proof")
            for name, val in (
                ("cold_rpc_roundtrips_per_proof", cold),
                ("sync_rpc_roundtrips_per_proof", sync),
            ):
                if not isinstance(val, _NUM) or isinstance(val, bool):
                    problems.append(
                        f"asyncfetch gate: {name} is {val!r} "
                        "(asyncfetch leg did not run?)"
                    )
            if (
                isinstance(cold, _NUM) and not isinstance(cold, bool)
                and isinstance(sync, _NUM) and not isinstance(sync, bool)
                and cold >= sync
            ):
                problems.append(
                    f"asyncfetch gate: cold_rpc_roundtrips_per_proof={cold} "
                    f">= sync_rpc_roundtrips_per_proof={sync} — the fetch "
                    "plane must need strictly fewer round-trips than the "
                    "sync walker"
                )
        # the cluster gate: with spare cores, 4 shard processes must keep
        # ≥ 80% of ideal linear scaling over 1 shard. A 1-core host
        # time-slices the shard processes (linearity collapses by design),
        # so the gate applies on the same host shape as the speedup gate.
        # the onchip gate: with more than one accelerator device, the
        # mesh-sharded match kernel must keep ≥ 80% of ideal linear
        # scaling over the single-device path. A 1-device host runs both
        # sides on the same chip — the ratio then measures pjit dispatch
        # overhead, not scaling — so the gate only applies multi-device.
        if onchip_gate_skip_reason(obj) is None:
            linearity = obj.get("device_linearity_Nchip")
            if not isinstance(linearity, _NUM) or isinstance(linearity, bool):
                problems.append(
                    "onchip gate: device_linearity_Nchip is "
                    f"{linearity!r} on a {obj.get('onchip_devices')}-device "
                    "host (onchip leg did not run?)"
                )
            elif linearity < 0.8:
                problems.append(
                    f"onchip gate: device_linearity_Nchip={linearity} "
                    f"< 0.8 on a {obj.get('onchip_devices')}-device host — "
                    "mesh-sharded matching must scale near-linearly across "
                    "local devices"
                )
        # the standing gate: fan-out amortization is an invariant, not a
        # scheduling outcome — proofs generate once per distinct (pair,
        # filter) and fan out to every subscriber, so generations per
        # tipset can never exceed the distinct filter count, on any host
        # shape. Only artifacts predating the leg skip.
        if standing_gate_skip_reason(obj) is None:
            gens = obj.get("standing_generations_per_tipset")
            filts = obj.get("standing_distinct_filters")
            for name, val in (
                ("standing_generations_per_tipset", gens),
                ("standing_distinct_filters", filts),
            ):
                if not isinstance(val, _NUM) or isinstance(val, bool):
                    problems.append(
                        f"standing gate: {name} is {val!r} "
                        "(standing leg did not run?)"
                    )
            if (
                isinstance(gens, _NUM) and not isinstance(gens, bool)
                and isinstance(filts, _NUM) and not isinstance(filts, bool)
                and gens > filts
            ):
                problems.append(
                    f"standing gate: standing_generations_per_tipset={gens} "
                    f"> standing_distinct_filters={filts} — fan-out must "
                    "amortize: one generation per distinct filter shared by "
                    "all its subscribers"
                )
        # the witness-diet gate: aggregation and delta savings are wire
        # accounting, not scheduling — K=16 co-tipset claims must cost
        # strictly fewer bytes per proof than K=1 (the claim table shares
        # one witness), and a consecutive-epoch delta must be strictly
        # smaller than re-shipping the full bundle. Host-shape
        # independent; only artifacts predating the leg skip.
        if witnessdiet_gate_skip_reason(obj) is None:
            k1 = obj.get("witness_bytes_per_proof_k1")
            k16 = obj.get("witness_bytes_per_proof_k16")
            dratio = obj.get("witness_delta_ratio")
            for name, val in (
                ("witness_bytes_per_proof_k1", k1),
                ("witness_bytes_per_proof_k16", k16),
                ("witness_delta_ratio", dratio),
            ):
                if not isinstance(val, _NUM) or isinstance(val, bool):
                    problems.append(
                        f"witness-diet gate: {name} is {val!r} "
                        "(witness leg did not run?)"
                    )
            if (
                isinstance(k1, _NUM) and not isinstance(k1, bool)
                and isinstance(k16, _NUM) and not isinstance(k16, bool)
                and k16 >= k1
            ):
                problems.append(
                    f"witness-diet gate: witness_bytes_per_proof_k16={k16} "
                    f">= witness_bytes_per_proof_k1={k1} — aggregating 16 "
                    "co-tipset claims must cost strictly fewer bytes per "
                    "proof than one claim per response"
                )
            if (
                isinstance(dratio, _NUM) and not isinstance(dratio, bool)
                and dratio >= 1.0
            ):
                problems.append(
                    f"witness-diet gate: witness_delta_ratio={dratio} "
                    ">= 1.0 — a consecutive-epoch delta must be strictly "
                    "smaller than re-shipping the full bundle"
                )
        # the fleet-observability gate: the whole observability plane
        # (federated scraping, SLO watchdog, tenant accounting, sampled
        # trace shipping) must cost ≤ 3% of router throughput. The ratio
        # needs spare cores — on ≤2-core hosts the scrape and watchdog
        # threads time-slice the request loop, so the measurement is core
        # contention, not the plane's cost.
        if fleetobs_gate_skip_reason(obj) is None:
            ovh = obj.get("fleetobs_overhead_pct")
            if not isinstance(ovh, _NUM) or isinstance(ovh, bool):
                problems.append(
                    f"fleetobs gate: fleetobs_overhead_pct is {ovh!r} "
                    "(fleetobs leg did not run?)"
                )
            elif ovh > 3.0:
                problems.append(
                    f"fleetobs gate: fleetobs_overhead_pct={ovh} > 3.0 — "
                    "the fleet observability plane must cost at most 3% "
                    "of router throughput"
                )
        # span stitching is correctness, not perf (measured outside the
        # timed window at sample=1.0): enforced on every artifact carrying
        # the fleetobs keys regardless of host shape.
        if (
            "fleetobs_overhead_pct" in obj
            or "fleetobs_stitched_spans" in obj
        ):
            stitched = obj.get("fleetobs_stitched_spans")
            if (
                isinstance(stitched, _NUM) and not isinstance(stitched, bool)
                and stitched < 1
            ):
                problems.append(
                    f"fleetobs gate: fleetobs_stitched_spans={stitched} "
                    "< 1 — a fully-sampled scatter must graft shard span "
                    "subtrees into the router's trace"
                )
        # the trace-overhead gate: the span collector's ≤ 3% budget,
        # enforced the same way as the fleetobs gate — the off/on delta
        # needs spare cores; on ≤2-core hosts the collector's lock and
        # ring maintenance time-slice the pipeline's only cores and the
        # measurement is contention, not the spine's cost.
        if trace_gate_skip_reason(obj) is None:
            ovh = obj.get("trace_overhead_pct")
            if not isinstance(ovh, _NUM) or isinstance(ovh, bool):
                problems.append(
                    f"trace gate: trace_overhead_pct is {ovh!r} "
                    "(observability leg did not run?)"
                )
            elif ovh > 3.0:
                problems.append(
                    f"trace gate: trace_overhead_pct={ovh} > 3.0 — the "
                    "trace spine must cost at most 3% of pipelined range "
                    "throughput"
                )
        # the verify-autotune gate: the lane the per-host tuner picks must
        # never lose to scalar — either the tuned crossover selected the
        # device lane AND it is at least as fast (speedup ≥ 1.0), or the
        # tuner honestly stayed scalar-only. Host-shape independent: the
        # tuner's whole job is to make the choice correct on THIS host.
        if verify_autotune_gate_skip_reason(obj) is None:
            tuned = obj.get("verify_tuned_speedup")
            scalar_only = obj.get("verify_autotune_scalar_only")
            if not isinstance(tuned, _NUM) or isinstance(tuned, bool):
                problems.append(
                    f"verify-autotune gate: verify_tuned_speedup is "
                    f"{tuned!r} (onchip leg did not run?)"
                )
            elif scalar_only is not True and tuned < 1.0:
                problems.append(
                    f"verify-autotune gate: verify_tuned_speedup={tuned} "
                    "< 1.0 with the device lane selected — the autotuned "
                    "crossover must pick the device lane only when it "
                    "actually wins (or record scalar_only honestly)"
                )
        # the backfill gate: a batch job must make progress AND stream —
        # epochs/s strictly positive and the first chunk strictly before
        # completion. Both are accounting over the engine's own clock, so
        # the gate is host-shape independent.
        if backfill_gate_skip_reason(obj) is None:
            eps = obj.get("backfill_epochs_per_sec")
            ttfc = obj.get("backfill_ttfc_ms")
            total = obj.get("backfill_total_ms")
            for name, val in (
                ("backfill_epochs_per_sec", eps),
                ("backfill_ttfc_ms", ttfc),
                ("backfill_total_ms", total),
            ):
                if not isinstance(val, _NUM) or isinstance(val, bool):
                    problems.append(
                        f"backfill gate: {name} is {val!r} "
                        "(backfill leg did not run?)"
                    )
            if isinstance(eps, _NUM) and not isinstance(eps, bool) and eps <= 0:
                problems.append(
                    f"backfill gate: backfill_epochs_per_sec={eps} <= 0 — "
                    "the batch job made no progress"
                )
            if (
                isinstance(ttfc, _NUM) and not isinstance(ttfc, bool)
                and isinstance(total, _NUM) and not isinstance(total, bool)
                and ttfc >= total
            ):
                problems.append(
                    f"backfill gate: backfill_ttfc_ms={ttfc} >= "
                    f"backfill_total_ms={total} — incremental delivery "
                    "must stream the first chunk strictly before the job "
                    "completes"
                )
        # the zero-copy gate: block payload bytes copied through Python
        # per disk-warm streamed response must be EXACTLY zero — the
        # stream writer accounts every payload it sends as zero-copy
        # (memoryview of a segment frame) or copied, so any non-zero
        # value means a fallback path ran on a warm store. Pure
        # accounting; host-shape independent.
        if zerocopy_gate_skip_reason(obj) is None:
            copied = obj.get("warm_block_bytes_copied_per_resp")
            ttfb = obj.get("stream_ttfb_ms")
            if not isinstance(copied, _NUM) or isinstance(copied, bool):
                problems.append(
                    "zerocopy gate: warm_block_bytes_copied_per_resp is "
                    f"{copied!r} (zerocopy leg did not run?)"
                )
            elif copied != 0:
                problems.append(
                    f"zerocopy gate: warm_block_bytes_copied_per_resp="
                    f"{copied} != 0 — disk-warm streamed responses must "
                    "send block payloads as segment-frame slices, never "
                    "copies"
                )
            if (
                isinstance(ttfb, _NUM)
                and not isinstance(ttfb, bool)
                and ttfb <= 0
            ):
                problems.append(
                    f"zerocopy gate: stream_ttfb_ms={ttfb} <= 0 — "
                    "time-to-first-byte must be a positive measurement"
                )
        # the QoS fairness gate: under a saturating heavy tenant, the
        # light tenant's tail must stay near its median — fair tenant
        # queues bound every light request's wait to a constant number
        # of rounds, while FIFO starvation balloons p99 relative to p50.
        if qos_gate_skip_reason(obj) is None:
            p99 = obj.get("qos_light_tenant_p99_ms")
            p50 = obj.get("qos_light_tenant_p50_ms")
            if not isinstance(p99, _NUM) or isinstance(p99, bool):
                problems.append(
                    f"qos gate: qos_light_tenant_p99_ms is {p99!r} "
                    "(zerocopy leg did not run?)"
                )
            elif isinstance(p50, _NUM) and not isinstance(p50, bool):
                bound = max(10 * p50, 250.0)
                if p99 > bound:
                    problems.append(
                        f"qos gate: qos_light_tenant_p99_ms={p99} > "
                        f"{bound} (max(10 x p50={p50}, 250)) — the fair "
                        "queue must bound the light tenant's tail under "
                        "a heavy tenant's flood"
                    )
        # the hostkill gate: under replication_factor=2, killing one host
        # mid-load must leave the cluster whole again quickly, the
        # replica plane must absorb corrupt-frame evictions without
        # touching Lotus, and the replicated pair must still do real
        # work. All three measurements need spare cores — on ≤2-core
        # hosts the shards, the load clients, and the recovery probe
        # time-slice the same core, so the clock measures contention,
        # not the failover plane (the artifact still records the
        # honestly-measured numbers).
        if hostkill_gate_skip_reason(obj) is None:
            recovery = obj.get("kill_recovery_ms")
            hit_rate = obj.get("replica_repair_hit_rate")
            agg = obj.get("aggregate_proofs_per_sec_2host")
            for name, val in (
                ("kill_recovery_ms", recovery),
                ("replica_repair_hit_rate", hit_rate),
                ("aggregate_proofs_per_sec_2host", agg),
            ):
                if not isinstance(val, _NUM) or isinstance(val, bool):
                    problems.append(
                        f"hostkill gate: {name} is {val!r} "
                        "(hostkill leg did not run?)"
                    )
            if (
                isinstance(recovery, _NUM) and not isinstance(recovery, bool)
                and recovery > 10_000
            ):
                problems.append(
                    f"hostkill gate: kill_recovery_ms={recovery} > 10000 — "
                    "a byte-identical scatter must complete within 10 s of "
                    "a host death"
                )
            if (
                isinstance(hit_rate, _NUM) and not isinstance(hit_rate, bool)
                and hit_rate < 0.99
            ):
                problems.append(
                    f"hostkill gate: replica_repair_hit_rate={hit_rate} "
                    "< 0.99 — with a live replica every corrupt-frame "
                    "eviction must repair peer-to-peer, not from Lotus"
                )
            if (
                isinstance(agg, _NUM) and not isinstance(agg, bool)
                and agg <= 0
            ):
                problems.append(
                    f"hostkill gate: aggregate_proofs_per_sec_2host={agg} "
                    "<= 0 — the replicated pair did no work"
                )
        # the overload gate: a serve plane at 2× offered load must keep
        # doing ≈ its capacity's worth of real work — shedding the excess
        # with honest 429s instead of letting queue collapse drag goodput
        # down. Needs spare cores: on ≤2-core hosts the overload clients
        # time-slice the server's only cores and the ratio measures
        # scheduler contention, not admission control.
        if overload_gate_skip_reason(obj) is None:
            ratio = obj.get("goodput_ratio_at_2x")
            reclaim = obj.get("cancel_reclaim_pct")
            if not isinstance(ratio, _NUM) or isinstance(ratio, bool):
                problems.append(
                    f"overload gate: goodput_ratio_at_2x is {ratio!r} "
                    "(overload leg did not run?)"
                )
            elif ratio < 0.8:
                problems.append(
                    f"overload gate: goodput_ratio_at_2x={ratio} < 0.8 — "
                    "under 2x offered load the admission gate must shed "
                    "the excess and keep goodput near capacity, not let "
                    "queueing collapse it"
                )
            if (
                isinstance(reclaim, _NUM)
                and not isinstance(reclaim, bool)
                and reclaim <= 0
            ):
                problems.append(
                    f"overload gate: cancel_reclaim_pct={reclaim} <= 0 — "
                    "tight-deadline requests must be refused or dropped "
                    "before burning a worker, at least sometimes"
                )
        # the registry gate: sealing one provenance frame per served
        # bundle must cost < 1% of the request it rides on, and the
        # fleet base directory must beat per-shard base caches when a
        # lookup lands on a shard that didn't serve the base. Both are
        # ratios of measurements taken on the SAME host — the append/
        # request costs scale together, and the hit rates are counting —
        # so the gates are host-shape independent; only artifacts
        # predating the registry leg skip.
        if registry_gate_skip_reason(obj) is None:
            ovh = obj.get("registry_append_overhead_pct")
            proof_ms = obj.get("registry_inclusion_proof_ms")
            fleet = obj.get("fleet_delta_hit_rate")
            base = obj.get("fleet_delta_baseline_hit_rate")
            for name, val in (
                ("registry_append_overhead_pct", ovh),
                ("registry_inclusion_proof_ms", proof_ms),
                ("fleet_delta_hit_rate", fleet),
                ("fleet_delta_baseline_hit_rate", base),
            ):
                if not isinstance(val, _NUM) or isinstance(val, bool):
                    problems.append(
                        f"registry gate: {name} is {val!r} "
                        "(registry leg did not run?)"
                    )
            if (
                isinstance(ovh, _NUM) and not isinstance(ovh, bool)
                and ovh >= 1.0
            ):
                problems.append(
                    f"registry gate: registry_append_overhead_pct={ovh} "
                    ">= 1.0 — sealing a provenance frame must cost under "
                    "1% of the request it audits"
                )
            if (
                isinstance(proof_ms, _NUM) and not isinstance(proof_ms, bool)
                and proof_ms <= 0
            ):
                problems.append(
                    f"registry gate: registry_inclusion_proof_ms={proof_ms} "
                    "<= 0 — inclusion proving must be a positive measurement"
                )
            if (
                isinstance(fleet, _NUM) and not isinstance(fleet, bool)
                and isinstance(base, _NUM) and not isinstance(base, bool)
                and fleet <= base
            ):
                problems.append(
                    f"registry gate: fleet_delta_hit_rate={fleet} <= "
                    f"fleet_delta_baseline_hit_rate={base} — the fleet base "
                    "directory must strictly beat per-shard base caches on "
                    "scattered lookups"
                )
        if cluster_gate_skip_reason(obj) is None:
            linearity = obj.get("cluster_linearity_4shard")
            if not isinstance(linearity, _NUM) or isinstance(linearity, bool):
                problems.append(
                    "cluster gate: cluster_linearity_4shard is "
                    f"{linearity!r} on a {obj.get('host_cores')}-core host "
                    "(cluster leg did not run?)"
                )
            elif linearity < 0.8:
                problems.append(
                    f"cluster gate: cluster_linearity_4shard={linearity} "
                    f"< 0.8 on a {obj.get('host_cores')}-core host — "
                    "4 shard processes must scale near-linearly when cores "
                    "are available"
                )
    return problems


def speedup_gate_skip_reason(obj: dict) -> "str | None":
    """Why the ≥1.0 pipeline-speedup gate does NOT apply to this artifact
    (None when it does). Callers print the reason so a skipped gate is
    visible, never silent."""
    cores = obj.get("host_cores")
    if not isinstance(cores, int):
        return f"host_cores={cores!r} (unknown host shape)"
    if cores <= 2:
        return (
            f"host_cores={cores} ≤ 2 — stage overlap cannot pay without "
            "spare cores (1-core hosts run the serial fallback by design)"
        )
    return None


def asyncfetch_gate_skip_reason(obj: dict) -> "str | None":
    """Why the cold-below-sync round-trip gate does NOT apply (None when
    it does). The gate is host-shape independent — round-trip counts are
    I/O accounting — so the only skip is an artifact that predates the
    asyncfetch leg entirely (no keys at all, old vintage validated
    without --require-current)."""
    if (
        "cold_rpc_roundtrips_per_proof" not in obj
        and "sync_rpc_roundtrips_per_proof" not in obj
    ):
        return "artifact predates the asyncfetch leg"
    return None


def cluster_gate_skip_reason(obj: dict) -> "str | None":
    """Why the ≥0.8 cluster-linearity gate does NOT apply to this artifact
    (None when it does). Callers print the reason so a skipped gate is
    visible, never silent."""
    cores = obj.get("host_cores")
    if not isinstance(cores, int):
        return f"host_cores={cores!r} (unknown host shape)"
    if cores <= 2:
        return (
            f"host_cores={cores} ≤ 2 — four shard processes time-slice the "
            "same cores, so linearity over one shard cannot hold"
        )
    return None


def onchip_gate_skip_reason(obj: dict) -> "str | None":
    """Why the ≥0.8 device-linearity gate does NOT apply to this artifact
    (None when it does). Callers print the reason so a skipped gate is
    visible, never silent."""
    devices = obj.get("onchip_devices")
    if not isinstance(devices, int):
        return f"onchip_devices={devices!r} (unknown device count)"
    if devices <= 1:
        return (
            f"onchip_devices={devices} ≤ 1 — mesh and single-device paths "
            "share the one chip, so the ratio measures pjit dispatch "
            "overhead, not device scaling"
        )
    return None


def standing_gate_skip_reason(obj: dict) -> "str | None":
    """Why the generations ≤ distinct-filters amortization gate does NOT
    apply (None when it does). Like the asyncfetch gate this is
    host-shape independent — generation counts are deterministic
    accounting — so the only skip is an artifact predating the standing
    leg (old vintage validated without --require-current)."""
    if (
        "standing_generations_per_tipset" not in obj
        and "standing_distinct_filters" not in obj
    ):
        return "artifact predates the standing leg"
    return None


def witnessdiet_gate_skip_reason(obj: dict) -> "str | None":
    """Why the K=16 < K=1 / delta < 1.0 witness-diet gate does NOT apply
    (None when it does). Wire byte counts are deterministic accounting —
    host-shape independent — so the only skip is an artifact predating
    the witness-diet measurements (old vintage validated without
    --require-current)."""
    if (
        "witness_bytes_per_proof_k1" not in obj
        and "witness_delta_ratio" not in obj
    ):
        return "artifact predates the witness-diet leg"
    return None


def fleetobs_gate_skip_reason(obj: dict) -> "str | None":
    """Why the ≤3% fleet-observability overhead gate does NOT apply (None
    when it does). Measuring the ratio needs spare cores: on ≤2-core
    hosts the federation scrape and SLO watchdog threads time-slice the
    request loop's only cores, so the observed/plain delta reflects core
    contention, not the plane's cost. The companion span-stitching check
    is host-shape independent and is NOT skipped with the ratio."""
    if (
        "fleetobs_overhead_pct" not in obj
        and "fleetobs_stitched_spans" not in obj
    ):
        return "artifact predates the fleetobs leg"
    cores = obj.get("host_cores")
    if not isinstance(cores, int):
        return f"host_cores={cores!r} (unknown host shape)"
    if cores <= 2:
        return (
            f"host_cores={cores} ≤ 2 — the federation scrape and SLO "
            "watchdog threads time-slice the request loop's cores, so "
            "measured overhead is core contention, not the plane's cost"
        )
    return None


def trace_gate_skip_reason(obj: dict) -> "str | None":
    """Why the ≤3% trace-overhead gate does NOT apply (None when it
    does). Same shape as the fleetobs gate: the off/on ratio needs spare
    cores — on ≤2-core hosts the collector time-slices the pipeline's
    only cores, so the measured delta is core contention, not the trace
    spine's cost (BENCH_r18 measured 12.29% on a 1-core host for exactly
    this reason). Callers print the reason so a skipped gate is visible,
    never silent."""
    if "trace_overhead_pct" not in obj:
        return "artifact predates the observability leg"
    cores = obj.get("host_cores")
    if not isinstance(cores, int):
        return f"host_cores={cores!r} (unknown host shape)"
    if cores <= 2:
        return (
            f"host_cores={cores} ≤ 2 — the span collector time-slices the "
            "pipeline's only cores, so the off/on delta measures core "
            "contention, not the trace spine's cost"
        )
    return None


def verify_autotune_gate_skip_reason(obj: dict) -> "str | None":
    """Why the chosen-lane-never-loses gate does NOT apply (None when it
    does). The gate is host-shape independent — the autotuner's contract
    is precisely to be correct per host — so the only skip is an
    artifact predating the autotuned keys."""
    if (
        "verify_tuned_speedup" not in obj
        and "verify_autotune_scalar_only" not in obj
    ):
        return "artifact predates the verify-lane autotuner"
    return None


def backfill_gate_skip_reason(obj: dict) -> "str | None":
    """Why the progress + streaming backfill gate does NOT apply (None
    when it does). Epoch throughput and first-chunk-before-completion are
    accounting over the engine's own clock — host-shape independent — so
    the only skip is an artifact predating the backfill leg."""
    if (
        "backfill_epochs_per_sec" not in obj
        and "backfill_ttfc_ms" not in obj
    ):
        return "artifact predates the backfill leg"
    return None


def zerocopy_gate_skip_reason(obj: dict) -> "str | None":
    """Why the copied-bytes==0 zero-copy gate does NOT apply (None when
    it does). The gate is pure accounting over the stream writer's own
    counters — host-shape independent — so the only skip is an artifact
    predating the zerocopy leg."""
    if (
        "warm_block_bytes_copied_per_resp" not in obj
        and "stream_ttfb_ms" not in obj
    ):
        return "artifact predates the zerocopy leg"
    return None


def qos_gate_skip_reason(obj: dict) -> "str | None":
    """Why the light-tenant-tail fairness gate does NOT apply (None when
    it does). Bounding the tail needs spare cores: on ≤2-core hosts the
    heavy tenant's closed-loop threads time-slice the light tenant's
    only cores, so the measured p99 reflects core contention, not queue
    ordering. Callers print the reason so a skipped gate is visible,
    never silent."""
    if "qos_light_tenant_p99_ms" not in obj:
        return "artifact predates the zerocopy leg"
    cores = obj.get("host_cores")
    if not isinstance(cores, int):
        cores = obj.get("zerocopy_host_cpus")
    if not isinstance(cores, int):
        return f"host_cores={obj.get('host_cores')!r} (unknown host shape)"
    if cores <= 2:
        return (
            f"host_cores={cores} ≤ 2 — the heavy tenant's closed-loop "
            "threads time-slice the light tenant's only cores, so the "
            "measured tail is core contention, not queue ordering"
        )
    return None


def hostkill_gate_skip_reason(obj: dict) -> "str | None":
    """Why the kill-recovery / replica-repair / 2-host throughput gates do
    NOT apply (None when they do). The measurements need spare cores: on
    ≤2-core hosts the two shards, the closed-loop load clients, and the
    recovery probe all time-slice the same core, so kill_recovery_ms and
    the aggregate rate measure scheduler contention, not the failover
    plane. Callers print the reason so a skipped gate is visible, never
    silent."""
    if (
        "kill_recovery_ms" not in obj
        and "replica_repair_hit_rate" not in obj
        and "aggregate_proofs_per_sec_2host" not in obj
    ):
        return "artifact predates the hostkill leg"
    cores = obj.get("host_cores")
    if not isinstance(cores, int):
        return f"host_cores={cores!r} (unknown host shape)"
    if cores <= 2:
        return (
            f"host_cores={cores} ≤ 2 — the shards, load clients, and "
            "recovery probe time-slice the same core, so the clock "
            "measures contention, not the failover plane"
        )
    return None


def registry_gate_skip_reason(obj: dict) -> "str | None":
    """Why the append-overhead / fleet-directory gates do NOT apply (None
    when they do). Both are same-host ratios (append cost over request
    cost; hit counting over scattered lookups) — host-shape independent —
    so the only skip is an artifact predating the registry leg."""
    if (
        "registry_append_overhead_pct" not in obj
        and "fleet_delta_hit_rate" not in obj
    ):
        return "artifact predates the registry leg"
    return None


def overload_gate_skip_reason(obj: dict) -> "str | None":
    """Why the goodput-at-2× gate does NOT apply (None when it does).
    The ratio needs spare cores: on ≤2-core hosts the 2× closed-loop
    clients time-slice the server's only cores, so the measured goodput
    collapse is scheduler contention, not admission control. Callers
    print the reason so a skipped gate is visible, never silent."""
    if "goodput_ratio_at_2x" not in obj and "shed_rate" not in obj:
        return "artifact predates the overload leg"
    cores = obj.get("host_cores")
    if not isinstance(cores, int):
        cores = obj.get("overload_host_cpus")
    if not isinstance(cores, int):
        return f"host_cores={obj.get('host_cores')!r} (unknown host shape)"
    if cores <= 2:
        return (
            f"host_cores={cores} ≤ 2 — the 2× closed-loop clients "
            "time-slice the server's only cores, so the goodput ratio "
            "measures scheduler contention, not admission control"
        )
    return None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("artifacts", nargs="+", help="BENCH_*.json files")
    parser.add_argument(
        "--require-current",
        action="store_true",
        help="demand the full present-day key set (newest artifact only)",
    )
    args = parser.parse_args(argv)
    rc = 0
    for path in args.artifacts:
        try:
            with open(path) as fh:
                obj = json.load(fh)
        except (OSError, ValueError) as exc:
            print(f"{path}: UNREADABLE ({exc})")
            rc = 1
            continue
        problems = check_artifact(obj, require_current=args.require_current)
        if args.require_current:
            reason = speedup_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: speedup gate SKIPPED ({reason})")
            reason = cluster_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: cluster gate SKIPPED ({reason})")
            reason = asyncfetch_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: asyncfetch gate SKIPPED ({reason})")
            reason = onchip_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: onchip gate SKIPPED ({reason})")
            reason = witnessdiet_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: witness-diet gate SKIPPED ({reason})")
            reason = fleetobs_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: fleetobs gate SKIPPED ({reason})")
            reason = standing_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: standing gate SKIPPED ({reason})")
            reason = trace_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: trace gate SKIPPED ({reason})")
            reason = verify_autotune_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: verify-autotune gate SKIPPED ({reason})")
            reason = backfill_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: backfill gate SKIPPED ({reason})")
            reason = zerocopy_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: zerocopy gate SKIPPED ({reason})")
            reason = qos_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: qos gate SKIPPED ({reason})")
            reason = hostkill_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: hostkill gate SKIPPED ({reason})")
            reason = overload_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: overload gate SKIPPED ({reason})")
            reason = registry_gate_skip_reason(obj)
            if reason is not None:
                print(f"{path}: registry gate SKIPPED ({reason})")
        if problems:
            rc = 1
            print(f"{path}: {len(problems)} problem(s)")
            for p in problems:
                print(f"  - {p}")
        else:
            print(f"{path}: ok")
    return rc


if __name__ == "__main__":
    sys.exit(main())
