"""Fresh-seed soak driver for the differential/adversarial fuzz program.

The committed test suite pins small seed lists; this driver re-runs the
SAME harness code with fresh seeds at soak scale — the methodology that
found every real divergence to date (round 4: C scanner skip laxness, AMT
count acceptance, base32 aliasing, an OverflowError leak; round 5: three
decode-boundary type/canonicality divergences, see NOTES_r05.md). Any
assertion failure is a real bug: the scalar path is the verdict
authority, the reference's serde semantics the acceptance authority.

Usage:
    python tools/soak.py BASE_SEED [phase ...] [--quick]

Phases (default: all): event storage shapes codec rleplus cert dagcbor
header trees range json chaos crash hostkill overload. Every phase
derives its seeds from
BASE_SEED, so a NOTES entry of (base seed, phase) reproduces a run
exactly.
"""

from __future__ import annotations

import os
import random
import sys
import time

# the soak is host-side differential work: always force CPU (the env var
# alone is not enough once the axon plugin has registered — see
# tests/conftest.py)
os.environ["JAX_PLATFORMS"] = "cpu"
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tests"))
sys.path.insert(0, _REPO)

import jax

jax.config.update("jax_platforms", "cpu")

_T0 = time.time()


def log(msg: str) -> None:
    print(f"[{time.time()-_T0:7.1f}s] {msg}", flush=True)


def phase_event(rng, quick):
    import test_batch_verifier_fuzz as ev

    n = 40 if quick else 2000
    for i in range(n):
        ev.test_randomized_mutation_differential(rng.randrange(1 << 30))
        if (i + 1) % max(1, n // 4) == 0:
            log(f"event differential: {i+1}/{n} seeds clean")


def phase_storage(rng, quick):
    import test_storage_batch_verifier_fuzz as st

    n = 40 if quick else 2000
    for i in range(n):
        st.test_randomized_storage_mutation_differential(rng.randrange(1 << 30))
        if (i + 1) % max(1, n // 4) == 0:
            log(f"storage differential: {i+1}/{n} seeds clean")


def phase_shapes(rng, quick):
    import test_batch_verifier_fuzz as ev
    import test_storage_batch_verifier_fuzz as st

    n = 10 if quick else 500
    for i in range(n):
        ev.test_shape_varied_mutation_differential(rng.randrange(1 << 30))
        st.test_shape_varied_storage_mutation_differential(rng.randrange(1 << 30))
        if (i + 1) % max(1, n // 4) == 0:
            log(f"shape-varied differentials: {i+1}/{n} seeds clean")


def phase_codec(rng, quick):
    import test_codec_exec_fuzz as cf

    n = 20 if quick else 300
    for _ in range(n):
        s = rng.randrange(1 << 30)
        cf.test_cid_string_codec_acceptance_parity(s)
        cf.test_cid_bytes_codec_acceptance_parity(s)
        cf.test_exec_order_batch_scalar_parity_under_corruption(rng.randrange(1 << 30))
    log(f"codec/exec-order parity: {n} fresh seeds each clean")


def phase_rleplus(rng, quick):
    from ipc_proofs_tpu.crypto.rleplus import decode_rleplus, encode_rleplus

    r = random.Random(rng.randrange(1 << 30))
    n = 5000 if quick else 60000
    accepted = rejected = 0
    for _ in range(n):
        blob = bytes(r.randrange(256) for _ in range(r.randrange(0, 12)))
        try:
            idxs = decode_rleplus(blob, max_bits=1 << 20)
        except ValueError:
            rejected += 1
            continue
        accepted += 1
        assert encode_rleplus(idxs) == blob, blob.hex()
    assert accepted and rejected
    log(f"rle+ canonicality: {n} blobs, {accepted} accepted all canonical")


def phase_cert(rng, quick):
    import test_cert_cbor as tc
    from ipc_proofs_tpu.proofs.cert_cbor import certificate_from_cbor, certificate_to_cbor

    base = certificate_to_cbor(tc._cert())
    r = random.Random(rng.randrange(1 << 30))
    n = 2000 if quick else 20000
    accepted = rejected = 0
    for _ in range(n):
        raw = bytearray(base)
        for _ in range(r.randrange(1, 4)):
            k = r.randrange(3)
            if k == 0 and raw:
                raw[r.randrange(len(raw))] ^= 1 << r.randrange(8)
            elif k == 1 and raw:
                del raw[r.randrange(len(raw))]
            else:
                raw.insert(r.randrange(len(raw) + 1), r.randrange(256))
        raw = bytes(raw)
        try:
            cert = certificate_from_cbor(raw)
        except ValueError:
            rejected += 1
            continue
        accepted += 1
        assert certificate_to_cbor(cert) == raw, raw.hex()
    assert accepted and rejected  # both regimes exercised, no vacuous pass
    log(f"cert cbor mutants: {n}, {accepted} accepted all canonical, {rejected} rejected")


def phase_dagcbor(rng, quick):
    import test_native_dagcbor as nd
    from ipc_proofs_tpu.core.dagcbor import decode_py, encode

    ext = nd.ext
    if ext is None:
        log("dag-cbor: native extension unavailable, skipped")
        return
    r = random.Random(rng.randrange(1 << 30))
    n = 500 if quick else 3000
    for _ in range(n):
        value = nd._random_value(r)
        raw = encode(value)
        assert ext.decode(raw) == decode_py(raw) == value
    log(f"dag-cbor native/python equivalence: {n} fresh values clean")


def phase_header(rng, quick):
    from ipc_proofs_tpu.core.cid import CID
    from ipc_proofs_tpu.state.header import BlockHeader, decode_header_lite

    r = random.Random(rng.randrange(1 << 30))
    h = BlockHeader(
        parents=[CID.hash_of(b"p"), CID.hash_of(b"q")],
        height=77,
        parent_state_root=CID.hash_of(b"s"),
        parent_message_receipts=CID.hash_of(b"r"),
        messages=CID.hash_of(b"m"),
    )
    raw = h.encode()
    n = 10000 if quick else 120000
    agree = 0
    for _ in range(n):
        mutated = bytearray(raw)
        for _ in range(r.randint(1, 4)):
            k = r.randrange(3)
            if k == 0:
                mutated[r.randrange(len(mutated))] = r.randrange(256)
            elif k == 1 and len(mutated) > 1:
                del mutated[r.randrange(len(mutated))]
            else:
                mutated.insert(r.randrange(len(mutated) + 1), r.randrange(256))
        case = bytes(mutated)
        try:
            full = BlockHeader.decode(case)
            full_err = None
        except (ValueError, KeyError) as e:
            full, full_err = None, type(e)
        try:
            lite = BlockHeader.decode_lite(case)
            lite_err = None
        except (ValueError, KeyError) as e:
            lite, lite_err = None, type(e)
        assert (full_err is None) == (lite_err is None), case.hex()
        # the module-level decode_header_lite (C 5-field fast path) has its
        # OWN keep mask and folded validation — same accept/reject set
        # (UnicodeDecodeError narrows to its ValueError parent on skipped
        # text fields, so compare at the ValueError family)
        try:
            lh = decode_header_lite(case)
            lh_err = None
        except (ValueError, KeyError):
            lh, lh_err = None, True
        assert (full_err is None) == (lh_err is None), case.hex()
        if full_err is None:
            assert lite.parents == full.parents and lite.height == full.height
            assert lh.parents == full.parents and lh.height == full.height
            assert lh.messages == full.messages
            agree += 1
    log(f"header lite/full acceptance: {n} mutants, {agree} accepted identically")


def phase_trees(rng, quick):
    from ipc_proofs_tpu.ipld.amt import AMT, amt_build, amt_build_v0
    from ipc_proofs_tpu.ipld.hamt import HAMT, hamt_build, hamt_get_batch
    from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

    n = 200 if quick else 10000
    batch_checked = False
    for _ in range(n):
        bw = rng.choice([2, 3, 4, 5, 6, 8])
        kv = {
            rng.randbytes(rng.randrange(1, 40)): rng.randbytes(rng.randrange(0, 40))
            for _ in range(rng.randrange(1, 120))
        }
        bs = MemoryBlockstore()
        root = hamt_build(bs, kv, bit_width=bw)
        h = HAMT.load(bs, root, bit_width=bw)
        keys = list(kv) + [rng.randbytes(8) for _ in range(10)]
        rng.shuffle(keys)
        out = hamt_get_batch(bs, [root], [0] * len(keys), keys, bit_width=bw)
        if out is None:  # no native extension: scalar-only round-trips below
            batch_checked = False
        else:
            batch_checked = True
            for k, v in zip(keys, out):
                assert h.get(k) == v, (bw, k.hex())
        assert dict(h.items()) == kv
    log(
        f"HAMT random shapes: {n} trees clean "
        + ("(batch==scalar, items()==built)" if batch_checked
           else "(NATIVE UNAVAILABLE: scalar round-trips only)")
    )
    for _ in range(n):
        v0 = rng.random() < 0.5
        bw = 3 if v0 else rng.choice([1, 2, 3, 4, 5, 8])
        hi = rng.choice([50, 1000, 100000])
        entries = {
            rng.randrange(hi): rng.randbytes(rng.randrange(0, 30))
            for _ in range(rng.randrange(0, 150))
        }
        bs = MemoryBlockstore()
        if v0:
            root = amt_build_v0(bs, entries)
            a = AMT.load(bs, root, expected_version=0)
        else:
            root = amt_build(bs, entries, bit_width=bw)
            a = AMT.load(bs, root, expected_version=3)
        got = {}
        a.for_each(lambda i, v: got.__setitem__(i, v))
        assert got == entries
        for probe in list(entries)[:10] + [rng.randrange(hi) for _ in range(5)]:
            assert a.get(probe) == entries.get(probe)
    log(f"AMT random shapes: {n} trees clean (v0+v3 round-trips)")


def phase_range(rng, quick):
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import (
        generate_and_verify_range_overlapped,
        generate_event_proofs_for_range,
        generate_event_proofs_for_range_pipelined,
    )
    from ipc_proofs_tpu.proofs.trust import TrustPolicy
    from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle

    SIG, SUBNET, ACTOR = "NewTopDownMessage(bytes32,uint256)", "calib-subnet-1", 1001
    n = 20 if quick else 500
    for w in range(n):
        bs, pairs, n_match = build_range_world(
            rng.choice([1, 3, 7, 16, 33]),
            rng.choice([1, 4, 16]),
            rng.choice([1, 2, 5]),
            rng.choice([0.0, 0.05, 0.3]),
            signature=SIG,
            topic1=SUBNET,
            actor_id=ACTOR,
        )
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
        # half the worlds also prove a storage slot grid at every pair
        # (mixed range bundles exercise the batched storage generator)
        storage_specs = None
        if rng.random() < 0.5:
            from ipc_proofs_tpu.proofs.storage_batch import MappingSlotSpec

            storage_specs = [
                MappingSlotSpec(actor_id=ACTOR, key=SUBNET, slot_index=0),
                MappingSlotSpec(actor_id=ACTOR, key="absent-subnet", slot_index=0),
            ]
        prior = os.environ.get("IPC_SCAN_FUSED_MATCH")
        try:
            os.environ["IPC_SCAN_FUSED_MATCH"] = "1"
            flat = generate_event_proofs_for_range(
                bs, pairs, spec, storage_specs=storage_specs
            )
            os.environ["IPC_SCAN_FUSED_MATCH"] = "0"
            unfused = generate_event_proofs_for_range(
                bs, pairs, spec, storage_specs=storage_specs
            )
        finally:
            if prior is None:
                del os.environ["IPC_SCAN_FUSED_MATCH"]
            else:
                os.environ["IPC_SCAN_FUSED_MATCH"] = prior
        piped = generate_event_proofs_for_range_pipelined(
            bs, pairs, spec, chunk_size=rng.choice([1, 2, 5, 64]),
            storage_specs=storage_specs,
        )
        overlapped, chunk_results = generate_and_verify_range_overlapped(
            bs,
            pairs,
            spec,
            chunk_size=rng.choice([1, 2, 5, 64]),
            verify_chunk=lambda bundle: verify_proof_bundle(
                bundle, TrustPolicy.accept_all(), verify_witness_cids=True
            ),
            storage_specs=storage_specs,
        )
        ref = flat.to_json()
        assert unfused.to_json() == ref, f"unfused diverged, world {w}"
        assert piped.to_json() == ref, f"pipelined diverged, world {w}"
        assert overlapped.to_json() == ref, f"overlapped diverged, world {w}"
        assert all(r.all_valid() for r in chunk_results), f"verify failed, world {w}"
        assert len(flat.event_proofs) == n_match, f"count mismatch, world {w}"
        if (w + 1) % max(1, n // 4) == 0:
            log(f"range drivers: {w+1}/{n} random worlds bit-identical + verified")


def phase_json(rng, quick):
    import test_bls as tb
    import test_codec_exec_fuzz as cf

    n = 20 if quick else 200
    bundle_inst = cf.TestBundleJsonParsing()
    cert_inst = tb.TestCertificateJsonParsing()
    for _ in range(n):
        bundle_inst.test_randomized_structural_garbage_never_leaks(rng.randrange(1 << 30))
        cert_inst.test_randomized_structural_garbage_never_leaks(rng.randrange(1 << 30))
    log(f"bundle+cert JSON garbage: {n} fresh seeds each clean")


def phase_chaos(rng, quick):
    # fault-injection differential: under any seeded fault schedule the
    # pipelined driver must emit a bundle byte-identical to the fault-free
    # run or raise a typed error (tools/chaos.py holds the harness)
    import chaos

    summary = chaos.run_grid(
        rng.randrange(1 << 30),
        runs=5 if quick else 40,
        n_pairs=6 if quick else 16,
        log=log,
    )
    assert summary["ok"], summary
    log(
        f"chaos differential: {summary['runs']} runs clean "
        f"({summary['counts']['identical']} identical, "
        f"{summary['counts']['typed_error']} typed errors, "
        f"{summary['total_faults_injected']} faults injected)"
    )


def phase_crash(rng, quick):
    # crash-recovery differential: SIGKILL the journaled range driver at
    # fresh seeded kill points (chunk boundaries + torn mid-record writes),
    # resume, and demand a bundle byte-identical to the uninterrupted run
    # (tools/crashtest.py holds the harness)
    import crashtest

    summary = crashtest.run_grid(
        rng.randrange(1 << 30),
        points=4 if quick else 16,
        n_pairs=8 if quick else 16,
        log=log,
    )
    assert summary["ok"], summary
    log(
        f"crash recovery: {summary['points']} kill points over "
        f"{summary['n_chunks']} chunks, all resumed byte-identical"
    )


def phase_overload(rng, quick):
    # overload-survival differential: a deadline storm (seeded ample /
    # tight / mid-expiry budgets) against the admission-gated HTTP front
    # end — every answer must be byte-identical to the fault-free
    # reference for its pair or a TYPED refusal (deadline / admit /
    # throttle), never an untyped 500 and never a divergent bundle; plus
    # fresh-seed reruns of the SIGTERM grid and the slow-shard
    # quarantine grid (tools/crashtest.py / tools/chaos.py harnesses)
    import json as _json
    import threading

    from http.client import HTTPConnection

    import chaos
    import crashtest

    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.serve import ProofService, ServiceConfig
    from ipc_proofs_tpu.serve.httpd import ProofHTTPServer

    SIG, SUBNET = "NewTopDownMessage(bytes32,uint256)", "calib-subnet-1"
    n_pairs = 3 if quick else 6
    store, pairs, _ = build_range_world(
        n_pairs, 4, 2, 0.4, signature=SIG, topic1=SUBNET,
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET)
    service = ProofService(
        store=store, spec=spec,
        config=ServiceConfig(
            max_batch=4, max_wait_ms=2.0, workers=2,
            admit_gradient=True, admit_initial=4,
            admit_delay_budget_ms=50.0,
        ),
    )
    httpd = ProofHTTPServer(service, pairs=pairs).start()
    typed_refusals = {"deadline", "cancelled", "admit_rejected",
                      "tenant_throttled", "degraded"}
    try:
        def post(obj):
            conn = HTTPConnection("127.0.0.1", httpd.port, timeout=60)
            try:
                conn.request(
                    "POST", "/v1/generate", _json.dumps(obj),
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                return resp.status, resp.read()
            finally:
                conn.close()

        def canonical(data):
            # strip the per-request envelope (trace id, timing) — the
            # differential verdict is about the PROOF payload bytes
            obj = _json.loads(data)
            obj.pop("trace_id", None)
            obj.pop("server_timing", None)
            return _json.dumps(obj, sort_keys=True)

        references = {}
        for i in range(n_pairs):
            st, data = post({"pair_index": i})
            assert st == 200, data[:200]
            references[i] = canonical(data)

        n = 60 if quick else 400
        outcomes = {"identical": 0, "typed": 0}
        lock = threading.Lock()

        def storm(seed):
            import random as _random

            r = _random.Random(seed)
            for _ in range(n // 4):
                i = r.randrange(n_pairs)
                body = {"pair_index": i}
                draw = r.random()
                if draw < 0.3:
                    body["deadline_ms"] = r.choice([1, 3, 8, 15])  # tight
                elif draw < 0.5:
                    body["deadline_ms"] = r.randrange(2_000, 10_000)  # ample
                st, data = post(body)
                if st == 200:
                    assert canonical(data) == references[i], (
                        f"divergent bundle for pair {i} under deadline storm"
                    )
                    with lock:
                        outcomes["identical"] += 1
                else:
                    obj = _json.loads(data)
                    assert obj.get("error_type") in typed_refusals, (
                        st, obj,
                    )
                    with lock:
                        outcomes["typed"] += 1

        seeds = [rng.randrange(1 << 30) for _ in range(4)]
        threads = [threading.Thread(target=storm, args=(s,)) for s in seeds]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert outcomes["identical"] > 0, outcomes  # storms must do real work
        log(
            f"overload deadline storm: {sum(outcomes.values())} requests "
            f"({outcomes['identical']} identical, {outcomes['typed']} typed "
            "refusals), zero divergent/untyped"
        )
    finally:
        httpd.shutdown(timeout=30)
        service.drain()

    summary = crashtest.run_sigterm_grid(rng.randrange(1 << 30), log=log)
    assert summary["ok"], summary
    log("overload SIGTERM grid clean")
    summary = chaos.run_slow_shard_grid(
        rng.randrange(1 << 30), rounds=4 if quick else 10, log=log
    )
    assert summary["ok"], summary
    log(
        f"overload slow-shard grid clean "
        f"({summary['slow_quarantines']} quarantines)"
    )


def phase_hostkill(rng, quick):
    # multi-host recovery differential: kill a live shard mid-load in an
    # R=2 replicated cluster at fresh seeded victims/timings — every
    # answer that completes must be byte-identical to the single-process
    # driver (zero wrong bytes), and the cluster must serve a whole
    # scatter again within a bounded recovery window
    import json as _json
    import tempfile
    import threading

    from ipc_proofs_tpu.cluster import ClusterRouter, LocalShard
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_chunked
    from ipc_proofs_tpu.serve.service import ServiceConfig
    from ipc_proofs_tpu.utils.metrics import Metrics

    SIG, SUBNET = "NewTopDownMessage(bytes32,uint256)", "calib-subnet-1"
    store, pairs, _ = build_range_world(
        6 if quick else 10, 4, 2, 0.3, signature=SIG, topic1=SUBNET,
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET)
    reference = _json.dumps(
        generate_event_proofs_for_range_chunked(
            store, list(pairs), spec, chunk_size=3
        ).to_json_obj(),
        sort_keys=True,
    )
    idxs = list(range(len(pairs)))
    rounds = 2 if quick else 6
    n_shards = 3
    for rnd in range(rounds):
        with tempfile.TemporaryDirectory(prefix="soak_hostkill_") as workdir:
            shards = [
                LocalShard(
                    f"s{k}", store, pairs, spec,
                    config=ServiceConfig(
                        max_batch=8, max_wait_ms=5.0, workers=1,
                        store_dir=os.path.join(workdir, f"s{k}"),
                        store_owner=f"s{k}",
                        store_segment_max_bytes=1,
                    ),
                    metrics=Metrics(),
                ).start()
                for k in range(n_shards)
            ]
            m = Metrics()
            router = ClusterRouter(
                {s.name: s.url for s in shards}, pairs,
                replication_factor=2, metrics=m, scrape_interval_s=60.0,
            )
            try:
                status, obj = router.generate_range(idxs, chunk_size=3)
                assert status == 200, obj
                summary = router.replicate_now()
                assert not summary["errors"], summary

                wrong: list = []
                stop = threading.Event()

                def load():
                    while not stop.is_set():
                        try:
                            st, o = router.generate_range(idxs, chunk_size=3)
                        except Exception as exc:  # fail-soft: an untyped escape IS the phase finding — recorded in `wrong` and failed below
                            wrong.append(f"untyped {type(exc).__name__}: {exc}")
                            return
                        if st != 200:
                            # a typed refusal must still be typed JSON
                            if not isinstance(o, dict) or "error" not in o:
                                wrong.append(f"untyped non-200: {st} {o!r}")
                                return
                            continue
                        got = _json.dumps(o["bundle"], sort_keys=True)
                        if got != reference:
                            wrong.append("DIVERGENT BYTES")
                            return

                t = threading.Thread(target=load)
                t.start()
                time.sleep(0.02 + rng.random() * 0.1)  # kill mid-load
                victim = shards[rng.randrange(n_shards)]
                t_kill = time.monotonic()
                victim.kill()
                # recovery: the next whole byte-identical scatter
                recovered = None
                while time.monotonic() - t_kill < 30.0:
                    st, o = router.generate_range(idxs, chunk_size=3)
                    if st == 200 and _json.dumps(
                        o["bundle"], sort_keys=True
                    ) == reference:
                        recovered = (time.monotonic() - t_kill) * 1000.0
                        break
                stop.set()
                t.join()
                assert not wrong, f"round {rnd}: {wrong}"
                assert recovered is not None, (
                    f"round {rnd}: no whole scatter within 30s of killing "
                    f"{victim.name}"
                )
                log(
                    f"hostkill round {rnd}: killed {victim.name}, whole again "
                    f"in {recovered:,.0f} ms, zero wrong bytes"
                )
            finally:
                router.close()
                for s in shards:
                    try:
                        s.stop(timeout=10)
                    except Exception:  # fail-soft: best-effort teardown; a shard that won't stop must not mask the round verdict
                        pass


PHASES = {
    "event": phase_event,
    "storage": phase_storage,
    "shapes": phase_shapes,
    "codec": phase_codec,
    "rleplus": phase_rleplus,
    "cert": phase_cert,
    "dagcbor": phase_dagcbor,
    "header": phase_header,
    "trees": phase_trees,
    "range": phase_range,
    "json": phase_json,
    "chaos": phase_chaos,
    "crash": phase_crash,
    "hostkill": phase_hostkill,
    "overload": phase_overload,
}


def main() -> None:
    args = [a for a in sys.argv[1:]]
    quick = "--quick" in args
    args = [a for a in args if a != "--quick"]
    if not args:
        print(__doc__)
        raise SystemExit(2)
    base = int(args[0])
    wanted = args[1:] or list(PHASES)
    unknown = [p for p in wanted if p not in PHASES]
    if unknown:
        raise SystemExit(f"unknown phase(s): {unknown}; have {list(PHASES)}")
    log(f"base seed {base}, phases {wanted}, quick={quick}")
    for name in wanted:
        # one rng per phase, seeded from (base, name): running a phase
        # alone reproduces exactly what the all-phases run gave it
        PHASES[name](random.Random(f"{base}:{name}"), quick)
    log("SOAK CLEAN")


if __name__ == "__main__":
    main()
