"""Standing queries: register once, stream proofs as tipsets finalize.

The subsystem that turns the serve daemon from request/response into a
proof *streaming* service (ROADMAP item 2). Lifecycle:

    register → follow → match → generate-once → fan-out → ack

- `registry.SubscriptionRegistry` — IPJ1-journaled (filter, target)
  table; registrations survive restart, duplicate ids absorb idempotently.
- `matcher.StandingQueryMatcher` — the `ChainFollower` finalized-tipset
  hook; one generation per distinct (pair, filter) shared by every
  subscriber, byte-identical to the request/response path.
- `delivery.DeliveryLog` / `delivery.PushDelivery` — at-least-once
  fan-out: per-sub monotonic cursors, idempotency keys, webhook push
  with bounded full-jitter retry, long-poll fallback, byte-capped
  truncation only below the acked cursor.

`StandingQueries` is the facade the CLI/HTTP layers wire: one object
owning all four pieces, with `on_tipset` as the follower hook and
`drain()` ordered so delivery workers finish before the store tiers
close.
"""

from __future__ import annotations

import random
import time
from typing import Any, Optional

from ipc_proofs_tpu.subs.delivery import (
    Delivery,
    DeliveryLog,
    PushDelivery,
    delivery_idempotency_key,
)
from ipc_proofs_tpu.subs.matcher import StandingQueryMatcher
from ipc_proofs_tpu.subs.registry import (
    Subscription,
    SubscriptionRegistry,
    filter_key,
    normalize_filter,
    normalize_target,
    subscription_ring_key,
)
from ipc_proofs_tpu.utils.metrics import Metrics, get_metrics

__all__ = [
    "Delivery",
    "DeliveryLog",
    "PushDelivery",
    "StandingQueries",
    "StandingQueryMatcher",
    "Subscription",
    "SubscriptionRegistry",
    "delivery_idempotency_key",
    "filter_key",
    "normalize_filter",
    "normalize_target",
    "subscription_ring_key",
]


class StandingQueries:
    """Facade owning registry + delivery log + push workers + matcher."""

    def __init__(
        self,
        root: str,
        store,
        metrics: Optional[Metrics] = None,
        *,
        chunk_size: int = 8,
        match_backend=None,
        fsync: bool = True,
        log_cap_bytes: int = 64 << 20,
        push_max_inflight: int = 4,
        retry_attempts: int = 4,
        retry_base_s: float = 0.25,
        retry_max_s: float = 4.0,
        push_timeout_s: float = 10.0,
        gen_workers: int = 2,
        delta: bool = True,
        service=None,
        provenance=None,
        fleet: str = "default",
        opener=None,
        sleep=time.sleep,
        rng: Optional[random.Random] = None,
    ):
        self._metrics = metrics if metrics is not None else get_metrics()
        self.registry = SubscriptionRegistry(root, metrics=self._metrics, fsync=fsync)
        self.log = DeliveryLog(
            root, metrics=self._metrics, cap_bytes=log_cap_bytes, fsync=fsync
        )
        self.push = PushDelivery(
            self.log,
            metrics=self._metrics,
            max_inflight=push_max_inflight,
            max_attempts=retry_attempts,
            base_delay_s=retry_base_s,
            max_delay_s=retry_max_s,
            timeout_s=push_timeout_s,
            opener=opener,
            sleep=sleep,
            rng=rng,
        )
        self.matcher = StandingQueryMatcher(
            self.registry,
            self.log,
            self.push,
            store,
            metrics=self._metrics,
            chunk_size=chunk_size,
            match_backend=match_backend,
            gen_workers=gen_workers,
            delta=delta,
            service=service,
            provenance=provenance,
            fleet=fleet,
        )
        # fleet base directory (ROADMAP item 5): acked-base advances flow
        # into the provenance registry keyed (fleet, filter key, sub), so
        # a base survives failover AND compaction fleet-wide — any shard
        # can cut a delta against the newest base this fleet acked
        self.provenance = provenance
        self.fleet = fleet
        if provenance is not None:
            self.log.set_base_reporter(self._report_base)
            # restart sweep: re-seed the directory from replayed acked
            # state (the registry dedups (sub, cursor, digest) replays)
            for sub_id, (digest, cursor) in self.log.bases().items():
                self._report_base(sub_id, digest, cursor)
        # Restart convergence: deliveries that were unacked at the last
        # shutdown/crash re-push as soon as the daemon is back.
        if self.log.pending_total():
            self.push.repush_pending(self.registry)

    def _report_base(self, sub_id: str, digest: str, cursor: int) -> None:
        """DeliveryLog base-advance hook → registry base record. Fail-soft:
        directory trouble never blocks the ack path."""
        sub = self.registry.get(sub_id)
        if sub is None:
            return
        try:
            self.provenance.append_base_ack(
                self.fleet, filter_key(sub.filter), sub_id, digest, cursor
            )
        except Exception:  # fail-soft: losing one base ack only costs a future delta, never the push
            self._metrics.count("registry.append_failures")

    # ---------------------------------------------------------- follower hook

    def on_tipset(self, tipset) -> int:
        return self.matcher.on_tipset(tipset)

    # ------------------------------------------------------------- HTTP plane

    def subscribe(self, body: Any) -> dict:
        """``POST /v1/subscribe`` — body: {filter, target?, sub_id?}."""
        if not isinstance(body, dict):
            raise ValueError("body must be a JSON object")
        sub, created = self.registry.subscribe(
            body.get("filter"), body.get("target"), sub_id=body.get("sub_id")
        )
        return {"sub_id": sub.sub_id, "created": created}

    def unsubscribe(self, body: Any) -> dict:
        """``POST /v1/unsubscribe`` — body: {sub_id}."""
        if not isinstance(body, dict) or not body.get("sub_id"):
            raise ValueError("body.sub_id is required")
        return {"removed": self.registry.unsubscribe(str(body["sub_id"]))}

    def subscriptions(self) -> dict:
        """``GET /v1/subscriptions``."""
        subs = sorted(self.registry.active(), key=lambda s: s.sub_id)
        return {
            "count": len(subs),
            "subscriptions": [s.to_json_obj() for s in subs],
        }

    def deliveries(
        self, sub_id: str, cursor: int = 0, wait_s: float = 0.0
    ) -> Optional[dict]:
        """``GET /v1/deliveries?sub=<id>&cursor=<n>`` — the long-poll
        fallback. A client at cursor N owns everything ≤ N (acked here),
        and blocks up to ``wait_s`` for entries above it. Returns None
        for an unknown subscription."""
        if self.registry.get(sub_id) is None:
            return None
        cursor = max(0, int(cursor))
        if cursor:
            self.log.ack_through(sub_id, cursor)
        entries = self.log.entries_after(sub_id, cursor, wait_s=wait_s)
        return {
            "sub_id": sub_id,
            "cursor": max([e.cursor for e in entries], default=cursor),
            "deliveries": [e.to_json_obj() for e in entries],
        }

    # ------------------------------------------------------------ diagnostics

    def health_fields(self) -> dict:
        """Merged into ``/healthz`` beside the durable queue's fields."""
        return {
            "subscriptions": len(self.registry),
            "pending_deliveries": self.log.pending_total(),
            "delivery_log_bytes": self.log.journal_bytes,
            "subs_degraded": bool(self.registry.degraded or self.log.degraded),
        }

    def drain(self) -> None:
        """Matcher first (stop producing), then push workers (finish
        delivering — they read proof payloads, so this MUST complete
        before the serve plane closes its store tiers), then the logs."""
        self.matcher.drain()
        self.push.drain()
        self.log.close()
        self.registry.close()
