"""Standing-query matcher: the follower's tipset-finalized hook.

On each finalized tipset the matcher forms the (previous, current)
`TipsetPair` and compiles the active subscription set down to its
**distinct filters** — generation cost scales with filters, never with
subscribers (``subs.generations`` counts exactly one per (pair, filter);
the bench gate asserts generations per tipset ≤ distinct filters).

Each distinct filter generates through the SAME driver the
request/response path uses (`generate_event_proofs_for_range_chunked`
with the service's chunk size and match backend), so a pushed bundle is
byte-identical to what `/v1/generate_range` would return for the same
(pair, filter). Distinct filters generate concurrently, and when the
match backend speaks the fp-mask protocol their per-chunk device
predicate calls route through ONE shared
`parallel.pipeline.MatchCoalescer` — one batched device match dispatch
serves every subscriber of the tipset.

Delta delivery (the witness diet, ROADMAP item 1): the matcher keeps each
filter's previous (digest, CID set) and, when a subscriber's acked base
(`DeliveryLog.acked_base`) is exactly that digest, ships a
``bundle_delta`` payload — only the blocks the base doesn't hold — via
`ipc_proofs_tpu.witness.delta`. Consecutive epochs share HAMT/AMT
interiors, so a subscriber who acked epoch N receives a fraction of epoch
N+1's bytes. Any base mismatch (lagging sub, restart, compaction) falls
back to the full bundle with ``witness.delta_fallbacks`` counted.

Everything here is fail-soft: a filter whose generation raises counts
``subs.errors`` and the other filters still deliver; the follower's hook
wrapper catches the rest (``follow.errors``) so the follow loop never
stalls on the streaming plane.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from ipc_proofs_tpu.proofs.bundle import bundle_obj_digest
from ipc_proofs_tpu.proofs.generator import EventProofSpec, StorageProofSpec
from ipc_proofs_tpu.subs.registry import Subscription, filter_key
from ipc_proofs_tpu.utils.lockdep import named_lock
from ipc_proofs_tpu.utils.log import get_logger
from ipc_proofs_tpu.utils.metrics import Metrics, get_metrics

__all__ = ["StandingQueryMatcher"]

logger = get_logger(__name__)


class _CoalescingBackend:
    """Backend proxy routing fp-mask calls through one shared coalescer.

    Concurrent per-filter generations each scan the same tipset pair;
    wrapping the backend so ``event_match_mask_fp`` is a shared
    `MatchCoalescer.match_fp` (a documented drop-in for it) folds their
    simultaneous predicate calls into one batched device dispatch.
    Every other attribute (mesh, flat/fused entry points, ...) delegates
    to the real backend, and the coalescer's masks are bit-identical to
    unbatched calls (elementwise predicate), so bundles don't change.
    """

    def __init__(self, backend, metrics: Optional[Metrics] = None):
        from ipc_proofs_tpu.parallel.pipeline import MatchCoalescer

        self._backend = backend
        self.event_match_mask_fp = MatchCoalescer(backend, metrics=metrics).match_fp

    def __getattr__(self, name):
        return getattr(self._backend, name)


# Content digest of a bundle's canonical JSON — the idempotency-key
# ingredient that makes matcher replays of a (pair, filter) dedup, and the
# delta-witness base identity (kept under its historical name; the shared
# definition lives beside the bundle type).
_bundle_digest = bundle_obj_digest


class StandingQueryMatcher:
    """Compiles the active filter set against each finalized tipset pair."""

    def __init__(
        self,
        registry,
        log,
        push,
        store,
        metrics: Optional[Metrics] = None,
        chunk_size: int = 8,
        match_backend=None,
        gen_workers: int = 2,
        delta: bool = True,
        service=None,
        provenance=None,
        fleet: str = "default",
    ):
        self._registry = registry
        self._log = log
        self._push = push
        self._store = store
        # provenance registry (ipc_proofs_tpu/registry/): every pushed
        # bundle seals a serve record (whose CID set feeds the fleet base
        # directory), and the delta fallback consults that directory so a
        # base acked against ANOTHER shard — or against this one before a
        # restart — still cuts a delta instead of re-shipping full bytes
        self._provenance = provenance
        self.fleet = fleet
        # with a ProofService attached, generations ride its batcher's
        # PUSH lane (`submit_range_window(lane="push")`) instead of this
        # matcher's private executor — one priority order across
        # interactive requests, standing-query pushes and backfill windows
        # instead of two planes competing blindly for the same workers
        self._service = service
        self._metrics = metrics if metrics is not None else get_metrics()
        self.chunk_size = max(1, int(chunk_size))
        self.delta = bool(delta)
        if match_backend is not None and hasattr(match_backend, "event_match_mask_fp"):
            match_backend = _CoalescingBackend(match_backend, metrics=self._metrics)
        self._backend = match_backend
        self._executor = ThreadPoolExecutor(
            max_workers=max(1, int(gen_workers)), thread_name_prefix="subs-match"
        )
        self._lock = named_lock("StandingQueryMatcher._lock")
        self._prev = None  # guarded-by: _lock (previous finalized tipset)
        self._closed = False  # guarded-by: _lock
        # delta-witness bases: filter key → (digest, frozenset of raw CIDs)
        # of the PREVIOUS cycle's bundle. In-memory only — after a restart
        # the first cycle ships full bundles (witness.delta_fallbacks), the
        # documented sound degradation.
        self._filter_bases: Dict[str, Tuple[str, frozenset]] = {}  # guarded-by: _lock

    def on_tipset(self, tipset) -> int:
        """The `ChainFollower` finalized hook: pair this tipset with the
        previous one and match. Returns deliveries appended."""
        with self._lock:
            if self._closed:
                return 0
            prev, self._prev = self._prev, tipset
        if prev is None or tipset.height <= prev.height:
            return 0  # first observation (no pair yet) or a replayed height
        from ipc_proofs_tpu.proofs.range import TipsetPair

        return self.match_pair(TipsetPair(parent=prev, child=tipset))

    def match_pair(self, pair) -> int:
        """One matching cycle: re-push stragglers, generate once per
        distinct filter, fan the bundles out."""
        subs = self._registry.active()
        self._metrics.count("subs.tipsets_matched")
        # Convergence first: deliveries whose webhook failed on an earlier
        # cycle re-enqueue before this tipset's new work.
        self._push.repush_pending(self._registry)
        if not subs:
            return 0
        groups: Dict[str, Tuple[dict, List[Subscription]]] = {}
        for sub in subs:
            fkey = filter_key(sub.filter)
            if fkey not in groups:
                groups[fkey] = (sub.filter, [])
            groups[fkey][1].append(sub)
        futures = {
            fkey: self._executor.submit(self._generate, filt, pair)
            for fkey, (filt, _members) in groups.items()
        }
        appended = 0
        for fkey, fut in futures.items():
            try:
                result = fut.result()
            except Exception as exc:  # fail-soft: one filter's generation failure must not starve the other filters' subscribers
                self._metrics.count("subs.errors")
                logger.warning("standing-query generation failed: %s", exc)
                continue
            if result is None:
                self._metrics.count("subs.empty_matches")
                continue
            bundle, payload, digest = result
            if self._provenance is not None:
                try:
                    self._provenance.append_served(
                        digest, key=fkey, verdict="pushed",
                        cids=bundle.cid_set(),
                    )
                except Exception:  # fail-soft: a registry write failure must never block the push
                    self._metrics.count("registry.append_failures")
            with self._lock:
                prev = self._filter_bases.get(fkey)
            # one delta per (filter, base) serves every subscriber parked
            # on that base — same amortization as the generate-once rule
            deltas: Dict[str, Tuple[dict, str]] = {}
            for sub in groups[fkey][1]:
                pay, pdigest = payload, digest
                if self.delta:
                    pay, pdigest = self._delta_payload(
                        sub, bundle, payload, digest, prev, deltas
                    )
                d = self._log.append(
                    sub.sub_id,
                    pair.child.height,
                    digest,
                    pay,
                    payload_digest=pdigest,
                )
                if d is None:
                    continue  # idempotent replay of a served (pair, filter)
                self._metrics.count("subs.notifications")
                appended += 1
                self._push.push(sub, d)
            with self._lock:
                self._filter_bases[fkey] = (digest, bundle.cid_set())
        return appended

    def _delta_payload(
        self, sub, bundle, payload: dict, digest: str, prev, deltas: dict
    ) -> "Tuple[dict, str]":
        """Pick full vs delta for one subscriber.

        A delta ships ONLY when the sub's acked base (the bundle it
        provably expanded — `DeliveryLog.acked_base`) is exactly the
        filter's previous digest, whose CID set we still hold. Any
        mismatch — sub lagging, matcher restarted, base compacted away —
        falls back to the full bundle and counts
        ``witness.delta_fallbacks``: degradation, never a wrong delta.
        """
        base = self._log.acked_base(sub.sub_id)
        if base is None and self._provenance is not None:
            # fresh delivery log (failover takeover): the fleet directory
            # still knows the base THIS subscriber last acked — recorded
            # by whichever shard served it — so the delta survives the
            # shard that held the local acked state
            try:
                base = self._provenance.fleet_acked_base(
                    self.fleet, filter_key(sub.filter), sub.sub_id
                )
            except Exception:  # fail-soft: directory trouble degrades to a full bundle, never an error
                base = None
        if base is None or base == digest:
            return payload, digest  # nothing held yet / replay of same bundle
        if prev is not None and base == prev[0]:
            base_cids = prev[1]
        else:
            # local miss (matcher restarted, base compacted, or the sub
            # last acked against another shard): the fleet base directory
            # may still know the base's CID set via ANY shard's serve
            # record — a hit keeps the delta alive across failover
            base_cids = None
            if self._provenance is not None:
                try:
                    base_cids = self._provenance.lookup_base(base)
                except Exception:  # fail-soft: directory trouble degrades to a full bundle, never an error
                    base_cids = None
                self._metrics.count(
                    "witness.fleet_base_hits"
                    if base_cids is not None
                    else "witness.fleet_base_misses"
                )
            if base_cids is None:
                self._metrics.count("witness.delta_fallbacks")
                return payload, digest
        if base not in deltas:
            from ipc_proofs_tpu.witness.delta import encode_delta

            dobj = encode_delta(
                bundle, base_cids, base, digest=digest, metrics=self._metrics
            )
            deltas[base] = ({"bundle_delta": dobj}, f"delta:{base}:{digest}")
        self._metrics.count("witness.delta_hits")
        return deltas[base]

    def _generate(self, filt: dict, pair):
        """One generation per distinct (pair, filter) — the amortized unit."""
        from ipc_proofs_tpu.proofs.range import (
            generate_event_proofs_for_range_chunked,
        )

        spec = EventProofSpec(
            event_signature=filt["signature"],
            topic_1=filt.get("topic1"),
            actor_id_filter=filt.get("actor_id"),
        )
        storage_specs = None
        if "slot" in filt:
            storage_specs = [
                StorageProofSpec(
                    actor_id=filt["actor_id"], slot=bytes.fromhex(filt["slot"])
                )
            ]
        if self._service is not None:
            # unified priority lane: the service's batcher orders this
            # push ahead of interactive batches, and the canonical
            # chunked driver keeps the bytes identical to the direct call
            bundle = self._service.submit_range_window(
                [pair],
                chunk_size=self.chunk_size,
                lane="push",
                spec=spec,
                storage_specs=storage_specs,
            ).result()
        else:
            bundle = generate_event_proofs_for_range_chunked(
                self._store,
                [pair],
                spec,
                chunk_size=self.chunk_size,
                match_backend=self._backend,
                metrics=self._metrics,
                storage_specs=storage_specs,
            )
        self._metrics.count("subs.generations")
        if not bundle.event_proofs and not bundle.storage_proofs:
            return None
        bundle_obj = bundle.to_json_obj()
        return bundle, {"bundle": bundle_obj}, _bundle_digest(bundle_obj)

    def drain(self) -> None:
        """Stop matching and wait for in-flight generations."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._executor.shutdown(wait=True)
