"""At-least-once proof delivery: append-only log + webhook push.

`DeliveryLog` is the durable half: one shared ``IPJ1`` journal
(``<root>/deliveries.bin``) holding every subscription's deliveries with
per-subscription **monotonic cursors**. A delivery's idempotency key is
derived from ``(sub_id, tipset, proof digest)``, so re-running the
matcher over a tipset it already served (follower restart, cluster
failover replay) dedups instead of double-delivering. Acks journal too:
unacked deliveries survive SIGKILL and are re-pushed after restart.

Payloads are content-addressed: the bundle JSON journals ONCE per proof
digest (a ``pay`` frame) and every subscriber's ``dlv`` frame references
it by digest — the on-disk fan-out cost of a 10k-subscriber filter is
10k tiny cursor frames plus one bundle, mirroring the matcher's
generate-once amortization. A payload is dropped from memory (and from
the next compaction) only when no unacked delivery references it.

Byte-capped truncation: when the journal exceeds ``cap_bytes`` it is
compacted to per-sub state records plus the still-unacked deliveries —
truncation only ever drops entries **below the acked cursor**, so an
unacked delivery is never lost to the cap. Journal write failures
(ENOSPC/EROFS) degrade fail-soft (``subs.log_failures``): the log keeps
serving from memory and the run completes.

`PushDelivery` is the webhook half: bounded full-jitter retry with the
same injectable ``opener``/``sleep``/``rng`` seams as
`obs.export.post_otlp_trace`, acking on 2xx. A push that exhausts its
retries leaves the delivery unacked — the long-poll
``/v1/deliveries?sub=<id>&cursor=<n>`` fallback and the next matcher
cycle's re-push both converge on it later (at-least-once, never
at-most-once).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple
from urllib.error import HTTPError

from ipc_proofs_tpu.jobs.journal import (
    JournalWriter,
    frame_record,
    read_journal_entries,
)
from ipc_proofs_tpu.utils.lockdep import named_condition, named_lock
from ipc_proofs_tpu.utils.log import get_logger
from ipc_proofs_tpu.utils.threads import locked
from ipc_proofs_tpu.utils.metrics import Metrics, get_metrics

__all__ = [
    "Delivery",
    "DeliveryLog",
    "PushDelivery",
    "delivery_idempotency_key",
]

logger = get_logger(__name__)

DELIVERY_JOURNAL = "deliveries.bin"
DEFAULT_LOG_CAP_BYTES = 64 << 20

# Retry policy mirrors obs.export.post_otlp_trace: retry throttle/server
# errors, fail fast on 4xx client errors.
_RETRYABLE_STATUSES = frozenset({429, 500, 502, 503, 504})


def delivery_idempotency_key(sub_id: str, tipset: int, digest: str) -> str:
    """Stable identity of one delivery: (sub_id, tipset, proof digest)."""
    raw = f"{sub_id}|{int(tipset)}|{digest}".encode("utf-8")
    return hashlib.sha256(raw).hexdigest()[:32]


@dataclass(frozen=True)
class Delivery:
    """One appended (not-yet-acked) proof delivery.

    ``digest`` is always the FULL canonical bundle digest (the client's
    post-expansion identity and the idempotency-key ingredient);
    ``payload_digest`` names the bytes actually shipped — identical to
    ``digest`` for full bundles, distinct for delta payloads, so the
    content-addressed payload store never conflates a delta with the
    full bundle it reconstructs."""

    sub_id: str
    cursor: int
    key: str
    tipset: int
    digest: str
    payload: dict
    payload_digest: str = ""

    def __post_init__(self):
        if not self.payload_digest:
            object.__setattr__(self, "payload_digest", self.digest)

    def to_json_obj(self) -> dict:
        return {
            "cursor": self.cursor,
            "idempotency_key": self.key,
            "tipset": self.tipset,
            "digest": self.digest,
            "payload": self.payload,
        }


@dataclass
class _SubLog:
    """Per-subscription delivery state (guarded by DeliveryLog._cond)."""

    next_cursor: int = 1
    acked: int = 0  # contiguous ack watermark: every cursor <= acked is acked
    acked_extra: Set[int] = field(default_factory=set)  # acks above the watermark
    entries: Dict[int, Delivery] = field(default_factory=dict)  # unacked, by cursor
    keys: Set[str] = field(default_factory=set)  # idempotency keys ever appended
    # delta-witness cursor hygiene: the FULL-bundle digest of the highest
    # acked delivery — the bundle this subscriber provably holds, i.e. the
    # only sound delta base. Persisted in sstate frames so compaction
    # dropping the acked entry (and its pay frame) never leaves a delta
    # referencing a base the log no longer knows about.
    base_digest: Optional[str] = None
    base_cursor: int = 0  # cursor whose ack set base_digest


class DeliveryLog:
    """Shared append-only delivery journal with per-sub monotonic cursors."""

    def __init__(
        self,
        root: str,
        metrics: Optional[Metrics] = None,
        cap_bytes: int = DEFAULT_LOG_CAP_BYTES,
        fsync: bool = True,
    ):
        os.makedirs(root, exist_ok=True)
        self.path = os.path.join(root, DELIVERY_JOURNAL)
        self.cap_bytes = max(1 << 16, int(cap_bytes))
        self._fsync = fsync
        self._metrics = metrics if metrics is not None else get_metrics()
        # The condition's lock guards ALL log state; long-poll waiters
        # block on it until an append lands for their subscription.
        self._cond = named_condition("DeliveryLog._cond")
        self._subs: Dict[str, _SubLog] = {}  # guarded-by: _cond
        # content-addressed payload store: digest → bundle payload, with a
        # refcount of unacked deliveries pointing at it
        self._payloads: Dict[str, dict] = {}  # guarded-by: _cond
        self._payload_refs: Dict[str, int] = {}  # guarded-by: _cond
        # running count of unacked entries across all subs — the gauges
        # publish on every append/ack, so this must be O(1), not a sweep
        self._pending = 0  # guarded-by: _cond
        # idempotency key → append monotonic time, for the delivery-lag
        # histogram; replayed deliveries have no entry (lag across a
        # restart would be measuring downtime, not delivery)
        self._append_ts: Dict[str, float] = {}  # guarded-by: _cond
        # fleet base-directory feed (set_base_reporter): called AFTER the
        # lock is released with (sub_id, base_digest, base_cursor) whenever
        # an ack advances a sub's delta base — the callback may take its
        # own locks (provenance registry) so it must never run under _cond
        self._base_reporter = None
        self.replayed = 0
        if os.path.exists(self.path):
            entries, good_offset, torn = read_journal_entries(self.path)
            if torn:
                logger.warning(
                    "delivery journal %s has a torn tail — truncating to "
                    "last good frame at %d",
                    self.path,
                    good_offset,
                )
                with open(self.path, "r+b") as fh:
                    fh.truncate(good_offset)
            for rec, _off, _end in entries:
                self._replay(rec)
            self.replayed = len(entries)
        self._writer = JournalWriter(self.path, metrics=self._metrics, fsync=fsync)
        self._publish_gauges_locked()

    # ------------------------------------------------------------------ replay

    @locked
    def _sub(self, sub_id: str) -> _SubLog:
        sl = self._subs.get(sub_id)
        if sl is None:
            sl = self._subs[sub_id] = _SubLog()
        return sl

    @locked  # construction-time only: runs before the log is published
    def _replay(self, rec: Any) -> None:
        if not isinstance(rec, dict):
            return
        op = rec.get("op")
        try:
            if op == "pay":
                self._payloads[str(rec["digest"])] = rec.get("payload") or {}
            elif op == "dlv":
                sl = self._sub(str(rec["sub"]))
                cursor = int(rec["cursor"])
                digest = str(rec["digest"])
                # dlv frames reference their payload by payload digest
                # (== digest for full bundles); an inline "payload" key is
                # the pre-content-addressing format
                pdigest = str(rec.get("pdigest") or digest)
                payload = (
                    rec["payload"]
                    if "payload" in rec
                    else self._payloads.get(pdigest, {})
                )
                d = Delivery(
                    sub_id=str(rec["sub"]),
                    cursor=cursor,
                    key=str(rec["key"]),
                    tipset=int(rec["tipset"]),
                    digest=digest,
                    payload=payload or {},
                    payload_digest=pdigest,
                )
                if cursor not in sl.entries:
                    self._pending += 1
                sl.entries[cursor] = d
                sl.keys.add(d.key)
                sl.next_cursor = max(sl.next_cursor, cursor + 1)
                self._payloads.setdefault(pdigest, d.payload)
                self._payload_refs[pdigest] = self._payload_refs.get(pdigest, 0) + 1
            elif op == "ack":
                sl = self._sub(str(rec["sub"]))
                self._ack_entry(sl, int(rec["cursor"]))
            elif op == "sstate":
                sl = self._sub(str(rec["sub"]))
                sl.next_cursor = max(sl.next_cursor, int(rec["next"]))
                sl.acked = max(sl.acked, int(rec["acked"]))
                sl.acked_extra.update(int(c) for c in rec.get("acked_extra", []))
                sl.keys.update(str(k) for k in rec.get("keys", []))
                if int(rec.get("base_cursor", 0)) >= sl.base_cursor and rec.get(
                    "base_digest"
                ):
                    sl.base_digest = str(rec["base_digest"])
                    sl.base_cursor = int(rec.get("base_cursor", 0))
        except (KeyError, ValueError, TypeError):
            return  # fail-soft: one bad frame, not the whole replay

    @staticmethod
    def _apply_ack(sl: _SubLog, cursor: int) -> None:
        sl.entries.pop(cursor, None)
        if cursor > sl.acked:
            sl.acked_extra.add(cursor)
        while (sl.acked + 1) in sl.acked_extra:
            sl.acked += 1
            sl.acked_extra.discard(sl.acked)

    @locked
    def _ack_entry(self, sl: _SubLog, cursor: int) -> None:
        """Ack + payload-refcount bookkeeping: the last unacked reference
        to a payload digest releases it from the content store. An ack
        also advances the sub's delta base: the acked delivery's FULL
        digest is a bundle the subscriber now provably holds."""
        d = sl.entries.get(cursor)
        self._apply_ack(sl, cursor)
        if d is None:
            return
        self._pending -= 1
        t0 = self._append_ts.pop(d.key, None)
        if t0 is not None:
            self._metrics.observe(
                "subs.delivery_lag_ms", (time.monotonic() - t0) * 1000.0
            )
        if cursor >= sl.base_cursor:
            sl.base_digest = d.digest
            sl.base_cursor = cursor
        n = self._payload_refs.get(d.payload_digest, 0) - 1
        if n <= 0:
            self._payload_refs.pop(d.payload_digest, None)
            self._payloads.pop(d.payload_digest, None)
        else:
            self._payload_refs[d.payload_digest] = n

    # ---------------------------------------------------------------- mutation

    @locked
    def _append_rec(self, rec: dict) -> None:
        """Journal one frame; the delivery / ack frame must land before
        the cursor becomes observable, hence under the lock."""
        if not self._writer.append(rec):  # ipclint: disable=lock-held-blocking (durability: frame lands before the cursor is observable)
            self._metrics.count("subs.log_failures")

    @locked
    def _publish_gauges_locked(self) -> None:
        self._metrics.set_gauge("subs.pending_deliveries", self._pending)
        self._metrics.set_gauge("subs.log_bytes", self._writer.journal_bytes)

    def append(
        self,
        sub_id: str,
        tipset: int,
        digest: str,
        payload: dict,
        payload_digest: Optional[str] = None,
    ) -> Optional[Delivery]:
        """Append one delivery; returns ``None`` if its idempotency key was
        already seen (matcher replay absorbed, nothing to deliver twice).

        ``payload_digest`` names the shipped bytes when they differ from
        the full bundle (a delta payload); idempotency stays keyed on the
        FULL digest, so a delta re-delivery of an already-served proof
        still dedups."""
        key = delivery_idempotency_key(sub_id, tipset, digest)
        pdigest = payload_digest or digest
        with self._cond:
            sl = self._sub(sub_id)
            if key in sl.keys:
                self._metrics.count("subs.delivery_dedup")
                return None
            cursor = sl.next_cursor
            sl.next_cursor = cursor + 1
            d = Delivery(
                sub_id=sub_id,
                cursor=cursor,
                key=key,
                tipset=int(tipset),
                digest=digest,
                payload=payload,
                payload_digest=pdigest,
            )
            sl.entries[cursor] = d
            sl.keys.add(key)
            self._pending += 1
            self._append_ts[key] = time.monotonic()
            if pdigest not in self._payloads:
                # first subscriber of this payload journals it; the other
                # 9,999 journal a reference
                self._payloads[pdigest] = payload
                self._append_rec({"op": "pay", "digest": pdigest, "payload": payload})
            self._payload_refs[pdigest] = self._payload_refs.get(pdigest, 0) + 1
            rec = {
                "op": "dlv",
                "sub": sub_id,
                "cursor": cursor,
                "key": key,
                "tipset": int(tipset),
                "digest": digest,
            }
            if pdigest != digest:
                rec["pdigest"] = pdigest
            self._append_rec(rec)
            self._metrics.count("subs.deliveries")
            self._maybe_compact_locked()
            self._publish_gauges_locked()
            self._cond.notify_all()
        return d

    def set_base_reporter(self, reporter) -> None:
        """Install the fleet base-directory feed: ``reporter(sub_id,
        base_digest, base_cursor)`` fires outside the log lock whenever an
        ack advances a sub's delta base."""
        self._base_reporter = reporter

    def _report_base(self, sub_id: str, before, sl: _SubLog) -> None:
        """Fire the reporter if the (digest, cursor) base moved past
        ``before``. Called WITHOUT _cond held; fail-soft."""
        if self._base_reporter is None:
            return
        after = (sl.base_digest, sl.base_cursor)
        if after == before or after[0] is None:
            return
        try:
            self._base_reporter(sub_id, after[0], after[1])
        except Exception as exc:  # fail-soft: the reporter is observability; the ack itself already committed
            logger.warning("delta-base reporter failed for %s: %s", sub_id, exc)

    def ack(self, sub_id: str, cursor: int) -> bool:
        """Ack one delivery; ``False`` if unknown or already acked — the
        duplicate-ack guard the push retry loop relies on."""
        with self._cond:
            sl = self._subs.get(sub_id)
            if sl is None or cursor not in sl.entries:
                self._metrics.count("subs.duplicate_acks")
                return False
            base_before = (sl.base_digest, sl.base_cursor)
            self._ack_entry(sl, cursor)
            self._append_rec({"op": "ack", "sub": sub_id, "cursor": cursor})
            self._metrics.count("subs.acks")
            self._maybe_compact_locked()
            self._publish_gauges_locked()
        self._report_base(sub_id, base_before, sl)
        return True

    def ack_through(self, sub_id: str, cursor: int) -> int:
        """Ack every unacked delivery with cursor <= ``cursor`` (the
        long-poll contract: a client asking from cursor N owns all <= N)."""
        acked = 0
        with self._cond:
            sl = self._subs.get(sub_id)
            if sl is None:
                return 0
            base_before = (sl.base_digest, sl.base_cursor)
            for c in sorted(sl.entries):
                if c > cursor:
                    break
                self._ack_entry(sl, c)
                self._append_rec({"op": "ack", "sub": sub_id, "cursor": c})
                self._metrics.count("subs.acks")
                acked += 1
            if acked:
                self._maybe_compact_locked()
                self._publish_gauges_locked()
        if acked:
            self._report_base(sub_id, base_before, sl)
        return acked

    def bases(self) -> "Dict[str, Tuple[str, int]]":
        """Every sub's current acked base ``{sub_id: (digest, cursor)}`` —
        the restart sweep that re-seeds the fleet base directory from
        replayed sstate/ack frames (the registry dedups replays)."""
        with self._cond:
            return {
                sub_id: (sl.base_digest, sl.base_cursor)
                for sub_id, sl in self._subs.items()
                if sl.base_digest is not None
            }

    # ------------------------------------------------------------------- reads

    def pending(self, sub_id: str) -> List[Delivery]:
        """Unacked deliveries for one subscription, in cursor order."""
        with self._cond:
            sl = self._subs.get(sub_id)
            if sl is None:
                return []
            return [sl.entries[c] for c in sorted(sl.entries)]

    def pending_total(self) -> int:
        with self._cond:
            return self._pending

    def entries_after(
        self, sub_id: str, cursor: int, wait_s: float = 0.0
    ) -> List[Delivery]:
        """Unacked deliveries with cursor > ``cursor``; blocks up to
        ``wait_s`` for one to arrive (the long-poll primitive)."""
        deadline = time.monotonic() + max(0.0, wait_s)
        with self._cond:
            while True:
                sl = self._subs.get(sub_id)
                if sl is not None:
                    out = [sl.entries[c] for c in sorted(sl.entries) if c > cursor]
                    if out:
                        return out
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(timeout=remaining)

    def cursor(self, sub_id: str) -> int:
        """Highest assigned cursor for a subscription (0 if none)."""
        with self._cond:
            sl = self._subs.get(sub_id)
            return (sl.next_cursor - 1) if sl is not None else 0

    def acked_base(self, sub_id: str) -> Optional[str]:
        """FULL-bundle digest of this sub's highest acked delivery — the
        only bundle a delta may be cut against (the subscriber provably
        expanded it). None until the first ack (or for unknown subs);
        survives compaction via the sstate cursor record."""
        with self._cond:
            sl = self._subs.get(sub_id)
            return sl.base_digest if sl is not None else None

    @property
    def degraded(self) -> bool:
        return self._writer.degraded

    @property
    def journal_bytes(self) -> int:
        return self._writer.journal_bytes

    # -------------------------------------------------------------- compaction

    @locked
    def _maybe_compact_locked(self) -> None:
        # Degraded writers skip compaction: the rewrite would hit the same
        # failing filesystem, and in-memory state is already authoritative.
        if self._writer.degraded or self._writer.journal_bytes <= self.cap_bytes:
            return
        self._compact_locked()

    @locked
    def _compact_locked(self) -> None:
        """Rewrite the journal as per-sub state + unacked deliveries.

        Drops only acked history (entries below/at the ack watermark and
        their ack frames); every unacked delivery and every idempotency
        key survives byte-for-byte state-wise, so the cap can never lose
        an undelivered proof or re-open a dedup window.
        """
        tmp = self.path + ".compact"
        try:
            with open(tmp, "wb") as fh:
                # payloads first (once per digest still referenced by an
                # unacked delivery) so replaying dlv frames can resolve them
                live: Dict[str, dict] = {}
                for sl in self._subs.values():
                    for d in sl.entries.values():
                        live.setdefault(d.payload_digest, d.payload)
                for dg in sorted(live):
                    fh.write(
                        frame_record(
                            {"op": "pay", "digest": dg, "payload": live[dg]}
                        )
                    )
                for sub_id in sorted(self._subs):
                    sl = self._subs[sub_id]
                    # the sstate frame is the cursor record: it carries the
                    # sub's delta base digest precisely BECAUSE this rewrite
                    # drops the acked delivery (and possibly its pay frame)
                    # that established it — after replay the base identity
                    # survives even though its bytes are gone, so the delta
                    # path falls back to a full bundle instead of
                    # referencing a vanished base
                    srec = {
                        "op": "sstate",
                        "sub": sub_id,
                        "next": sl.next_cursor,
                        "acked": sl.acked,
                        "acked_extra": sorted(sl.acked_extra),
                        "keys": sorted(sl.keys),
                    }
                    if sl.base_digest is not None:
                        srec["base_digest"] = sl.base_digest
                        srec["base_cursor"] = sl.base_cursor
                    fh.write(frame_record(srec))
                    for c in sorted(sl.entries):
                        d = sl.entries[c]
                        drec = {
                            "op": "dlv",
                            "sub": sub_id,
                            "cursor": d.cursor,
                            "key": d.key,
                            "tipset": d.tipset,
                            "digest": d.digest,
                        }
                        if d.payload_digest != d.digest:
                            drec["pdigest"] = d.payload_digest
                        fh.write(frame_record(drec))
                if self._fsync:
                    fh.flush()
                    os.fsync(fh.fileno())  # ipclint: disable=lock-held-blocking (durability: compaction must not race concurrent appends)
            self._writer.close()
            os.replace(tmp, self.path)  # atomic: a crash keeps old or new, never half
            self._writer = JournalWriter(
                self.path, metrics=self._metrics, fsync=self._fsync
            )
            self._metrics.count("subs.log_compactions")
        except OSError as exc:
            # fail-soft: compaction is an optimization; the oversized (or
            # unwritable) journal keeps appending and memory stays correct
            self._metrics.count("subs.log_failures")
            logger.warning("delivery journal compaction failed: %s", exc)
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def close(self) -> None:
        self._writer.close()


def _default_opener(url: str, body: bytes, timeout_s: float) -> int:
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"}, method="POST"
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return resp.status


class PushDelivery:
    """Bounded webhook push workers over a `DeliveryLog`.

    Each push POSTs the delivery envelope and acks the log on 2xx.
    Retries are bounded full-jitter exponential backoff — the same shape
    (and the same injectable ``opener``/``sleep``/``rng`` seams) as
    `obs.export.post_otlp_trace` — so tests and the bench drive it with
    zero sockets and zero real sleeps. Exhausted pushes stay unacked;
    `repush_pending` (called by the matcher each tipset cycle) converges
    them, and the log's single-ack contract makes the retries safe.
    """

    def __init__(
        self,
        log: DeliveryLog,
        metrics: Optional[Metrics] = None,
        max_inflight: int = 4,
        max_attempts: int = 4,
        base_delay_s: float = 0.25,
        max_delay_s: float = 4.0,
        timeout_s: float = 10.0,
        opener=None,
        sleep=time.sleep,
        rng: Optional[random.Random] = None,
    ):
        self._log = log
        self._metrics = metrics if metrics is not None else get_metrics()
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.timeout_s = timeout_s
        self._opener = opener if opener is not None else _default_opener
        self._sleep = sleep
        self._rng = rng if rng is not None else random.Random()
        self._executor = ThreadPoolExecutor(
            max_workers=max(1, int(max_inflight)), thread_name_prefix="subs-push"
        )
        self._lock = named_lock("PushDelivery._lock")
        self._closed = False  # guarded-by: _lock
        self._inflight = 0  # guarded-by: _lock
        self._active: Set[str] = set()  # guarded-by: _lock (in-flight delivery keys)
        # payload digest → serialized payload JSON: fanning one proof out
        # to 10k subscribers serializes the bundle (or delta) once, not
        # 10k times. A tipset cycle touches at most a few digests per
        # distinct filter, so a tiny bound suffices.
        self._bundle_json: Dict[str, str] = {}  # guarded-by: _lock
        self._bundle_json_cap = 32

    def push(self, sub, delivery: Delivery):
        """Enqueue one webhook push; no-op for poll-mode targets, closed
        pushers, and deliveries already in flight (duplicate-push guard —
        at-least-once still holds because the delivery stays logged)."""
        if sub.target.get("mode") != "webhook":
            return None
        with self._lock:
            if self._closed or delivery.key in self._active:
                return None
            self._active.add(delivery.key)
            self._inflight += 1
            self._metrics.set_gauge("subs.push_inflight", self._inflight)
        return self._executor.submit(self._push_one, sub.target["url"], delivery)

    def repush_pending(self, registry) -> int:
        """Re-enqueue every unacked webhook delivery (retry convergence
        across tipset cycles and across restarts)."""
        n = 0
        for sub in registry.active():
            if sub.target.get("mode") != "webhook":
                continue
            for d in self._log.pending(sub.sub_id):
                if self.push(sub, d) is not None:
                    n += 1
        return n

    def _serialized_payload(self, delivery: Delivery) -> "tuple[str, str]":
        """(envelope key, serialized JSON) for this delivery's payload —
        ``bundle`` for full bundles, ``bundle_delta`` for delta payloads.
        Cached by PAYLOAD digest: a delta and the full bundle it expands
        to share a full digest but never a cache slot."""
        kind = "bundle_delta" if "bundle_delta" in delivery.payload else "bundle"
        with self._lock:
            cached = self._bundle_json.get(delivery.payload_digest)
        if cached is not None:
            return kind, cached
        raw = json.dumps(delivery.payload.get(kind), sort_keys=True)
        with self._lock:
            if len(self._bundle_json) >= self._bundle_json_cap:
                self._bundle_json.clear()
            self._bundle_json[delivery.payload_digest] = raw
        return kind, raw

    def _push_one(self, url: str, delivery: Delivery) -> bool:
        envelope = json.dumps(
            {
                "sub_id": delivery.sub_id,
                "cursor": delivery.cursor,
                "idempotency_key": delivery.key,
                "tipset": delivery.tipset,
                "digest": delivery.digest,
            },
            sort_keys=True,
        )
        kind, raw = self._serialized_payload(delivery)
        body = (envelope[:-1] + f', "{kind}": ' + raw + "}").encode("utf-8")
        try:
            for attempt in range(self.max_attempts):
                if attempt:
                    cap = min(
                        self.max_delay_s, self.base_delay_s * (2 ** (attempt - 1))
                    )
                    self._sleep(self._rng.uniform(0.0, cap))  # full jitter
                    self._metrics.count("subs.push_retries")
                try:
                    status = int(self._opener(url, body, self.timeout_s))
                except HTTPError as exc:
                    status = exc.code
                except Exception:  # fail-soft: transport errors are retryable; the delivery stays logged
                    continue
                if 200 <= status < 300:
                    # ack() returning False means someone acked first
                    # (long-poll raced us) — never a second ack frame
                    self._log.ack(delivery.sub_id, delivery.cursor)
                    self._metrics.count("subs.pushes")
                    return True
                if status not in _RETRYABLE_STATUSES:
                    break
            self._metrics.count("subs.push_failures")
            logger.warning(
                "webhook push for sub %s cursor %d failed after %d attempts "
                "— left unacked for long-poll/re-push",
                delivery.sub_id,
                delivery.cursor,
                self.max_attempts,
            )
            return False
        finally:
            with self._lock:
                self._active.discard(delivery.key)
                self._inflight -= 1
                self._metrics.set_gauge("subs.push_inflight", self._inflight)

    def drain(self) -> None:
        """Stop accepting pushes and wait for in-flight webhooks to land."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._executor.shutdown(wait=True)
