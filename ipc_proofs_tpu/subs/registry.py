"""Durable standing-query subscription registry.

A subscription is a (filter, delivery target) pair keyed by a caller- or
server-assigned subscription id. Filters are the same shape the proof
planes already serve — an event leg ``(signature, topic1, actor_id)``
plus an optional storage-slot leg ``(actor_id, slot)`` — so the matcher
can compile them straight into `EventProofSpec` / `StorageProofSpec`.

Durability rides the existing ``IPJ1`` journal framing
(`jobs.journal.JournalWriter`): every subscribe/unsubscribe appends one
CRC-framed record to ``<root>/subs.bin`` and a restart replays the log,
so registrations survive SIGKILL. Re-subscribing an existing id with the
same filter is a no-op (``subs.replays_absorbed``) — that idempotence is
what lets cluster shard failover re-register arcs under their ORIGINAL
subscription ids without duplicating state.

Journal write failures (ENOSPC/EROFS) are fail-soft like the serve
queue's: the append is counted (``subs.log_failures``), the registry
keeps serving from memory, and only durability degrades — never the run.
"""

from __future__ import annotations

import hashlib
import json
import os
import uuid
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ipc_proofs_tpu.jobs.journal import JournalWriter, read_journal_entries
from ipc_proofs_tpu.utils.lockdep import named_lock
from ipc_proofs_tpu.utils.log import get_logger
from ipc_proofs_tpu.utils.metrics import Metrics, get_metrics
from ipc_proofs_tpu.utils.threads import locked

__all__ = [
    "Subscription",
    "SubscriptionRegistry",
    "filter_key",
    "normalize_filter",
    "normalize_target",
    "subscription_ring_key",
]

logger = get_logger(__name__)

REGISTRY_JOURNAL = "subs.bin"


def normalize_filter(obj: Any) -> dict:
    """Validate and canonicalize a subscription filter.

    Required: ``signature`` (event signature string) and ``topic1``
    (the subnet topic — `EventMatcher` matches both topics uncondition-
    ally). Optional: ``actor_id`` (int emitter filter), ``slot``
    (64-char hex of the 32-byte storage-slot preimage digest; requires
    ``actor_id`` because a slot proves against a specific actor's state).
    Unknown keys are rejected so a typo'd filter fails loudly at
    registration instead of silently never matching.
    """
    if not isinstance(obj, dict):
        raise ValueError("filter must be a JSON object")
    unknown = set(obj) - {"signature", "topic1", "actor_id", "slot"}
    if unknown:
        raise ValueError(f"unknown filter keys: {sorted(unknown)}")
    sig = obj.get("signature")
    if not isinstance(sig, str) or not sig:
        raise ValueError("filter.signature (event signature string) is required")
    topic1 = obj.get("topic1")
    if not isinstance(topic1, str) or not topic1:
        raise ValueError("filter.topic1 (subnet topic string) is required")
    out: dict = {"signature": sig, "topic1": topic1}
    actor_id = obj.get("actor_id")
    if actor_id is not None:
        if isinstance(actor_id, bool) or not isinstance(actor_id, int):
            raise ValueError("filter.actor_id must be an integer")
        out["actor_id"] = actor_id
    slot = obj.get("slot")
    if slot is not None:
        if not isinstance(slot, str):
            raise ValueError("filter.slot must be a hex string")
        try:
            raw = bytes.fromhex(slot)
        except ValueError:
            raise ValueError("filter.slot must be valid hex")
        if len(raw) != 32:
            raise ValueError("filter.slot must be 32 bytes (64 hex chars)")
        if "actor_id" not in out:
            raise ValueError("filter.slot requires filter.actor_id")
        out["slot"] = slot.lower()
    return out


def normalize_target(obj: Any) -> dict:
    """Validate a delivery target: webhook POST or long-poll fallback."""
    if obj is None:
        return {"mode": "poll"}
    if not isinstance(obj, dict):
        raise ValueError("target must be a JSON object")
    mode = obj.get("mode") or ("webhook" if obj.get("url") else "poll")
    if mode == "poll":
        return {"mode": "poll"}
    if mode == "webhook":
        url = obj.get("url")
        if not isinstance(url, str) or "://" not in url:
            raise ValueError("webhook target needs a url")
        return {"mode": "webhook", "url": url}
    raise ValueError(f"unknown target mode {mode!r}")


def filter_key(filt: dict) -> str:
    """Canonical identity of a filter — the matcher's amortization unit.

    Two subscriptions with equal ``filter_key`` share ONE generation per
    tipset pair; the bundle fans out to both.
    """
    return json.dumps(filt, sort_keys=True, separators=(",", ":"))


def subscription_ring_key(filt: dict) -> str:
    """Ring placement key for a subscription, by its canonical filter.

    Plays the role `cluster.hashring.pair_ring_key` plays for proof
    requests: a stable string the `HashRing` sha256-hashes onto an arc.
    Keying by filter (not sub id) lands every subscriber of one filter on
    the same shard, so the per-shard matcher still generates once per
    distinct filter — fan-out amortization survives sharding.
    """
    return "subs:" + hashlib.sha256(filter_key(filt).encode("utf-8")).hexdigest()


@dataclass(frozen=True)
class Subscription:
    """One registered standing query."""

    sub_id: str
    filter: dict
    target: dict

    def to_json_obj(self) -> dict:
        return {"sub_id": self.sub_id, "filter": self.filter, "target": self.target}


class SubscriptionRegistry:
    """IPJ1-journaled subscription table; survives SIGKILL via replay."""

    def __init__(self, root: str, metrics: Optional[Metrics] = None, fsync: bool = True):
        os.makedirs(root, exist_ok=True)
        self.path = os.path.join(root, REGISTRY_JOURNAL)
        self._metrics = metrics if metrics is not None else get_metrics()
        self._lock = named_lock("SubscriptionRegistry._lock")
        self._subs: Dict[str, Subscription] = {}  # guarded-by: _lock
        self.replayed = 0
        if os.path.exists(self.path):
            entries, good_offset, torn = read_journal_entries(self.path)
            if torn:
                logger.warning(
                    "subscription journal %s has a torn tail — truncating to "
                    "last good frame at %d",
                    self.path,
                    good_offset,
                )
                with open(self.path, "r+b") as fh:
                    fh.truncate(good_offset)
            for rec, _off, _end in entries:
                self._replay(rec)
            self.replayed = len(entries)
        self._writer = JournalWriter(self.path, metrics=self._metrics, fsync=fsync)
        self._metrics.set_gauge("subs.active", len(self._subs))

    @locked  # construction-time only: runs before the registry is published
    def _replay(self, rec: Any) -> None:
        if not isinstance(rec, dict):
            return
        op = rec.get("op")
        if op == "sub":
            try:
                sub = Subscription(
                    sub_id=str(rec["id"]),
                    filter=normalize_filter(rec["filter"]),
                    target=normalize_target(rec.get("target")),
                )
            except (KeyError, ValueError):
                return  # fail-soft: a bad frame degrades one record, not the replay
            self._subs[sub.sub_id] = sub
        elif op == "unsub":
            self._subs.pop(str(rec.get("id")), None)

    @property
    def degraded(self) -> bool:
        return self._writer.degraded

    @property
    def journal_bytes(self) -> int:
        return self._writer.journal_bytes

    @locked
    def _append(self, rec: dict) -> None:
        """Journal one frame; a registration is only durable if the frame
        lands before the caller is acked, hence under the lock."""
        if not self._writer.append(rec):  # ipclint: disable=lock-held-blocking (durability: frame lands before the caller is acked)
            self._metrics.count("subs.log_failures")

    def subscribe(
        self, filt: Any, target: Any = None, sub_id: Optional[str] = None
    ) -> "tuple[Subscription, bool]":
        """Register a standing query; returns ``(subscription, created)``.

        Re-registering an existing ``sub_id`` is absorbed idempotently
        (``created=False``) — the durable dedup that makes cluster
        failover re-registration and journal replays safe.
        """
        filt = normalize_filter(filt)
        target = normalize_target(target)
        sub_id = str(sub_id) if sub_id else uuid.uuid4().hex
        with self._lock:
            existing = self._subs.get(sub_id)
            if existing is not None:
                self._metrics.count("subs.replays_absorbed")
                return existing, False
            sub = Subscription(sub_id=sub_id, filter=filt, target=target)
            self._subs[sub_id] = sub
            self._append({"op": "sub", "id": sub_id, "filter": filt, "target": target})
            self._metrics.count("subs.registered")
            self._metrics.set_gauge("subs.active", len(self._subs))
        return sub, True

    def unsubscribe(self, sub_id: str) -> bool:
        with self._lock:
            sub = self._subs.pop(str(sub_id), None)
            if sub is None:
                return False
            self._append({"op": "unsub", "id": sub.sub_id})
            self._metrics.count("subs.unsubscribed")
            self._metrics.set_gauge("subs.active", len(self._subs))
        return True

    def get(self, sub_id: str) -> Optional[Subscription]:
        with self._lock:
            return self._subs.get(str(sub_id))

    def active(self) -> List[Subscription]:
        with self._lock:
            return list(self._subs.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._subs)

    def close(self) -> None:
        self._writer.close()
