"""keccak-f[1600] and batch keccak256 as JAX kernels (u32-pair lanes).

Batch-first array form: the whole state lives in ``[N, 25]`` uint32 pairs and
the 24 rounds run under `lax.fori_loop` — a compact graph XLA compiles in
seconds (a fully unrolled scalar version took minutes on XLA:CPU), while
every op stays an [N]-wide vector op for the TPU VPU. Rotation amounts are
compile-time constant [25]-arrays, so the u64-on-u32 rotations lower to
static shift/or patterns.

Golden model: :func:`ipc_proofs_tpu.core.hashes.keccak256` (tested equal).
Reference-use parity: keccak256 is the event-signature / mapping-slot hash
(reference `src/proofs/common/evm.rs:81-88`, `storage/utils.rs:5-12`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["keccak_f1600_batch", "keccak256_blocks", "RATE_BYTES", "LANES_PER_BLOCK_U32"]

RATE_BYTES = 136
LANES_PER_BLOCK = RATE_BYTES // 8  # 17 u64 lanes absorbed per block
LANES_PER_BLOCK_U32 = LANES_PER_BLOCK * 2  # 34 u32 words

_ROUND_CONSTANTS = (
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
)

# Rotation offsets r[x][y] for lane A[x, y]; flat lane index i = x + 5*y.
_ROTATION = (
    (0, 36, 3, 41, 18),
    (1, 44, 10, 45, 2),
    (62, 6, 43, 15, 61),
    (28, 55, 25, 21, 56),
    (27, 20, 39, 8, 14),
)

# rho+pi as one flat permutation: dest[y + 5*((2x+3y)%5)] <- rot(src[x+5y]).
_PERM_SRC = np.zeros(25, dtype=np.int32)
_PERM_ROT = np.zeros(25, dtype=np.int32)
for _x in range(5):
    for _y in range(5):
        _dest = _y + 5 * ((2 * _x + 3 * _y) % 5)
        _PERM_SRC[_dest] = _x + 5 * _y
        _PERM_ROT[_dest] = _ROTATION[_x][_y]

_RC_LO = np.array([rc & 0xFFFFFFFF for rc in _ROUND_CONSTANTS], dtype=np.uint32)
_RC_HI = np.array([rc >> 32 for rc in _ROUND_CONSTANTS], dtype=np.uint32)

_IDX_X = np.arange(25, dtype=np.int32) % 5  # lane i → its x column


def _rotl64_const(lo, hi, rot: np.ndarray):
    """Rotate [N, K] u64 pairs left by the constant [K]-array ``rot``."""
    swap = rot >= 32
    low = jnp.where(swap, hi, lo)
    high = jnp.where(swap, lo, hi)
    m = (rot % 32).astype(np.uint32)
    s = ((32 - m) % 32).astype(np.uint32)
    carry_h = jnp.where(m == 0, jnp.uint32(0), high >> s)
    carry_l = jnp.where(m == 0, jnp.uint32(0), low >> s)
    return (low << m) | carry_h, (high << m) | carry_l


def keccak_f1600_batch(lo, hi, tables=None):
    """keccak-f[1600] over a batch: ``lo``/``hi`` are uint32 [N, 25].

    ``tables`` optionally supplies ``(idx_x, perm_src, perm_rot, rc_lo,
    rc_hi)`` as traced arrays — Pallas kernels may not close over array
    constants, so they thread the tables through as kernel inputs. The
    default (None) uses the module's numpy constants (XLA folds them).
    """
    if tables is None:
        idx_x, perm_src, perm_rot = _IDX_X, _PERM_SRC, _PERM_ROT
        rc_lo, rc_hi = jnp.asarray(_RC_LO), jnp.asarray(_RC_HI)
    else:
        idx_x, perm_src, perm_rot, rc_lo, rc_hi = tables

    def round_fn(r, state):
        a_lo, a_hi = state
        # theta: c[x] = xor over y of a[x + 5y]
        a_lo5 = a_lo.reshape(-1, 5, 5)
        a_hi5 = a_hi.reshape(-1, 5, 5)
        c_lo = a_lo5[:, 0] ^ a_lo5[:, 1] ^ a_lo5[:, 2] ^ a_lo5[:, 3] ^ a_lo5[:, 4]
        c_hi = a_hi5[:, 0] ^ a_hi5[:, 1] ^ a_hi5[:, 2] ^ a_hi5[:, 3] ^ a_hi5[:, 4]
        # rotl by 1 (static, uniform across lanes)
        cr_lo = jnp.roll(c_lo, -1, axis=-1)
        cr_hi = jnp.roll(c_hi, -1, axis=-1)
        rot1_lo = (cr_lo << 1) | (cr_hi >> 31)
        rot1_hi = (cr_hi << 1) | (cr_lo >> 31)
        d_lo = jnp.roll(c_lo, 1, axis=-1) ^ rot1_lo
        d_hi = jnp.roll(c_hi, 1, axis=-1) ^ rot1_hi
        a_lo = a_lo ^ d_lo[:, idx_x]
        a_hi = a_hi ^ d_hi[:, idx_x]
        # rho + pi: one gather + per-lane rotation
        b_lo, b_hi = _rotl64_const(a_lo[:, perm_src], a_hi[:, perm_src], perm_rot)
        # chi over rows: a[x] = b[x] ^ (~b[x+1] & b[x+2])
        b_lo5 = b_lo.reshape(-1, 5, 5)
        b_hi5 = b_hi.reshape(-1, 5, 5)
        a_lo = (
            b_lo5 ^ (~jnp.roll(b_lo5, -1, axis=2) & jnp.roll(b_lo5, -2, axis=2))
        ).reshape(-1, 25)
        a_hi = (
            b_hi5 ^ (~jnp.roll(b_hi5, -1, axis=2) & jnp.roll(b_hi5, -2, axis=2))
        ).reshape(-1, 25)
        # iota
        a_lo = a_lo.at[:, 0].set(a_lo[:, 0] ^ rc_lo[r])
        a_hi = a_hi.at[:, 0].set(a_hi[:, 0] ^ rc_hi[r])
        return a_lo, a_hi

    return lax.fori_loop(0, 24, round_fn, (lo, hi))


@jax.jit
def keccak256_blocks(blocks, n_blocks):
    """Batch keccak256 over pre-padded blocks (jitted; traced once per shape).

    Args:
      blocks: uint32 [N, B, 34] — padded rate blocks (see `pack.pad_keccak`).
      n_blocks: int32 [N] — actual block count per message (≥ 1).

    Returns:
      uint32 [N, 8] digests (little-endian u32 words).
    """
    n = blocks.shape[0]
    state_lo = jnp.zeros((n, 25), dtype=jnp.uint32)
    state_hi = jnp.zeros((n, 25), dtype=jnp.uint32)

    def step(carry, inp):
        lo, hi = carry
        block, idx = inp  # block: [N, 34]
        xored_lo = lo.at[:, :LANES_PER_BLOCK].set(lo[:, :LANES_PER_BLOCK] ^ block[:, 0::2])
        xored_hi = hi.at[:, :LANES_PER_BLOCK].set(hi[:, :LANES_PER_BLOCK] ^ block[:, 1::2])
        new_lo, new_hi = keccak_f1600_batch(xored_lo, xored_hi)
        active = (idx < n_blocks)[:, None]
        return (jnp.where(active, new_lo, lo), jnp.where(active, new_hi, hi)), None

    num_blocks = blocks.shape[1]
    (state_lo, state_hi), _ = lax.scan(
        step,
        (state_lo, state_hi),
        (jnp.moveaxis(blocks, 1, 0), jnp.arange(num_blocks, dtype=jnp.int32)),
    )
    # 32-byte digest = first 4 lanes, (lo, hi) interleaved little-endian
    digest = jnp.stack(
        [state_lo[:, 0], state_hi[:, 0], state_lo[:, 1], state_hi[:, 1],
         state_lo[:, 2], state_hi[:, 2], state_lo[:, 3], state_hi[:, 3]],
        axis=1,
    )
    return digest
