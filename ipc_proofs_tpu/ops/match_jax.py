"""Event predicate mask: the pjit'd boolean filter over padded event tensors.

This is the TPU replacement for the reference's hottest loop — the per-event
topic0/topic1/emitter check inside pass 1 of the event generator
(`src/proofs/events/generator.rs:217-233`): a pure elementwise mask over a
padded ``[events, ...]`` tensor plus a segment any-reduce per receipt.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["event_match_mask", "event_match_mask_jit", "receipts_with_match", "pad_to_bucket"]


def event_match_mask(
    topics,  # uint32 [N, 2, 8]: first two topics as u32 words
    n_topics,  # int32 [N]
    emitters,  # int32/uint32 [N]
    valid,  # bool [N] (padding rows are False)
    topic0,  # uint32 [8]
    topic1,  # uint32 [8]
    actor_id_filter=None,  # optional scalar
):
    """Boolean [N] mask: event matches (sig, topic1[, emitter]) exactly like
    `EventMatcher.matches_log` + the actor filter."""
    t0_eq = jnp.all(topics[:, 0, :] == topic0[None, :], axis=-1)
    t1_eq = jnp.all(topics[:, 1, :] == topic1[None, :], axis=-1)
    mask = valid & (n_topics >= 2) & t0_eq & t1_eq
    if actor_id_filter is not None:
        mask = mask & (emitters == actor_id_filter)
    return mask


@jax.jit
def _match_mask_topics(topics, n_topics, valid, topic0, topic1):
    t0_eq = jnp.all(topics[:, 0, :] == topic0[None, :], axis=-1)
    t1_eq = jnp.all(topics[:, 1, :] == topic1[None, :], axis=-1)
    return valid & (n_topics >= 2) & t0_eq & t1_eq


def pad_to_bucket(n: int, minimum: int = 256) -> int:
    """Round an event count up to a power-of-two bucket so jit traces a small
    fixed set of shapes instead of recompiling per range size."""
    bucket = minimum
    while bucket < n:
        bucket *= 2
    return bucket


def event_match_mask_jit(topics, n_topics, emitters, valid, topic0, topic1, actor_id_filter=None):
    """Jitted, shape-bucketed wrapper: one fused kernel, one dispatch.

    Inputs are host numpy arrays of true length N; they are zero-padded to a
    power-of-two bucket (padding rows have valid=False) so repeated calls at
    nearby sizes hit the jit cache. The emitter filter is applied host-side
    in numpy (actor IDs are u64 — exact regardless of jax x64 mode); the
    device kernel checks only topic equality. Returns a device bool array of
    the padded length — slice ``[:N]`` after readback.
    """
    import numpy as np

    if actor_id_filter is not None:
        valid = valid & (np.asarray(emitters) == actor_id_filter)
    n = topics.shape[0]
    bucket = pad_to_bucket(n)
    if bucket != n:
        pad = bucket - n
        topics = np.concatenate([topics, np.zeros((pad, 2, 8), topics.dtype)])
        n_topics = np.concatenate([n_topics, np.zeros(pad, n_topics.dtype)])
        valid = np.concatenate([valid, np.zeros(pad, valid.dtype)])
    return _match_mask_topics(topics, n_topics, valid, topic0, topic1)


def _match_mask_fp_impl(fp2, valid, target2):
    # u64 fingerprints as [N, 2] u32 words (jax x64 stays off)
    return valid & (fp2[:, 0] == target2[0]) & (fp2[:, 1] == target2[1])


_match_mask_fp = jax.jit(_match_mask_fp_impl)
_sharded_fp_cache: dict = {}


def sharded_fp_mask_fn(mesh):
    """The fp mask jitted over a device mesh: event rows split across ALL
    mesh axes (dp × sp — the match is embarrassingly parallel over events),
    spec words replicated. Cached per mesh."""
    fn = _sharded_fp_cache.get(mesh)
    if fn is None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        axes = tuple(mesh.axis_names)
        rows = NamedSharding(mesh, P(axes))
        mat = NamedSharding(mesh, P(axes, None))
        rep = NamedSharding(mesh, P())
        fn = jax.jit(
            _match_mask_fp_impl, in_shardings=(mat, rows, rep), out_shardings=rows
        )
        _sharded_fp_cache[mesh] = fn
    return fn


def event_match_mask_fp_jit(
    fp, n_topics, emitters, valid, target_fp: int, actor_id_filter=None, mesh=None
):
    """Transfer-light device match: ships ONE u64 fingerprint + one valid bit
    per event instead of the 64-byte topic words (~8× less host→device
    traffic — the tunnel/PCIe-bound leg of the range pipeline).

    The n_topics≥2 and emitter predicates fold into the host-side valid mask
    (u64 actor IDs stay exact); the device compares fingerprints. Pass 2
    re-applies the full matcher per event, so claims are identical to the
    full-width kernel's even in the 2^-64 collision case.
    """
    import numpy as np

    valid = valid & (np.asarray(n_topics) >= 2)
    if actor_id_filter is not None:
        valid = valid & (np.asarray(emitters) == actor_id_filter)
    n = fp.shape[0]
    bucket = pad_to_bucket(n)
    if mesh is not None:  # rows must split evenly across every device
        n_dev = mesh.size
        bucket += (-bucket) % n_dev
    fp2 = np.ascontiguousarray(fp).view("<u4").reshape(n, 2)
    if bucket != n:
        pad = bucket - n
        fp2 = np.concatenate([fp2, np.zeros((pad, 2), fp2.dtype)])
        valid = np.concatenate([valid, np.zeros(pad, valid.dtype)])
    target2 = np.frombuffer(int(target_fp).to_bytes(8, "little"), dtype="<u4")
    if mesh is not None:
        return sharded_fp_mask_fn(mesh)(fp2, valid, target2)
    return _match_mask_fp(fp2, valid, target2)


def receipts_with_match(mask, receipt_ids, num_receipts: int):
    """Per-receipt any-reduce: bool [N] event mask + int32 [N] receipt ids →
    bool [num_receipts] (which receipts contain ≥1 matching event).

    The segment reduction is the only cross-event communication in pass 1 —
    under `shard_map` it lowers to a psum over the event axis.
    """
    hits = jnp.zeros(num_receipts, dtype=jnp.int32).at[receipt_ids].add(mask.astype(jnp.int32))
    return hits > 0
