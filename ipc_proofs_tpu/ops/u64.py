"""u64 arithmetic emulated on uint32 pairs for TPU lanes.

A u64 lane is carried as ``(lo, hi)`` uint32 arrays. All shift amounts are
Python ints (static), so every case below resolves at trace time — no
dynamic shifts reach XLA.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["rotl64", "rotr64", "add64", "xor64", "split_u64", "join_u64"]


def rotl64(lo, hi, n: int):
    """Rotate the u64 (lo, hi) left by static ``n``."""
    n %= 64
    if n == 0:
        return lo, hi
    if n == 32:
        return hi, lo
    if n > 32:
        return rotl64(hi, lo, n - 32)
    # 0 < n < 32
    new_lo = (lo << n) | (hi >> (32 - n))
    new_hi = (hi << n) | (lo >> (32 - n))
    return new_lo, new_hi


def rotr64(lo, hi, n: int):
    return rotl64(lo, hi, 64 - (n % 64))


def add64(alo, ahi, blo, bhi):
    """u64 addition with carry on u32 pairs (wrapping)."""
    sum_lo = alo + blo
    carry = (sum_lo < alo).astype(jnp.uint32)
    sum_hi = ahi + bhi + carry
    return sum_lo, sum_hi


def xor64(alo, ahi, blo, bhi):
    return alo ^ blo, ahi ^ bhi


def split_u64(value: int) -> tuple[int, int]:
    """Static u64 constant → (lo, hi) u32 ints."""
    return value & 0xFFFFFFFF, (value >> 32) & 0xFFFFFFFF


def join_u64(lo: int, hi: int) -> int:
    return (int(hi) << 32) | int(lo)
