"""Pallas TPU kernels for the single-block hash fast paths.

The overwhelmingly common shapes in this workload are single-block:
- keccak256 preimages are 64-byte mapping-slot keys and short event
  signatures (≤ 135 bytes ⇒ one rate block);
- most IPLD witness nodes are ≤ 128 bytes ⇒ one blake2b block (larger
  blocks use the XLA `lax.scan` kernels in `keccak_jax`/`blake2b_jax`).

Kernel structure (what Mosaic can actually lower, and fast): the state is
LANE-MAJOR — each u64 lane is a [1, TILE] u32-pair row vector, so every
elementwise op fills whole (8, 128) vregs (the batch-major [TILE, 1] layout
ran 15× slower: 1/128 vreg utilization). ALL schedule indices — the keccak
rho/pi permutation, per-lane rotation amounts, the blake2b sigma schedule —
are Python compile-time constants; keccak's 24 rounds run under an in-kernel
`fori_loop` whose only dynamic access is a scalar round-constant load from
SMEM (a fully unrolled 24-round graph took Mosaic >9 min to compile; the
loop form compiles in ~2 s). The earlier table-driven form (shared with the
XLA kernels) needed gather/scatter, which the TPU Pallas lowering rejects
(`Unimplemented ... scatter`).

Measured on TPU v5e (65k-message batch, slope-timed): keccak 44.8M hashes/s
vs 13.5M XLA (3.3×); blake2b 252M hashes/s vs 61.5M XLA (4.1×).

Digest-word layout matches the XLA kernels: [lo0, hi0, lo1, hi1, ...] — the
little-endian u32 view of the 32-byte digest. Golden models:
`core.hashes.keccak256` / `hashlib.blake2b(digest_size=32)`, tested equal.

On non-TPU hosts the kernels run in interpreter mode (CI equivalence
tests); callers fall back to the XLA kernels if Mosaic rejects at runtime
(`backend.tpu.TpuBackend._pallas_single_block`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ipc_proofs_tpu.ops.blake2b_jax import _IV, _PARAM_WORD0, _SIGMA
from ipc_proofs_tpu.ops.keccak_jax import _PERM_ROT, _PERM_SRC, _ROUND_CONSTANTS

__all__ = [
    "keccak256_single_block_pallas",
    "blake2b256_single_block_pallas",
    "blake2b256_two_block_pallas",
    "pack_single_block_keccak",
    "pack_single_block_blake2b",
    "pack_two_block_blake2b",
]

TILE = 256
_U32 = 0xFFFFFFFF


def _rotl64_static(lo, hi, r: int):
    """Rotate a u64 (as a [1, TILE] u32-pair row) left by the constant r."""
    r %= 64
    if r >= 32:
        lo, hi = hi, lo
        r -= 32
    if r == 0:
        return lo, hi
    return (lo << r) | (hi >> (32 - r)), (hi << r) | (lo >> (32 - r))


_RC_LO_COL = np.array([[rc & _U32] for rc in _ROUND_CONSTANTS], dtype=np.uint32)
_RC_HI_COL = np.array([[rc >> 32] for rc in _ROUND_CONSTANTS], dtype=np.uint32)


def _keccak_round(lo, hi, rc_lo, rc_hi):
    """One keccak-f round over 25 [1, TILE] u32-pair lanes — static
    permutation/rotations (Python constants), rc_* traced scalars."""
    c_lo = [lo[x] ^ lo[x + 5] ^ lo[x + 10] ^ lo[x + 15] ^ lo[x + 20] for x in range(5)]
    c_hi = [hi[x] ^ hi[x + 5] ^ hi[x + 10] ^ hi[x + 15] ^ hi[x + 20] for x in range(5)]
    d_lo, d_hi = [], []
    for x in range(5):
        r1_lo, r1_hi = _rotl64_static(c_lo[(x + 1) % 5], c_hi[(x + 1) % 5], 1)
        d_lo.append(c_lo[(x - 1) % 5] ^ r1_lo)
        d_hi.append(c_hi[(x - 1) % 5] ^ r1_hi)
    lo = [lo[i] ^ d_lo[i % 5] for i in range(25)]
    hi = [hi[i] ^ d_hi[i % 5] for i in range(25)]
    b_lo, b_hi = [None] * 25, [None] * 25
    for dest in range(25):
        src = int(_PERM_SRC[dest])
        b_lo[dest], b_hi[dest] = _rotl64_static(lo[src], hi[src], int(_PERM_ROT[dest]))
    for y in range(0, 25, 5):
        row_lo = b_lo[y : y + 5]
        row_hi = b_hi[y : y + 5]
        for x in range(5):
            lo[y + x] = row_lo[x] ^ (~row_lo[(x + 1) % 5] & row_lo[(x + 2) % 5])
            hi[y + x] = row_hi[x] ^ (~row_hi[(x + 1) % 5] & row_hi[(x + 2) % 5])
    lo[0] = lo[0] ^ rc_lo
    hi[0] = hi[0] ^ rc_hi
    return lo, hi


def _keccak_kernel(blo_ref, bhi_ref, rclo_ref, rchi_ref, out_ref):
    # lane-major layout: refs are [17|8, TILE_N] — each lane is a [1, TILE_N]
    # row vector, so every elementwise op fills whole (8,128) vregs
    tile_n = blo_ref.shape[1]
    zero = jnp.zeros((1, tile_n), dtype=jnp.uint32)
    lo = [blo_ref[i : i + 1, :] for i in range(17)] + [zero] * 8
    hi = [bhi_ref[i : i + 1, :] for i in range(17)] + [zero] * 8

    def round_body(r, state):
        lo25, hi25 = state
        lo_l = [lo25[i : i + 1, :] for i in range(25)]
        hi_l = [hi25[i : i + 1, :] for i in range(25)]
        # round constant: dynamic scalar load from the SMEM table (Mosaic
        # lowers ref indexing by a loop counter; value-level dynamic_slice
        # and gathers it does not)
        rc_lo = rclo_ref[r]
        rc_hi = rchi_ref[r]
        lo_l, hi_l = _keccak_round(lo_l, hi_l, rc_lo, rc_hi)
        return jnp.concatenate(lo_l, axis=0), jnp.concatenate(hi_l, axis=0)

    lo25, hi25 = jax.lax.fori_loop(
        0, 24, round_body, (jnp.concatenate(lo, axis=0), jnp.concatenate(hi, axis=0))
    )
    out_ref[:] = jnp.concatenate(
        [lo25[0:1], hi25[0:1], lo25[1:2], hi25[1:2],
         lo25[2:3], hi25[2:3], lo25[3:4], hi25[3:4]], axis=0
    )


def _add64_s(alo, ahi, blo, bhi):
    sum_lo = alo + blo
    carry = (sum_lo < alo).astype(jnp.uint32)
    return sum_lo, ahi + bhi + carry


def _rotr64_s(lo, hi, n: int):
    if n == 32:
        return hi, lo
    if n == 63:
        return (lo << 1) | (hi >> 31), (hi << 1) | (lo >> 31)
    return (lo >> n) | (hi << (32 - n)), (hi >> n) | (lo << (32 - n))


def _g_vec(a, b, c, d, mx, my):
    """One blake2b G mix over [4, TILE] u64-pair row groups (the four
    column — or diagonal, after row rotation — mixes at once)."""
    a = _add64_s(*_add64_s(*a, *b), *mx)
    d = _rotr64_s(d[0] ^ a[0], d[1] ^ a[1], 32)
    c = _add64_s(*c, *d)
    b = _rotr64_s(b[0] ^ c[0], b[1] ^ c[1], 24)
    a = _add64_s(*_add64_s(*a, *b), *my)
    d = _rotr64_s(d[0] ^ a[0], d[1] ^ a[1], 16)
    c = _add64_s(*c, *d)
    b = _rotr64_s(b[0] ^ c[0], b[1] ^ c[1], 63)
    return a, b, c, d


def _rot_rows(pair, k: int):
    """Rotate a [4, TILE] pair's rows up by the static k (diagonalization)."""
    lo, hi = pair
    return (
        jnp.concatenate([lo[k:], lo[:k]], axis=0),
        jnp.concatenate([hi[k:], hi[:k]], axis=0),
    )


def _blake2b_compress(const_rows, h03, h47, m_rows, t_row, f_row):
    """One blake2b compression over lane-major [4, TILE] u64-pair groups.

    ``h03``/``h47`` are (lo, hi) pairs for h0..3 / h4..7; ``m_rows`` is a
    (mlo_sel, mhi_sel) pair of row-selector callables for this block's 16
    message lanes; ``t_row`` is the u32 byte-counter row (t < 2^32 for the
    ≤2-block shapes these kernels serve); ``f_row`` is 0xFFFFFFFF where the
    block is final, else 0 (applied to both u32 halves of v14)."""
    mlo_sel, mhi_sel = m_rows
    a = (h03[0], h03[1])
    b = (h47[0], h47[1])
    c = (const_rows([w & _U32 for w in _IV[:4]]), const_rows([w >> 32 for w in _IV[:4]]))
    d_lo = const_rows([_IV[4] & _U32, _IV[5] & _U32, _IV[6] & _U32, _IV[7] & _U32])
    d_hi = const_rows([_IV[4] >> 32, _IV[5] >> 32, _IV[6] >> 32, _IV[7] >> 32])
    zero = t_row ^ t_row  # [1, T] zeros without capturing an array
    # v12 ^= t (lo half only); v14 ^= f (both halves)
    d_lo = jnp.concatenate(
        [d_lo[0:1, :] ^ t_row, d_lo[1:2, :], d_lo[2:3, :] ^ f_row, d_lo[3:4, :]], axis=0
    )
    d_hi = jnp.concatenate(
        [d_hi[0:1, :] ^ zero, d_hi[1:2, :], d_hi[2:3, :] ^ f_row, d_hi[3:4, :]], axis=0
    )
    d = (d_lo, d_hi)

    for r in range(12):
        s = [int(x) for x in _SIGMA[r % 10]]
        mx = (mlo_sel(s[0:8:2]), mhi_sel(s[0:8:2]))
        my = (mlo_sel(s[1:8:2]), mhi_sel(s[1:8:2]))
        a, b, c, d = _g_vec(a, b, c, d, mx, my)
        b, c, d = _rot_rows(b, 1), _rot_rows(c, 2), _rot_rows(d, 3)
        mx = (mlo_sel(s[8:16:2]), mhi_sel(s[8:16:2]))
        my = (mlo_sel(s[9:16:2]), mhi_sel(s[9:16:2]))
        a, b, c, d = _g_vec(a, b, c, d, mx, my)
        b, c, d = _rot_rows(b, 3), _rot_rows(c, 2), _rot_rows(d, 1)

    new_h03 = (h03[0] ^ a[0] ^ c[0], h03[1] ^ a[1] ^ c[1])
    new_h47 = (h47[0] ^ b[0] ^ d[0], h47[1] ^ b[1] ^ d[1])
    return new_h03, new_h47


def _blake2b2_kernel(mlo_ref, mhi_ref, len_ref, out_ref):
    """Two-block blake2b-256: messages up to 256 bytes (the ~200-byte IPLD
    node shape of BASELINE config 4). Both compressions run for every
    message; single-block messages take the first compression's digest via
    a final masked select, so no divergent control flow reaches Mosaic."""
    tile_n = mlo_ref.shape[1]

    def const_rows(words):
        return jnp.concatenate(
            [jnp.full((1, tile_n), w, dtype=jnp.uint32) for w in words], axis=0
        )

    def block_sel(ref, base):
        def sel(rows):
            return jnp.concatenate([ref[base + i : base + i + 1, :] for i in rows], axis=0)

        return sel

    length = len_ref[0:1, :].astype(jnp.uint32)
    ones = jnp.full((1, tile_n), _U32, dtype=jnp.uint32)
    zero = jnp.zeros((1, tile_n), dtype=jnp.uint32)
    two = length > 128
    t1 = jnp.where(two, jnp.full((1, tile_n), 128, dtype=jnp.uint32), length)
    f1 = jnp.where(two, zero, ones)

    h0 = _IV[0] ^ _PARAM_WORD0
    hw = [h0 if i == 0 else _IV[i] for i in range(8)]
    h03 = (const_rows([w & _U32 for w in hw[:4]]), const_rows([w >> 32 for w in hw[:4]]))
    h47 = (const_rows([w & _U32 for w in hw[4:]]), const_rows([w >> 32 for w in hw[4:]]))

    h03_1, h47_1 = _blake2b_compress(
        const_rows, h03, h47,
        (block_sel(mlo_ref, 0), block_sel(mhi_ref, 0)), t1, f1,
    )
    h03_2, _ = _blake2b_compress(
        const_rows, h03_1, h47_1,
        (block_sel(mlo_ref, 16), block_sel(mhi_ref, 16)), length, ones,
    )

    rows = []
    for i in range(4):
        rows.append(jnp.where(two, h03_2[0][i : i + 1, :], h03_1[0][i : i + 1, :]))
        rows.append(jnp.where(two, h03_2[1][i : i + 1, :], h03_1[1][i : i + 1, :]))
    out_ref[:] = jnp.concatenate(rows, axis=0)


def _blake2b_kernel(mlo_ref, mhi_ref, len_ref, out_ref):
    # lane-major: refs [16|1|8, TILE_N]; state kept as four [4, TILE_N]
    # row groups so each G mixes all four columns in one vector op chain
    tile_n = mlo_ref.shape[1]

    def sel(ref, rows):
        return jnp.concatenate([ref[i : i + 1, :] for i in rows], axis=0)

    def const_rows(words):
        # built from Python scalars — Pallas kernels may not capture arrays
        return jnp.concatenate(
            [jnp.full((1, tile_n), w, dtype=jnp.uint32) for w in words], axis=0
        )

    t_lo = len_ref[0:1, :].astype(jnp.uint32)
    h0 = _IV[0] ^ _PARAM_WORD0
    hw = [h0 if i == 0 else _IV[i] for i in range(8)]
    h_lo = (const_rows([w & _U32 for w in hw[:4]]), const_rows([w & _U32 for w in hw[4:]]))
    h_hi = (const_rows([w >> 32 for w in hw[:4]]), const_rows([w >> 32 for w in hw[4:]]))

    a = (h_lo[0], h_hi[0])  # v0..3
    b = (h_lo[1], h_hi[1])  # v4..7
    c = (const_rows([w & _U32 for w in _IV[:4]]), const_rows([w >> 32 for w in _IV[:4]]))
    # v12..15: v12 ^= t_lo; v14 = ~IV[6] (single final block, f0 = ~0)
    inv6 = _IV[6] ^ ((1 << 64) - 1)
    d_lo = const_rows([_IV[4] & _U32, _IV[5] & _U32, inv6 & _U32, _IV[7] & _U32])
    d_hi = const_rows([_IV[4] >> 32, _IV[5] >> 32, inv6 >> 32, _IV[7] >> 32])
    # xor t_lo into row 0 without a slice-update (Mosaic: concat only)
    d_lo = jnp.concatenate([d_lo[0:1, :] ^ t_lo, d_lo[1:4, :]], axis=0)
    d = (d_lo, d_hi)

    for r in range(12):
        s = [int(x) for x in _SIGMA[r % 10]]
        mx = (sel(mlo_ref, s[0:8:2]), sel(mhi_ref, s[0:8:2]))
        my = (sel(mlo_ref, s[1:8:2]), sel(mhi_ref, s[1:8:2]))
        a, b, c, d = _g_vec(a, b, c, d, mx, my)
        # diagonalize, mix, un-diagonalize
        b, c, d = _rot_rows(b, 1), _rot_rows(c, 2), _rot_rows(d, 3)
        mx = (sel(mlo_ref, s[8:16:2]), sel(mhi_ref, s[8:16:2]))
        my = (sel(mlo_ref, s[9:16:2]), sel(mhi_ref, s[9:16:2]))
        a, b, c, d = _g_vec(a, b, c, d, mx, my)
        b, c, d = _rot_rows(b, 3), _rot_rows(c, 2), _rot_rows(d, 1)

    out_lo = h_lo[0] ^ a[0] ^ c[0]  # h0..3 ^ v0..3 ^ v8..11
    out_hi = h_hi[0] ^ a[1] ^ c[1]
    # interleave [lo0, hi0, lo1, hi1, ...] rows
    rows = []
    for i in range(4):
        rows.append(out_lo[i : i + 1, :])
        rows.append(out_hi[i : i + 1, :])
    out_ref[:] = jnp.concatenate(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def keccak256_single_block_pallas(blocks_lo, blocks_hi, interpret: bool = False):
    """Batch keccak256 for one-rate-block messages.

    Args: blocks_lo/blocks_hi uint32 [N, 17] (padded rate block, N % TILE == 0).
    Returns uint32 [N, 8] digests.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = blocks_lo.shape[0]
    table_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    digests_t = pl.pallas_call(
        _keccak_kernel,
        grid=(n // TILE,),
        in_specs=[
            pl.BlockSpec((17, TILE), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((17, TILE), lambda i: (0, i), memory_space=pltpu.VMEM),
            table_spec,
            table_spec,
        ],
        out_specs=pl.BlockSpec((8, TILE), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, n), jnp.uint32),
        interpret=interpret,
    )(
        blocks_lo.T,  # lane-major [17, N]; transpose fuses into the same jit
        blocks_hi.T,
        jnp.asarray(_RC_LO_COL[:, 0]),
        jnp.asarray(_RC_HI_COL[:, 0]),
    )
    return digests_t.T


@functools.partial(jax.jit, static_argnames=("interpret",))
def blake2b256_single_block_pallas(m_lo, m_hi, lengths, interpret: bool = False):
    """Batch blake2b-256 for single-block (≤ 128 byte) messages.

    Args: m_lo/m_hi uint32 [N, 16]; lengths int32 [N, 1]. N % TILE == 0.
    Returns uint32 [N, 8] digests.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = m_lo.shape[0]
    digests_t = pl.pallas_call(
        _blake2b_kernel,
        grid=(n // TILE,),
        in_specs=[
            pl.BlockSpec((16, TILE), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((16, TILE), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((8, TILE), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, n), jnp.uint32),
        interpret=interpret,
    )(m_lo.T, m_hi.T, lengths.T)
    return digests_t.T


@functools.partial(jax.jit, static_argnames=("interpret",))
def blake2b256_two_block_pallas(m_lo, m_hi, lengths, interpret: bool = False):
    """Batch blake2b-256 for messages up to 256 bytes (two compression
    blocks). Single-block rows are computed in the same pass and selected
    by mask, so mixed batches stay correct.

    Args: m_lo/m_hi uint32 [N, 32] (block0 words 0..15, block1 16..31);
    lengths int32 [N, 1]. N % TILE == 0. Returns uint32 [N, 8] digests.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = m_lo.shape[0]
    digests_t = pl.pallas_call(
        _blake2b2_kernel,
        grid=(n // TILE,),
        in_specs=[
            pl.BlockSpec((32, TILE), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((32, TILE), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((8, TILE), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, n), jnp.uint32),
        interpret=interpret,
    )(m_lo.T, m_hi.T, lengths.T)
    return digests_t.T


# --- host-side packing (single-block, de-interleaved, TILE-padded) ----------


def pack_single_block_keccak(messages: "list[bytes]"):
    """Pad ≤135-byte messages into de-interleaved keccak rate blocks.

    Returns (blocks_lo u32[Np, 17], blocks_hi u32[Np, 17], n) where
    Np is n rounded up to TILE.
    """
    n = len(messages)
    n_pad = ((n + TILE - 1) // TILE) * TILE
    raw = np.zeros((n_pad, 136), dtype=np.uint8)
    for i, msg in enumerate(messages):
        if len(msg) >= 136:
            raise ValueError("single-block keccak kernel requires len < 136")
        raw[i, : len(msg)] = np.frombuffer(msg, dtype=np.uint8)
        raw[i, len(msg)] ^= 0x01
        raw[i, 135] ^= 0x80
    words = raw.view(np.uint32).reshape(n_pad, 34)
    return np.ascontiguousarray(words[:, 0::2]), np.ascontiguousarray(words[:, 1::2]), n


def pack_single_block_blake2b(messages: "list[bytes]"):
    """Pad ≤128-byte messages into de-interleaved blake2b blocks.

    Returns (m_lo u32[Np, 16], m_hi u32[Np, 16], lengths i32[Np, 1], n).
    """
    n = len(messages)
    n_pad = ((n + TILE - 1) // TILE) * TILE
    raw = np.zeros((n_pad, 128), dtype=np.uint8)
    lengths = np.zeros((n_pad, 1), dtype=np.int32)
    for i, msg in enumerate(messages):
        if len(msg) > 128:
            raise ValueError("single-block blake2b kernel requires len <= 128")
        raw[i, : len(msg)] = np.frombuffer(msg, dtype=np.uint8)
        lengths[i, 0] = len(msg)
    words = raw.view(np.uint32).reshape(n_pad, 32)
    return (
        np.ascontiguousarray(words[:, 0::2]),
        np.ascontiguousarray(words[:, 1::2]),
        lengths,
        n,
    )


def pack_two_block_blake2b(messages: "list[bytes]"):
    """Pad ≤256-byte messages into de-interleaved 2×128-byte blake2b blocks.

    Returns (m_lo u32[Np, 32], m_hi u32[Np, 32], lengths i32[Np, 1], n).
    """
    n = len(messages)
    n_pad = ((n + TILE - 1) // TILE) * TILE
    raw = np.zeros((n_pad, 256), dtype=np.uint8)
    lengths = np.zeros((n_pad, 1), dtype=np.int32)
    for i, msg in enumerate(messages):
        if len(msg) > 256:
            raise ValueError("two-block blake2b kernel requires len <= 256")
        raw[i, : len(msg)] = np.frombuffer(msg, dtype=np.uint8)
        lengths[i, 0] = len(msg)
    words = raw.view(np.uint32).reshape(n_pad, 64)
    return (
        np.ascontiguousarray(words[:, 0::2]),
        np.ascontiguousarray(words[:, 1::2]),
        lengths,
        n,
    )
