"""Pallas TPU kernels for the single-block hash fast paths.

The overwhelmingly common shapes in this workload are single-block:
- keccak256 preimages are 64-byte mapping-slot keys and short event
  signatures (≤ 135 bytes ⇒ one rate block);
- most IPLD witness nodes are ≤ 128 bytes ⇒ one blake2b block (larger
  blocks use the XLA `lax.scan` kernels in `keccak_jax`/`blake2b_jax`).

Each kernel tiles the batch over a 1-D grid ([TILE, lanes] blocks resident
in VMEM) and reuses the exact round logic of the XLA kernels — so the
Pallas and XLA paths cannot drift. On non-TPU hosts the kernels run in
interpreter mode (CI equivalence tests); callers should fall back to the
XLA kernels if Mosaic rejects a shape at runtime.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ipc_proofs_tpu.ops.blake2b_jax import _IV_HI, _IV_LO, _PARAM_WORD0, _SIGMA, _compress
from ipc_proofs_tpu.ops.keccak_jax import (
    _IDX_X,
    _PERM_ROT,
    _PERM_SRC,
    _RC_HI,
    _RC_LO,
    keccak_f1600_batch,
)

__all__ = [
    "keccak256_single_block_pallas",
    "blake2b256_single_block_pallas",
    "pack_single_block_keccak",
    "pack_single_block_blake2b",
]

TILE = 256


def _digest_columns(lo, hi):
    return jnp.stack(
        [lo[:, 0], hi[:, 0], lo[:, 1], hi[:, 1], lo[:, 2], hi[:, 2], lo[:, 3], hi[:, 3]],
        axis=1,
    )


def _keccak_kernel(blo_ref, bhi_ref, idx_x_ref, perm_ref, rot_ref, rclo_ref, rchi_ref, out_ref):
    tile = blo_ref.shape[0]
    lo = jnp.zeros((tile, 25), dtype=jnp.uint32).at[:, :17].set(blo_ref[:])
    hi = jnp.zeros((tile, 25), dtype=jnp.uint32).at[:, :17].set(bhi_ref[:])
    tables = (idx_x_ref[:], perm_ref[:], rot_ref[:], rclo_ref[:], rchi_ref[:])
    lo, hi = keccak_f1600_batch(lo, hi, tables=tables)
    out_ref[:] = _digest_columns(lo, hi)


def _blake2b_kernel(mlo_ref, mhi_ref, len_ref, ivlo_ref, ivhi_ref, sigma_ref, out_ref):
    tile = mlo_ref.shape[0]
    iv_lo = ivlo_ref[:]
    iv_hi = ivhi_ref[:]
    h_lo = jnp.broadcast_to(iv_lo, (tile, 8)).astype(jnp.uint32)
    h_lo = h_lo.at[:, 0].set(h_lo[:, 0] ^ jnp.uint32(_PARAM_WORD0))
    h_hi = jnp.broadcast_to(iv_hi, (tile, 8)).astype(jnp.uint32)
    t_lo = len_ref[:, 0].astype(jnp.uint32)
    f_word = jnp.full((tile,), 0xFFFFFFFF, dtype=jnp.uint32)
    h_lo, h_hi = _compress(
        h_lo, h_hi, mlo_ref[:], mhi_ref[:], t_lo, f_word,
        tables=(iv_lo, iv_hi, sigma_ref[:]),
    )
    out_ref[:] = _digest_columns(h_lo, h_hi)


def _interpret_default() -> bool:
    return jax.devices()[0].platform != "tpu"


@functools.partial(jax.jit, static_argnames=("interpret",))
def keccak256_single_block_pallas(blocks_lo, blocks_hi, interpret: bool = False):
    """Batch keccak256 for one-rate-block messages.

    Args: blocks_lo/blocks_hi uint32 [N, 17] (padded rate block, N % TILE == 0).
    Returns uint32 [N, 8] digests.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = blocks_lo.shape[0]
    table_spec = pl.BlockSpec(memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _keccak_kernel,
        grid=(n // TILE,),
        in_specs=[
            pl.BlockSpec((TILE, 17), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE, 17), lambda i: (i, 0), memory_space=pltpu.VMEM),
            table_spec, table_spec, table_spec, table_spec, table_spec,
        ],
        out_specs=pl.BlockSpec((TILE, 8), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, 8), jnp.uint32),
        interpret=interpret,
    )(
        blocks_lo,
        blocks_hi,
        jnp.asarray(_IDX_X),
        jnp.asarray(_PERM_SRC),
        jnp.asarray(_PERM_ROT),
        jnp.asarray(_RC_LO),
        jnp.asarray(_RC_HI),
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def blake2b256_single_block_pallas(m_lo, m_hi, lengths, interpret: bool = False):
    """Batch blake2b-256 for single-block (≤ 128 byte) messages.

    Args: m_lo/m_hi uint32 [N, 16]; lengths int32 [N, 1]. N % TILE == 0.
    Returns uint32 [N, 8] digests.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = m_lo.shape[0]
    table_spec = pl.BlockSpec(memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _blake2b_kernel,
        grid=(n // TILE,),
        in_specs=[
            pl.BlockSpec((TILE, 16), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE, 16), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            table_spec, table_spec, table_spec,
        ],
        out_specs=pl.BlockSpec((TILE, 8), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, 8), jnp.uint32),
        interpret=interpret,
    )(
        m_lo,
        m_hi,
        lengths,
        jnp.asarray(_IV_LO),
        jnp.asarray(_IV_HI),
        jnp.asarray(_SIGMA),
    )


# --- host-side packing (single-block, de-interleaved, TILE-padded) ----------


def pack_single_block_keccak(messages: "list[bytes]"):
    """Pad ≤135-byte messages into de-interleaved keccak rate blocks.

    Returns (blocks_lo u32[Np, 17], blocks_hi u32[Np, 17], n) where
    Np is n rounded up to TILE.
    """
    n = len(messages)
    n_pad = ((n + TILE - 1) // TILE) * TILE
    raw = np.zeros((n_pad, 136), dtype=np.uint8)
    for i, msg in enumerate(messages):
        if len(msg) >= 136:
            raise ValueError("single-block keccak kernel requires len < 136")
        raw[i, : len(msg)] = np.frombuffer(msg, dtype=np.uint8)
        raw[i, len(msg)] ^= 0x01
        raw[i, 135] ^= 0x80
    words = raw.view(np.uint32).reshape(n_pad, 34)
    return np.ascontiguousarray(words[:, 0::2]), np.ascontiguousarray(words[:, 1::2]), n


def pack_single_block_blake2b(messages: "list[bytes]"):
    """Pad ≤128-byte messages into de-interleaved blake2b blocks.

    Returns (m_lo u32[Np, 16], m_hi u32[Np, 16], lengths i32[Np, 1], n).
    """
    n = len(messages)
    n_pad = ((n + TILE - 1) // TILE) * TILE
    raw = np.zeros((n_pad, 128), dtype=np.uint8)
    lengths = np.zeros((n_pad, 1), dtype=np.int32)
    for i, msg in enumerate(messages):
        if len(msg) > 128:
            raise ValueError("single-block blake2b kernel requires len <= 128")
        raw[i, : len(msg)] = np.frombuffer(msg, dtype=np.uint8)
        lengths[i, 0] = len(msg)
    words = raw.view(np.uint32).reshape(n_pad, 32)
    return (
        np.ascontiguousarray(words[:, 0::2]),
        np.ascontiguousarray(words[:, 1::2]),
        lengths,
        n,
    )
