"""Host-side packing: variable-length byte messages → padded u32 tensors.

The bridge between the pointer-chasing host world (IPLD blocks, event
entries) and fixed-shape device tensors. Length-dependent padding (keccak's
0x01…0x80 domain bits, blake2b's zero fill + byte counters) happens here so
the device kernels see only dense arrays + per-message counts.
"""

from __future__ import annotations

import numpy as np

from ipc_proofs_tpu.ops.blake2b_jax import BLOCK_BYTES as B2B_BLOCK
from ipc_proofs_tpu.ops.keccak_jax import RATE_BYTES

__all__ = ["pad_keccak", "pad_blake2b", "digests_to_bytes"]


def pad_keccak(messages: "list[bytes]", max_blocks: "int | None" = None):
    """Pack messages into keccak rate blocks with multi-rate padding applied.

    Returns (blocks u32[N, B, 34], n_blocks i32[N]).
    """
    n = len(messages)
    counts = np.array([len(m) // RATE_BYTES + 1 for m in messages], dtype=np.int32)
    b = int(counts.max()) if n else 1
    if max_blocks is not None:
        if counts.size and counts.max() > max_blocks:
            raise ValueError(f"message needs {counts.max()} blocks > cap {max_blocks}")
        b = max_blocks
    raw = np.zeros((n, b * RATE_BYTES), dtype=np.uint8)
    for i, msg in enumerate(messages):
        raw[i, : len(msg)] = np.frombuffer(msg, dtype=np.uint8)
        raw[i, len(msg)] ^= 0x01
        raw[i, counts[i] * RATE_BYTES - 1] ^= 0x80
    blocks = raw.reshape(n, b, RATE_BYTES).view(np.uint32).reshape(n, b, RATE_BYTES // 4)
    # u32 words are already (lo, hi) interleaved little-endian: word 2i = lane i lo
    return np.ascontiguousarray(blocks), counts


def pad_blake2b(messages: "list[bytes]", max_blocks: "int | None" = None):
    """Pack messages into zero-padded 128-byte blake2b blocks.

    Returns (blocks u32[N, B, 32], n_blocks i32[N], lengths i32[N]).
    """
    n = len(messages)
    lengths = np.array([len(m) for m in messages], dtype=np.int32)
    counts = np.maximum((lengths + B2B_BLOCK - 1) // B2B_BLOCK, 1).astype(np.int32)
    b = int(counts.max()) if n else 1
    if max_blocks is not None:
        if counts.size and counts.max() > max_blocks:
            raise ValueError(f"message needs {counts.max()} blocks > cap {max_blocks}")
        b = max_blocks
    raw = np.zeros((n, b * B2B_BLOCK), dtype=np.uint8)
    for i, msg in enumerate(messages):
        raw[i, : len(msg)] = np.frombuffer(msg, dtype=np.uint8)
    blocks = raw.reshape(n, b, B2B_BLOCK).view(np.uint32).reshape(n, b, B2B_BLOCK // 4)
    return np.ascontiguousarray(blocks), counts, lengths


def digests_to_bytes(digests) -> "list[bytes]":
    """uint32 [N, 8] little-endian words → 32-byte digests."""
    arr = np.asarray(digests, dtype=np.uint32)
    return [arr[i].astype("<u4").tobytes() for i in range(arr.shape[0])]
