"""Shared kernel-selection + slope-timing harness for the witness-CID
recompute benchmarks (BASELINE config 4 and bench.py's secondary line).

Both benchmarks measure the same thing — blake2b-256 CID recompute over
~200-byte IPLD nodes — so the kernel choice (two-block Pallas on a chip
that accepts it, XLA scan otherwise, including a runtime Mosaic-rejection
fallback) lives here exactly once.
"""

from __future__ import annotations

__all__ = ["blake2b_cid_bench_setup"]


def blake2b_cid_bench_setup(messages: "list[bytes]"):
    """Build the timing closure for a blake2b CID-recompute benchmark.

    Returns ``(one_pass, args_j, first_digests, kernel_name)`` where
    ``one_pass(i, *args_j)`` is slope-timeable (perturbs the input with
    ``^ i`` so passes cannot be CSE'd), ``first_digests`` is the
    correctness-check array for the unperturbed input, and ``kernel_name``
    names the kernel that will actually run.
    """
    import numpy as np
    import jax.numpy as jnp

    from ipc_proofs_tpu.backend import get_backend

    if get_backend("tpu")._pallas_usable():
        # the single-block probe passing does not guarantee Mosaic accepts
        # the larger two-block kernel — compile it here and fall back
        try:
            from ipc_proofs_tpu.ops.pallas_kernels import (
                blake2b256_two_block_pallas,
                pack_two_block_blake2b,
            )

            m_lo, m_hi, lengths, _ = pack_two_block_blake2b(messages)
            args_j = (jnp.asarray(m_lo), jnp.asarray(m_hi), jnp.asarray(lengths))
            first = np.asarray(blake2b256_two_block_pallas(*args_j))

            def one_pass(i, a, b, l):
                d = blake2b256_two_block_pallas(a ^ i.astype(jnp.uint32), b, l)
                return d.sum(dtype=jnp.uint32).astype(jnp.int32)

            return one_pass, args_j, first, "pallas-2blk"
        except Exception:  # fail-soft: Mosaic rejection — the bench measures the XLA kernel instead
            pass

    from ipc_proofs_tpu.ops.blake2b_jax import blake2b256_blocks
    from ipc_proofs_tpu.ops.pack import pad_blake2b

    blocks, counts, lengths = pad_blake2b(messages)
    args_j = (jnp.asarray(blocks), jnp.asarray(counts), jnp.asarray(lengths))
    first = np.asarray(blake2b256_blocks(*args_j))

    def one_pass(i, b, c, l):
        d = blake2b256_blocks(b ^ i.astype(jnp.uint32), c, l)
        return d.sum(dtype=jnp.uint32).astype(jnp.int32)

    return one_pass, args_j, first, "xla"
