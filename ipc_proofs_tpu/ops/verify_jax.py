"""Device-batched multihash verification: the on-chip integrity plane.

Every cold read path re-hashes witness blocks before anything observes
them (`store.rpc.verify_block_bytes`) — per-block Python on exactly the
workload the batch hash kernels were built for. `verify_blocks_batch`
turns one chunk's worth of blocks (a fetch-plane landed wave, a follower
prefetch batch, a segment-store multi-read) into ONE fused device call
per multihash family: blake2b-256 rides `ops.blake2b_jax.blake2b256_blocks`
and keccak-256 rides `ops.keccak_jax.keccak256_blocks`, both packed
host-side by `ops.pack` into size-class chunks so a batch of 1 KiB blocks
never pads to its largest member.

Verdict contract: ``verify_blocks_batch(cids, blocks)[i]`` equals
``verify_block_bytes(cids[i], blocks[i])`` for every i — including the
"unknown multihash codes are accepted" rule — pinned by the differential
grid in tests/test_verify_batch.py. Codes without a device kernel
(sha2-256, identity, unknown) and sub-crossover batches take the scalar
lane; the verdicts are identical either way, only the hashing lane moves.

Shape discipline mirrors the match path: message counts pad to
power-of-two buckets and block counts to power-of-two size classes, so
repeated waves compile O(log² n) kernel shapes, not one per batch.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional, Sequence

import numpy as np

from ipc_proofs_tpu.core.cid import BLAKE2B_256, CID, IDENTITY, KECCAK_256, SHA2_256
from ipc_proofs_tpu.core.hashes import blake2b_256, keccak256

__all__ = ["verify_blocks_batch", "batch_min_bytes"]

# Below this many payload bytes in one batch, XLA dispatch + packing costs
# more than hashlib's C loop — the scalar lane runs instead (verdicts are
# identical; this is the same crossover discipline as backend.tpu).
_DEFAULT_MIN_BYTES = 256 * 1024

# one device call hashes at most this many messages (bounds the padded
# [N, B, words] tensor one size-class chunk packs)
_CHUNK_MAX_MSGS = 512
_MIN_MSG_BUCKET = 8

_jax_ok: "bool | None" = None


def batch_min_bytes() -> int:
    """Device-lane crossover in payload bytes (env IPC_VERIFY_MIN_BYTES)."""
    try:
        return int(os.environ.get("IPC_VERIFY_MIN_BYTES", _DEFAULT_MIN_BYTES))
    except ValueError:
        return _DEFAULT_MIN_BYTES


def _device_ready() -> bool:
    global _jax_ok
    if _jax_ok is None:
        try:
            import jax  # noqa: F401

            _jax_ok = True
        except Exception:  # fail-soft: no jax = scalar lane, never an error
            _jax_ok = False
    return _jax_ok


def _verify_one(cid: CID, data: bytes) -> bool:
    """Scalar verdict — same rules as `store.rpc.verify_block_bytes`
    (kept import-cycle-free here; the differential test pins equality)."""
    mh = cid.mh_code
    data = bytes(data)
    if mh == BLAKE2B_256:
        return blake2b_256(data) == cid.digest
    if mh == SHA2_256:
        return hashlib.sha256(data).digest() == cid.digest
    if mh == KECCAK_256:
        return keccak256(data) == cid.digest
    if mh == IDENTITY:
        return data == bytes(cid.digest)
    return True


def _pow2_at_least(n: int, minimum: int) -> int:
    bucket = minimum
    while bucket < n:
        bucket *= 2
    return bucket


def _size_class_chunks(idxs: "list[int]", blocks_needed: "list[int]"):
    """Partition message indices into (class_blocks, [idx, …]) chunks:
    messages group by power-of-two block-count class (so one huge block
    never inflates everyone's padding) and each chunk holds at most
    `_CHUNK_MAX_MSGS` messages."""
    by_class: "dict[int, list[int]]" = {}
    for i in idxs:
        by_class.setdefault(_pow2_at_least(blocks_needed[i], 1), []).append(i)
    for cls in sorted(by_class):
        members = by_class[cls]
        for start in range(0, len(members), _CHUNK_MAX_MSGS):
            yield cls, members[start : start + _CHUNK_MAX_MSGS]


def _device_digests(code: int, chunk_msgs: "list[bytes]", cls: int) -> "list[bytes]":
    """One fused kernel dispatch: digests of `chunk_msgs` (padded to a
    power-of-two message bucket; the filler digests are discarded)."""
    from ipc_proofs_tpu.ops.pack import digests_to_bytes, pad_blake2b, pad_keccak

    n_real = len(chunk_msgs)
    bucket = _pow2_at_least(n_real, _MIN_MSG_BUCKET)
    msgs = chunk_msgs + [b""] * (bucket - n_real)
    if code == BLAKE2B_256:
        from ipc_proofs_tpu.ops.blake2b_jax import blake2b256_blocks

        blocks_t, counts, lengths = pad_blake2b(msgs, max_blocks=cls)
        out = blake2b256_blocks(blocks_t, counts, lengths)
    else:  # KECCAK_256
        from ipc_proofs_tpu.ops.keccak_jax import keccak256_blocks

        blocks_t, counts = pad_keccak(msgs, max_blocks=cls)
        out = keccak256_blocks(blocks_t, counts)
    return digests_to_bytes(np.asarray(out))[:n_real]


def verify_blocks_batch(
    cids: Sequence[CID], blocks: Sequence[bytes], metrics=None
) -> "list[bool]":
    """Batch form of `verify_block_bytes`: one verdict per (cid, block).

    blake2b-256 and keccak-256 blocks hash in fused device batches when
    the batch clears the crossover (`batch_min_bytes`); everything else —
    and every block when jax is unavailable — verifies on the scalar
    lane. Verdicts are bit-identical across lanes by construction.
    """
    cids = list(cids)
    blocks = [bytes(b) for b in blocks]
    if len(cids) != len(blocks):
        raise ValueError(f"{len(cids)} cids vs {len(blocks)} blocks")
    n = len(cids)
    verdicts = [False] * n
    if metrics is not None:
        metrics.count("verify.batch_calls")
        metrics.count("verify.batch_blocks", n)
    if n == 0:
        return verdicts

    device_idx: "dict[int, list[int]]" = {BLAKE2B_256: [], KECCAK_256: []}
    scalar_idx: "list[int]" = []
    for i, cid in enumerate(cids):
        lane = device_idx.get(cid.mh_code)
        (lane if lane is not None else scalar_idx).append(i)

    batchable = device_idx[BLAKE2B_256] + device_idx[KECCAK_256]
    device_bytes = sum(len(blocks[i]) for i in batchable)
    if not (
        _device_ready() and len(batchable) >= 2 and device_bytes >= batch_min_bytes()
    ):
        scalar_idx.extend(batchable)
        device_idx = {BLAKE2B_256: [], KECCAK_256: []}

    for code, idxs in device_idx.items():
        if not idxs:
            continue
        if code == BLAKE2B_256:
            from ipc_proofs_tpu.ops.blake2b_jax import BLOCK_BYTES

            need = [max(1, -(-len(blocks[i]) // BLOCK_BYTES)) for i in range(n)]
        else:
            from ipc_proofs_tpu.ops.keccak_jax import RATE_BYTES

            need = [len(blocks[i]) // RATE_BYTES + 1 for i in range(n)]
        try:
            for cls, chunk in _size_class_chunks(idxs, need):
                digests = _device_digests(code, [blocks[i] for i in chunk], cls)
                for i, digest in zip(chunk, digests):
                    verdicts[i] = digest == cids[i].digest
                if metrics is not None:
                    metrics.count("verify.device_calls")
                    metrics.count("verify.device_blocks", len(chunk))
        except Exception:  # fail-soft: a device fault must never fail a read path — the scalar lane re-derives the same verdicts
            scalar_idx.extend(idxs)

    for i in scalar_idx:
        verdicts[i] = _verify_one(cids[i], blocks[i])
    if metrics is not None and scalar_idx:
        metrics.count("verify.scalar_blocks", len(scalar_idx))
    return verdicts
