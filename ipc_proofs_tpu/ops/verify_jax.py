"""Device-batched multihash verification: the on-chip integrity plane.

Every cold read path re-hashes witness blocks before anything observes
them (`store.rpc.verify_block_bytes`) — per-block Python on exactly the
workload the batch hash kernels were built for. `verify_blocks_batch`
turns one chunk's worth of blocks (a fetch-plane landed wave, a follower
prefetch batch, a segment-store multi-read) into ONE fused device call
per multihash family: blake2b-256 rides `ops.blake2b_jax.blake2b256_blocks`
and keccak-256 rides `ops.keccak_jax.keccak256_blocks`, both packed
host-side by `ops.pack` into size-class chunks so a batch of 1 KiB blocks
never pads to its largest member.

Verdict contract: ``verify_blocks_batch(cids, blocks)[i]`` equals
``verify_block_bytes(cids[i], blocks[i])`` for every i — including the
"unknown multihash codes are accepted" rule — pinned by the differential
grid in tests/test_verify_batch.py. Codes without a device kernel
(sha2-256, identity, unknown) and sub-crossover batches take the scalar
lane; the verdicts are identical either way, only the hashing lane moves.

Shape discipline mirrors the match path: message counts pad to
power-of-two buckets and block counts to power-of-two size classes, so
repeated waves compile O(log² n) kernel shapes, not one per batch.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from typing import Optional, Sequence

import numpy as np

from ipc_proofs_tpu.core.cid import BLAKE2B_256, CID, IDENTITY, KECCAK_256, SHA2_256
from ipc_proofs_tpu.core.hashes import blake2b_256, keccak256

__all__ = [
    "verify_blocks_batch",
    "batch_min_bytes",
    "autotune_crossover",
    "load_autotune",
    "SCALAR_ONLY_MIN_BYTES",
]

# Below this many payload bytes in one batch, XLA dispatch + packing costs
# more than hashlib's C loop — the scalar lane runs instead (verdicts are
# identical; this is the same crossover discipline as backend.tpu).
_DEFAULT_MIN_BYTES = 256 * 1024

# Autotuned crossover persisted per host under --store-dir. Resolution
# order in `batch_min_bytes`: env IPC_VERIFY_MIN_BYTES (always wins, so
# an operator override survives autotuning) > loaded autotune record >
# `_DEFAULT_MIN_BYTES`.
_AUTOTUNE_FILE = "verify_autotune.json"
_AUTOTUNE_VERSION = 1

#: Sentinel crossover meaning "the device lane never beat hashlib on this
#: host — stay scalar at every batch size". Large enough that no real
#: batch reaches it.
SCALAR_ONLY_MIN_BYTES = 1 << 62

_tuned_min_bytes: "int | None" = None

# one device call hashes at most this many messages (bounds the padded
# [N, B, words] tensor one size-class chunk packs)
_CHUNK_MAX_MSGS = 512
_MIN_MSG_BUCKET = 8

_jax_ok: "bool | None" = None


def batch_min_bytes() -> int:
    """Device-lane crossover in payload bytes.

    env IPC_VERIFY_MIN_BYTES > autotuned value (`autotune_crossover` /
    `load_autotune`) > built-in default.
    """
    env = os.environ.get("IPC_VERIFY_MIN_BYTES")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass
    if _tuned_min_bytes is not None:
        return _tuned_min_bytes
    return _DEFAULT_MIN_BYTES


def _device_ready() -> bool:
    global _jax_ok
    if _jax_ok is None:
        try:
            import jax  # noqa: F401

            _jax_ok = True
        except Exception:  # fail-soft: no jax = scalar lane, never an error
            _jax_ok = False
    return _jax_ok


def _verify_one(cid: CID, data: bytes) -> bool:
    """Scalar verdict — same rules as `store.rpc.verify_block_bytes`
    (kept import-cycle-free here; the differential test pins equality)."""
    mh = cid.mh_code
    data = bytes(data)
    if mh == BLAKE2B_256:
        return blake2b_256(data) == cid.digest
    if mh == SHA2_256:
        return hashlib.sha256(data).digest() == cid.digest
    if mh == KECCAK_256:
        return keccak256(data) == cid.digest
    if mh == IDENTITY:
        return data == bytes(cid.digest)
    return True


def _pow2_at_least(n: int, minimum: int) -> int:
    bucket = minimum
    while bucket < n:
        bucket *= 2
    return bucket


def _size_class_chunks(idxs: "list[int]", blocks_needed: "list[int]"):
    """Partition message indices into (class_blocks, [idx, …]) chunks:
    messages group by power-of-two block-count class (so one huge block
    never inflates everyone's padding) and each chunk holds at most
    `_CHUNK_MAX_MSGS` messages."""
    by_class: "dict[int, list[int]]" = {}
    for i in idxs:
        by_class.setdefault(_pow2_at_least(blocks_needed[i], 1), []).append(i)
    for cls in sorted(by_class):
        members = by_class[cls]
        for start in range(0, len(members), _CHUNK_MAX_MSGS):
            yield cls, members[start : start + _CHUNK_MAX_MSGS]


def _device_digests(code: int, chunk_msgs: "list[bytes]", cls: int) -> "list[bytes]":
    """One fused kernel dispatch: digests of `chunk_msgs` (padded to a
    power-of-two message bucket; the filler digests are discarded)."""
    from ipc_proofs_tpu.ops.pack import digests_to_bytes, pad_blake2b, pad_keccak

    n_real = len(chunk_msgs)
    bucket = _pow2_at_least(n_real, _MIN_MSG_BUCKET)
    msgs = chunk_msgs + [b""] * (bucket - n_real)
    if code == BLAKE2B_256:
        from ipc_proofs_tpu.ops.blake2b_jax import blake2b256_blocks

        blocks_t, counts, lengths = pad_blake2b(msgs, max_blocks=cls)
        out = blake2b256_blocks(blocks_t, counts, lengths)
    else:  # KECCAK_256
        from ipc_proofs_tpu.ops.keccak_jax import keccak256_blocks

        blocks_t, counts = pad_keccak(msgs, max_blocks=cls)
        out = keccak256_blocks(blocks_t, counts)
    return digests_to_bytes(np.asarray(out))[:n_real]


def verify_blocks_batch(
    cids: Sequence[CID], blocks: Sequence[bytes], metrics=None
) -> "list[bool]":
    """Batch form of `verify_block_bytes`: one verdict per (cid, block).

    blake2b-256 and keccak-256 blocks hash in fused device batches when
    the batch clears the crossover (`batch_min_bytes`); everything else —
    and every block when jax is unavailable — verifies on the scalar
    lane. Verdicts are bit-identical across lanes by construction.
    """
    cids = list(cids)
    blocks = [bytes(b) for b in blocks]
    if len(cids) != len(blocks):
        raise ValueError(f"{len(cids)} cids vs {len(blocks)} blocks")
    n = len(cids)
    verdicts = [False] * n
    if metrics is not None:
        metrics.count("verify.batch_calls")
        metrics.count("verify.batch_blocks", n)
    if n == 0:
        return verdicts

    device_idx: "dict[int, list[int]]" = {BLAKE2B_256: [], KECCAK_256: []}
    scalar_idx: "list[int]" = []
    for i, cid in enumerate(cids):
        lane = device_idx.get(cid.mh_code)
        (lane if lane is not None else scalar_idx).append(i)

    batchable = device_idx[BLAKE2B_256] + device_idx[KECCAK_256]
    device_bytes = sum(len(blocks[i]) for i in batchable)
    if not (
        _device_ready() and len(batchable) >= 2 and device_bytes >= batch_min_bytes()
    ):
        scalar_idx.extend(batchable)
        device_idx = {BLAKE2B_256: [], KECCAK_256: []}

    for code, idxs in device_idx.items():
        if not idxs:
            continue
        if code == BLAKE2B_256:
            from ipc_proofs_tpu.ops.blake2b_jax import BLOCK_BYTES

            need = [max(1, -(-len(blocks[i]) // BLOCK_BYTES)) for i in range(n)]
        else:
            from ipc_proofs_tpu.ops.keccak_jax import RATE_BYTES

            need = [len(blocks[i]) // RATE_BYTES + 1 for i in range(n)]
        try:
            for cls, chunk in _size_class_chunks(idxs, need):
                digests = _device_digests(code, [blocks[i] for i in chunk], cls)
                for i, digest in zip(chunk, digests):
                    verdicts[i] = digest == cids[i].digest
                if metrics is not None:
                    metrics.count("verify.device_calls")
                    metrics.count("verify.device_blocks", len(chunk))
        except Exception:  # fail-soft: a device fault must never fail a read path — the scalar lane re-derives the same verdicts
            scalar_idx.extend(idxs)

    for i in scalar_idx:
        verdicts[i] = _verify_one(cids[i], blocks[i])
    if metrics is not None and scalar_idx:
        metrics.count("verify.scalar_blocks", len(scalar_idx))
    return verdicts


# --- per-host crossover autotuning ------------------------------------------
#
# `_DEFAULT_MIN_BYTES` is a guess; the real crossover between hashlib's C
# loop and the XLA lane varies by host (on a CPU-only host the u32-lane
# device emulation can lose at EVERY size — BENCH_r18 measured the forced
# device lane at 0.039× scalar). `autotune_crossover` measures both lanes
# once per host, persists the winner's crossover under --store-dir, and
# every later daemon on the host loads the record instead of re-measuring.


def load_autotune(store_dir: str) -> "int | None":
    """Load a persisted autotune record, activating its crossover.

    Returns the tuned min-bytes (possibly `SCALAR_ONLY_MIN_BYTES`) or
    None when no valid record exists. Never raises: an unreadable or
    wrong-version record is treated as absent.
    """
    global _tuned_min_bytes
    path = os.path.join(store_dir, _AUTOTUNE_FILE)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            record = json.load(fh)
        if record.get("version") != _AUTOTUNE_VERSION:
            return None
        min_bytes = int(record["min_bytes"])
    except (OSError, ValueError, TypeError, KeyError):  # fail-soft: a bad tuning record must never block serving — the default crossover applies
        return None
    _tuned_min_bytes = min_bytes
    return min_bytes


def _autotune_fixture(payload_bytes: int, block_bytes: int = 1024):
    """Deterministic (cids, blocks) covering `payload_bytes` of blake2b
    blocks — the multihash family every witness block in this repo uses."""
    n = max(2, payload_bytes // block_bytes)
    rng = np.random.default_rng(11)
    payload = rng.integers(0, 256, size=(n, block_bytes), dtype=np.uint8)
    blocks = [payload[i].tobytes() for i in range(n)]
    from ipc_proofs_tpu.core.cid import DAG_CBOR

    cids = [CID.hash_of(b, codec=DAG_CBOR, mh_code=BLAKE2B_256) for b in blocks]
    return cids, blocks


def _best_of(fn, reps: int = 3) -> float:
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _device_lane_wall(cids, blocks) -> float:
    """Best-of-3 wall of the device lane over (cids, blocks), compile
    warmed outside the timing. Digests are checked against the cids so a
    lane that silently mis-hashes can never win the tuning."""
    from ipc_proofs_tpu.ops.blake2b_jax import BLOCK_BYTES

    need = [max(1, -(-len(b) // BLOCK_BYTES)) for b in blocks]
    idxs = list(range(len(blocks)))

    def run():
        for cls, chunk in _size_class_chunks(idxs, need):
            digests = _device_digests(BLAKE2B_256, [blocks[i] for i in chunk], cls)
            for i, digest in zip(chunk, digests):
                if digest != cids[i].digest:
                    raise RuntimeError("autotune fixture digest mismatch")

    run()  # warm (compile) outside the timing
    return _best_of(run)


def autotune_crossover(
    store_dir: Optional[str] = None, quick: bool = True, force: bool = False
) -> dict:
    """One-shot per-host crossover measurement.

    Times the scalar hashlib loop against the fused device lane over the
    same blake2b blocks at increasing batch payloads; the tuned crossover
    is the smallest payload where the device lane wins (or
    `SCALAR_ONLY_MIN_BYTES` when it never does — the honest outcome on
    CPU-only hosts). With `store_dir` the record persists as
    ``verify_autotune.json`` and later calls load it instead of
    re-measuring (`force=True` re-measures). The active crossover updates
    either way; env IPC_VERIFY_MIN_BYTES still overrides everything.
    """
    global _tuned_min_bytes
    if store_dir and not force:
        loaded = load_autotune(store_dir)
        if loaded is not None:
            path = os.path.join(store_dir, _AUTOTUNE_FILE)
            with open(path, "r", encoding="utf-8") as fh:
                return json.load(fh)

    sizes = [64 * 1024, 256 * 1024, 1024 * 1024]
    if not quick:
        sizes.append(4 * 1024 * 1024)
    samples: "list[dict]" = []
    min_bytes = SCALAR_ONLY_MIN_BYTES
    scalar_only = True
    reason = None
    if not _device_ready():
        reason = "no-device"
    else:
        try:
            for payload in sizes:
                cids, blocks = _autotune_fixture(payload)
                t_scalar = _best_of(
                    lambda: [_verify_one(c, b) for c, b in zip(cids, blocks)]
                )
                t_device = _device_lane_wall(cids, blocks)
                samples.append(
                    {
                        "payload_bytes": payload,
                        "scalar_s": round(t_scalar, 6),
                        "device_s": round(t_device, 6),
                    }
                )
                if scalar_only and t_device <= t_scalar:
                    min_bytes = payload
                    scalar_only = False
                    # keep sampling: the record shows the full curve
        except Exception:  # fail-soft: a device fault during tuning means the device lane cannot be trusted to win — scalar-only is the safe record
            min_bytes = SCALAR_ONLY_MIN_BYTES
            scalar_only = True
            reason = "device-error"

    record = {
        "version": _AUTOTUNE_VERSION,
        "min_bytes": min_bytes,
        "scalar_only": scalar_only,
        "samples": samples,
    }
    if reason is not None:
        record["reason"] = reason
    if store_dir:
        os.makedirs(store_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=store_dir, prefix=_AUTOTUNE_FILE, suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(record, fh, indent=2, sort_keys=True)
            os.replace(tmp, os.path.join(store_dir, _AUTOTUNE_FILE))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:  # fail-soft: best-effort temp cleanup on a failed persist
                pass
            raise
    _tuned_min_bytes = min_bytes
    return record
