"""JAX/Pallas kernels for the batch inner loops.

TPUs have no native u64 integer lanes, so keccak-f[1600] and blake2b-256 are
implemented over u32 pairs (`u64.py`) with all rotation amounts static —
the whole permutation unrolls at trace time into [N]-wide elementwise vector
ops, i.e. the classic bitslice-over-batch layout. `vmap` adds the batch
dimension; multi-block messages absorb via `lax.scan` with per-message
block-count masking (`pack.py` does the host-side padding).
"""
