"""blake2b-256 as a batch JAX kernel (u32-pair lanes, array form).

Filecoin's chain CID hash. The TPU witness verifier recomputes the CID of
every witness block with this kernel (BASELINE.json config 4: 1M-block CID
recompute) — the integrity check the reference leaves implicit.

Layout: the 16-word working vector lives in uint32 [N, 16] pairs; the 12
rounds run under `lax.fori_loop` with the sigma schedule as a constant
gather, and each round does the 4 column G-mixes and 4 diagonal G-mixes as
[N, 4]-vectorized ops — compact graph, fully batched.

Golden model: `hashlib.blake2b(digest_size=32)` via
:func:`ipc_proofs_tpu.core.hashes.blake2b_256` (tested equal).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["blake2b256_blocks", "BLOCK_BYTES"]

BLOCK_BYTES = 128
WORDS_PER_BLOCK_U32 = 32  # 16 u64 message words

_IV = (
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
    0x510E527FADE682D1, 0x9B05688C2B3E6C1F, 0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
)

# digest_length=32, key=0, fanout=1, depth=1
_PARAM_WORD0 = 0x01010020

_SIGMA = np.array(
    [
        [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15],
        [14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3],
        [11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4],
        [7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8],
        [9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13],
        [2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9],
        [12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11],
        [13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10],
        [6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5],
        [10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0],
    ],
    dtype=np.int32,
)

_IV_LO = np.array([x & 0xFFFFFFFF for x in _IV], dtype=np.uint32)
_IV_HI = np.array([x >> 32 for x in _IV], dtype=np.uint32)


def _add64(alo, ahi, blo, bhi):
    sum_lo = alo + blo
    carry = (sum_lo < alo).astype(jnp.uint32)
    return sum_lo, ahi + bhi + carry


def _rotr64(lo, hi, n: int):
    """Rotate right by static n — specialized for blake2b's 32/24/16/63."""
    if n == 32:
        return hi, lo
    if n == 63:  # rotr 63 == rotl 1
        return (lo << 1) | (hi >> 31), (hi << 1) | (lo >> 31)
    # 0 < n < 32
    return (lo >> n) | (hi << (32 - n)), (hi >> n) | (lo << (32 - n))


def _g(a, b, c, d, mx, my):
    """Vectorized G over [N, 4] u64 pairs."""
    a = _add64(*_add64(*a, *b), *mx)
    d = _rotr64(d[0] ^ a[0], d[1] ^ a[1], 32)
    c = _add64(*c, *d)
    b = _rotr64(b[0] ^ c[0], b[1] ^ c[1], 24)
    a = _add64(*_add64(*a, *b), *my)
    d = _rotr64(d[0] ^ a[0], d[1] ^ a[1], 16)
    c = _add64(*c, *d)
    b = _rotr64(b[0] ^ c[0], b[1] ^ c[1], 63)
    return a, b, c, d


def _compress(h_lo, h_hi, m_lo, m_hi, t_lo, f_word, tables=None):
    """One compression for the whole batch.

    h: [N, 8] pairs; m: [N, 16] message-word pairs; t_lo: [N] byte counters
    (messages < 4 GiB, so the u64 counter's high word is 0);
    f_word: [N] all-ones where final block. ``tables`` optionally supplies
    ``(iv_lo, iv_hi, sigma)`` as traced arrays (Pallas kernels cannot close
    over array constants).
    """
    if tables is None:
        iv_lo, iv_hi, sigma = jnp.asarray(_IV_LO), jnp.asarray(_IV_HI), jnp.asarray(_SIGMA)
    else:
        iv_lo, iv_hi, sigma = tables
    batch = h_lo.shape[0]
    v_lo = jnp.concatenate([h_lo, jnp.broadcast_to(iv_lo, (batch, 8))], axis=1)
    v_hi = jnp.concatenate([h_hi, jnp.broadcast_to(iv_hi, (batch, 8))], axis=1)
    v_lo = v_lo.at[:, 12].set(v_lo[:, 12] ^ t_lo)
    v_lo = v_lo.at[:, 14].set(v_lo[:, 14] ^ f_word)
    v_hi = v_hi.at[:, 14].set(v_hi[:, 14] ^ f_word)

    def round_fn(r, v):
        v_lo, v_hi = v
        s = sigma[r % 10]
        mx_lo = jnp.take(m_lo, s[0::2], axis=1)  # [N, 8]
        mx_hi = jnp.take(m_hi, s[0::2], axis=1)
        my_lo = jnp.take(m_lo, s[1::2], axis=1)
        my_hi = jnp.take(m_hi, s[1::2], axis=1)

        # columns: (0,4,8,12) (1,5,9,13) (2,6,10,14) (3,7,11,15)
        a = (v_lo[:, 0:4], v_hi[:, 0:4])
        b = (v_lo[:, 4:8], v_hi[:, 4:8])
        c = (v_lo[:, 8:12], v_hi[:, 8:12])
        d = (v_lo[:, 12:16], v_hi[:, 12:16])
        a, b, c, d = _g(a, b, c, d, (mx_lo[:, 0:4], mx_hi[:, 0:4]), (my_lo[:, 0:4], my_hi[:, 0:4]))

        # diagonals: (0,5,10,15) (1,6,11,12) (2,7,8,13) (3,4,9,14)
        b = (jnp.roll(b[0], -1, axis=1), jnp.roll(b[1], -1, axis=1))
        c = (jnp.roll(c[0], -2, axis=1), jnp.roll(c[1], -2, axis=1))
        d = (jnp.roll(d[0], -3, axis=1), jnp.roll(d[1], -3, axis=1))
        a, b, c, d = _g(a, b, c, d, (mx_lo[:, 4:8], mx_hi[:, 4:8]), (my_lo[:, 4:8], my_hi[:, 4:8]))
        b = (jnp.roll(b[0], 1, axis=1), jnp.roll(b[1], 1, axis=1))
        c = (jnp.roll(c[0], 2, axis=1), jnp.roll(c[1], 2, axis=1))
        d = (jnp.roll(d[0], 3, axis=1), jnp.roll(d[1], 3, axis=1))

        v_lo = jnp.concatenate([a[0], b[0], c[0], d[0]], axis=1)
        v_hi = jnp.concatenate([a[1], b[1], c[1], d[1]], axis=1)
        return v_lo, v_hi

    v_lo, v_hi = lax.fori_loop(0, 12, round_fn, (v_lo, v_hi))
    new_h_lo = h_lo ^ v_lo[:, :8] ^ v_lo[:, 8:]
    new_h_hi = h_hi ^ v_hi[:, :8] ^ v_hi[:, 8:]
    return new_h_lo, new_h_hi


@jax.jit
def blake2b256_blocks(blocks, n_blocks, lengths):
    """Batch blake2b-256 over pre-padded blocks (jitted).

    Args:
      blocks: uint32 [N, B, 32] zero-padded 128-byte blocks
        (see `pack.pad_blake2b`).
      n_blocks: int32 [N] block count per message (≥ 1, even for empty).
      lengths: int32 [N] true byte lengths.

    Returns:
      uint32 [N, 8] digests (little-endian u32 words).
    """
    n = blocks.shape[0]
    h0_lo = _IV_LO.copy()
    h0_hi = _IV_HI.copy()
    h0_lo[0] ^= _PARAM_WORD0 & 0xFFFFFFFF
    h_lo = jnp.broadcast_to(jnp.asarray(h0_lo), (n, 8))
    h_hi = jnp.broadcast_to(jnp.asarray(h0_hi), (n, 8))

    def step(carry, inp):
        lo, hi = carry
        block, idx = inp  # [N, 32], scalar
        active = idx < n_blocks  # [N]
        is_last = idx == n_blocks - 1
        t_lo = jnp.where(is_last, lengths, (idx + 1) * BLOCK_BYTES).astype(jnp.uint32)
        f_word = jnp.where(is_last, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
        new_lo, new_hi = _compress(lo, hi, block[:, 0::2], block[:, 1::2], t_lo, f_word)
        mask = active[:, None]
        return (jnp.where(mask, new_lo, lo), jnp.where(mask, new_hi, hi)), None

    num_blocks = blocks.shape[1]
    (h_lo, h_hi), _ = lax.scan(
        step,
        (h_lo, h_hi),
        (jnp.moveaxis(blocks, 1, 0), jnp.arange(num_blocks, dtype=jnp.int32)),
    )
    return jnp.stack(
        [h_lo[:, 0], h_hi[:, 0], h_lo[:, 1], h_hi[:, 1],
         h_lo[:, 2], h_hi[:, 2], h_lo[:, 3], h_hi[:, 3]],
        axis=1,
    )
