"""Deterministic consistent-hash ring for shard affinity.

The router hashes each request's key — for proof traffic, the tipset
pair identity ``(parent cids, child cids)``; the contract is fixed
per-deployment (one service serves one spec), so the pair IS the
``(tipset, contract)`` key from ROADMAP item 2 — onto a ring of shard
names. Each shard owns ``vnodes`` points on the ring (classic virtual
nodes: removing one shard redistributes only its own arc, spread evenly
over the survivors, so every other shard's BlockCache stays hot for its
key range).

Determinism matters here the same way it does on the proof path: the
router and every test must agree on placement across processes and
Python invocations, so points come from sha256, never from Python's
salted ``hash()``. Affinity is a cache hint only — any shard can serve
any key — which is what makes work stealing and failover re-routing
safe (see ``cluster/router.py``).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, Sequence

__all__ = ["HashRing", "pair_ring_key"]


def _point(token: str) -> int:
    """64-bit ring position of one token (process-independent)."""
    return int.from_bytes(
        hashlib.sha256(token.encode("utf-8")).digest()[:8], "big"
    )


def pair_ring_key(pair) -> str:
    """The routing key of one tipset pair: its parent+child block CIDs.

    Pure function of the pair (duck-typed: anything with ``parent.cids``
    / ``child.cids``), so the router and an offline test partition a pair
    table identically.
    """
    parent = "|".join(str(c) for c in pair.parent.cids)
    child = "|".join(str(c) for c in pair.child.cids)
    return f"{parent}->{child}"


class HashRing:
    """Sorted-points consistent-hash ring over string node names.

    Not thread-safe on its own: the router serializes membership changes
    and lookups under its routing lock.
    """

    def __init__(self, nodes: Iterable[str] = (), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self._vnodes = int(vnodes)
        # parallel sorted arrays: point -> node; ties broken by node name
        # (the tuple sort) so ring order is total and deterministic
        self._points: "list[tuple[int, str]]" = []
        self._nodes: "set[str]" = set()
        for node in nodes:
            self.add(node)

    def add(self, node: str) -> None:
        if not node:
            raise ValueError("node name must be non-empty")
        if node in self._nodes:
            return
        self._nodes.add(node)
        for i in range(self._vnodes):
            entry = (_point(f"{node}#{i}"), node)
            bisect.insort(self._points, entry)

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._points = [(p, n) for p, n in self._points if n != node]

    def node_for(self, key: str) -> str:
        """The node owning ``key``'s arc (clockwise successor point)."""
        if not self._points:
            raise ValueError("hash ring is empty (no shards)")
        point = _point(key)
        # "￿" sorts above any node name: land after every entry
        # sharing `point` exactly, then wrap to the successor
        idx = bisect.bisect_right(self._points, (point, "￿"))
        if idx == len(self._points):
            idx = 0  # wrap around the ring
        return self._points[idx][1]

    def nodes_for(self, key: str, n: int) -> "list[str]":
        """The first ``n`` DISTINCT nodes clockwise from ``key``'s point:
        ``[primary, replica 1, replica 2, ...]`` — the replica-placement
        walk (R-way replication puts a key's bytes on its arc owner plus
        the next ``n - 1`` distinct successors). Returns fewer than ``n``
        when the ring holds fewer nodes."""
        if not self._points:
            raise ValueError("hash ring is empty (no shards)")
        point = _point(key)
        idx = bisect.bisect_right(self._points, (point, "￿"))
        out: "list[str]" = []
        seen: "set[str]" = set()
        for step in range(len(self._points)):
            _, node = self._points[(idx + step) % len(self._points)]
            if node not in seen:
                seen.add(node)
                out.append(node)
                if len(out) >= n:
                    break
        return out

    def nodes(self) -> Sequence[str]:
        return sorted(self._nodes)

    def __len__(self) -> int:
        return len(self._nodes)

    def __contains__(self, node: str) -> bool:
        return node in self._nodes
