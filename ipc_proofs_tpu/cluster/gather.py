"""Scatter partitioning + byte-identical merge of per-shard range bundles.

The canonical range bundle (what every range driver in
``proofs/range.py`` emits, and what the chunk-grid bit-identity tests
pin) is:

- **event proofs** in pair order — pair ``i``'s proofs before pair
  ``i+1``'s, each pair's proofs in deterministic scan order;
- **storage proofs** likewise in pair order;
- **witness blocks** deduplicated by CID and sorted by
  ``cid.to_bytes()`` (the ``_MergeFold.finish()`` / chunked-driver
  ordering).

Because each pair's proof bytes depend only on that pair, and the
witness-block *set* depends only on the pair set, a range request split
across N shards in ANY partition merges back to the exact bytes the
single-daemon run produces: re-interleave the proofs into the request's
global pair order (a proof names its pair via ``child_block_cid``) and
re-sort the CID-union of the witness blocks. That is the whole
correctness story of the scatter-gather path — no shard coordination,
no merge ambiguity, bit-identity by construction.

`BundleFold` is the incremental form: the router folds each shard's
sub-bundle into ONE CID-keyed map as its future completes and sorts the
union exactly once at `seal()` (``witness.merge_sorts`` counts seals, so
the bench can prove one sort per scatter rather than one per arrival).
`merge_range_bundles` stays as the fold-everything-then-seal wrapper.
The witness plane's cross-request aggregation
(`ipc_proofs_tpu/witness/aggregate.py`) layers per-claim spans over the
same canonical bundle — this module owns the merge law, that one the
claim table.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.proofs.bundle import ProofBlock, UnifiedProofBundle
from ipc_proofs_tpu.utils.metrics import Metrics, get_metrics

__all__ = [
    "BundleFold",
    "MergeConflictError",
    "merge_range_bundles",
    "partition_indexes",
]


class MergeConflictError(ValueError):
    """Two shards shipped different bytes for the same witness CID — one
    of them is lying or corrupt. Never silently picks a side."""


def partition_indexes(
    indexes: Sequence[int], assign: Dict[int, str]
) -> "Dict[str, List[int]]":
    """Group request pair-indexes by their assigned shard, preserving the
    request's relative order inside each group (``assign`` maps pair index
    → shard name; the router builds it from the hash ring + steal state).
    """
    groups: "Dict[str, List[int]]" = {}
    for idx in indexes:
        groups.setdefault(assign[idx], []).append(idx)
    return groups


class BundleFold:
    """Incremental canonical merge: fold sub-bundles as they arrive, sort
    the witness-CID union ONCE at seal.

    ``pairs`` is the full pair table; ``indexes`` the requested global
    pair indexes in request order (the order the single-daemon comparator
    would generate them in). Every proof in every folded bundle must map
    to one of ``indexes`` via its ``child_block_cid``.
    """

    def __init__(
        self,
        pairs: Sequence,
        indexes: Sequence[int],
        metrics: Optional[Metrics] = None,
    ):
        self._metrics = metrics if metrics is not None else get_metrics()
        self.indexes = list(indexes)
        # child block CID -> global pair index (a child block cid identifies
        # its pair — the same mapping the micro-batcher splits batches with)
        self._child_to_idx: "Dict[str, int]" = {}
        for idx in self.indexes:
            for c in pairs[idx].child.cids:
                self._child_to_idx[str(c)] = idx
        self._event_buckets: "Dict[int, list]" = {i: [] for i in self.indexes}
        self._storage_buckets: "Dict[int, list]" = {i: [] for i in self.indexes}
        self._by_cid: "Dict[bytes, ProofBlock]" = {}
        self._sealed = False

    def fold(self, bundle: UnifiedProofBundle) -> "List[ProofBlock]":
        """Fold one sub-bundle: bucket its proofs by pair, union its
        witness blocks into the single CID map (conflict-checked, never
        sorted here — sorting N times over an ever-growing map is the
        quadratic arrival cost `seal()` exists to avoid).

        Returns the blocks this fold saw for the FIRST time, in the
        sub-bundle's order — the streamed door sends exactly these as
        ``B`` chunks, so a block shared by several shards' sub-bundles
        crosses the client wire once even though each shard shipped it.
        """
        if self._sealed:
            raise RuntimeError("BundleFold already sealed")
        for proof in bundle.event_proofs:
            idx = self._child_to_idx.get(proof.child_block_cid)
            if idx is None:
                raise MergeConflictError(
                    f"event proof for unknown child block "
                    f"{proof.child_block_cid} (not in this request)"
                )
            self._event_buckets[idx].append(proof)
        for proof in bundle.storage_proofs:
            idx = self._child_to_idx.get(proof.child_block_cid)
            if idx is None:
                raise MergeConflictError(
                    f"storage proof for unknown child block "
                    f"{proof.child_block_cid} (not in this request)"
                )
            self._storage_buckets[idx].append(proof)
        fresh: "List[ProofBlock]" = []
        for block in bundle.blocks:
            raw = block.cid.to_bytes()
            prior = self._by_cid.get(raw)
            if prior is None:
                self._by_cid[raw] = block
                fresh.append(block)
            elif prior.data != block.data:
                raise MergeConflictError(
                    f"witness block {block.cid} has conflicting bytes "
                    "across shards"
                )
        return fresh

    def fold_block(self, cid_raw: bytes, data: bytes) -> bool:
        """Fold ONE raw witness block — the cut-through relay's door: a
        shard's ``B`` chunk folds the moment it arrives, without ever
        materializing that shard's sub-bundle. Returns True on first
        sight (exactly the blocks the relay forwards downstream, so the
        dedup guarantee of `fold` holds chunk-by-chunk); conflicting
        bytes for a seen CID raise `MergeConflictError`, same law as
        whole-bundle folding."""
        if self._sealed:
            raise RuntimeError("BundleFold already sealed")
        raw = bytes(cid_raw)
        prior = self._by_cid.get(raw)
        if prior is None:
            self._by_cid[raw] = ProofBlock(cid=CID.from_bytes(raw), data=data)
            return True
        if prior.data != data:
            raise MergeConflictError(
                f"witness block {CID.from_bytes(raw)} has conflicting bytes "
                "across shards"
            )
        return False

    def seal(self) -> UnifiedProofBundle:
        """One canonical sort over the folded CID union → the exact
        single-daemon bytes. Counted (``witness.merge_sorts``) so tests
        and the bench can assert one sort per scatter."""
        if self._sealed:
            raise RuntimeError("BundleFold already sealed")
        self._sealed = True
        self._metrics.count("witness.merge_sorts")
        by_cid = self._by_cid
        return UnifiedProofBundle(
            storage_proofs=[
                p for idx in self.indexes for p in self._storage_buckets[idx]
            ],
            event_proofs=[
                p for idx in self.indexes for p in self._event_buckets[idx]
            ],
            blocks=[by_cid[raw] for raw in sorted(by_cid)],
        )


def merge_range_bundles(
    bundles: Sequence[UnifiedProofBundle],
    pairs: Sequence,
    indexes: Sequence[int],
    metrics: Optional[Metrics] = None,
) -> UnifiedProofBundle:
    """Merge per-shard sub-bundles into the canonical single-daemon bundle
    (the all-at-once wrapper over `BundleFold`)."""
    fold = BundleFold(pairs, indexes, metrics=metrics)
    for bundle in bundles:
        fold.fold(bundle)
    return fold.seal()
