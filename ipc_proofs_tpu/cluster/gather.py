"""Scatter partitioning + byte-identical merge of per-shard range bundles.

The canonical range bundle (what every range driver in
``proofs/range.py`` emits, and what the chunk-grid bit-identity tests
pin) is:

- **event proofs** in pair order — pair ``i``'s proofs before pair
  ``i+1``'s, each pair's proofs in deterministic scan order;
- **storage proofs** likewise in pair order;
- **witness blocks** deduplicated by CID and sorted by
  ``cid.to_bytes()`` (the ``_MergeFold.finish()`` / chunked-driver
  ordering).

Because each pair's proof bytes depend only on that pair, and the
witness-block *set* depends only on the pair set, a range request split
across N shards in ANY partition merges back to the exact bytes the
single-daemon run produces: re-interleave the proofs into the request's
global pair order (a proof names its pair via ``child_block_cid``) and
re-sort the CID-union of the witness blocks. That is the whole
correctness story of the scatter-gather path — no shard coordination,
no merge ambiguity, bit-identity by construction.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from ipc_proofs_tpu.proofs.bundle import ProofBlock, UnifiedProofBundle

__all__ = ["MergeConflictError", "merge_range_bundles", "partition_indexes"]


class MergeConflictError(ValueError):
    """Two shards shipped different bytes for the same witness CID — one
    of them is lying or corrupt. Never silently picks a side."""


def partition_indexes(
    indexes: Sequence[int], assign: Dict[int, str]
) -> "Dict[str, List[int]]":
    """Group request pair-indexes by their assigned shard, preserving the
    request's relative order inside each group (``assign`` maps pair index
    → shard name; the router builds it from the hash ring + steal state).
    """
    groups: "Dict[str, List[int]]" = {}
    for idx in indexes:
        groups.setdefault(assign[idx], []).append(idx)
    return groups


def merge_range_bundles(
    bundles: Sequence[UnifiedProofBundle],
    pairs: Sequence,
    indexes: Sequence[int],
) -> UnifiedProofBundle:
    """Merge per-shard sub-bundles into the canonical single-daemon bundle.

    ``pairs`` is the full pair table; ``indexes`` the requested global
    pair indexes in request order (the order the single-daemon comparator
    would generate them in). Every proof in every sub-bundle must map to
    one of ``indexes`` via its ``child_block_cid``.
    """
    # child block CID -> global pair index (a child block cid identifies
    # its pair — the same mapping the micro-batcher splits batches with)
    child_to_idx: "Dict[str, int]" = {}
    for idx in indexes:
        for c in pairs[idx].child.cids:
            child_to_idx[str(c)] = idx

    event_buckets: "Dict[int, list]" = {idx: [] for idx in indexes}
    storage_buckets: "Dict[int, list]" = {idx: [] for idx in indexes}
    by_cid: "Dict[bytes, ProofBlock]" = {}
    for bundle in bundles:
        for proof in bundle.event_proofs:
            idx = child_to_idx.get(proof.child_block_cid)
            if idx is None:
                raise MergeConflictError(
                    f"event proof for unknown child block "
                    f"{proof.child_block_cid} (not in this request)"
                )
            event_buckets[idx].append(proof)
        for proof in bundle.storage_proofs:
            idx = child_to_idx.get(proof.child_block_cid)
            if idx is None:
                raise MergeConflictError(
                    f"storage proof for unknown child block "
                    f"{proof.child_block_cid} (not in this request)"
                )
            storage_buckets[idx].append(proof)
        for block in bundle.blocks:
            raw = block.cid.to_bytes()
            prior = by_cid.get(raw)
            if prior is None:
                by_cid[raw] = block
            elif prior.data != block.data:
                raise MergeConflictError(
                    f"witness block {block.cid} has conflicting bytes "
                    "across shards"
                )

    return UnifiedProofBundle(
        storage_proofs=[p for idx in indexes for p in storage_buckets[idx]],
        event_proofs=[p for idx in indexes for p in event_buckets[idx]],
        blocks=[by_cid[raw] for raw in sorted(by_cid)],
    )
