"""Shard workers: a full serve daemon per shard, in-process or spawned.

A shard is not a thinner thing than a daemon — it IS `ProofService` +
`ProofHTTPServer` (+ `DurableAdmission` when given a queue dir), so
everything the single-daemon stack guarantees (micro-batching, bounded
admission, crash-recovery via the durable queue, the tiered disk store)
survives sharding unchanged. The router treats a shard as an opaque HTTP
base URL; these classes only manage lifecycle.

Two flavors:

- `LocalShard` — in-process, for tests and the scatter-gather identity
  grid: same pair table object, ephemeral port, and a ``kill()`` that
  abandons in-flight work (`ProofHTTPServer.abort`) to simulate a shard
  crash without tearing down the test process.
- `SubprocessShard` (via `spawn_serve_shard`) — a real
  ``python -m ipc_proofs_tpu.cli serve`` child process, which is what the
  cluster CLI and the bench's linearity leg use: separate GILs, separate
  crash domains. The child writes its bound port to ``--port-file``
  (ephemeral ports can't be known up front) and each child gets its own
  ``--store-owner`` token so N children can share one ``--store-dir``.

Shards must agree on the pair table (the router speaks pair indexes).
`fixtures.build_range_world` is fully deterministic, so every child
spawned with the same ``--demo-world`` arguments rebuilds the identical
world and table — no table-shipping protocol needed for the hermetic
modes this repo serves.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request
from typing import Optional, Sequence

from ipc_proofs_tpu.serve.durable import DurableAdmission
from ipc_proofs_tpu.serve.httpd import ProofHTTPServer
from ipc_proofs_tpu.serve.service import ProofService, ServiceConfig
from ipc_proofs_tpu.utils.log import get_logger

__all__ = ["LocalShard", "RemoteShard", "SubprocessShard", "spawn_serve_shard"]

logger = get_logger(__name__)


class LocalShard:
    """One in-process shard daemon (service + HTTP front end).

    ``store_wrapper`` wraps the blockstore before the service sees it —
    the hook the fault-harness tests use to inject seeded RPC faults into
    exactly one shard of a scatter.
    """

    def __init__(
        self,
        name: str,
        store,
        pairs: Sequence,
        spec,
        config: Optional[ServiceConfig] = None,
        queue_dir: Optional[str] = None,
        metrics=None,
        trust_policy=None,
        event_filter=None,
        store_wrapper=None,
        subs=None,
        backfill_jobs_dir=None,
        backfill_window_size: int = 8,
    ):
        self.name = name
        self.pairs = list(pairs)
        if store_wrapper is not None:
            store = store_wrapper(store)
        self.service = ProofService(
            store=store,
            spec=spec,
            trust_policy=trust_policy,
            event_filter=event_filter,
            config=config,
            metrics=metrics,
        )
        self.durable = (
            DurableAdmission(
                self.service, queue_dir, pairs=self.pairs,
                metrics=self.service.metrics,
            )
            if queue_dir
            else None
        )
        self.subs = subs  # StandingQueries, when the shard serves streams
        self.backfill = None
        if backfill_jobs_dir:
            # mirrors the serve daemon: windows enter the generate
            # batcher's LOW lane, so backfill yields to interactive work
            from ipc_proofs_tpu.backfill import BackfillEngine

            service = self.service

            def _run_window(window, wpairs):
                return service.submit_range_window(wpairs).result()

            self.backfill = BackfillEngine(
                self.pairs,
                spec,
                _run_window,
                jobs_dir=backfill_jobs_dir,
                window_size=backfill_window_size,
                metrics=self.service.metrics,
                delivery=(subs.log if subs is not None else None),
            )
        self.httpd = ProofHTTPServer(
            self.service, port=0, pairs=self.pairs, durable=self.durable,
            subs=subs, backfill=self.backfill,
        )

    def start(self) -> "LocalShard":
        self.httpd.start()
        return self

    @property
    def url(self) -> str:
        return self.httpd.address

    def stop(self, timeout: Optional[float] = 10.0) -> None:
        """Graceful: drain accepted work, then release everything."""
        self.httpd.shutdown(timeout=timeout)

    def kill(self) -> None:
        """Crash simulation: the port goes connection-refused with work
        possibly still in flight; the service is NOT drained."""
        self.httpd.abort()

    def __enter__(self) -> "LocalShard":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class RemoteShard:
    """Handle to a serve daemon SOMEONE ELSE runs (``--shard-url``).

    The multi-host member: the router did not spawn it and must not kill
    it, so lifecycle is reduced to health probing — ``stop()``/``kill()``
    only mark the handle dead locally (the same drain contract shape as
    the owned flavors, minus the process control). ``probe()`` is the
    liveness check the cluster CLI runs before admitting the member and
    the router's health loop repeats; a member that stops answering is
    failed over exactly like a dead subprocess (the router only ever sees
    the URL go connection-refused either way).
    """

    def __init__(self, url: str, name: Optional[str] = None, timeout_s: float = 5.0):
        self.url = url.rstrip("/")
        # default name = host:port — stable across router restarts, so
        # ring arcs (and seg-<owner> tokens keyed on the name) survive
        self.name = name or self.url.split("//", 1)[-1].replace("/", "_")
        self.timeout_s = timeout_s
        self._stopped = False

    def probe(self) -> "Optional[dict]":
        """One ``GET /healthz``: the parsed body (status 200 or 503 —
        draining still answers), or None when the host is unreachable."""
        try:
            with urllib.request.urlopen(
                self.url + "/healthz", timeout=self.timeout_s
            ) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as exc:
            try:
                return json.loads(exc.read().decode("utf-8"))
            except ValueError:
                return {"status": f"http {exc.code}"}
        except (urllib.error.URLError, ConnectionError, TimeoutError, OSError, ValueError):
            return None

    @property
    def alive(self) -> bool:
        return not self._stopped

    def stop(self, timeout: Optional[float] = None) -> None:
        """Drop the handle — never the remote daemon (its own operator
        drains it). Matches the owned shards' drain contract shape."""
        self._stopped = True

    def kill(self) -> None:
        self._stopped = True


class SubprocessShard:
    """Handle to one spawned ``serve`` child process."""

    def __init__(self, name: str, proc: subprocess.Popen, url: str):
        self.name = name
        self.proc = proc
        self.url = url

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def stop(self, timeout_s: float = 15.0) -> None:
        """Graceful: SIGTERM (the serve CLI drains on it), then wait."""
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5.0)

    def kill(self) -> None:
        """Crash simulation: SIGKILL, no drain, no journal flush."""
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=5.0)


def spawn_serve_shard(
    name: str,
    demo_world: int,
    event_sig: str,
    topic1: str,
    store_dir: Optional[str] = None,
    queue_dir: Optional[str] = None,
    extra_args: Sequence[str] = (),
    startup_timeout_s: float = 60.0,
) -> SubprocessShard:
    """Spawn one ``serve`` child on an ephemeral port and wait for it.

    The child rebuilds the deterministic ``--demo-world`` (identical pair
    table in every shard) and reports its bound port through a temp
    ``--port-file``. With ``store_dir`` set the child joins the shared
    disk tier under its own ``--store-owner`` token (= ``name``).
    """
    fd, port_file = tempfile.mkstemp(prefix=f"shard-{name}-", suffix=".port")
    os.close(fd)
    os.remove(port_file)  # the child's atomic write recreates it
    cmd = [
        sys.executable,
        "-m",
        "ipc_proofs_tpu.cli",
        "serve",
        "--port",
        "0",
        "--port-file",
        port_file,
        "--demo-world",
        str(demo_world),
        "--event-sig",
        event_sig,
        "--topic1",
        topic1,
    ]
    if store_dir:
        cmd += ["--store-dir", store_dir, "--store-owner", name]
    if queue_dir:
        cmd += ["--queue-dir", queue_dir]
    cmd += list(extra_args)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        env=env,
        start_new_session=True,  # a router SIGINT must not strafe the shards
    )
    deadline = time.monotonic() + startup_timeout_s
    port = None
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"shard {name!r} exited with {proc.returncode} before binding"
            )
        try:
            with open(port_file) as fh:
                text = fh.read().strip()
            if text:
                port = int(text)
                break
        except (OSError, ValueError):
            pass
        time.sleep(0.05)
    if port is None:
        proc.kill()
        raise RuntimeError(
            f"shard {name!r} did not report a port within {startup_timeout_s}s"
        )
    try:
        os.remove(port_file)
    except OSError:
        pass
    return SubprocessShard(name, proc, f"http://127.0.0.1:{port}")
