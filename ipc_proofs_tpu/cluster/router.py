"""Consistent-hash front-end router over N shard daemons.

The router is the cluster's single client-facing door. Placement is a
two-layer decision:

1. **Affinity** — the hash ring (`cluster/hashring.py`) maps a request's
   pair key onto a shard, so repeated traffic for one tipset pair lands
   where its witness blocks are already cached. Affinity is a CACHE hint.
2. **Stealing** — if the affine shard's in-flight depth exceeds the
   least-loaded shard's by ``steal_threshold``, the request is stolen by
   the least-loaded shard instead (``cluster.steals``). Any shard can
   serve any key, so stealing can never be wrong — it only trades cache
   warmth for queue latency.

Failover follows from the same property: a shard that stops answering is
marked dead, its ring arc redistributes to the survivors
(``cluster.shard_failovers``), and the in-flight request is re-dispatched
to the next shard **with the same idempotency key** it was first sent
with. Delivery is at-least-once; the durable queue's idempotency dedup
(PR 4) absorbs the retry, so a request that executed on a shard that died
mid-response is served from that shard's journal on recovery rather than
double-executed — and without durable queues the replay merely
regenerates a deterministic (identical) response.

Range requests scatter-gather: pairs partition by per-pair affinity
(steal-aware), each group dispatches concurrently as one
``/v1/generate_range`` sub-request carrying the router span's trace
carrier (one trace covers the fan-out), and the sub-bundles fold
incrementally through `cluster.gather.BundleFold` — one CID map, one
seal-time sort — into bytes identical to a single-daemon run. See README
"Cluster serving".

Standing queries shard differently: a subscription is STATE, not a
request, so it must live on exactly the shard that owns its filter's
ring arc (`subscription_ring_key` — all subscribers of one filter
colocate, preserving the generate-once amortization). Subscription
routes therefore use `_dispatch_affine`, which never steals. The router
mirrors ``sub_id → (ring_key, register body)`` so that when a shard
dies, `_mark_dead` re-registers the dead arc's subscriptions on their
new affine shards under the ORIGINAL subscription ids
(``cluster.subs_rearced``) — the registry's durable dedup absorbs
replays, and unacked deliveries re-push from the surviving shard's
journal on recovery.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import os
import threading
import time
import urllib.error
import urllib.request
import uuid
from concurrent.futures import ThreadPoolExecutor, as_completed
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple
from urllib.parse import parse_qs, urlencode, urlsplit

from ipc_proofs_tpu.cluster.gather import BundleFold, partition_indexes
from ipc_proofs_tpu.cluster.hashring import HashRing, pair_ring_key
from ipc_proofs_tpu.obs.fleet import (
    FleetFederation,
    TenantLedger,
    extract_tenant,
    graft_spans,
    merge_flight_snapshots,
    render_fleet_prometheus,
)
from ipc_proofs_tpu.obs.flight import get_flight_recorder
from ipc_proofs_tpu.obs.prom import CONTENT_TYPE as _PROM_CONTENT_TYPE
from ipc_proofs_tpu.obs.trace import (
    carrier_from_context,
    current_context,
    root_span,
    span,
    use_context,
)
from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
from ipc_proofs_tpu.serve.qos import TenantQoS, TenantThrottledError
from ipc_proofs_tpu.witness.errors import (
    StreamAbortError,
    WitnessEncodingError,
    WitnessIntegrityError,
)
from ipc_proofs_tpu.subs.registry import normalize_filter, subscription_ring_key
from ipc_proofs_tpu.witness.stream import (
    CHUNK_BLOCK,
    CHUNK_ERROR,
    CHUNK_TRAILER,
    CHUNKED_TERMINATOR,
    STREAM_CONTENT_TYPE,
    BundleStreamWriter,
    iter_stream_chunks,
    negotiate_stream,
    parse_block_chunk,
    send_buffers,
    stream_backfill_chunks,
)
from ipc_proofs_tpu.utils.deadline import (
    CancelScope,
    Deadline,
    DeadlineError,
    current_scope,
    remaining_budget_s,
    use_scope,
)
from ipc_proofs_tpu.utils.log import get_logger
from ipc_proofs_tpu.utils.threads import locked
from ipc_proofs_tpu.utils.metrics import Metrics
from ipc_proofs_tpu.utils.lockdep import named_lock

__all__ = [
    "ClusterRouter",
    "NoShardsError",
    "RouterHTTPServer",
    "ShardClient",
    "ShardUnavailable",
]

logger = get_logger(__name__)


class ShardUnavailable(RuntimeError):
    """Transport-level shard failure (refused, reset, timed out) — the
    signal that triggers failover. An HTTP error status is NOT this:
    a shard that answers 4xx/5xx is alive and its answer is authoritative."""


class NoShardsError(RuntimeError):
    """Every shard is dead (or was born dead) — nothing to route to."""


class ShardClient:
    """Minimal stdlib HTTP client for one shard base URL.

    Returns ``(status, json_obj)`` for whatever the shard answered;
    raises `ShardUnavailable` only for transport failures. No retries
    here — retry/failover policy belongs to the router, which must
    preserve idempotency keys across attempts.
    """

    def __init__(self, name: str, base_url: str, timeout_s: float = 120.0):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def post(self, path: str, body: dict) -> "tuple[int, dict]":
        data = json.dumps(body).encode("utf-8")
        req = urllib.request.Request(
            self.base_url + path,
            data=data,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        return self._roundtrip(req)

    def get(self, path: str) -> "tuple[int, dict]":
        req = urllib.request.Request(self.base_url + path, method="GET")
        return self._roundtrip(req)

    def post_stream(self, path: str, body: dict):
        """POST asking for the IPBS streamed form (``Accept``). Returns
        ``("stream", resp)`` with the LIVE response object when the shard
        streamed — the caller reads chunks incrementally and must close
        it — or ``("json", (status, obj))`` when the shard answered
        buffered JSON (error statuses, or doors that don't stream).
        Transport failure raises `ShardUnavailable`, exactly like `post`.
        """
        data = json.dumps(body).encode("utf-8")
        req = urllib.request.Request(
            self.base_url + path,
            data=data,
            headers={
                "Content-Type": "application/json",
                "Accept": STREAM_CONTENT_TYPE,
            },
            method="POST",
        )
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout_s)
        except urllib.error.HTTPError as exc:
            try:
                obj = json.loads(exc.read())
            except (ValueError, OSError):
                obj = {"error": f"shard returned {exc.code}"}
            return "json", (exc.code, obj)
        except (urllib.error.URLError, ConnectionError, TimeoutError, OSError) as exc:
            raise ShardUnavailable(f"shard {self.name}: {exc}") from exc
        ctype = resp.headers.get("Content-Type", "")
        if STREAM_CONTENT_TYPE not in ctype:
            try:
                with resp:
                    return "json", (resp.status, json.loads(resp.read()))
            except (
                ValueError,
                ConnectionError,
                TimeoutError,
                OSError,
                http.client.HTTPException,
            ) as exc:
                raise ShardUnavailable(f"shard {self.name}: {exc}") from exc
        return "stream", resp

    def _roundtrip(self, req) -> "tuple[int, dict]":
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            # an HTTP status IS an answer from a live shard — pass it up
            try:
                obj = json.loads(exc.read())
            except (ValueError, OSError):
                obj = {"error": f"shard returned {exc.code}"}
            return exc.code, obj
        except (urllib.error.URLError, ConnectionError, TimeoutError, OSError) as exc:
            raise ShardUnavailable(f"shard {self.name}: {exc}") from exc


class _ShardState:
    __slots__ = ("client", "alive", "inflight", "latency_ewma_s")

    def __init__(self, client: ShardClient):
        self.client = client
        self.alive = True
        self.inflight = 0
        # EWMA of observed dispatch latency (s). Starts at 0 so a shard
        # is judged purely on queue depth until it has been measured —
        # remote members earn their latency penalty from real traffic.
        self.latency_ewma_s = 0.0


class ClusterRouter:
    """Route requests across shard daemons; steal, fail over, gather.

    ``shards`` maps shard name → base URL (or pre-built `ShardClient`).
    ``pairs`` is the shared pair table every shard was built with — the
    router speaks pair indexes on the wire exactly like the single-daemon
    HTTP API, so a cluster of one is protocol-identical to plain serve.
    """

    def __init__(
        self,
        shards: "Dict[str, str] | Dict[str, ShardClient]",
        pairs: Sequence,
        steal_threshold: int = 4,
        steal_latency_unit_s: float = 0.25,
        deadline_floor_ms: float = 5.0,
        replication_factor: int = 1,
        cut_through: bool = True,
        vnodes: int = 64,
        metrics: Optional[Metrics] = None,
        request_timeout_s: float = 120.0,
        max_workers: int = 16,
        scrape_interval_s: float = 5.0,
        scrape_timeout_s: float = 2.0,
        slo=None,
        tenant_top_k: int = 8,
        tenant_rate: Optional[float] = None,
        tenant_burst: Optional[float] = None,
        spec=None,
        backfill_jobs_dir: Optional[str] = None,
        backfill_window_size: int = 8,
        backfill_window_parallelism: Optional[int] = None,
    ):
        if not shards:
            raise NoShardsError("a cluster needs at least one shard")
        self.pairs = list(pairs)
        self.steal_threshold = max(1, int(steal_threshold))
        # latency-penalty term for placement: a shard's observed dispatch
        # EWMA counts as `ewma / unit` phantom queue entries, so a slow
        # (remote, cross-host) shard loses steals it would win on queue
        # depth alone. The unit is "one queue slot's worth of latency".
        self.steal_latency_unit_s = max(1e-6, float(steal_latency_unit_s))
        # hop floor for deadline propagation: a forwarded request whose
        # remaining budget is at/below this is refused typed rather than
        # dispatched to a shard that can only fail it late
        self.deadline_floor_ms = max(0.0, float(deadline_floor_ms))
        # R-way replication of the segment tier (1 = off): every owner's
        # segment files are mirrored onto the next R-1 distinct ring
        # successors so a corrupt frame repairs peer-first and a dead
        # host's arcs survive elsewhere. Supervised by `replicate_now`.
        self.replication_factor = max(1, int(replication_factor))
        # streamed scatters relay shard B chunks as they arrive instead
        # of buffering each shard's JSON sub-response (`post_stream`)
        self.cut_through = bool(cut_through)
        self.metrics = metrics if metrics is not None else Metrics()
        self._lock = named_lock("ClusterRouter._lock")
        self._shards: "Dict[str, _ShardState]" = {}  # guarded-by: _lock
        self._ring = HashRing(vnodes=vnodes)  # guarded-by: _lock
        for name, target in shards.items():
            client = (
                target
                if isinstance(target, ShardClient)
                else ShardClient(name, target, timeout_s=request_timeout_s)
            )
            self._shards[name] = _ShardState(client)
            self._ring.add(name)
        self._keys = [pair_ring_key(p) for p in self.pairs]
        # sub_id → (ring_key, register body): the failover mirror that lets
        # _mark_dead re-home a dead shard's subscription arc.
        self._standing: "Dict[str, Tuple[str, dict]]" = {}  # guarded-by: _lock
        # last replication supervision pass (see replicate_now)
        self._replication_last: Optional[dict] = None  # guarded-by: _lock
        self._executor = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="cluster-scatter"
        )
        # Fleet observability plane: a short-timeout scraper federating
        # every shard's metrics/health into one router-side view, a
        # per-tenant accounting ledger, and an optional SLO watchdog
        # (owned by the caller; the router only surfaces its status).
        self.federation = FleetFederation(
            self._alive_shard_urls,
            metrics=self.metrics,
            interval_s=scrape_interval_s,
            timeout_s=scrape_timeout_s,
        )
        self.tenants = TenantLedger(metrics=self.metrics, top_k=tenant_top_k)
        # per-tenant QoS at the cluster door (--tenant-rate/--tenant-burst):
        # the ONE front door throttles, so shard-side buckets aren't also
        # needed — a router-admitted request must not 429 halfway through
        # its scatter
        self.qos = (
            TenantQoS(
                tenant_rate,
                burst=tenant_burst,
                metrics=self.metrics,
                ledger=self.tenants,
            )
            if tenant_rate
            else None
        )
        self.slo = slo
        # bulk backfill over the whole cluster: windows fan out to their
        # arc shards through the steal-aware dispatch below. The engine
        # is built lazily on first submit (it snapshots alive shards for
        # planning); `spec` binds journal manifests to the deployment's
        # filter when the caller has it (optional — one cluster serves
        # one spec, so an opaque manifest is still unambiguous)
        self._backfill_spec = spec
        self._backfill_jobs_dir = backfill_jobs_dir
        self._backfill_window_size = int(backfill_window_size)
        self._backfill_parallelism = backfill_window_parallelism
        self._backfill = None  # guarded-by: _lock
        self._gauge_alive_locked()

    # --- placement (all under _lock) --------------------------------------

    @locked
    def _gauge_alive_locked(self) -> None:
        self.metrics.set_gauge(
            "cluster.shards_alive",
            sum(1 for s in self._shards.values() if s.alive),
        )

    @locked
    def _affinity_locked(self, key: str) -> str:
        return self._ring.node_for(key)

    def _effective_load_locked(self, state: _ShardState) -> float:
        """Queue depth plus the latency penalty: the shard's dispatch
        EWMA expressed in queue-slot units (`steal_latency_unit_s`). A
        cross-host member with a slow link looks busier than its raw
        inflight count, so stealing doesn't flood the slowest shard."""
        return state.inflight + state.latency_ewma_s / self.steal_latency_unit_s

    @locked
    def _place_locked(self, key: str) -> str:
        """Affinity shard unless stealing wins (see module docstring)."""
        if not len(self._ring):
            raise NoShardsError("all shards are dead")
        affine = self._affinity_locked(key)
        least_state = min(
            (s for s in self._shards.values() if s.alive),
            key=lambda s: (self._effective_load_locked(s), s.client.name),
        )
        least = least_state.client.name
        if (
            least != affine
            and self._effective_load_locked(self._shards[affine])
            - self._effective_load_locked(least_state)
            >= self.steal_threshold
        ):
            self.metrics.count("cluster.steals")
            affine_state = self._shards[affine]
            if (
                affine_state.inflight - least_state.inflight
                < self.steal_threshold
            ):
                # raw queue depth alone would NOT have stolen — the
                # latency-EWMA penalty drove placement off the affine
                # shard. That's the slow-not-dead quarantine: a shard
                # answering slowly sheds traffic without being marked dead
                self.metrics.count("cluster.slow_quarantines")
            return least
        return affine

    def _acquire(self, key: str) -> "tuple[str, ShardClient]":
        with self._lock:
            name = self._place_locked(key)
            state = self._shards[name]
            state.inflight += 1
            self.metrics.set_gauge(f"cluster.inflight.{name}", state.inflight)
            return name, state.client

    def _release(self, name: str) -> None:
        with self._lock:
            state = self._shards.get(name)
            if state is not None and state.inflight > 0:
                state.inflight -= 1
                self.metrics.set_gauge(
                    f"cluster.inflight.{name}", state.inflight
                )

    def _note_latency(self, name: str, elapsed_s: float) -> None:
        """Fold one observed dispatch latency into the shard's EWMA
        (alpha 0.2 — a few requests to converge, one slow blip decays)."""
        with self._lock:
            state = self._shards.get(name)
            if state is not None:
                state.latency_ewma_s = (
                    0.8 * state.latency_ewma_s + 0.2 * elapsed_s
                )

    def _alive_shard_urls(self) -> "Dict[str, str]":
        """Scrape targets for the federation: live shards' base URLs."""
        with self._lock:
            return {
                name: state.client.base_url
                for name, state in self._shards.items()
                if state.alive
            }

    def _mark_dead(self, name: str) -> None:
        rearc: "List[Tuple[str, str, dict]]" = []
        with self._lock:
            state = self._shards.get(name)
            if state is None or not state.alive:
                return  # concurrent requests race to report one death once
            # Collect the dying shard's subscription arc BEFORE the ring
            # mutates — node_for() must still see the old topology to know
            # which subscriptions lived there.
            for sid, (key, body) in self._standing.items():
                if len(self._ring) and self._ring.node_for(key) == name:
                    rearc.append((sid, key, body))
            state.alive = False
            self._ring.remove(name)
            self._gauge_alive_locked()
        self.metrics.count("cluster.shard_errors")
        logger.warning(
            "cluster: shard %s unreachable — ring arc redistributed", name
        )
        self._rearc_subscriptions(name, rearc)
        if self.replication_factor > 1:
            # a death drops some arcs below R — re-replicate onto the
            # survivors in the background (tests call replicate_now()
            # synchronously instead; the pass is idempotent)
            try:
                self._executor.submit(self._replicate_after_death)
            except RuntimeError:
                pass  # executor already shut down (router closing)

    def _rearc_subscriptions(
        self, dead: str, rearc: "List[Tuple[str, str, dict]]"
    ) -> None:
        """Re-register a dead shard's subscriptions on their new affine
        shards under the ORIGINAL sub ids — the registries' durable dedup
        absorbs replays, so this is safe to repeat."""
        for sid, key, body in rearc:
            try:
                status, _obj = self._dispatch_affine(
                    key, "/v1/subscribe", dict(body)
                )
            except NoShardsError:
                logger.warning(
                    "cluster: no shard left to re-home subscriptions from %s",
                    dead,
                )
                return
            if status == 200:
                self.metrics.count("cluster.subs_rearced")
            else:  # fail-soft: a live shard rejected the replay — log & go on
                logger.warning(
                    "cluster: re-registering %s after %s died failed: %s",
                    sid,
                    dead,
                    status,
                )

    def revive(self, name: str) -> None:
        """Re-admit a recovered shard (ops action / test hook): its ring
        arc comes back and traffic re-affinitizes on the next request."""
        with self._lock:
            state = self._shards.get(name)
            if state is None or state.alive:
                return
            state.alive = True
            self._ring.add(name)
            self._gauge_alive_locked()

    def alive_shards(self) -> "List[str]":
        with self._lock:
            return sorted(n for n, s in self._shards.items() if s.alive)

    # --- replicated segment tier (storex.replica) ---------------------------

    @locked
    def _replication_plan_locked(self) -> "Dict[str, List[str]]":
        """Owner token → replica shard names. A LIVE owner's segments
        mirror onto the next R-1 distinct ring successors; a DEAD
        owner's token still walks the (survivor) ring but needs R full
        copies — its own copy died with it. Deterministic in membership,
        so every supervision pass converges to the same placement."""
        plan: "Dict[str, List[str]]" = {}
        want = self.replication_factor
        if not len(self._ring):
            return plan
        for owner, state in self._shards.items():
            nodes = [
                n
                for n in self._ring.nodes_for(owner, want + 1)
                if n != owner
            ]
            plan[owner] = nodes[: want - 1] if state.alive else nodes[:want]
        return plan

    def _replicate_after_death(self) -> None:
        try:
            self.replicate_now()
        except Exception:  # fail-soft: a failed supervision pass must not poison the failover path; the next periodic pass retries and under_replicated_arcs stays raised
            logger.exception("cluster: replication pass after death failed")

    def replicate_now(self) -> dict:
        """One replication supervision pass (idempotent, safe to repeat):

        1. compute the owner → replicas plan from the ring;
        2. install every live shard's read-repair peer set
           (``POST /v1/replica_peers`` — all OTHER live shards: segments
           are content-addressed, so over-asking is merely wasted probes);
        3. tell each replica shard to pull its assigned owners' segment
           files (``POST /v1/replicate``).

        Runs at boot, after any `_mark_dead`, and on demand. Gauges:
        ``cluster.under_replicated_arcs`` (owners whose plan didn't fully
        sync this pass) and ``cluster.replication_lag_segments`` (segment
        pulls still pending under the per-pass byte budget)."""
        summary: dict = {
            "factor": self.replication_factor,
            "plan": {},
            "shards": {},
            "errors": [],
        }
        if self.replication_factor <= 1:
            with self._lock:
                self._replication_last = summary
            return summary
        self.metrics.count("cluster.replications_triggered")
        with self._lock:
            plan = self._replication_plan_locked()
            live = {
                n: s.client for n, s in self._shards.items() if s.alive
            }
        summary["plan"] = {o: list(r) for o, r in plan.items()}
        pull: "Dict[str, List[str]]" = {n: [] for n in live}
        for owner, replicas in plan.items():
            for name in replicas:
                if name in pull:
                    pull[name].append(owner)
        lag = 0
        failed_owners: "set[str]" = set()
        for name in sorted(live):
            client = live[name]
            peers = [
                {"name": n, "url": c.base_url}
                for n, c in sorted(live.items())
                if n != name
            ]
            owners = sorted(pull[name])
            try:
                status, _obj = client.post(
                    "/v1/replica_peers", {"peers": peers}
                )
                if status != 200:
                    # shard without a disk tier: can't hold replicas
                    if owners:
                        failed_owners.update(owners)
                    continue
                if not owners:
                    continue
                status, obj = client.post(
                    "/v1/replicate", {"sources": peers, "owners": owners}
                )
            except ShardUnavailable:
                self._mark_dead(name)
                failed_owners.update(owners)
                summary["errors"].append(f"{name}: unreachable")
                continue
            if status != 200 or not isinstance(obj, dict):
                failed_owners.update(owners)
                summary["errors"].append(f"{name}: http {status}")
                continue
            if obj.get("errors"):
                failed_owners.update(owners)
                summary["errors"].extend(
                    f"{name}: {e}" for e in obj["errors"]
                )
            lag += int(obj.get("pending") or 0)
            summary["shards"][name] = {
                k: obj.get(k) for k in ("pulled", "bytes", "blocks", "pending")
            }
        summary["under_replicated"] = sorted(failed_owners)
        summary["lag_segments"] = lag
        self.metrics.set_gauge(
            "cluster.under_replicated_arcs", len(failed_owners)
        )
        self.metrics.set_gauge("cluster.replication_lag_segments", lag)
        with self._lock:
            self._replication_last = summary
        return summary

    # --- dispatch with failover -------------------------------------------

    def _stamp_deadline(self, body: dict, path: str) -> None:
        """Re-emit the ambient deadline budget on a forwarded body.

        The ambient `Deadline` is absolute-monotonic, so reading it here
        yields the budget ALREADY decremented by router time (parse,
        placement, earlier failover attempts). A budget at/below the
        router floor refuses the hop typed (``deadline.rejects.router``)
        instead of dispatching work a shard can only fail late."""
        rem_s = remaining_budget_s()
        if rem_s is None:
            return
        rem_ms = rem_s * 1000.0
        if rem_ms <= self.deadline_floor_ms:
            self.metrics.count("serve.deadline_rejects")
            self.metrics.count("deadline.rejects.router")
            raise DeadlineError(
                f"remaining budget {rem_ms:.0f}ms at/below router floor "
                f"({self.deadline_floor_ms:.0f}ms) forwarding {path}",
                stage="router.dispatch",
            )
        body["deadline_ms"] = rem_ms

    def _dispatch(self, key: str, path: str, body: dict) -> "tuple[int, dict]":
        """Send one request, failing over (same idempotency key) until a
        live shard answers or none remain. At-least-once by construction:
        a shard that died after executing leaves a journaled result the
        retry's dedup key recovers instead of re-executing."""
        body = dict(body)
        body.setdefault("idempotency_key", uuid.uuid4().hex)
        carrier = carrier_from_context()
        if carrier is not None:
            body["trace"] = carrier
        attempted: "set[str]" = set()
        while True:
            # re-read the budget each attempt: failover retries burn it
            self._stamp_deadline(body, path)
            name, client = self._acquire(key)
            if name in attempted:
                # the ring only has shards we already failed against —
                # give up rather than hot-loop on a flapping shard
                self._release(name)
                raise NoShardsError(
                    f"no shard answered {path} (tried {sorted(attempted)})"
                )
            attempted.add(name)
            self.metrics.count("cluster.sub_requests")
            try:
                t0 = time.monotonic()
                with span(
                    "cluster.dispatch", {"shard": name, "path": path}
                ):
                    status, obj = client.post(path, body)
                self._note_latency(name, time.monotonic() - t0)
                if isinstance(obj, dict):
                    self._graft_shard_spans(name, obj)
                return status, obj
            except ShardUnavailable:
                self._mark_dead(name)
                # every re-dispatch after a death is a failover — including
                # the first attempt finding a corpse
                self.metrics.count("cluster.shard_failovers")
            finally:
                self._release(name)

    def _graft_shard_spans(self, shard: str, obj: dict) -> None:
        """Stitch a shard's shipped span subtree into this process's trace.

        Shards attach a bounded ``spans`` field to sampled responses (see
        ``httpd._attach_spans``); the router grafts those spans under its
        own dispatch spans so one scatter-gather renders as ONE tree. The
        field is stripped either way — clients never see the plumbing.
        In-process shards (tests' LocalShard) share our span store, so a
        matching ``spans_pid`` means the subtree is already recorded.
        """
        shipped = obj.pop("spans", None)
        shipped_pid = obj.pop("spans_pid", None)
        if not shipped or shipped_pid == os.getpid():
            return
        graft_spans(shipped, shard, metrics=self.metrics)

    def _dispatch_affine(
        self, key: str, path: str, body: Optional[dict] = None
    ) -> "tuple[int, dict]":
        """Affinity-PINNED dispatch for subscription state. Unlike
        `_dispatch` this never steals: the registry shard owning ``key``'s
        arc is the only one holding that filter's subscriptions, so the
        request must land there. Failover recomputes the arc owner after
        `_mark_dead` shrinks the ring (which also re-homes the dead arc's
        subscriptions — see `_rearc_subscriptions`)."""
        attempted: "set[str]" = set()
        while True:
            with self._lock:
                if not len(self._ring):
                    raise NoShardsError("all shards are dead")
                name = self._affinity_locked(key)
                client = self._shards[name].client
            if name in attempted:
                raise NoShardsError(
                    f"no shard answered {path} (tried {sorted(attempted)})"
                )
            attempted.add(name)
            self.metrics.count("cluster.sub_requests")
            try:
                if body is None:
                    return client.get(path)
                return client.post(path, dict(body))
            except ShardUnavailable:
                self._mark_dead(name)
                self.metrics.count("cluster.shard_failovers")

    # --- standing-query routes ---------------------------------------------

    def subscribe(self, body: dict) -> "tuple[int, dict]":
        """Route ``POST /v1/subscribe`` to the filter arc's owning shard
        and mirror the registration for failover re-homing."""
        self.metrics.count("cluster.subscribe_requests")
        try:
            filt = normalize_filter((body or {}).get("filter"))
        except ValueError as exc:
            return 400, {"error": str(exc)}
        key = subscription_ring_key(filt)
        send = dict(body)
        send["filter"] = filt
        status, obj = self._dispatch_affine(key, "/v1/subscribe", send)
        if status == 200 and isinstance(obj, dict) and obj.get("sub_id"):
            mirrored = dict(send)
            mirrored["sub_id"] = obj["sub_id"]
            with self._lock:
                self._standing[obj["sub_id"]] = (key, mirrored)
        return status, obj

    def unsubscribe(self, body: dict) -> "tuple[int, dict]":
        """Route ``POST /v1/unsubscribe`` via the mirror when the sub is
        known; broadcast to every live shard otherwise (a router restart
        loses the in-memory mirror, not the shards' durable registries)."""
        sub_id = str((body or {}).get("sub_id") or "")
        if not sub_id:
            return 400, {"error": "body.sub_id is required"}
        with self._lock:
            entry = self._standing.pop(sub_id, None)
        if entry is not None:
            return self._dispatch_affine(
                entry[0], "/v1/unsubscribe", {"sub_id": sub_id}
            )
        removed = False
        for name in self.alive_shards():
            with self._lock:
                state = self._shards.get(name)
                if state is None or not state.alive:
                    continue
                client = state.client
            try:
                status, obj = client.post(
                    "/v1/unsubscribe", {"sub_id": sub_id}
                )
            except ShardUnavailable:
                self._mark_dead(name)
                continue
            if status == 200 and isinstance(obj, dict) and obj.get("removed"):
                removed = True
        return 200, {"removed": removed}

    def subscriptions(self) -> "tuple[int, dict]":
        """Aggregate ``GET /v1/subscriptions`` across live shards."""
        subs: "List[dict]" = []
        per_shard: "Dict[str, int]" = {}
        for name in self.alive_shards():
            with self._lock:
                state = self._shards.get(name)
                if state is None or not state.alive:
                    continue
                client = state.client
            try:
                status, obj = client.get("/v1/subscriptions")
            except ShardUnavailable:
                self._mark_dead(name)
                continue
            if status != 200 or not isinstance(obj, dict):
                continue
            got = obj.get("subscriptions") or []
            per_shard[name] = len(got)
            subs.extend(got)
        subs.sort(key=lambda s: s.get("sub_id", ""))
        return 200, {
            "count": len(subs),
            "subscriptions": subs,
            "shards": per_shard,
        }

    def deliveries(
        self, sub_id: str, cursor: int = 0, wait_s: float = 0.0
    ) -> "tuple[int, dict]":
        """Proxy the long-poll fallback to the sub's owning shard. Falls
        back to probing every live shard when the mirror doesn't know the
        sub (router restarted; the shards' registries are the truth)."""
        qs = f"/v1/deliveries?sub={sub_id}&cursor={int(cursor)}"
        if wait_s:
            qs += f"&wait_s={float(wait_s)}"
        with self._lock:
            entry = self._standing.get(sub_id)
        if entry is not None:
            return self._dispatch_affine(entry[0], qs)
        for name in self.alive_shards():
            with self._lock:
                state = self._shards.get(name)
                if state is None or not state.alive:
                    continue
                client = state.client
            try:
                status, obj = client.get(qs)
            except ShardUnavailable:
                self._mark_dead(name)
                continue
            if status == 200:
                return status, obj
        return 404, {"error": f"no such subscription: {sub_id}"}

    # --- public request API ------------------------------------------------

    def generate(
        self,
        pair_index: int,
        timeout_s: Optional[float] = None,
        idempotency_key: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> "tuple[int, dict]":
        """Route one single-pair generate to its affine shard."""
        if not (
            isinstance(pair_index, int)
            and not isinstance(pair_index, bool)
            and 0 <= pair_index < len(self.pairs)
        ):
            return 400, {
                "error": f"pair_index must be an int in [0, {len(self.pairs)})"
            }
        self.metrics.count("cluster.requests")
        body: dict = {"pair_index": pair_index}
        if timeout_s is not None:
            body["timeout_s"] = timeout_s
        if idempotency_key is not None:
            body["idempotency_key"] = idempotency_key
        if tenant is not None:
            body["tenant"] = tenant
        with root_span("cluster.generate", {"pair_index": pair_index}):
            return self._dispatch(self._keys[pair_index], "/v1/generate", body)

    def verify(self, body: dict) -> "tuple[int, dict]":
        """Route one verify. Verification has no data affinity (the bundle
        travels with the request), so the key is the bundle digest — it
        spreads uniformly and repeats of the same bundle reuse a shard's
        verify-side caches."""
        self.metrics.count("cluster.requests")
        bundle_obj = body.get("bundle", body)
        key = hashlib.sha256(
            json.dumps(bundle_obj, sort_keys=True).encode()
        ).hexdigest()
        with root_span("cluster.verify"):
            return self._dispatch(key, "/v1/verify", dict(body))

    def generate_range(
        self,
        pair_indexes: Sequence[int],
        chunk_size: Optional[int] = None,
        timeout_s: Optional[float] = None,
        aggregate: bool = False,
        tenant: Optional[str] = None,
        writer_factory=None,
    ) -> "Optional[tuple[int, dict]]":
        """Scatter a multi-pair range across shards, gather one canonical
        bundle (byte-identical to a single-daemon run over the same list).

        Sub-bundles fold into a `cluster.gather.BundleFold` AS EACH SHARD
        ANSWERS — one CID map, one sort at seal (``witness.merge_sorts``)
        — instead of buffering every response and re-sorting per arrival.
        With ``aggregate=True`` the index list may repeat (K co-tipset
        claims); the scatter covers the distinct pairs once and the
        response carries the witness-plane ``claims`` span table.

        With ``writer_factory`` (the streamed door) the fold never
        buffers a sealed response: the factory is called once, after
        validation and partition — the HTTP handler commits its 200 +
        chunked headers there and hands back a `BundleStreamWriter` —
        then every shard sub-bundle's blocks go out as ``B`` chunks the
        moment that shard answers; the trailer carries the merged proof
        sections and the sealed digest. Returns None once streaming has
        begun (errors after that point travel as in-band ``E`` chunks);
        pre-stream failures still return ``(status, obj)``.
        """
        n = len(self.pairs)
        idxs = list(pair_indexes)
        if not idxs or not all(
            isinstance(i, int) and not isinstance(i, bool) and 0 <= i < n
            for i in idxs
        ):
            return 400, {
                "error": f"pair_indexes must be non-empty ints in [0, {n})"
            }
        claim_idxs = idxs
        if aggregate:
            idxs = list(dict.fromkeys(idxs))
        self.metrics.count("cluster.requests")
        self.metrics.count("cluster.scatter_requests")
        with root_span(
            "cluster.generate_range", {"n_pairs": len(idxs)}
        ) as sp:
            with self._lock:
                if not len(self._ring):
                    raise NoShardsError("all shards are dead")
                assign = {
                    idx: self._affinity_locked(self._keys[idx]) for idx in idxs
                }
            groups = partition_indexes(idxs, assign)
            sp.set_attr("n_groups", len(groups))
            ctx = current_context()  # scatter threads parent under this span
            scope = current_scope()  # deadline/cancel hops with the scatter
            if writer_factory is not None and self.cut_through:
                # cut-through relay: shard B chunks forward the moment
                # they arrive — the router never holds a shard's whole
                # JSON sub-response in memory
                return self._scatter_cut_through(
                    groups,
                    idxs,
                    claim_idxs,
                    aggregate,
                    chunk_size,
                    timeout_s,
                    tenant,
                    writer_factory,
                    sp,
                    ctx,
                )

            def one(group: "List[int]") -> "tuple[int, dict]":
                body: dict = {"pair_indexes": group}
                if chunk_size is not None:
                    body["chunk_size"] = chunk_size
                if timeout_s is not None:
                    body["timeout_s"] = timeout_s
                if tenant is not None:
                    body["tenant"] = tenant
                # group affinity = first member's key: the whole group was
                # binned by that shard's arc, and failover re-keys anyway
                with use_context(ctx), use_scope(scope):
                    return self._dispatch(
                        self._keys[group[0]], "/v1/generate_range", body
                    )

            futures = {
                self._executor.submit(one, group): name
                for name, group in groups.items()
            }
            writer = None
            if writer_factory is not None:
                # commit the streamed response now: validation and
                # placement are done, so everything past this point is
                # in-band (a shard failure becomes an E chunk)
                writer = writer_factory()
                writer.begin(
                    {
                        "witness_encoding": "identity",
                        "n_pairs": len(idxs),
                        "n_groups": len(groups),
                        "trace_id": sp.trace_id,
                    }
                )
            fold = BundleFold(self.pairs, idxs, metrics=self.metrics)
            try:
                for fut in as_completed(futures):
                    name = futures[fut]
                    status, obj = fut.result()  # NoShardsError propagates
                    if status != 200:
                        # a shard's error verdict is the scatter's verdict
                        # — partial bundles are never silently merged
                        if writer is None:
                            return status, obj
                        writer.error(
                            str(obj.get("error", f"shard group {name} failed")),
                            str(obj.get("error_type", "shard_error")),
                        )
                        return None
                    payload = (
                        obj.get("result", obj) if obj.get("ok", True) else obj
                    )
                    if "bundle" not in payload:
                        if writer is None:
                            return 502, {
                                "error": f"shard group {name} returned no bundle",
                                "shard_response": obj,
                            }
                        writer.error(
                            f"shard group {name} returned no bundle",
                            "shard_error",
                        )
                        return None
                    sub = UnifiedProofBundle.from_json_obj(payload["bundle"])
                    fresh = fold.fold(sub)
                    if writer is not None:
                        # blocks leave NOW, in arrival order, and only on
                        # first sight — a block several shards shipped
                        # crosses the client wire once; the decoder
                        # restores canonical order (the merge law), so no
                        # sealed bundle is ever buffered
                        for b in fresh:
                            writer.block(b.cid.to_bytes(), b.data)
                        if len(fresh) != len(sub.blocks):
                            self.metrics.count(
                                "cluster.stream_blocks_deduped",
                                len(sub.blocks) - len(fresh),
                            )
            except Exception as exc:
                if writer is None:
                    raise
                writer.error(str(exc), "internal")
                return None
            merged = fold.seal()
            claims = None
            if aggregate:
                from ipc_proofs_tpu.witness import aggregate_range_bundle

                claims = aggregate_range_bundle(
                    merged,
                    self.pairs,
                    idxs,
                    claim_indexes=claim_idxs,
                    metrics=self.metrics,
                ).claims_json()
            if writer is not None:
                tail = {
                    "storage_proofs": [
                        p.to_json_obj() for p in merged.storage_proofs
                    ],
                    "event_proofs": [
                        p.to_json_obj() for p in merged.event_proofs
                    ],
                    "digest": merged.digest(),
                    "n_event_proofs": len(merged.event_proofs),
                }
                if claims is not None:
                    tail["claims"] = claims
                writer.end(tail)
                return None
            out = {
                "bundle": merged.to_json_obj(),
                "n_event_proofs": len(merged.event_proofs),
                "n_pairs": len(idxs),
                "n_groups": len(groups),
                "trace_id": sp.trace_id,
            }
            if claims is not None:
                out["claims"] = claims
            return 200, out

    # --- cut-through streamed scatter ---------------------------------------

    def _relay_stream(self, resp, fold, writer, relay_lock, aborted) -> None:
        """Relay ONE shard's IPBS stream chunk-by-chunk: each ``B`` chunk
        folds (first-sight dedup) and forwards under ``relay_lock`` the
        moment it arrives; the ``T`` chunk folds the shard's proof
        sections and ENDS the relay without waiting for stream EOF (so a
        connection death after the trailer can never re-fold proofs on a
        failover retry). Transport faults and truncation surface as
        `ShardUnavailable` (→ failover, same idempotency key — the fold's
        dedup absorbs re-sent blocks); an in-band ``E`` chunk is the
        authoritative answer of a LIVE shard and raises `StreamAbortError`
        (→ typed abort, never failover)."""
        try:
            for kind, payload in iter_stream_chunks(resp):
                if aborted.is_set():
                    return
                if kind == CHUNK_BLOCK:
                    cid_raw, data = parse_block_chunk(payload)
                    with relay_lock:
                        if aborted.is_set():
                            return
                        if fold.fold_block(cid_raw, data):
                            writer.block(bytes(cid_raw), data)
                        else:
                            self.metrics.count("cluster.stream_blocks_deduped")
                elif kind == CHUNK_TRAILER:
                    tail = json.loads(payload)
                    sub = UnifiedProofBundle.from_json_obj(
                        {
                            "storage_proofs": tail.get("storage_proofs") or [],
                            "event_proofs": tail.get("event_proofs") or [],
                            "blocks": [],
                        }
                    )
                    with relay_lock:
                        if not aborted.is_set():
                            fold.fold(sub)
                    return
                elif kind == CHUNK_ERROR:
                    try:
                        err = json.loads(payload)
                    except ValueError:
                        err = {}
                    raise StreamAbortError(
                        str(err.get("error", "shard aborted its stream")),
                        str(err.get("error_type", "internal")),
                    )
            raise ShardUnavailable("shard stream ended without a trailer")
        except (ConnectionError, TimeoutError, OSError) as exc:
            raise ShardUnavailable(f"shard stream failed mid-relay: {exc}") from exc
        except http.client.HTTPException as exc:
            # chunked-transfer truncation (IncompleteRead): the shard died
            # with chunks in flight
            raise ShardUnavailable(f"shard stream failed mid-relay: {exc}") from exc
        except WitnessIntegrityError as exc:
            raise ShardUnavailable(f"shard stream truncated: {exc}") from exc

    def _scatter_cut_through(
        self,
        groups: "Dict[str, List[int]]",
        idxs: "List[int]",
        claim_idxs: "List[int]",
        aggregate: bool,
        chunk_size: Optional[int],
        timeout_s: Optional[float],
        tenant: Optional[str],
        writer_factory,
        sp,
        ctx,
    ) -> None:
        """The streamed scatter, cut-through flavor: sub-requests ask the
        shards for THEIR streamed form (`ShardClient.post_stream`) and
        relay blocks as they arrive instead of buffering per-shard JSON
        sub-responses. Peak router memory per scatter drops from
        O(largest sub-response) to O(one chunk) per shard; byte identity
        is unchanged because the same `BundleFold` merge law runs, one
        block at a time. Always returns None — the writer is committed
        before any sub-request, so failures travel as in-band E chunks."""
        writer = writer_factory()
        writer.begin(
            {
                "witness_encoding": "identity",
                "n_pairs": len(idxs),
                "n_groups": len(groups),
                "trace_id": sp.trace_id,
            }
        )
        fold = BundleFold(self.pairs, idxs, metrics=self.metrics)
        # serializes fold mutation + writer chunk emission across the
        # scatter's relay threads (the writer's socket is one wire)
        relay_lock = named_lock("ClusterRouter._relay_lock")
        aborted = threading.Event()
        scope = current_scope()  # deadline/cancel hops with the relay threads

        def one_stream(group: "List[int]") -> "tuple[int, Optional[dict]]":
            body: dict = {"pair_indexes": group}
            if chunk_size is not None:
                body["chunk_size"] = chunk_size
            if timeout_s is not None:
                body["timeout_s"] = timeout_s
            if tenant is not None:
                body["tenant"] = tenant
            # failover retries reuse this key (at-least-once + dedup)
            body["idempotency_key"] = uuid.uuid4().hex
            key = self._keys[group[0]]
            attempted: "set[str]" = set()
            with use_context(ctx), use_scope(scope):
                carrier = carrier_from_context()
                if carrier is not None:
                    body["trace"] = carrier
                while True:
                    # re-read the budget each attempt: failovers burn it
                    self._stamp_deadline(body, "/v1/generate_range")
                    name, client = self._acquire(key)
                    if name in attempted:
                        self._release(name)
                        raise NoShardsError(
                            "no shard answered /v1/generate_range "
                            f"(tried {sorted(attempted)})"
                        )
                    attempted.add(name)
                    self.metrics.count("cluster.sub_requests")
                    t0 = time.monotonic()
                    try:
                        with span(
                            "cluster.dispatch",
                            {"shard": name, "path": "/v1/generate_range"},
                        ):
                            kind, payload = client.post_stream(
                                "/v1/generate_range", dict(body)
                            )
                            if kind == "json":
                                # buffered fallback: a shard that didn't
                                # stream still folds + forwards (its error
                                # verdict stays authoritative)
                                status, obj = payload
                                if isinstance(obj, dict):
                                    self._graft_shard_spans(name, obj)
                                if status != 200:
                                    return status, obj
                                pl = (
                                    obj.get("result", obj)
                                    if obj.get("ok", True)
                                    else obj
                                )
                                if "bundle" not in pl:
                                    return 502, {
                                        "error": (
                                            f"shard group {name} "
                                            "returned no bundle"
                                        ),
                                        "shard_response": obj,
                                    }
                                sub = UnifiedProofBundle.from_json_obj(
                                    pl["bundle"]
                                )
                                with relay_lock:
                                    if not aborted.is_set():
                                        fresh = fold.fold(sub)
                                        for b in fresh:
                                            writer.block(
                                                b.cid.to_bytes(), b.data
                                            )
                                        if len(fresh) != len(sub.blocks):
                                            self.metrics.count(
                                                "cluster.stream_blocks_deduped",
                                                len(sub.blocks) - len(fresh),
                                            )
                            else:
                                resp = payload
                                try:
                                    self._relay_stream(
                                        resp, fold, writer,
                                        relay_lock, aborted,
                                    )
                                finally:
                                    try:
                                        resp.close()
                                    except OSError:
                                        pass
                                self.metrics.count("cluster.stream_cut_through")
                        self._note_latency(name, time.monotonic() - t0)
                        return 200, None
                    except ShardUnavailable:
                        self._mark_dead(name)
                        self.metrics.count("cluster.shard_failovers")
                    finally:
                        self._release(name)

        futures = {
            self._executor.submit(one_stream, group): name
            for name, group in groups.items()
        }
        failure = None
        # drain EVERY future before touching the writer from this thread:
        # lagging relays write chunks until they observe the abort flag,
        # and the terminator must be the last thing on the wire
        for fut in as_completed(futures):
            name = futures[fut]
            try:
                status, obj = fut.result()
            except Exception as exc:  # fail-soft: first failure becomes the typed in-band E chunk below; later ones lose the race but every relay still drains
                if failure is None:
                    failure = exc
                    aborted.set()
                continue
            if status != 200 and failure is None:
                failure = (status, obj, name)
                aborted.set()
        if failure is not None:
            with relay_lock:
                if isinstance(failure, StreamAbortError):
                    writer.error(str(failure), failure.remote_error_type)
                elif isinstance(failure, tuple):
                    _status, obj, name = failure
                    writer.error(
                        str(obj.get("error", f"shard group {name} failed")),
                        str(obj.get("error_type", "shard_error")),
                    )
                else:
                    writer.error(str(failure), "internal")
            return None
        merged = fold.seal()
        claims = None
        if aggregate:
            from ipc_proofs_tpu.witness import aggregate_range_bundle

            claims = aggregate_range_bundle(
                merged,
                self.pairs,
                idxs,
                claim_indexes=claim_idxs,
                metrics=self.metrics,
            ).claims_json()
        tail = {
            "storage_proofs": [p.to_json_obj() for p in merged.storage_proofs],
            "event_proofs": [p.to_json_obj() for p in merged.event_proofs],
            "digest": merged.digest(),
            "n_event_proofs": len(merged.event_proofs),
        }
        if claims is not None:
            tail["claims"] = claims
        writer.end(tail)
        return None

    # --- bulk backfill ------------------------------------------------------

    def _backfill_engine(self):
        """The router's `BackfillEngine`, built lazily on first use:
        windows are planned onto the arcs of the shards alive NOW and
        executed at shard-count parallelism through the same steal-aware,
        at-least-once dispatch every interactive request uses."""
        from ipc_proofs_tpu.backfill import BackfillEngine

        with self._lock:
            if self._backfill is None:
                nodes = [
                    name
                    for name, s in self._shards.items()
                    if s.alive
                ] or sorted(self._shards)
                self._backfill = BackfillEngine(
                    self.pairs,
                    self._backfill_spec,
                    self._run_backfill_window,
                    jobs_dir=self._backfill_jobs_dir,
                    window_size=self._backfill_window_size,
                    window_parallelism=(
                        self._backfill_parallelism or max(1, len(nodes))
                    ),
                    nodes=nodes,
                    metrics=self.metrics,
                )
            return self._backfill

    def _run_backfill_window(self, window, pairs):
        """Window runner: one `/v1/generate_range` sub-request to the
        window's arc shard (work stealing and failover come free from
        `_dispatch`; the stable idempotency key lets a durable shard
        dedup a failover replay)."""
        del pairs  # shards hold the pair table; the wire speaks indexes
        self.metrics.count("cluster.sub_requests")
        body = {
            "pair_indexes": list(range(window.lo, window.hi)),
            "idempotency_key": f"backfill-{window.lo}-{window.hi}",
        }
        status, obj = self._dispatch(
            self._keys[window.lo], "/v1/generate_range", body
        )
        if status != 200:
            raise ShardUnavailable(
                f"backfill window {window.index} failed with {status}: "
                f"{obj.get('error', obj)}"
            )
        payload = obj.get("result", obj) if obj.get("ok", True) else obj
        if "bundle" not in payload:
            raise ShardUnavailable(
                f"backfill window {window.index}: shard returned no bundle"
            )
        return UnifiedProofBundle.from_json_obj(payload["bundle"])

    def backfill_submit(self, body: dict) -> "tuple[int, dict]":
        """``POST /v1/backfill`` (router door): same contract as the
        single-daemon handler — rows ``[pair_start, pair_end)`` of the
        shared pair table, idempotent by journal manifest."""
        n = len(self.pairs)
        start, end = body.get("pair_start"), body.get("pair_end")

        def _row(v) -> bool:
            return isinstance(v, int) and not isinstance(v, bool)

        if not (_row(start) and _row(end) and 0 <= start < end <= n):
            return 400, {
                "error": "pair_start/pair_end must be ints with "
                f"0 <= start < end <= {n} (cluster pair table)"
            }
        wsize = body.get("window_size")
        if wsize is not None and (not _row(wsize) or wsize < 1):
            return 400, {"error": "window_size must be a positive int"}
        sub_id = body.get("sub_id")
        if sub_id is not None and not isinstance(sub_id, str):
            return 400, {"error": "sub_id must be a string"}
        try:
            job = self._backfill_engine().submit(
                start, end, window_size=wsize, sub_id=sub_id
            )
        except (ValueError, RuntimeError) as exc:
            return 400, {"error": str(exc)}
        return 200, job.status()

    def backfill_status(self, job_id: str) -> "tuple[int, dict]":
        with self._lock:
            engine = self._backfill
        job = engine.job(job_id) if engine is not None else None
        if job is None:
            return 404, {"error": f"no such backfill job: {job_id}"}
        return 200, job.status()

    def backfill_chunks(
        self, job_id: str, cursor: int, wait_s: float = 0.0
    ) -> "tuple[int, dict]":
        with self._lock:
            engine = self._backfill
        job = engine.job(job_id) if engine is not None else None
        if job is None:
            return 404, {"error": f"no such backfill job: {job_id}"}
        return 200, job.chunks_after(cursor, wait_s=wait_s)

    def backfill_jobs(self) -> "tuple[int, dict]":
        with self._lock:
            engine = self._backfill
        return 200, {"jobs": engine.jobs() if engine is not None else []}

    # --- cluster health / metrics -----------------------------------------

    def healthz(self) -> "tuple[int, dict]":
        """Aggregate shard health: ``ok`` iff every live shard says ok,
        ``degraded`` when any shard is dead or degraded but at least one
        serves, 503 ``unavailable`` when none do."""
        with self._lock:
            states = {n: s.alive for n, s in self._shards.items()}
            clients = {n: s.client for n, s in self._shards.items()}
        shard_health: "Dict[str, dict]" = {}
        n_ok = 0
        for name, alive in states.items():
            if not alive:
                shard_health[name] = {"status": "dead"}
                continue
            try:
                _status, obj = clients[name].get("/healthz")
            except ShardUnavailable:
                self._mark_dead(name)
                shard_health[name] = {"status": "dead"}
                continue
            shard_health[name] = obj
            if obj.get("status") == "ok":
                n_ok += 1
        serving = sum(
            1
            for h in shard_health.values()
            if h.get("status") not in ("dead", "draining")
        )
        if serving == 0:
            out: dict = {"status": "unavailable", "shards": shard_health}
            if self.slo is not None:
                out["slo"] = self.slo.status()
            return 503, out
        status = "ok" if n_ok == len(shard_health) else "degraded"
        out = {
            "status": status,
            "shards": shard_health,
            "shards_alive": serving,
        }
        # degraded serve mode is worth naming explicitly: these shards have
        # EVERY upstream breaker open and serve warm-tier traffic only
        lotus_down = sorted(
            name
            for name, h in shard_health.items()
            if h.get("mode") == "lotus_down"
        )
        if lotus_down:
            out["lotus_down"] = lotus_down
        if self.slo is not None:
            out["slo"] = self.slo.status()
        return 200, out

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    # --- fleet observability plane ----------------------------------------

    def fleet_prom(self) -> str:
        """One Prometheus exposition for the whole fleet: every shard's
        counters/gauges/histograms labelled ``shard="s<k>"``, the router's
        own labelled ``shard="router"``, plus ``shard="fleet"`` aggregates
        (counter sums, merged histograms). Dead shards simply drop out of
        the exposition — scraping keeps working while degraded."""
        latest = self.federation.latest(max_age_s=2.0 * self.federation.interval_s)
        shard_snaps = {
            name: entry.get("metrics")
            for name, entry in latest.get("shards", {}).items()
        }
        return render_fleet_prometheus(
            shard_snaps, router_snap=self.metrics.snapshot()
        )

    def cluster_status(self) -> "tuple[int, dict]":
        """The live cluster view: ring topology joined with each shard's
        scraped health/queue depths, follower finalization progress,
        delivery backlog, and store-tier bytes — one JSON document."""
        latest = self.federation.latest(max_age_s=2.0 * self.federation.interval_s)
        with self._lock:
            ring = {
                name: {
                    "alive": state.alive,
                    "inflight": state.inflight,
                    "url": state.client.base_url,
                }
                for name, state in self._shards.items()
            }
        shards: "Dict[str, dict]" = {}
        max_epoch: Optional[int] = None
        backlog = 0
        disk_bytes = 0
        registry_heads: "Dict[str, dict]" = {}
        registry_degraded = 0
        for name, entry in latest.get("shards", {}).items():
            health = entry.get("healthz") or {}
            snap = entry.get("metrics") or {}
            gauges = snap.get("gauges") or {}
            depths = {
                key[len("serve.queue_depth.") :]: val
                for key, val in gauges.items()
                if key.startswith("serve.queue_depth.")
            }
            epoch = health.get("last_finalized_epoch")
            pending = health.get("pending_deliveries")
            shard_disk = gauges.get("storex.disk_bytes")
            shards[name] = {
                "status": health.get("status")
                or ("unreachable" if entry.get("error") else "unknown"),
                # "lotus_down" when the shard serves degraded (all its
                # upstream breakers open, warm-tier-only); None otherwise
                "mode": health.get("mode"),
                "scrape_error": entry.get("error"),
                "queue_depth": depths,
                "pending_deliveries": pending,
                "last_finalized_epoch": epoch,
                "disk_bytes": shard_disk,
                "registry": health.get("registry"),
            }
            head = health.get("registry_head")
            if isinstance(head, dict):
                registry_heads[name] = head
                if health.get("registry") == "degraded":
                    registry_degraded += 1
            if isinstance(epoch, int):
                max_epoch = epoch if max_epoch is None else max(max_epoch, epoch)
            if isinstance(pending, (int, float)):
                backlog += int(pending)
            if isinstance(shard_disk, (int, float)):
                disk_bytes += int(shard_disk)
        counters = self.metrics.snapshot().get("counters", {})
        out: dict = {
            "captured_at": latest.get("captured_at"),
            "ring": ring,
            "shards": shards,
            "router": {
                "requests": counters.get("cluster.requests", 0),
                "steals": counters.get("cluster.steals", 0),
                "slow_quarantines": counters.get("cluster.slow_quarantines", 0),
                "deadline_rejects": counters.get("deadline.rejects.router", 0),
                "shard_failovers": counters.get("cluster.shard_failovers", 0),
                "scrape_errors": counters.get("fleet.scrape_errors", 0),
            },
            "last_finalized_epoch": max_epoch,
            "delivery_backlog": backlog,
            "store_disk_bytes": disk_bytes,
        }
        with self._lock:
            replication_last = self._replication_last
        out["replication"] = {
            "factor": self.replication_factor,
            "last_pass": replication_last,
        }
        if registry_heads:
            # per-shard provenance checkpoints: each shard's chain is
            # independent, so the fleet head is the set of (size, root)
            # checkpoints — an auditor pins each and asks any shard for
            # consistency proofs against its own pin
            out["registry"] = {
                "heads": registry_heads,
                "total_records": sum(
                    int(h.get("size") or 0) for h in registry_heads.values()
                ),
                "degraded_shards": registry_degraded,
            }
        if self.slo is not None:
            out["slo"] = self.slo.status()
        return 200, out

    def registry_query(self, sub_path: str, qs: dict) -> "tuple[int, dict]":
        """Fleet audit surface over the per-shard provenance chains.

        ``head`` with no ``?shard=`` aggregates every live shard's
        checkpoint (each chain is independent — the fleet head is the set
        of per-shard (size, root) pins). ``entry`` / ``proof`` /
        ``consistency`` (and ``head?shard=``) proxy to the named shard:
        proofs only verify against the chain that sealed the record.
        ``base`` (the fleet delta-base directory) needs no shard — the
        registry dir is shared, so any live shard answers for the fleet."""
        shard = (qs.get("shard") or [""])[0]
        with self._lock:
            clients = {
                name: st.client for name, st in self._shards.items() if st.alive
            }
        if sub_path == "head" and not shard:
            heads: dict = {}
            errors: dict = {}
            for name, client in sorted(clients.items()):
                try:
                    status, obj = client.get("/v1/registry/head")
                except ShardUnavailable as exc:
                    errors[name] = str(exc)
                    continue
                if status == 200:
                    heads[name] = obj
                else:
                    errors[name] = obj.get("error", f"status {status}")
            return 200, {
                "heads": heads,
                "errors": errors,
                "total_records": sum(
                    int(h.get("size") or 0) for h in heads.values()
                ),
                "degraded_shards": sum(
                    1 for h in heads.values() if h.get("degraded")
                ),
            }
        if sub_path not in ("head", "entry", "proof", "consistency", "base"):
            return 404, {"error": f"no such registry path: {sub_path}"}
        if not shard and sub_path == "base" and clients:
            shard = sorted(clients)[0]  # shared dir: any live shard answers
        if not shard:
            return 400, {"error": f"registry/{sub_path} requires ?shard=<name>"}
        client = clients.get(shard)
        if client is None:
            return 404, {"error": f"unknown or dead shard: {shard}"}
        pairs = [
            (k, v)
            for k, vals in qs.items()
            if k != "shard"
            for v in vals
        ]
        tail = ("?" + urlencode(pairs)) if pairs else ""
        try:
            return client.get(f"/v1/registry/{sub_path}{tail}")
        except ShardUnavailable as exc:
            return 503, {"error": f"shard {shard} unreachable: {exc}"}

    def flight(self) -> dict:
        """Aggregate the fleet's flight rings (shards' ``/debug/flight``
        plus the router's own) into one shard-labelled, newest-first
        snapshot. Unreachable shards land in ``failed`` — fail-soft."""
        shard_flights: "Dict[str, Optional[dict]]" = {}
        for name, url in sorted(self._alive_shard_urls().items()):
            probe = ShardClient(name, url, timeout_s=self.federation.timeout_s)
            try:
                status, obj = probe.get("/debug/flight")
                shard_flights[name] = obj if status == 200 else None
            except ShardUnavailable:
                shard_flights[name] = None
        return merge_flight_snapshots(
            shard_flights, local_snap=get_flight_recorder().snapshot()
        )

    def close(self) -> None:
        self.federation.stop()
        if self.slo is not None:
            self.slo.stop()
        with self._lock:
            backfill = self._backfill
        if backfill is not None:
            # first: running windows hold shard dispatches in flight and
            # must wind down while shard clients are still usable
            backfill.close()
        self._executor.shutdown(wait=True)


class _RouterHandler(BaseHTTPRequestHandler):
    router: ClusterRouter

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        pass

    def _send_json(self, status: int, obj: dict, headers=None):
        body = json.dumps(obj).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)
        # response bytes charge the tenant at send time, mirroring the
        # single-daemon door — tenant.bytes.* is what crossed the wire
        if getattr(self, "_account_response", False):
            self.router.tenants.account_bytes(self._tenant, len(body))

    # --- streamed responses (application/x-ipc-bundle-stream) -------------

    def _start_stream(self) -> None:
        self.send_response(200)
        self.send_header("Content-Type", STREAM_CONTENT_TYPE)
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Witness-Encoding", "identity")
        self.end_headers()
        self.wfile.flush()

    def _finish_stream(self, writer) -> None:
        try:
            self.connection.sendall(CHUNKED_TERMINATOR)
        except OSError:
            pass
        self.router.metrics.count("serve.stream.responses")
        if getattr(self, "_account_response", False):
            self.router.tenants.account_bytes(self._tenant, writer.bytes_sent)
        # one stream per connection: don't risk framing drift poisoning a
        # keep-alive successor request
        self.close_connection = True

    def _stream_generate_range(self, body: dict) -> None:
        """Streamed scatter-gather: the router commits its 200 the moment
        placement succeeds (the writer factory below), then re-emits each
        shard's blocks as that shard answers — no sealed bundle is ever
        buffered router-side. Pre-stream failures (validation, all shards
        dead) still map to plain JSON statuses."""
        made: dict = {}

        def factory():
            self._start_stream()
            made["w"] = BundleStreamWriter(
                self._send_buffers, metrics=self.router.metrics
            )
            return made["w"]

        try:
            out = self.router.generate_range(
                body.get("pair_indexes") or [],
                chunk_size=body.get("chunk_size"),
                timeout_s=body.get("timeout_s"),
                aggregate=body.get("aggregate", False) is True,
                tenant=body.get("tenant"),
                writer_factory=factory,
            )
        except NoShardsError as exc:
            if "w" not in made:
                self._send_json(503, {"error": str(exc)})
                return
            out = None
        if out is not None:
            status, obj = out
            self._send_json(status, obj)
            return
        self._finish_stream(made["w"])

    def _send_buffers(self, buffers) -> None:
        send_buffers(self.connection, buffers)

    def _send_text(self, status: int, text: str, content_type: str):
        body = text.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        parts = urlsplit(self.path)
        if parts.path == "/healthz":
            status, obj = self.router.healthz()
            self._send_json(status, obj)
        elif parts.path in ("/metrics", "/metrics.json"):
            self._send_json(200, self.router.metrics_snapshot())
        elif parts.path == "/metrics.prom":
            self._send_text(200, self.router.fleet_prom(), _PROM_CONTENT_TYPE)
        elif parts.path == "/v1/cluster/status":
            status, obj = self.router.cluster_status()
            self._send_json(status, obj)
        elif parts.path == "/debug/flight":
            self._send_json(200, self.router.flight())
        elif parts.path == "/v1/subscriptions":
            status, obj = self.router.subscriptions()
            self._send_json(status, obj)
        elif parts.path == "/v1/backfill":
            status, obj = self.router.backfill_jobs()
            self._send_json(status, obj)
        elif parts.path.startswith("/v1/backfill/"):
            rest = parts.path[len("/v1/backfill/") :]
            job_id, _, tail = rest.partition("/")
            if tail == "":
                status, obj = self.router.backfill_status(job_id)
            elif tail == "chunks":
                try:
                    qs = parse_qs(parts.query)
                    cursor = int((qs.get("cursor") or ["0"])[0])
                    wait_s = min(30.0, float((qs.get("wait_s") or ["0"])[0]))
                except ValueError as exc:
                    self._send_json(400, {"error": f"bad query: {exc}"})
                    return
                status, obj = self.router.backfill_chunks(
                    job_id, cursor=cursor, wait_s=wait_s
                )
                if status == 200 and negotiate_stream({}, headers=self.headers):
                    # multi-document IPBS stream; no segment tier at the
                    # router, so block payloads re-emit as copied bytes
                    self._start_stream()
                    writer = BundleStreamWriter(
                        self._send_buffers, metrics=self.router.metrics
                    )
                    try:
                        stream_backfill_chunks(writer, obj)
                    except Exception as exc:  # fail-soft: headers are already on the wire — the only sound exit is an in-band typed abort chunk, never a half-document
                        writer.error(str(exc), "internal")
                    self._finish_stream(writer)
                    return
            else:
                status, obj = 404, {"error": f"no such path: {self.path}"}
            self._send_json(status, obj)
        elif parts.path == "/v1/deliveries":
            try:
                qs = parse_qs(parts.query)
                sub_id = (qs.get("sub") or [""])[0]
                if not sub_id:
                    raise ValueError("query param 'sub' is required")
                cursor = int((qs.get("cursor") or ["0"])[0])
                wait_s = min(30.0, float((qs.get("wait_s") or ["0"])[0]))
            except ValueError as exc:
                self._send_json(400, {"error": f"bad query: {exc}"})
                return
            try:
                status, obj = self.router.deliveries(
                    sub_id, cursor=cursor, wait_s=wait_s
                )
            except NoShardsError as exc:
                status, obj = 503, {"error": str(exc)}
            self._send_json(status, obj)
        elif parts.path.startswith("/v1/registry/"):
            sub_path = parts.path[len("/v1/registry/") :]
            status, obj = self.router.registry_query(
                sub_path, parse_qs(parts.query)
            )
            self._send_json(status, obj)
        else:
            self._send_json(404, {"error": f"no such path: {self.path}"})

    def do_POST(self):
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length <= 0 or length > 64 * 1024 * 1024:
                raise ValueError("Content-Length required")
            body = json.loads(self.rfile.read(length))
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as exc:
            self._send_json(400, {"error": f"bad request body: {exc}"})
            return
        self._account_response = False
        self._scope = None  # CancelScope carrying this request's deadline
        if self.path in ("/v1/generate", "/v1/verify", "/v1/generate_range"):
            # Per-tenant accounting at the front door, and the (sanitized)
            # tenant rides the forwarded body so shards account it too.
            tenant = extract_tenant(body, self.headers)
            self._tenant = tenant
            self._account_response = True
            self.router.tenants.account(tenant, nbytes=length)
            if tenant is not None:
                body["tenant"] = tenant
            # QoS throttles at the cluster door, before any scatter work
            if self.router.qos is not None:
                try:
                    self.router.qos.admit(tenant)
                except TenantThrottledError as exc:
                    self._send_json(
                        429,
                        {
                            "error": str(exc),
                            "error_type": "tenant_throttled",
                            "retry_after_s": exc.retry_after_s,
                        },
                        headers={
                            "Retry-After": f"{max(1, round(exc.retry_after_s))}"
                        },
                    )
                    return
            # deadline propagation at the cluster door: same contract as
            # the single-daemon door (body deadline_ms wins over the
            # X-IPC-Deadline-Ms header; both mean budget REMAINING)
            raw = body.get("deadline_ms", None)
            if raw is None:
                raw = self.headers.get("X-IPC-Deadline-Ms")
            if raw is not None:
                try:
                    ms = float(raw)
                except (TypeError, ValueError):
                    self._send_json(
                        400,
                        {"error": "deadline_ms must be a number of milliseconds"},
                    )
                    return
                deadline = Deadline.from_ms(max(0.0, ms))
                if deadline.remaining_ms() <= self.router.deadline_floor_ms:
                    self.router.metrics.count("serve.deadline_rejects")
                    self.router.metrics.count("deadline.rejects.router")
                    self._send_json(
                        504,
                        {
                            "error": f"deadline budget {ms:.0f}ms at/below "
                            f"the router floor "
                            f"({self.router.deadline_floor_ms:.0f}ms)",
                            "error_type": "deadline",
                        },
                    )
                    return
                self._scope = CancelScope(deadline)
        if self.path == "/v1/generate_range":
            try:
                stream = negotiate_stream(body, headers=self.headers)
            except WitnessEncodingError as exc:
                self._send_json(
                    400,
                    {"error": str(exc), "error_type": "witness_encoding"},
                )
                return
            if stream:
                try:
                    with use_scope(self._scope):
                        self._stream_generate_range(body)
                except NoShardsError as exc:
                    self._send_json(503, {"error": str(exc)})
                except DeadlineError as exc:
                    self._send_json(
                        504, {"error": str(exc), "error_type": exc.error_type}
                    )
                return
        try:
            with use_scope(self._scope):
                if self.path == "/v1/generate":
                    status, obj = self.router.generate(
                        body.get("pair_index"),
                        timeout_s=body.get("timeout_s"),
                        idempotency_key=body.get("idempotency_key"),
                        tenant=body.get("tenant"),
                    )
                elif self.path == "/v1/verify":
                    status, obj = self.router.verify(body)
                elif self.path == "/v1/generate_range":
                    status, obj = self.router.generate_range(
                        body.get("pair_indexes") or [],
                        chunk_size=body.get("chunk_size"),
                        timeout_s=body.get("timeout_s"),
                        aggregate=body.get("aggregate", False) is True,
                        tenant=body.get("tenant"),
                    )
                elif self.path == "/v1/subscribe":
                    status, obj = self.router.subscribe(body)
                elif self.path == "/v1/unsubscribe":
                    status, obj = self.router.unsubscribe(body)
                elif self.path == "/v1/backfill":
                    status, obj = self.router.backfill_submit(body)
                else:
                    status, obj = 404, {"error": f"no such path: {self.path}"}
        except NoShardsError as exc:
            status, obj = 503, {"error": str(exc)}
        except DeadlineError as exc:
            # a budget that ran out mid-scatter: typed, never partial
            status, obj = 504, {"error": str(exc), "error_type": exc.error_type}
        self._send_json(status, obj)


class RouterHTTPServer:
    """The cluster's client-facing HTTP door (same wire protocol as the
    single-daemon `ProofHTTPServer`, so clients don't know it's a cluster)."""

    def __init__(
        self, router: ClusterRouter, host: str = "127.0.0.1", port: int = 0
    ):
        self.router = router
        handler = type("_BoundRouterHandler", (_RouterHandler,), {"router": router})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def serve_forever(self) -> None:
        self._httpd.serve_forever(poll_interval=0.1)

    def start(self) -> "RouterHTTPServer":
        # start()/shutdown() are owner-thread lifecycle calls with a
        # happens-before edge through Thread.start()/join(); no lock needed
        self._thread = threading.Thread(  # ipclint: disable=race-unannotated
            target=self.serve_forever, name="cluster-router-httpd", daemon=True
        )
        self._thread.start()
        # background scrape loop + SLO watchdog ride the server lifecycle;
        # router.close() (via shutdown) stops both
        self.router.federation.start()
        if self.router.slo is not None:
            self.router.slo.start()
        return self

    def shutdown(self, timeout: Optional[float] = None) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout)
        self.router.close()
