"""Sharded serve plane: consistent-hash router over N full serve daemons.

The cluster scales the serve plane horizontally without weakening any
single-daemon guarantee, because a shard IS a complete serve daemon
(micro-batching, bounded admission, durable-queue crash recovery, tiered
disk store) and the router adds only placement:

- `hashring.py` — deterministic sha256 consistent hashing of pair keys
  onto shard names (vnodes; affinity = cache hint, never correctness);
- `shard.py`    — shard lifecycle: in-process `LocalShard` for tests,
  `spawn_serve_shard` subprocess children for real parallelism;
- `router.py`   — `ClusterRouter`: steal-aware placement, at-least-once
  failover under stable idempotency keys, scatter-gather ranges, health
  aggregation; `RouterHTTPServer` speaks the single-daemon wire protocol;
- `gather.py`   — the byte-identity merge law: per-shard range bundles
  union back into exactly the single-daemon bundle bytes.

Shards can share one ``--store-dir`` disk tier (per-owner segment files,
flock-coordinated eviction — `storex/segments.py`) and elect one chain
follower (`storex.FollowLeaderLock`). See README "Cluster serving" and
the ``cluster`` CLI subcommand.
"""

from ipc_proofs_tpu.cluster.gather import (
    BundleFold,
    MergeConflictError,
    merge_range_bundles,
    partition_indexes,
)
from ipc_proofs_tpu.cluster.hashring import HashRing, pair_ring_key
from ipc_proofs_tpu.cluster.router import (
    ClusterRouter,
    NoShardsError,
    RouterHTTPServer,
    ShardClient,
    ShardUnavailable,
)
from ipc_proofs_tpu.cluster.shard import (
    LocalShard,
    RemoteShard,
    SubprocessShard,
    spawn_serve_shard,
)

__all__ = [
    "BundleFold",
    "ClusterRouter",
    "HashRing",
    "LocalShard",
    "MergeConflictError",
    "NoShardsError",
    "RemoteShard",
    "RouterHTTPServer",
    "ShardClient",
    "ShardUnavailable",
    "SubprocessShard",
    "merge_range_bundles",
    "pair_ring_key",
    "partition_indexes",
    "spawn_serve_shard",
]
