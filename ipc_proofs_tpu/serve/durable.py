"""Durable admission queue: serve requests journaled before ACK.

PR 1's daemon loses every queued request on restart; this wraps
`ProofService` with the jobs journal (`ipc_proofs_tpu.jobs.journal`) so
an accepted request survives process death:

- **admit record** appended (fsync'd) BEFORE the request executes — the
  client's ACK therefore implies durable intent;
- **done record** appended with the rendered result once the batcher
  answers — replay skips finished work and serves retried clients from
  the cache;
- on restart, admits without a matching done **re-execute** through the
  fresh service (``serve.requests_replayed`` counter; `/healthz` reports
  ``resumed_jobs`` / ``journal_bytes``).

Idempotency keys: a client that retries a timed-out request with the
same ``idempotency_key`` gets the cached result instead of a second
execution; concurrent duplicates coalesce onto one in-flight execution.
Keys are client-chosen; omitted keys get a server-generated UUID (no
dedup across retries — the key IS the dedup handle). The result cache is
byte-bounded (`_ResultCache`): hot payloads live in an LRU capped by
``results_max_bytes`` and cold ones are re-read from their own ``done``
frame in the journal, so dedup survives restart without unbounded RSS.

At-least-once semantics: a request that failed *admission* (queue full /
draining / deadline) keeps its admit record but writes no done record —
the next restart re-executes it. Semantic failures (bad request) write a
done-with-error record so a poison request can't crash-loop the replay.

Journal I/O is fail-soft end-to-end (`JournalWriter` degrades to
in-memory on ENOSPC/EROFS with ``jobs.journal_failures``): the service
keeps answering, it just stops being able to resume.

Streaming contract: the journal always stores the PLAIN canonical
result object — never a stream framing and never a compressed/delta
witness encoding. A streamed response (``"stream": true``) re-encodes
from the journaled plain bundle at send time (`_stream_durable` in the
HTTP layer), so an idempotent retry may freely switch between buffered
and streamed transports, or between witness encodings, and always
reassembles byte-identical canonical fields from the same done record.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Optional, Sequence

from ipc_proofs_tpu.jobs.journal import (
    JournalError,
    JournalWriter,
    encode_record,
    read_journal_entries,
    read_record_at,
)
from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
from ipc_proofs_tpu.utils.threads import locked
from ipc_proofs_tpu.serve.batcher import (
    QueueFullError,
    ServiceClosedError,
)
from ipc_proofs_tpu.utils.deadline import DeadlineError, current_scope
from ipc_proofs_tpu.utils.log import get_logger
from ipc_proofs_tpu.utils.lockdep import named_lock

__all__ = ["DurableAdmission", "QUEUE_JOURNAL_NAME"]

QUEUE_JOURNAL_NAME = "queue.bin"

logger = get_logger(__name__)

# admission-layer failures: the request never (finishably) executed, so
# its admit record stays pending and the next restart re-executes it.
# DeadlineError covers the batcher's DeadlineExceededError plus every
# propagated deadline/cancel hop (rpc retry, range chunk, pipeline
# stage) — a budget that ran out must NOT journal as a durable error, or
# an idempotent retry with fresh budget would be served the stale failure
_ADMISSION_ERRORS = (QueueFullError, ServiceClosedError, DeadlineError)


class _Inflight:
    __slots__ = ("event", "result", "error")

    def __init__(self):
        self.event = threading.Event()
        self.result: Optional[dict] = None
        self.error: Optional[BaseException] = None


class _ResultCache:
    """Completed-request results: bounded hot LRU over a journal spill.

    The ``done`` record every result already writes to ``queue.bin`` IS
    the disk copy — this cache never writes a second one. In memory it
    keeps only ``key → frame offset`` plus a byte-bounded hot LRU of
    payloads, so idempotency dedup survives restart while RSS stays
    bounded no matter how many requests the process has answered.

    A spilled hit re-reads its frame through `read_record_at`
    (CRC-verified); a corrupt or unreadable frame drops the entry
    fail-soft and the caller re-executes the request (at-least-once) —
    the cache never serves bytes the journal can't vouch for.
    """

    def __init__(self, path: str, max_bytes: int, metrics=None):
        self._path = path
        self._max_bytes = max(1, int(max_bytes))
        self._metrics = metrics
        self._lock = named_lock("_ResultCache._lock")
        # offset None = result was never durably framed (degraded journal):
        # once it ages out of the hot tier it is gone and re-executes
        self._offsets: "dict[str, Optional[int]]" = {}  # guarded-by: _lock
        # key → (payload, encoded size); coldest first
        self._hot: "OrderedDict[str, tuple]" = OrderedDict()  # guarded-by: _lock
        self._hot_bytes = 0  # guarded-by: _lock

    def seed(self, key: str, offset: int) -> None:
        """Index a replayed done record without loading its payload."""
        with self._lock:
            self._offsets[key] = offset

    def put(self, key: str, offset: "Optional[int]", payload: dict) -> None:
        with self._lock:
            self._offsets[key] = offset
            self._insert_hot_locked(key, payload)

    @locked
    def _insert_hot_locked(self, key: str, payload: dict) -> None:
        size = len(encode_record(payload))
        old = self._hot.pop(key, None)
        if old is not None:
            self._hot_bytes -= old[1]
        if size <= self._max_bytes:
            self._hot[key] = (payload, size)
            self._hot_bytes += size
        evicted = 0
        while self._hot_bytes > self._max_bytes and self._hot:
            _, (_, esize) = self._hot.popitem(last=False)
            self._hot_bytes -= esize
            evicted += 1
        metrics = self._metrics
        if metrics is not None:
            if evicted:
                metrics.count("serve.result_cache_evictions", evicted)
            metrics.set_gauge("serve.result_cache_bytes", self._hot_bytes)

    def get(self, key: str) -> Optional[dict]:
        with self._lock:
            entry = self._hot.get(key)
            if entry is not None:
                self._hot.move_to_end(key)
                return entry[0]
            if key not in self._offsets:
                return None
            offset = self._offsets[key]
        if offset is None:
            return None
        try:
            rec = read_record_at(self._path, offset)
        except (JournalError, OSError) as exc:
            logger.warning(
                "result cache: spilled frame for %s unreadable (%s) — "
                "dropping entry; the request will re-execute", key, exc,
            )
            self._drop(key, offset)
            return None
        if not isinstance(rec, dict) or rec.get("key") != key:
            logger.warning(
                "result cache: frame at %d does not belong to %s — "
                "dropping entry; the request will re-execute", offset, key,
            )
            self._drop(key, offset)
            return None
        payload = rec.get("payload")
        with self._lock:
            self._insert_hot_locked(key, payload)
        return payload

    def _drop(self, key: str, offset: "Optional[int]") -> None:
        with self._lock:
            if self._offsets.get(key) == offset:
                del self._offsets[key]

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._offsets

    def __len__(self) -> int:
        with self._lock:
            return len(self._offsets)

    def hot_bytes(self) -> int:
        with self._lock:
            return self._hot_bytes


class DurableAdmission:
    """Journal-backed idempotent request layer over one `ProofService`."""

    def __init__(
        self,
        service,
        queue_dir: str,
        pairs: Sequence = (),
        metrics=None,
        replay: bool = True,
        results_max_bytes: int = 64 * 1024 * 1024,
    ):
        self.service = service
        self.pairs = list(pairs)
        self.metrics = metrics if metrics is not None else service.metrics
        os.makedirs(queue_dir, exist_ok=True)
        self._path = os.path.join(queue_dir, QUEUE_JOURNAL_NAME)
        self._lock = named_lock("DurableAdmission._lock")
        # serializes journal appends AND makes (offset, append) atomic so a
        # done record's spill offset is exact even under concurrent submits
        self._jlock = named_lock("DurableAdmission._jlock")
        self._results = _ResultCache(
            self._path, results_max_bytes, metrics=self.metrics
        )
        self._inflight: "dict[str, _Inflight]" = {}  # guarded-by: _lock
        self.resumed_jobs = 0  # admitted-but-unfinished requests re-executed

        pending: "list[dict]" = []
        if os.path.exists(self._path):
            entries, good_offset, torn = read_journal_entries(self._path)
            if torn:
                logger.warning(
                    "serve queue journal %s has a torn tail — truncating to "
                    "%d bytes", self._path, good_offset,
                )
                with open(self._path, "r+b") as fh:
                    fh.truncate(good_offset)
                    fh.flush()
                    os.fsync(fh.fileno())
            admits: "dict[str, dict]" = {}
            order: "list[str]" = []
            for pos, (rec, offset, _end) in enumerate(entries):
                if not isinstance(rec, dict) or not isinstance(rec.get("key"), str):
                    raise JournalError(
                        f"malformed serve queue record {pos} in {self._path}"
                    )
                kind = rec.get("t")
                if kind == "admit":
                    if rec["key"] not in admits:
                        admits[rec["key"]] = rec
                        order.append(rec["key"])
                elif kind == "done":
                    # index only — the payload stays on disk until asked for
                    self._results.seed(rec["key"], offset)
                else:
                    raise JournalError(
                        f"unknown serve queue record type {kind!r} ({pos})"
                    )
            pending = [admits[k] for k in order if k not in self._results]
        self._writer = JournalWriter(self._path, metrics=self.metrics)
        if replay and pending:
            self._replay(pending)

    # --- restart replay ----------------------------------------------------

    def _replay(self, pending: "list[dict]") -> None:
        for rec in pending:
            self.resumed_jobs += 1
            self.metrics.count("serve.requests_replayed")
            key, kind, payload = rec["key"], rec["kind"], rec["payload"]
            try:
                result = self._execute(
                    kind, payload, timeout_s=None, tenant=rec.get("tenant")
                )
                done = {"ok": True, "result": result}
            except Exception as exc:  # fail-soft: replay must terminate — a poison request journals as an error result, not a restart crash-loop
                # any failure (even admission) finishes with an error here:
                # a poison request must not crash-loop every restart
                done = {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
            self._finish(key, done)

    # --- execution ---------------------------------------------------------

    def _execute(
        self,
        kind: str,
        payload: Any,
        timeout_s: "float | None",
        tenant: "str | None" = None,
    ) -> dict:
        # the HTTP layer installs the request's CancelScope as ambient
        # before calling submit(); forwarding it into the batcher keeps
        # cooperative cancellation working through the durable hop (replay
        # runs scope-less: current_scope() is None on the restart thread)
        scope = current_scope()
        if kind == "verify":
            bundle = UnifiedProofBundle.from_json_obj(payload)
            resp = self.service.verify(
                bundle, timeout_s=timeout_s, tenant=tenant, cancel_scope=scope
            )
            return {
                "storage_results": resp.storage_results,
                "event_results": resp.event_results,
                "all_valid": resp.all_valid(),
                "batch_size": resp.batch_size,
                "trace_id": resp.trace_id,
                "server_timing": dict(resp.server_timing),
            }
        if kind == "generate":
            if not isinstance(payload, int) or not (0 <= payload < len(self.pairs)):
                raise ValueError(
                    f"pair_index {payload!r} outside [0, {len(self.pairs)})"
                )
            resp = self.service.generate(
                self.pairs[payload],
                timeout_s=timeout_s,
                tenant=tenant,
                cancel_scope=scope,
            )
            return {
                "bundle": resp.bundle.to_json_obj(),
                "n_event_proofs": resp.n_event_proofs,
                "batch_size": resp.batch_size,
                "trace_id": resp.trace_id,
                "server_timing": dict(resp.server_timing),
            }
        if kind == "generate_range":
            if not isinstance(payload, dict):
                raise ValueError("generate_range payload must be an object")
            idxs = payload.get("pair_indexes")
            n = len(self.pairs)
            if (
                not isinstance(idxs, list)
                or not idxs
                or not all(
                    isinstance(i, int)
                    and not isinstance(i, bool)
                    and 0 <= i < n
                    for i in idxs
                )
            ):
                raise ValueError(
                    f"pair_indexes must be a non-empty list of ints in [0, {n})"
                )
            bundle = self.service.generate_range(
                [self.pairs[i] for i in idxs],
                chunk_size=payload.get("chunk_size"),
            )
            return {
                "bundle": bundle.to_json_obj(),
                "n_event_proofs": len(bundle.event_proofs),
                "n_pairs": len(idxs),
            }
        raise ValueError(f"unknown request kind {kind!r}")

    def _finish(self, key: str, done_payload: dict) -> None:
        with self._jlock:
            offset = self._writer.journal_bytes
            ok = self._writer.append(  # ipclint: disable=lock-held-blocking (durability: done-frames serialize under the journal lock)
                {"t": "done", "key": key, "payload": done_payload}
            )
        # a degraded (in-memory) append has no frame to point at — the hot
        # tier is then the only copy and the entry dies with eviction
        self._results.put(key, offset if ok else None, done_payload)
        with self._lock:
            flight = self._inflight.pop(key, None)
        if flight is not None:
            flight.result = done_payload
            flight.event.set()

    # --- public API --------------------------------------------------------

    def submit(
        self,
        kind: str,
        payload: Any,
        idempotency_key: "str | None" = None,
        timeout_s: "float | None" = None,
        tenant: "str | None" = None,
    ) -> "tuple[str, dict, bool]":
        """Admit one request; returns ``(key, done_payload, cached)``.

        ``done_payload`` is ``{"ok": True, "result": ...}`` or
        ``{"ok": False, "error": ...}``; ``cached`` is True when the
        answer came from the idempotency cache (or a concurrent duplicate
        execution) instead of a fresh one. Admission errors re-raise.
        """
        key = idempotency_key or f"auto-{uuid.uuid4().hex}"
        # fast path outside _lock: a spilled hit may touch disk
        hit = self._results.get(key)
        if hit is not None:
            self.metrics.count("serve.idempotent_hits")
            return key, hit, True
        with self._lock:
            flight = self._inflight.get(key)
            if flight is None:
                # re-check under _lock: _finish publishes the result before
                # dropping the inflight entry, so a miss-then-no-flight race
                # must look again before re-executing
                hit = self._results.get(key)
                if hit is not None:
                    self.metrics.count("serve.idempotent_hits")
                    return key, hit, True
                owner = True
                flight = self._inflight[key] = _Inflight()
            else:
                owner = False
        if not owner:
            # duplicate of an in-flight request: one execution, shared result
            flight.event.wait()
            self.metrics.count("serve.idempotent_hits")
            if flight.error is not None:
                raise flight.error
            return key, flight.result, True

        # durable intent BEFORE execution: the ACK implies the journal has it
        j0 = time.perf_counter()
        admit = {"t": "admit", "key": key, "kind": kind, "payload": payload}
        if tenant:
            admit["tenant"] = tenant
        with self._jlock:
            self._writer.append(admit)  # ipclint: disable=lock-held-blocking (durability: admit-frames serialize under the journal lock)
        journal_ms = round((time.perf_counter() - j0) * 1e3, 3)
        try:
            result = self._execute(kind, payload, timeout_s=timeout_s, tenant=tenant)
            # surface the admission fsync in this request's latency
            # breakdown (the done-record append overlaps the response)
            timing = result.get("server_timing")
            if isinstance(timing, dict):
                timing["journal_ms"] = journal_ms
        except _ADMISSION_ERRORS as exc:
            # never executed: leave the admit pending for restart replay,
            # release any coalesced waiters with the same failure
            with self._lock:
                self._inflight.pop(key, None)
            flight.error = exc
            flight.event.set()
            raise
        except Exception as exc:  # fail-soft: semantic failure — journalled as the request's durable (idempotent) error result
            done = {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
            self._finish(key, done)
            return key, done, False
        done = {"ok": True, "result": result}
        self._finish(key, done)
        return key, done, False

    # --- observability / lifecycle ----------------------------------------

    @property
    def journal_bytes(self) -> int:
        return self._writer.journal_bytes

    def health_fields(self) -> dict:
        """Merged into `/healthz` by the HTTP front end."""
        with self._lock:
            inflight = len(self._inflight)
        return {
            "durable_queue": True,
            "resumed_jobs": self.resumed_jobs,
            "journal_bytes": self.journal_bytes,
            "completed_requests": len(self._results),
            "result_cache_hot_bytes": self._results.hot_bytes(),
            "inflight_requests": inflight,
            "journal_degraded": self._writer.degraded,
        }

    def close(self) -> None:
        self._writer.close()
