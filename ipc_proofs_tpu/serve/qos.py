"""Per-tenant QoS enforcement: token buckets at admission + fair queuing.

PR 15's `TenantLedger` built the accounting half (who is using what);
this module is the enforcement half ROADMAP item 2 names:

- `TenantQoS` — per-tenant token buckets checked at HTTP admission,
  BEFORE a request touches the micro-batcher. An exhausted bucket is a
  typed 429 (`TenantThrottledError` → ``error_type: tenant_throttled``
  with a ``Retry-After`` hint computed from the refill rate), counted as
  ``qos.throttled`` + ``tenant.throttled.<slot>``. Buckets are bounded:
  at most ``max_tenants`` live buckets, coldest evicted first — a
  million distinct tenant strings cannot balloon server memory, and an
  evicted bucket resurrects full (brief over-admission, never
  over-rejection of a tenant that was within its rate).

- `FairQueue` — deficit round-robin across per-tenant sub-queues, the
  `MicroBatcher`'s interactive lane ordering. Every request costs one
  unit and every tenant's quantum is one unit per turn, so DRR reduces
  to strict round-robin across tenants while staying FIFO within each
  tenant — one hot client can no longer monopolize a flush: with T
  active tenants a light tenant's request sits behind at most ~queue/T
  of the heavy tenant's backlog instead of all of it. Single-tenant
  traffic degenerates to the exact FIFO order the batcher always had.

Admission throttling and queue fairness compose: the bucket bounds a
tenant's admitted RATE, the fair queue bounds the LATENCY a burst that
did get admitted can impose on everyone else.
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque
from typing import Optional

from ipc_proofs_tpu.utils.lockdep import named_lock
from ipc_proofs_tpu.utils.metrics import Metrics, get_metrics

__all__ = ["FairQueue", "TenantQoS", "TenantThrottledError", "TokenBucket"]


class TenantThrottledError(RuntimeError):
    """A tenant's token bucket is exhausted; mapped to a typed 429 with
    ``Retry-After: retry_after_s`` at the HTTP front door."""

    def __init__(self, tenant: Optional[str], retry_after_s: float):
        super().__init__(
            f"tenant {tenant or 'anonymous'!s} exceeded its admission rate"
        )
        self.tenant = tenant
        self.retry_after_s = retry_after_s


class TokenBucket:
    """One tenant's admission budget: ``rate`` tokens/s, ``burst`` cap.

    Lazy refill on take (no timer thread); not thread-safe on its own —
    `TenantQoS` serializes access under its lock."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.stamp = now

    def take(self, now: float) -> "tuple[bool, float]":
        """(admitted, retry_after_s). Refills from elapsed wall, spends
        one token when available; otherwise says how long until one
        token exists."""
        elapsed = max(0.0, now - self.stamp)
        self.stamp = now
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True, 0.0
        needed = (1.0 - self.tokens) / self.rate if self.rate > 0 else float("inf")
        return False, needed


class TenantQoS:
    """Per-tenant token-bucket admission control (``--tenant-rate`` /
    ``--tenant-burst``). One bucket per tenant label (anonymous traffic
    shares one bucket), LRU-bounded at ``max_tenants``."""

    def __init__(
        self,
        rate: float,
        burst: Optional[float] = None,
        metrics: Optional[Metrics] = None,
        ledger=None,
        max_tenants: int = 1024,
        clock=time.monotonic,
    ):
        if rate <= 0:
            raise ValueError("tenant rate must be positive (omit to disable QoS)")
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else max(1.0, 2.0 * self.rate)
        if self.burst < 1.0:
            raise ValueError("tenant burst must admit at least one request")
        self._metrics = metrics if metrics is not None else get_metrics()
        self._ledger = ledger
        self._max_tenants = max(1, int(max_tenants))
        self._clock = clock
        self._lock = named_lock("TenantQoS._lock")
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()  # guarded-by: _lock

    def admit(self, tenant: Optional[str]) -> None:
        """Spend one token for ``tenant`` or raise `TenantThrottledError`."""
        key = tenant or "anonymous"
        now = self._clock()
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = TokenBucket(self.rate, self.burst, now)
                self._buckets[key] = bucket
                while len(self._buckets) > self._max_tenants:
                    self._buckets.popitem(last=False)  # coldest bucket out
            else:
                self._buckets.move_to_end(key)
            ok, retry_after = bucket.take(now)
        if ok:
            return
        self._metrics.count("qos.throttled")
        slot = self._ledger.slot_for(tenant) if self._ledger is not None else key
        self._metrics.count(f"tenant.throttled.{slot}")
        raise TenantThrottledError(tenant, retry_after)


class FairQueue:
    """Deficit round-robin across per-tenant FIFO sub-queues.

    Unit cost per request, quantum = the tenant's WEIGHT per turn
    (``--tenant-weight name=N``; unlisted tenants weigh 1): the scheduler
    visits tenants in arrival-of-first-request order, takes up to
    ``weight`` requests, and rotates — weighted round-robin across
    tenants, FIFO within a tenant. All-default weights reduce to strict
    round-robin; single-tenant traffic degenerates to the exact FIFO
    order the batcher always had. NOT thread-safe: the `MicroBatcher`
    owns it under its condition lock, exactly like the deque it
    replaces."""

    __slots__ = ("_queues", "_len", "_weights", "_credit")

    def __init__(self, weights: "Optional[dict[str, int]]" = None):
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._len = 0
        self._weights = dict(weights or {})
        # the head tenant's remaining quantum this turn; 0 forces a
        # refill from its weight on the next pop
        self._credit = 0

    def __len__(self) -> int:
        return self._len

    def append(self, pending) -> None:
        key = getattr(pending, "tenant", None) or ""
        q = self._queues.get(key)
        if q is None:
            q = deque()
            self._queues[key] = q
        q.append(pending)
        self._len += 1

    def popleft(self):
        """Next request under weighted-DRR order; a tenant rotates to the
        back of the round once its quantum (= weight) is spent, so its
        remaining backlog waits its turn."""
        if self._len == 0:
            raise IndexError("pop from empty FairQueue")
        while True:
            key, q = next(iter(self._queues.items()))
            if not q:
                del self._queues[key]  # drained tenant leaves the round
                self._credit = 0
                continue
            if self._credit <= 0:
                self._credit = max(1, int(self._weights.get(key, 1)))
            out = q.popleft()
            self._len -= 1
            self._credit -= 1
            if not q:
                del self._queues[key]
                self._credit = 0
            elif self._credit <= 0:
                self._queues.move_to_end(key)
            return out

    def tenants(self) -> int:
        """Live sub-queues (the ``qos.tenant_queues`` gauge)."""
        return sum(1 for q in self._queues.values() if q)
