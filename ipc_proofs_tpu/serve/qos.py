"""Per-tenant QoS enforcement: token buckets at admission + fair queuing.

PR 15's `TenantLedger` built the accounting half (who is using what);
this module is the enforcement half ROADMAP item 2 names:

- `TenantQoS` — per-tenant token buckets checked at HTTP admission,
  BEFORE a request touches the micro-batcher. An exhausted bucket is a
  typed 429 (`TenantThrottledError` → ``error_type: tenant_throttled``
  with a ``Retry-After`` hint computed from the refill rate), counted as
  ``qos.throttled`` + ``tenant.throttled.<slot>``. Buckets are bounded:
  at most ``max_tenants`` live buckets, coldest evicted first — a
  million distinct tenant strings cannot balloon server memory, and an
  evicted bucket resurrects full (brief over-admission, never
  over-rejection of a tenant that was within its rate).

- `FairQueue` — deficit round-robin across per-tenant sub-queues, the
  `MicroBatcher`'s interactive lane ordering. Every request costs one
  unit and every tenant's quantum is one unit per turn, so DRR reduces
  to strict round-robin across tenants while staying FIFO within each
  tenant — one hot client can no longer monopolize a flush: with T
  active tenants a light tenant's request sits behind at most ~queue/T
  of the heavy tenant's backlog instead of all of it. Single-tenant
  traffic degenerates to the exact FIFO order the batcher always had.

Admission throttling and queue fairness compose: the bucket bounds a
tenant's admitted RATE, the fair queue bounds the LATENCY a burst that
did get admitted can impose on everyone else.
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque
from typing import Optional

from ipc_proofs_tpu.utils.lockdep import named_lock
from ipc_proofs_tpu.utils.metrics import Metrics, get_metrics
from ipc_proofs_tpu.utils.threads import locked

__all__ = [
    "AdmitRejectedError",
    "FairQueue",
    "GradientLimiter",
    "TenantQoS",
    "TenantThrottledError",
    "TokenBucket",
]


class TenantThrottledError(RuntimeError):
    """A tenant's token bucket is exhausted; mapped to a typed 429 with
    ``Retry-After: retry_after_s`` at the HTTP front door."""

    def __init__(self, tenant: Optional[str], retry_after_s: float):
        super().__init__(
            f"tenant {tenant or 'anonymous'!s} exceeded its admission rate"
        )
        self.tenant = tenant
        self.retry_after_s = retry_after_s


class TokenBucket:
    """One tenant's admission budget: ``rate`` tokens/s, ``burst`` cap.

    Lazy refill on take (no timer thread); not thread-safe on its own —
    `TenantQoS` serializes access under its lock."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.stamp = now

    def take(self, now: float) -> "tuple[bool, float]":
        """(admitted, retry_after_s). Refills from elapsed wall, spends
        one token when available; otherwise says how long until one
        token exists."""
        elapsed = max(0.0, now - self.stamp)
        self.stamp = now
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True, 0.0
        needed = (1.0 - self.tokens) / self.rate if self.rate > 0 else float("inf")
        return False, needed


class TenantQoS:
    """Per-tenant token-bucket admission control (``--tenant-rate`` /
    ``--tenant-burst``). One bucket per tenant label (anonymous traffic
    shares one bucket), LRU-bounded at ``max_tenants``."""

    def __init__(
        self,
        rate: float,
        burst: Optional[float] = None,
        metrics: Optional[Metrics] = None,
        ledger=None,
        max_tenants: int = 1024,
        clock=time.monotonic,
    ):
        if rate <= 0:
            raise ValueError("tenant rate must be positive (omit to disable QoS)")
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else max(1.0, 2.0 * self.rate)
        if self.burst < 1.0:
            raise ValueError("tenant burst must admit at least one request")
        self._metrics = metrics if metrics is not None else get_metrics()
        self._ledger = ledger
        self._max_tenants = max(1, int(max_tenants))
        self._clock = clock
        self._lock = named_lock("TenantQoS._lock")
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()  # guarded-by: _lock

    def admit(self, tenant: Optional[str]) -> None:
        """Spend one token for ``tenant`` or raise `TenantThrottledError`."""
        key = tenant or "anonymous"
        now = self._clock()
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = TokenBucket(self.rate, self.burst, now)
                self._buckets[key] = bucket
                while len(self._buckets) > self._max_tenants:
                    self._buckets.popitem(last=False)  # coldest bucket out
            else:
                self._buckets.move_to_end(key)
            ok, retry_after = bucket.take(now)
        if ok:
            return
        self._metrics.count("qos.throttled")
        slot = self._ledger.slot_for(tenant) if self._ledger is not None else key
        self._metrics.count(f"tenant.throttled.{slot}")
        raise TenantThrottledError(tenant, retry_after)


class FairQueue:
    """Deficit round-robin across per-tenant FIFO sub-queues.

    Unit cost per request, quantum = the tenant's WEIGHT per turn
    (``--tenant-weight name=N``; unlisted tenants weigh 1): the scheduler
    visits tenants in arrival-of-first-request order, takes up to
    ``weight`` requests, and rotates — weighted round-robin across
    tenants, FIFO within a tenant. All-default weights reduce to strict
    round-robin; single-tenant traffic degenerates to the exact FIFO
    order the batcher always had. NOT thread-safe: the `MicroBatcher`
    owns it under its condition lock, exactly like the deque it
    replaces."""

    __slots__ = ("_queues", "_len", "_weights", "_credit")

    def __init__(self, weights: "Optional[dict[str, int]]" = None):
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._len = 0
        self._weights = dict(weights or {})
        # the head tenant's remaining quantum this turn; 0 forces a
        # refill from its weight on the next pop
        self._credit = 0

    def __len__(self) -> int:
        return self._len

    def append(self, pending) -> None:
        key = getattr(pending, "tenant", None) or ""
        q = self._queues.get(key)
        if q is None:
            q = deque()
            self._queues[key] = q
        q.append(pending)
        self._len += 1

    def popleft(self):
        """Next request under weighted-DRR order; a tenant rotates to the
        back of the round once its quantum (= weight) is spent, so its
        remaining backlog waits its turn."""
        if self._len == 0:
            raise IndexError("pop from empty FairQueue")
        while True:
            key, q = next(iter(self._queues.items()))
            if not q:
                del self._queues[key]  # drained tenant leaves the round
                self._credit = 0
                continue
            if self._credit <= 0:
                self._credit = max(1, int(self._weights.get(key, 1)))
            out = q.popleft()
            self._len -= 1
            self._credit -= 1
            if not q:
                del self._queues[key]
                self._credit = 0
            elif self._credit <= 0:
                self._queues.move_to_end(key)
            return out

    def tenants(self) -> int:
        """Live sub-queues (the ``qos.tenant_queues`` gauge)."""
        return sum(1 for q in self._queues.values() if q)


class AdmitRejectedError(RuntimeError):
    """The adaptive admission limiter shed this request; mapped to a
    typed 429 whose ``Retry-After`` is the limiter's drain estimate —
    honest backpressure, not a constant the client learns to ignore."""

    error_type = "admit_rejected"

    def __init__(self, retry_after_s: float, tenant: Optional[str] = None):
        super().__init__(
            "admission limit reached; retry in %.2fs" % retry_after_s
        )
        self.retry_after_s = retry_after_s
        self.tenant = tenant


class _AdmitSlot:
    """One held admission: returned by `GradientLimiter.acquire`, handed
    back to `release`. Carries the acquire stamp so the limiter can
    measure true service time without a side table."""

    __slots__ = ("tenant", "started", "released")

    def __init__(self, tenant: Optional[str], started: float):
        self.tenant = tenant
        self.started = started
        self.released = False


class GradientLimiter:
    """AIMD concurrency limiter driven by observed queue delay.

    Replaces the static ``queue_capacity`` as the serve plane's first
    gate (the batcher capacity stays as a hard backstop). The limit
    GROWS additively (+1) while recent queue delay sits comfortably
    under the SLO-derived budget, and SHRINKS multiplicatively
    (× ``shrink``) the moment the window's p99 queue delay crosses it —
    the classic gradient/AIMD response that keeps a fast host admitting
    near its true capacity and walks a melting host back down instead of
    letting a fixed bound choose wrong in both directions.

    Shedding is tenant-aware: tenants named in ``tenant_weights`` (the
    top-K by deficit weight, the same vocabulary the fair queue uses)
    ride a grace headroom of ``grace`` × limit before they shed, so
    under overload the anonymous/`other` pool sheds FIRST and paying
    tenants keep their latency (counted ``admit.shed_other``).

    429s carry an honest ``Retry-After``: the drain estimate
    ``excess_requests × avg_service_time / limit`` from the limiter's
    own EWMA of acquire→release service time.
    """

    WINDOW = 32  # completions per AIMD evaluation window
    GROW_FRACTION = 0.5  # grow while p99 delay < this fraction of budget

    def __init__(
        self,
        initial: int = 8,
        min_limit: int = 2,
        max_limit: int = 1024,
        delay_budget_ms: float = 250.0,
        shrink: float = 0.8,
        grace: float = 1.25,
        tenant_weights: "Optional[dict[str, int]]" = None,
        metrics: Optional[Metrics] = None,
        clock=time.monotonic,
    ):
        self.min_limit = max(1, int(min_limit))
        self.max_limit = max(self.min_limit, int(max_limit))
        self.delay_budget_ms = float(delay_budget_ms)
        self.shrink = float(shrink)
        self.grace = max(1.0, float(grace))
        self._named = frozenset(tenant_weights or ())
        self._metrics = metrics if metrics is not None else get_metrics()
        self._clock = clock
        self._lock = named_lock("GradientLimiter._lock")
        self._limit = float(min(self.max_limit, max(self.min_limit, initial)))  # guarded-by: _lock
        self._inflight = 0  # guarded-by: _lock
        self._delays: "deque[float]" = deque(maxlen=self.WINDOW)  # guarded-by: _lock
        self._avg_service_s = 0.05  # EWMA acquire→release; guarded-by: _lock
        self._completions = 0  # completions since last AIMD step; guarded-by: _lock

    @property
    def limit(self) -> int:
        with self._lock:
            return int(self._limit)

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def acquire(self, tenant: Optional[str] = None) -> _AdmitSlot:
        """Take one concurrency slot or raise `AdmitRejectedError`.

        Named (top-K weighted) tenants shed only past ``grace`` × limit;
        everyone else sheds at the limit — the `other` pool first.
        """
        named = tenant is not None and tenant in self._named
        now = self._clock()
        with self._lock:
            ceiling = self._limit * self.grace if named else self._limit
            if self._inflight >= ceiling:
                retry_after = self._drain_estimate_locked()
                shed_other = not named
            else:
                self._inflight += 1
                slot = _AdmitSlot(tenant, now)
                inflight = self._inflight
                retry_after = None
        if retry_after is not None:
            self._metrics.count("admit.rejects")
            if shed_other:
                self._metrics.count("admit.shed_other")
            raise AdmitRejectedError(retry_after, tenant)
        self._metrics.count("admit.accepted")
        self._metrics.set_gauge("admit.inflight", inflight)
        return slot

    def release(self, slot: _AdmitSlot, queue_delay_ms: float = 0.0) -> None:
        """Return a slot, feeding the AIMD window with this request's
        observed queue delay. Idempotent per slot (error paths may race
        a finally block)."""
        if slot.released:
            return
        slot.released = True
        now = self._clock()
        grew = shrank = False
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            inflight = self._inflight
            service_s = max(0.0, now - slot.started)
            self._avg_service_s = 0.8 * self._avg_service_s + 0.2 * service_s
            self._delays.append(max(0.0, float(queue_delay_ms)))
            self._completions += 1
            if self._completions >= min(self.WINDOW, max(4, int(self._limit))):
                p99 = self._p99_locked()
                if p99 > self.delay_budget_ms:
                    new = max(self.min_limit, int(self._limit * self.shrink))
                    shrank = new < int(self._limit)
                    self._limit = float(new)
                elif p99 < self.delay_budget_ms * self.GROW_FRACTION:
                    new = min(self.max_limit, int(self._limit) + 1)
                    grew = new > int(self._limit)
                    self._limit = float(new)
                self._completions = 0
                self._delays.clear()
            limit = int(self._limit)
        if grew:
            self._metrics.count("admit.grows")
        if shrank:
            self._metrics.count("admit.shrinks")
        self._metrics.set_gauge("admit.limit", limit)
        self._metrics.set_gauge("admit.inflight", inflight)

    def retry_after_s(self) -> float:
        """Current drain estimate (what a shed request should wait)."""
        with self._lock:
            return self._drain_estimate_locked()

    @locked
    def _p99_locked(self) -> float:
        if not self._delays:
            return 0.0
        ordered = sorted(self._delays)
        return ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))]

    @locked
    def _drain_estimate_locked(self) -> float:
        # How long until a slot frees: the excess queue over the limit
        # drains at limit/avg_service_time requests per second.
        excess = max(1.0, self._inflight - self._limit + 1.0)
        rate = max(1e-6, self._limit / max(1e-3, self._avg_service_s))
        return max(0.05, excess / rate)
