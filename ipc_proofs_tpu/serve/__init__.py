"""Proof-serving daemon: dynamic micro-batching over the batch engines.

The inference-serving shape — continuous batching, bounded admission with
backpressure, per-request deadlines, graceful drain, latency-percentile
observability — grafted onto the proof pipeline. See `serve/batcher.py`
(coalescing + admission), `serve/service.py` (the service proper),
`serve/httpd.py` (JSON-over-HTTP front end), and README "Serving".
"""

from ipc_proofs_tpu.serve.batcher import (
    DeadlineExceededError,
    MicroBatcher,
    PendingResult,
    QueueFullError,
    ServiceClosedError,
)
from ipc_proofs_tpu.serve.durable import DurableAdmission
from ipc_proofs_tpu.serve.httpd import ProofHTTPServer
from ipc_proofs_tpu.serve.service import (
    GenerateResponse,
    ProofService,
    ServiceConfig,
    VerifyResponse,
    sequential_verify_baseline,
)

__all__ = [
    "DeadlineExceededError",
    "DurableAdmission",
    "GenerateResponse",
    "MicroBatcher",
    "PendingResult",
    "ProofHTTPServer",
    "ProofService",
    "QueueFullError",
    "ServiceClosedError",
    "ServiceConfig",
    "VerifyResponse",
    "sequential_verify_baseline",
]
