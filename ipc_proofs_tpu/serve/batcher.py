"""Dynamic micro-batcher: bounded admission, flush-on-size-or-deadline.

The serving half of the inference-serving shape grafted onto the proof
pipeline (see PAPERS.md — Reddio's decoupling of request admission from
batched execution). Individual requests arrive one at a time; the batch
engines (`proofs/event_verifier.py` grouped replay, `proofs/range.py`)
only pay off when fed many proofs per call. The `MicroBatcher` bridges
them:

- **admission** is a bounded queue. A full queue REJECTS immediately with
  a retry hint (`QueueFullError.retry_after_s`) — it never blocks the
  caller and never grows without bound, so a traffic spike degrades into
  fast 503s instead of memory exhaustion and collapse.
- **coalescing** flushes a batch when it reaches ``max_batch`` requests OR
  the oldest queued request has waited ``max_wait_ms`` — whichever comes
  first. Under load, batches fill instantly and the wait bound never
  binds; at low traffic, a lone request pays at most ``max_wait_ms`` of
  extra latency.
- **deadlines** are per request: a request whose deadline passed while it
  sat in the queue is completed with `DeadlineExceededError` at dequeue
  time rather than wasting batch capacity on an answer nobody is waiting
  for.
- **drain** (`close(drain=True)`) stops admission, flushes everything
  already accepted, and joins the batcher thread — an accepted request is
  never dropped by shutdown.
- **priority** is three lanes, drained strictly in order: ``push`` >
  ``interactive`` > ``low``. The PUSH lane carries standing-query
  fan-out work (`subs/matcher.py` riding `submit_range_window`'s push
  lane) and is assembled greedily — a subscriber notification never
  waits a batching window behind interactive traffic. The LOW lane
  (backfill windows) is only drained when both others are empty and is
  abandoned mid-fill the moment higher work appears — a 100k-epoch job
  queues forever behind live ``/v1/verify`` traffic, never in front of
  it. ``submit(..., low_priority=True)`` remains the low-lane spelling.
- **fairness** inside the interactive lane is deficit round-robin across
  per-tenant sub-queues (`serve/qos.py::FairQueue`): one hot client's
  backlog no longer monopolizes batch assembly — tenants take turns,
  FIFO within each tenant, exact FIFO overall when only one tenant is
  talking.

The batcher owns one assembly thread; the flush callback may optionally be
dispatched to a shared executor so batch *assembly* overlaps batch
*execution* (the service's worker pool).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from ipc_proofs_tpu.obs.trace import current_context
from ipc_proofs_tpu.serve.qos import FairQueue
from ipc_proofs_tpu.utils.deadline import CancelledError, DeadlineError
from ipc_proofs_tpu.utils.metrics import Metrics
from ipc_proofs_tpu.utils.lockdep import named_condition

__all__ = [
    "DeadlineExceededError",
    "MicroBatcher",
    "PendingResult",
    "QueueFullError",
    "ServiceClosedError",
]


class QueueFullError(RuntimeError):
    """Admission queue is full; retry after ``retry_after_s`` seconds."""

    def __init__(self, retry_after_s: float):
        super().__init__(
            f"admission queue full; retry after {retry_after_s:.3f}s"
        )
        self.retry_after_s = retry_after_s


class ServiceClosedError(RuntimeError):
    """The service is draining or stopped; no new requests are admitted."""


class DeadlineExceededError(DeadlineError):
    """The request's deadline passed before it could be processed.

    Subclasses `utils.deadline.DeadlineError`, so it carries
    ``error_type == "deadline"`` and every typed-deadline door (504
    mapping, IPBS in-band abort, scatter merge) renders it uniformly."""


class PendingResult:
    """A slot for one request's eventual result (a minimal future).

    ``threading.Event`` + result/error pair rather than
    `concurrent.futures.Future` so completion stays allocation-light and
    the batcher controls exactly who may complete it.

    Carries the submitter's `TraceContext` (``trace_ctx``) across the
    queue hop so batch execution can parent its spans into the request's
    trace, and the dispatch instant (``dispatched_at``) so the per-request
    ``server_timing`` breakdown can attribute pure queue wait separately
    from batch execution.
    """

    __slots__ = (
        "payload",
        "deadline",
        "enqueued_at",
        "dispatched_at",
        "trace_ctx",
        "tenant",
        "cancel_scope",
        "_done",
        "_result",
        "_error",
    )

    def __init__(self, payload, deadline: Optional[float], enqueued_at: float):
        self.payload = payload
        self.deadline = deadline  # absolute time.monotonic() instant, or None
        self.enqueued_at = enqueued_at
        self.dispatched_at: Optional[float] = None
        self.trace_ctx = None  # obs.trace.TraceContext captured at submit
        self.tenant: Optional[str] = None  # sanitized accounting label
        # utils.deadline.CancelScope carried across the queue hop: the
        # batcher drops cancelled members at dispatch time and batch
        # execution installs it so chunk/stage checkpoints fire
        self.cancel_scope = None
        self._done = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def complete(self, result) -> None:
        self._result = result
        self._done.set()

    def fail(self, error: BaseException) -> None:
        self._error = error
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until the request completes; raise its error if it failed."""
        if not self._done.wait(timeout):
            raise TimeoutError("request not complete within wait timeout")
        if self._error is not None:
            raise self._error
        return self._result


class MicroBatcher:
    """Coalesce individual submissions into bounded, deadline-aware batches.

    ``flush_fn(batch)`` receives a non-empty ``list[PendingResult]`` and
    must complete (or fail) every element. If it raises instead, the
    batcher fails every still-pending element with that exception — a
    buggy flush can never strand callers in ``result()`` forever.
    """

    def __init__(
        self,
        flush_fn: Callable[[list[PendingResult]], None],
        max_batch: int = 32,
        max_wait_ms: float = 4.0,
        capacity: int = 256,
        name: str = "batch",
        metrics: Optional[Metrics] = None,
        executor=None,
        tenant_weights: Optional[dict] = None,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self._flush_fn = flush_fn
        self._max_batch = max_batch
        self._max_wait_s = max_wait_ms / 1000.0
        self._capacity = capacity
        self._name = name
        self._metrics = metrics if metrics is not None else Metrics()
        self._executor = executor
        self._cond = named_condition("MicroBatcher._cond")
        # interactive lane: deficit-round-robin across tenant sub-queues
        # (per-tenant quanta from --tenant-weight; unlisted tenants = 1)
        self._queue: FairQueue = FairQueue(weights=tenant_weights)  # guarded-by: _cond
        # push lane (standing-query fan-out): drained FIRST, greedily
        self._push: deque[PendingResult] = deque()  # guarded-by: _cond
        # low-priority lane (backfill windows): drained only when both
        # other lanes are empty, bounded by the same capacity
        self._low: deque[PendingResult] = deque()  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        # EWMA of recent flush wall times, seeding the retry-after hint for
        # rejected requests: "queue depth / batch size" flushes still ahead
        # of you, each costing roughly this long
        self._avg_flush_s = self._max_wait_s  # guarded-by: _cond
        self._thread = threading.Thread(
            target=self._run, name=f"micro-batcher-{name}", daemon=True
        )
        self._thread.start()

    # --- admission ---------------------------------------------------------

    def submit(
        self,
        payload,
        timeout_s: Optional[float] = None,
        tenant: Optional[str] = None,
        low_priority: bool = False,
        lane: Optional[str] = None,
        cancel_scope=None,
    ) -> PendingResult:
        """Admit one request; never blocks.

        Raises `ServiceClosedError` after `close()`, `QueueFullError` when
        the bounded lane is at capacity. ``lane`` is ``"push"`` |
        ``"interactive"`` (default) | ``"low"``; ``low_priority=True``
        remains the low-lane spelling. ``tenant`` keys the interactive
        lane's deficit-round-robin sub-queue (untenanted requests share
        one round-robin slot). ``cancel_scope`` rides the queue hop: a
        member whose scope is cancelled by dispatch time is dropped
        (typed) without spending batch capacity.
        """
        if lane is None:
            lane = "low" if low_priority else "interactive"
        if lane not in ("push", "interactive", "low"):
            raise ValueError(f"unknown batcher lane {lane!r}")
        now = time.monotonic()
        deadline = (now + timeout_s) if timeout_s is not None else None
        with self._cond:
            if self._closed:
                self._metrics.count(f"serve.rejected_closed.{self._name}")
                raise ServiceClosedError(f"{self._name} batcher is draining")
            q = {"push": self._push, "interactive": self._queue, "low": self._low}[lane]
            if len(q) >= self._capacity:
                self._metrics.count(f"serve.rejected_full.{self._name}")
                batches_ahead = max(1, len(q) // self._max_batch)
                raise QueueFullError(
                    retry_after_s=max(0.001, batches_ahead * self._avg_flush_s)
                )
            pending = PendingResult(payload, deadline, now)
            pending.trace_ctx = current_context()
            pending.tenant = tenant
            pending.cancel_scope = cancel_scope
            q.append(pending)
            if lane == "low":
                self._metrics.set_gauge(
                    f"serve.queue_depth_low.{self._name}", len(self._low)
                )
                self._metrics.count(f"serve.accepted_low.{self._name}")
            elif lane == "push":
                self._metrics.set_gauge(
                    f"serve.queue_depth_push.{self._name}", len(self._push)
                )
                self._metrics.count(f"serve.accepted_push.{self._name}")
            else:
                self._metrics.set_gauge(
                    f"serve.queue_depth.{self._name}", len(self._queue)
                )
                self._metrics.set_gauge(
                    "qos.tenant_queues", self._queue.tenants()
                )
                self._metrics.count(f"serve.accepted.{self._name}")
            self._cond.notify_all()
        return pending

    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def low_depth(self) -> int:
        with self._cond:
            return len(self._low)

    # --- batch assembly ----------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while (
                    not self._push
                    and not self._queue
                    and not self._low
                    and not self._closed
                ):
                    self._cond.wait()
                if (
                    not self._push
                    and not self._queue
                    and not self._low
                    and self._closed
                ):
                    return
                if self._push:
                    # push lane first, assembled greedily: a standing-query
                    # fan-out never waits a batching window
                    batch = [self._push.popleft()]
                    while self._push and len(batch) < self._max_batch:
                        batch.append(self._push.popleft())
                    self._metrics.set_gauge(
                        f"serve.queue_depth_push.{self._name}", len(self._push)
                    )
                elif self._queue:
                    # interactive lane: members pop in deficit-round-robin
                    # order, so the window opens at the FIRST POPPED
                    # member's arrival — a request's queueing latency is
                    # bounded by max_wait plus however many fair-share
                    # turns its own tenant's backlog costs it (that wait
                    # is the fairness, not a regression)
                    batch = [self._queue.popleft()]
                    window_end = batch[0].enqueued_at + self._max_wait_s
                    while len(batch) < self._max_batch:
                        if self._queue:
                            batch.append(self._queue.popleft())
                            continue
                        remaining = window_end - time.monotonic()
                        if remaining <= 0 or self._closed:
                            break
                        self._cond.wait(remaining)
                        if not self._queue and (
                            self._closed or time.monotonic() >= window_end
                        ):
                            break
                    self._metrics.set_gauge(
                        f"serve.queue_depth.{self._name}", len(self._queue)
                    )
                    self._metrics.set_gauge(
                        "qos.tenant_queues", self._queue.tenants()
                    )
                else:
                    # low lane: only reached with both other lanes EMPTY,
                    # assembled greedily (no wait window — waiting would
                    # delay any interactive arrival), and abandoned
                    # mid-fill the moment higher-priority work appears
                    batch = [self._low.popleft()]
                    while (
                        self._low
                        and len(batch) < self._max_batch
                        and not self._queue
                        and not self._push
                    ):
                        batch.append(self._low.popleft())
                    self._metrics.set_gauge(
                        f"serve.queue_depth_low.{self._name}", len(self._low)
                    )
            self._dispatch(batch)

    def _dispatch(self, batch: list[PendingResult]) -> None:
        now = time.monotonic()
        with self._cond:
            est_flush_s = self._avg_flush_s
        live: list[PendingResult] = []
        for pending in batch:
            pending.dispatched_at = now
            scope = pending.cancel_scope
            if scope is not None and scope.cancelled:
                # abandoned while queued: drop it HERE, before it costs a
                # worker anything — the whole flush estimate is reclaimed
                self._metrics.count("serve.cancelled_inflight")
                self._metrics.count(
                    "deadline.reclaimed_ms", max(1, int(est_flush_s * 1000.0))
                )
                pending.fail(
                    CancelledError(
                        scope.reason or "request cancelled while queued"
                    )
                )
            elif pending.deadline is not None and now > pending.deadline:
                self._metrics.count(f"serve.deadline_exceeded.{self._name}")
                self._metrics.count("serve.deadline_rejects")
                self._metrics.count("deadline.rejects.batcher")
                pending.fail(
                    DeadlineExceededError(
                        f"deadline exceeded after "
                        f"{now - pending.enqueued_at:.3f}s in queue"
                    )
                )
            elif (
                pending.deadline is not None
                and pending.deadline - now < est_flush_s * 0.5
            ):
                # remaining budget cannot plausibly cover even half a
                # typical flush: refuse typed rather than produce an
                # answer after the client stopped waiting
                self._metrics.count(f"serve.deadline_exceeded.{self._name}")
                self._metrics.count("serve.deadline_rejects")
                self._metrics.count("deadline.rejects.batcher")
                pending.fail(
                    DeadlineExceededError(
                        "remaining budget %.0fms below batch execution floor"
                        % ((pending.deadline - now) * 1000.0)
                    )
                )
            else:
                live.append(pending)
        if not live:
            return
        self._metrics.observe(f"serve.batch_size.{self._name}", len(live))
        if self._executor is not None:
            self._executor.submit(self._flush, live)
        else:
            self._flush(live)

    def _flush(self, batch: list[PendingResult]) -> None:
        start = time.monotonic()
        try:
            self._flush_fn(batch)
        except BaseException as exc:  # fail-soft: strand no caller — the error reaches every waiter via pending.fail()
            for pending in batch:
                if not pending.done():
                    pending.fail(exc)
        finally:
            elapsed = time.monotonic() - start
            with self._cond:
                self._avg_flush_s = 0.8 * self._avg_flush_s + 0.2 * elapsed
            for pending in batch:
                if not pending.done():
                    pending.fail(
                        RuntimeError(
                            f"{self._name} flush returned without completing "
                            "this request (bug in flush_fn)"
                        )
                    )

    # --- shutdown ----------------------------------------------------------

    def close(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Stop admitting. ``drain=True`` flushes everything accepted and
        joins the batcher thread; ``drain=False`` fails queued requests
        with `ServiceClosedError` (in-flight flushes still finish)."""
        with self._cond:
            self._closed = True
            if not drain:
                while self._queue:
                    self._queue.popleft().fail(
                        ServiceClosedError(f"{self._name} batcher stopped")
                    )
                while self._push:
                    self._push.popleft().fail(
                        ServiceClosedError(f"{self._name} batcher stopped")
                    )
                while self._low:
                    self._low.popleft().fail(
                        ServiceClosedError(f"{self._name} batcher stopped")
                    )
            self._cond.notify_all()
        self._thread.join(timeout)

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed
