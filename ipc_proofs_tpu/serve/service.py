"""Long-running proof service: micro-batched verify/generate with drain.

`ProofService` is the in-process API (the HTTP front end in
`serve/httpd.py` is a thin shim over it). Two independent `MicroBatcher`s
feed the existing batch engines:

- **verify**: N individual `UnifiedProofBundle`s merge into ONE bundle —
  witness blocks deduplicated, proofs concatenated in request order — and
  a single `verify_proof_bundle` call replays them all (grouped event
  replay + batched storage walk). Per-request verdicts are split back out
  by position. Requests whose witness blocks CONFLICT (same CID, different
  bytes — one of them is lying) are partitioned into compatible sub-merges
  rather than letting one forged block poison a neighbor's verdict.
- **generate**: N individual tipset-pair requests deduplicate into one
  pair list for `generate_event_proofs_for_range` (one device match call
  for the whole micro-batch). Each response carries its own pair's proofs
  — bit-identical to generating that pair alone — plus the micro-batch's
  shared deduplicated witness (a sound superset: every response bundle
  verifies independently; batching trades some response bytes for the
  shared scan).

All workers share one `CachedBlockstore` over the chain store, backed by a
`BlockCache` (size-capped + TTL) so the cache survives millions of
requests without becoming a slow OOM. With ``store_dir`` set the cache
grows a second, disk-resident tier (`storex.TieredBlockstore` over a
`SegmentStore`): blocks fetched once survive restarts and are shared by
every worker, so a warm tipset serves with zero upstream RPC fetches.

Verification policy (trust policy, event filter, witness-CID checking) is
service-level configuration, fixed at startup: a real deployment serves
one subnet's trust root, and batching is only sound when every request in
a merge is judged under the same policy.
"""

from __future__ import annotations

import hashlib
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from time import monotonic
from typing import Optional, Sequence

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.proofs.bundle import ProofBlock, UnifiedProofBundle
from ipc_proofs_tpu.proofs.range import (
    TipsetPair,
    generate_event_proofs_for_range,
    generate_event_proofs_for_range_chunked,
    generate_event_proofs_for_range_pipelined,
)
from ipc_proofs_tpu.obs.trace import (
    format_span_tree,
    spans_for_trace,
    use_context,
)
from ipc_proofs_tpu.proofs.trust import TrustPolicy
from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle
from ipc_proofs_tpu.serve.batcher import (
    MicroBatcher,
    PendingResult,
    ServiceClosedError,
)
from ipc_proofs_tpu.store.blockstore import BlockCache, CachedBlockstore
from ipc_proofs_tpu.utils.deadline import use_scope
from ipc_proofs_tpu.utils.log import get_logger
from ipc_proofs_tpu.utils.metrics import Metrics
from ipc_proofs_tpu.utils.lockdep import named_lock
from ipc_proofs_tpu.witness.bases import WitnessBaseCache

log = get_logger(__name__)

__all__ = [
    "GenerateResponse",
    "ProofService",
    "ServiceConfig",
    "VerifyResponse",
]


@dataclass
class ServiceConfig:
    """Tuning knobs for the serving loop (see README "Serving")."""

    max_batch: int = 32  # flush when a batch reaches this many requests…
    max_wait_ms: float = 4.0  # …or the oldest member has waited this long
    queue_capacity: int = 256  # bounded admission; beyond this → 503
    workers: int = 2  # batch-execution pool (assembly overlaps execution)
    cache_max_bytes: int = 256 * 1024 * 1024  # shared BlockCache budget
    cache_ttl_s: Optional[float] = None  # optional entry TTL
    verify_witness_cids: bool = False  # recompute witness CIDs on verify
    # multi-pair generate batches run the stage-overlapped range engine:
    # chunks of range_chunk_size pairs flow scan → record → merge (→
    # verify) with range_pipeline_depth chunks buffered between stages.
    # `threads` is the engine's ONE shared budget (--threads; partitioned
    # over stage workers + native scan fan-out by
    # utils.threads.resolve_thread_budget); range_scan_threads is the
    # legacy knob that pins the scan stage width
    range_chunk_size: int = 8
    range_scan_threads: Optional[int] = None
    range_pipeline_depth: int = 2
    threads: Optional[int] = None
    # write-ahead journal dir for generate batches: chunk commits become
    # durable/resumable and each response's Server-Timing grows a
    # `journal_ms` entry (wall time spent fsyncing chunk records)
    range_job_dir: Optional[str] = None
    # requests slower than this auto-log their span tree (flight ring) with
    # trace_id correlation and bump the serve.slow_requests counter
    slow_request_ms: float = 1000.0
    # disk tier (storex.SegmentStore) under the shared BlockCache: blocks
    # persist across restarts in append-only segment files, LRU-evicted at
    # store_cap_bytes; None keeps the memory-only CachedBlockstore
    store_dir: Optional[str] = None
    store_cap_bytes: int = 1 * 1024 * 1024 * 1024
    # roll the active segment once it reaches this size. Replication pulls
    # skip the active tail (another process may still be appending), so a
    # replicated tier wants this small enough that hot data rolls into
    # immutable segments promptly; the 64 MB default matches the
    # single-host behavior where rolling cadence is irrelevant
    store_segment_max_bytes: int = 64 * 1024 * 1024
    # owner token for a store_dir SHARED between shard daemons: each
    # process appends only to its own seg-<owner>.* segments and eviction
    # coordinates through the directory flock (see storex/segments.py).
    # None = exclusive single-writer store (the pre-cluster behavior)
    store_owner: Optional[str] = None
    # async fetch plane (store.fetchplane): when the backing store is
    # RPC-fed, interpose a want-queue so concurrent walkers' block fetches
    # ship as JSON-RPC batches and HAMT/AMT child links prefetch
    # speculatively. batch_rpc=False keeps the sync one-call-per-block
    # path; speculate_depth=0 batches without speculation
    batch_rpc: bool = True
    # "auto" starts at FetchPlane.AUTO_START_DEPTH and backs off when the
    # speculation waste ratio spikes (fetch.speculate_depth_downshifts)
    speculate_depth: "int | str" = 1
    # on-chip half (PR 12): match_backend name routes generate-range event
    # matching through a BatchHashBackend; mesh_devices lays coalesced
    # match batches across that many local devices (0 = all, None = no
    # mesh); batch_verify swaps chunk-granular read-path multihash checks
    # (fetch plane landings, disk-tier reads) for the device-batched
    # ops.verify_jax plane
    match_backend: Optional[str] = None
    mesh_devices: Optional[int] = None
    batch_verify: bool = False
    # witness plane (ipc_proofs_tpu/witness/): delta witnesses against
    # previously served bundles and compressed framing, negotiated
    # per-request. Disabling compress makes non-identity encodings a
    # typed 400 (encoding is a contract); disabling delta silently
    # serves full bundles (delta is an optimization with a sound
    # degradation). witness_agg_max caps claims per aggregated
    # generate_range; witness_base_cache bounds the digest→CID-set LRU
    witness_delta: bool = True
    witness_compress: bool = True
    witness_agg_max: int = 1024
    witness_base_cache: int = 64
    # per-tenant QoS enforcement (serve/qos.py): token-bucket admission at
    # tenant_rate requests/s with tenant_burst headroom (default 2×rate).
    # None disables throttling — accounting (TenantLedger) still runs.
    # The micro-batcher's fair interactive lane is always on; the bucket
    # only adds the typed-429 rate limit.
    tenant_rate: Optional[float] = None
    tenant_burst: Optional[float] = None
    # per-tenant deficit weights for the batcher's fair interactive lane
    # (--tenant-weight name=N): a weight-N tenant drains up to N queued
    # requests per round-robin turn; unlisted tenants weigh 1
    tenant_weights: Optional[dict] = None
    # adaptive admission (serve/qos.py GradientLimiter, --admit-gradient):
    # AIMD concurrency limit on queue delay replaces queue_capacity as the
    # FIRST gate at the HTTP front door (the batcher capacity stays as a
    # hard backstop). delay budget is the p99 queue-delay SLO in ms.
    admit_gradient: bool = False
    admit_initial: int = 8
    admit_min: int = 2
    admit_max: int = 1024
    admit_delay_budget_ms: float = 250.0
    # deadline propagation (--deadline-floor-ms): requests whose remaining
    # budget (X-IPC-Deadline-Ms header / deadline_ms body field) is below
    # this floor are refused typed at admission instead of admitted to die
    deadline_floor_ms: float = 5.0
    # pool-wide client retry budget in tokens/s (--retry-budget; None =
    # unbudgeted). Wired into EndpointPool at daemon build time.
    retry_budget: Optional[float] = None
    # proof provenance registry (ipc_proofs_tpu/registry/): when
    # registry_dir is set every served bundle seals one hash-linked IPR1
    # frame into reg-<registry_owner>.log under that directory, the
    # /v1/registry/* proof endpoints come up, and witness_bases is
    # front-ended by the fleet-wide base directory (siblings sharing the
    # dir see each other's serve records). registry_fsync=False rides
    # the page cache (the <1% serve-overhead budget); True restores the
    # per-record durable contract.
    registry_dir: Optional[str] = None
    registry_owner: str = "main"
    registry_fsync: bool = False


@dataclass
class VerifyResponse:
    """Per-request verdicts, split out of the merged-batch result."""

    storage_results: list[bool]
    event_results: list[bool]
    batch_size: int  # how many requests shared the replay (observability)
    # per-request latency attribution (queue_ms / batch_wait_ms /
    # verify_ms …), computed from this request's own timestamps — the
    # components sum to the admission→completion wall
    server_timing: dict = field(default_factory=dict)
    trace_id: str = ""

    def all_valid(self) -> bool:
        return all(self.storage_results) and all(self.event_results)


@dataclass
class GenerateResponse:
    """One request's bundle: its pair's proofs + the batch's shared witness."""

    bundle: UnifiedProofBundle
    batch_size: int
    server_timing: dict = field(default_factory=dict)
    trace_id: str = ""

    @property
    def n_event_proofs(self) -> int:
        return len(self.bundle.event_proofs)


def _pair_key(pair: TipsetPair) -> tuple:
    return (
        tuple(str(c) for c in pair.parent.cids),
        tuple(str(c) for c in pair.child.cids),
    )


@dataclass
class _GenerateRequest:
    pair: TipsetPair
    key: tuple = field(init=False)

    def __post_init__(self):
        self.key = _pair_key(self.pair)


@dataclass
class _RangeWindowRequest:
    """One range window riding the generate batcher's LOW or PUSH lane.

    The payload is a whole pair list (not one pair): the window executes
    as a single chunked-driver call, so its bundle is the canonical
    bytes for exactly those pairs and folds bit-identically. ``spec`` /
    ``storage_specs`` override the service-level spec for standing-query
    pushes (one distinct filter per window); None keeps the service's."""

    pairs: list
    chunk_size: Optional[int] = None
    spec: Optional[object] = None
    storage_specs: Optional[list] = None


class ProofService:
    """Micro-batching proof server (in-process API).

    ``store`` + ``spec`` enable the generate path (omit both for a
    verify-only service); ``trust_policy`` defaults to accept-all, which —
    as everywhere else in this repo — is for development and tests only.
    """

    def __init__(
        self,
        store=None,
        spec=None,
        trust_policy: Optional[TrustPolicy] = None,
        event_filter=None,
        config: Optional[ServiceConfig] = None,
        metrics: Optional[Metrics] = None,
        endpoint_pool=None,
    ):
        self.config = config or ServiceConfig()
        self.metrics = metrics if metrics is not None else Metrics()
        self._trust = trust_policy or TrustPolicy.accept_all()
        self._event_filter = event_filter
        self._spec = spec
        # optional store.failover.EndpointPool: when the backing store is
        # RPC-fed, /healthz reports per-endpoint breaker state through it
        self._endpoint_pool = endpoint_pool
        self.block_cache = BlockCache(
            max_bytes=self.config.cache_max_bytes, ttl_s=self.config.cache_ttl_s
        )
        # async fetch plane: interpose between the local tiers and an
        # RPC-fed store so concurrent request walkers' block fetches ride
        # shared JSON-RPC batches and walker-offered links prefetch
        # speculatively. Only a store that exposes its chain client
        # (RpcBlockstore.client) gets a plane — plain stores (demo worlds,
        # memory fixtures) keep the direct path.
        self.fetch_plane = None
        plane_client = getattr(store, "client", None)
        if store is not None and plane_client is not None and self.config.batch_rpc:
            from ipc_proofs_tpu.store.fetchplane import FetchPlane, PlaneBlockstore

            self.fetch_plane = FetchPlane(
                plane_client,
                speculate_depth=self.config.speculate_depth,
                metrics=self.metrics,
                batch_verify=self.config.batch_verify,
            )
            store = PlaneBlockstore(self.fetch_plane)
        if self.config.batch_verify and self.config.store_dir:
            # per-host verify-lane crossover: first daemon on a host
            # measures and persists verify_autotune.json under the store
            # dir, later ones load it (env IPC_VERIFY_MIN_BYTES overrides)
            from ipc_proofs_tpu.ops.verify_jax import autotune_crossover

            try:
                autotune_crossover(self.config.store_dir)
            except Exception:  # fail-soft: serving must come up on the default crossover if tuning fails
                pass
        self._disk_store = None
        if store is not None and self.config.store_dir:
            from ipc_proofs_tpu.storex import SegmentStore, TieredBlockstore

            self._disk_store = SegmentStore(
                self.config.store_dir,
                cap_bytes=self.config.store_cap_bytes,
                segment_max_bytes=self.config.store_segment_max_bytes,
                metrics=self.metrics,
                owner=self.config.store_owner,
                batch_verify=self.config.batch_verify,
            )
            self._store = TieredBlockstore(
                store,
                self._disk_store,
                cache=self.block_cache,
                metrics=self.metrics,
            )
        elif store is not None:
            self._store = CachedBlockstore(store, shared_cache=self.block_cache)
        else:
            self._store = None
        if self.fetch_plane is not None:
            # the plane's tier short-circuit reads the SAME local tiers
            # that sit above it (both TieredBlockstore and CachedBlockstore
            # expose get_local/has_local/put_local that never touch their
            # inner store, so this is not circular): wants satisfiable
            # locally never reach the queue, landings deposit for next time
            self.fetch_plane.set_local(self._store)
        # on-chip half: the generate-range drivers offload event matching
        # (and, under a mesh, shard each coalesced batch across devices)
        self._match_backend = None
        if self.config.match_backend:
            from ipc_proofs_tpu.backend import get_backend

            self._match_backend = get_backend(
                self.config.match_backend, mesh_devices=self.config.mesh_devices
            )
        # witness plane: every served bundle registers here under its
        # canonical digest so later requests can name it as a delta base
        self.witness_bases = WitnessBaseCache(cap=self.config.witness_base_cache)
        # provenance registry: seals every served bundle into the
        # hash-linked audit chain, and (as the fleet base directory)
        # front-ends the local base cache so a digest served by ANY
        # sibling shard still resolves here after a failover
        self.registry = None
        if self.config.registry_dir:
            from ipc_proofs_tpu.registry import ProvenanceRegistry
            from ipc_proofs_tpu.witness.bases import FleetBaseCache

            self.registry = ProvenanceRegistry(
                self.config.registry_dir,
                owner=self.config.registry_owner,
                metrics=self.metrics,
                fsync=self.config.registry_fsync,
            )
            self.witness_bases = FleetBaseCache(
                self.witness_bases, self.registry, metrics=self.metrics
            )
        self._executor = ThreadPoolExecutor(
            max_workers=self.config.workers, thread_name_prefix="proof-serve"
        )
        self._drain_lock = named_lock("ProofService._drain_lock")
        self._drained = False  # guarded-by: _drain_lock
        self._verify_batcher = MicroBatcher(
            self._flush_verify,
            max_batch=self.config.max_batch,
            max_wait_ms=self.config.max_wait_ms,
            capacity=self.config.queue_capacity,
            name="verify",
            metrics=self.metrics,
            executor=self._executor,
            tenant_weights=self.config.tenant_weights,
        )
        self._generate_batcher = (
            MicroBatcher(
                self._flush_generate,
                max_batch=self.config.max_batch,
                max_wait_ms=self.config.max_wait_ms,
                capacity=self.config.queue_capacity,
                name="generate",
                metrics=self.metrics,
                executor=self._executor,
                tenant_weights=self.config.tenant_weights,
            )
            if self._store is not None and self._spec is not None
            else None
        )

    # --- public API --------------------------------------------------------

    def submit_verify(
        self,
        bundle: UnifiedProofBundle,
        timeout_s: Optional[float] = None,
        tenant: Optional[str] = None,
        cancel_scope=None,
    ) -> PendingResult:
        """Admit one verify request; returns immediately with a pending slot.

        Raises `QueueFullError` / `ServiceClosedError` at admission time;
        ``.result()`` raises `DeadlineExceededError` if ``timeout_s`` passes
        before the batch containing it is processed. ``cancel_scope`` rides
        the queue: a cancelled member is dropped typed at dispatch."""
        return self._verify_batcher.submit(
            bundle, timeout_s=timeout_s, tenant=tenant, cancel_scope=cancel_scope
        )

    def verify(
        self,
        bundle: UnifiedProofBundle,
        timeout_s: Optional[float] = None,
        tenant: Optional[str] = None,
        cancel_scope=None,
    ) -> VerifyResponse:
        """Blocking verify: submit and wait for the micro-batched verdict."""
        return self.submit_verify(
            bundle, timeout_s=timeout_s, tenant=tenant, cancel_scope=cancel_scope
        ).result()

    def submit_generate(
        self,
        pair: TipsetPair,
        timeout_s: Optional[float] = None,
        tenant: Optional[str] = None,
        cancel_scope=None,
    ) -> PendingResult:
        if self._generate_batcher is None:
            raise RuntimeError(
                "generate path disabled: service was built without store/spec"
            )
        return self._generate_batcher.submit(
            _GenerateRequest(pair),
            timeout_s=timeout_s,
            tenant=tenant,
            cancel_scope=cancel_scope,
        )

    def generate(
        self,
        pair: TipsetPair,
        timeout_s: Optional[float] = None,
        tenant: Optional[str] = None,
        cancel_scope=None,
    ) -> GenerateResponse:
        return self.submit_generate(
            pair, timeout_s=timeout_s, tenant=tenant, cancel_scope=cancel_scope
        ).result()

    def submit_range_window(
        self,
        pairs: Sequence[TipsetPair],
        chunk_size: Optional[int] = None,
        timeout_s: Optional[float] = None,
        lane: str = "low",
        spec=None,
        storage_specs=None,
        tenant: Optional[str] = None,
        cancel_scope=None,
    ) -> PendingResult:
        """Admit one range window on the generate batcher's LOW (default)
        or PUSH lane.

        LOW is the `BackfillEngine` runner: the window waits behind ALL
        interactive verify/generate traffic and executes as one canonical
        chunked-driver call — a saturating backfill job can never starve
        ``/v1/verify``. PUSH is the standing-query matcher's lane: the
        window jumps AHEAD of interactive batches (a subscriber
        notification is already late by one finality delay) while still
        riding the same admission bounds and the same canonical driver,
        so pushed bundles stay byte-identical to request/response ones.
        ``spec``/``storage_specs`` override the service spec per window
        (the matcher generates one distinct filter per push)."""
        if self._generate_batcher is None:
            raise RuntimeError(
                "generate path disabled: service was built without store/spec"
            )
        return self._generate_batcher.submit(
            _RangeWindowRequest(list(pairs), chunk_size, spec, storage_specs),
            timeout_s=timeout_s,
            tenant=tenant,
            lane=lane if lane == "push" else "low",
            cancel_scope=cancel_scope,
        )

    def generate_range(
        self, pairs: Sequence[TipsetPair], chunk_size: Optional[int] = None
    ) -> UnifiedProofBundle:
        """One canonical range bundle for an explicit pair list.

        This is the scatter-gather sub-request: the cluster router already
        grouped pairs per shard, so it calls straight through to the
        chunked range driver instead of the micro-batcher (re-batching an
        already-batched group would only add latency). The chunked driver
        is the canonical comparator — its bundle is byte-identical to the
        single-daemon run over the same pairs, which is what lets
        `cluster.gather.merge_range_bundles` reassemble shard outputs
        into the exact single-process bytes.
        """
        if self._store is None or self._spec is None:
            raise RuntimeError(
                "generate path disabled: service was built without store/spec"
            )
        if self.draining:
            raise ServiceClosedError("service is draining")
        pairs = list(pairs)
        if not pairs:
            raise RuntimeError("generate_range needs at least one pair")
        with self.metrics.stage("serve.generate_batch"):
            bundle = generate_event_proofs_for_range_chunked(
                self._store,
                pairs,
                self._spec,
                chunk_size=chunk_size or self.config.range_chunk_size,
                metrics=self.metrics,
                match_backend=self._match_backend,
            )
        self.metrics.count("serve.batches.generate")
        return bundle

    @property
    def draining(self) -> bool:
        return self._verify_batcher.closed

    def health(self) -> dict:
        """Liveness summary for `/healthz`.

        ``"draining"`` once shutdown started (stop routing traffic here);
        ``"degraded"`` when the endpoint pool has an open/half-open breaker
        (still serving — from the remaining endpoints — but worth paging
        on); ``"ok"`` otherwise. Includes per-endpoint breaker state when a
        pool is attached."""
        if self.draining:
            return {"status": "draining"}
        out = (
            self._endpoint_pool.health()
            if self._endpoint_pool is not None
            else {"status": "ok"}
        )
        if self.registry is not None:
            out = dict(
                out,
                registry="degraded" if self.registry.degraded else "ok",
                registry_head=self.registry.head(),
            )
        return out

    @property
    def lotus_down(self) -> bool:
        """True while every pool endpoint's breaker is open (degraded
        serve mode: warm-tier requests still produce bit-identical
        bundles; cold requests fail fast typed ``degraded``)."""
        return self._endpoint_pool is not None and bool(
            getattr(self._endpoint_pool, "lotus_down", False)
        )

    @property
    def blockstore(self):
        """The service's layered store (tiered when ``store_dir`` is set) —
        the `ChainFollower` prefetches into exactly this object so demand
        traffic and the follower share one warm tier."""
        return self._store

    def read_block_slice(self, cid):
        """Zero-copy block read for the streaming wire: a CRC-verified
        ``memoryview`` straight out of the disk tier's segment frame, or
        None (no disk tier, cold block, or a frame that vanished under a
        concurrent eviction — the streamer falls back to the in-memory
        copy it already holds and counts the copied bytes honestly)."""
        if self._disk_store is None:
            return None
        return self._disk_store.read_frame_slice(cid)

    # --- replication plane (storex.replica) --------------------------------

    @property
    def disk_store(self):
        """The tier-2 `SegmentStore` (None without ``store_dir``) — the
        replication plane's unit of transfer is its segment files."""
        return self._disk_store

    def set_replica_peers(self, peers: "Sequence[dict]") -> None:
        """Install/replace the read-repair peer set (the router's
        ``POST /v1/replica_peers`` body: ``[{"name", "url"}, ...]``).
        From then on a local frame that fails CRC/multihash repairs from
        a peer before the inner store is ever consulted."""
        from ipc_proofs_tpu.storex import ReplicaClient, ReplicaSet

        if self._disk_store is None:
            raise RuntimeError("replication needs a disk tier (--store-dir)")
        clients = [ReplicaClient(p["name"], p["url"]) for p in peers]
        # self._store is a TieredBlockstore whenever a disk tier exists
        self._store.set_replicas(ReplicaSet(clients, metrics=self.metrics))

    def replicate_from(
        self, sources: "Sequence[dict]", owners=None
    ) -> dict:
        """Pull-sync segment files from peer shards (the router's
        ``POST /v1/replicate``). ``sources`` is ``[{"name", "url"}, ...]``;
        ``owners`` optionally restricts the pull to those owner tokens
        (the ring arcs this shard is replica for). Per-source failure is
        fail-soft — the error string lands in ``errors`` and the other
        sources still sync."""
        from ipc_proofs_tpu.storex import ReplicaClient, ReplicaError, Replicator

        if self._disk_store is None:
            raise RuntimeError("replication needs a disk tier (--store-dir)")
        rep = Replicator(self._disk_store, metrics=self.metrics)
        out = {"pulled": 0, "bytes": 0, "blocks": 0, "pending": 0, "errors": []}
        for src in sources:
            try:
                r = rep.sync_from(
                    ReplicaClient(src["name"], src["url"]), owners=owners
                )
            except (ReplicaError, KeyError, TypeError) as exc:
                out["errors"].append(str(exc))
                continue
            for k in ("pulled", "bytes", "blocks", "pending"):
                out[k] += r[k]
        return out

    def read_block_local(self, cid_str: str) -> "Optional[bytes]":
        """One block from the LOCAL tiers only (``GET /v1/blocks/<cid>``):
        never consults the inner store, so a peer's read-repair can never
        launder an upstream (Lotus) fetch through this shard. None = 404
        (unparseable CID included — an address we can't hold bytes for)."""
        if self._store is None:
            return None
        try:
            cid = CID.parse(cid_str)
        except (ValueError, KeyError, TypeError):
            return None
        get_local = getattr(self._store, "get_local", None)
        if get_local is None:
            return None
        return get_local(cid)

    @property
    def match_backend(self):
        """The resolved device match backend (None on the host path) —
        the standing-query matcher generates through the same backend so
        streamed bundles are byte-identical to request/response ones."""
        return self._match_backend

    def metrics_snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        snap["block_cache"] = self.block_cache.stats()
        if self._store is not None:
            snap["block_cache"]["hits"] = self._store.hits
            snap["block_cache"]["misses"] = self._store.misses
        if self._disk_store is not None:
            snap["disk_store"] = self._disk_store.stats()
        return snap

    def drain(self, timeout: Optional[float] = None) -> None:
        """Graceful shutdown: stop admitting, flush everything accepted,
        wait for in-flight batches, release the worker pool. Idempotent."""
        with self._drain_lock:
            if self._drained:
                return
            self._drained = True
        self._verify_batcher.close(drain=True, timeout=timeout)
        if self._generate_batcher is not None:
            self._generate_batcher.close(drain=True, timeout=timeout)
        self._executor.shutdown(wait=True)
        if self.fetch_plane is not None:
            self.fetch_plane.close()
        if self._disk_store is not None:
            self._disk_store.close()
        if self.registry is not None:
            self.registry.close()

    close = drain

    def __enter__(self) -> "ProofService":
        return self

    def __exit__(self, *exc) -> None:
        self.drain()

    # --- per-request latency attribution -----------------------------------

    def _request_timing(
        self, pending: PendingResult, exec_start: float, now: float, exec_key: str
    ) -> dict:
        """queue_ms (admission → batch dispatch) + batch_wait_ms (dispatch →
        execution start on a worker) + <exec_key> (batch execution): the
        components cover the admission→completion interval end to end."""
        dispatched = pending.dispatched_at or exec_start
        return {
            "queue_ms": round(max(0.0, dispatched - pending.enqueued_at) * 1e3, 3),
            "batch_wait_ms": round(max(0.0, exec_start - dispatched) * 1e3, 3),
            exec_key: round(max(0.0, now - exec_start) * 1e3, 3),
        }

    def _maybe_log_slow(
        self, pending: PendingResult, kind: str, total_ms: float, timing: dict
    ) -> None:
        if total_ms <= self.config.slow_request_ms:
            return
        self.metrics.count("serve.slow_requests")
        trace_id = pending.trace_ctx.trace_id if pending.trace_ctx else ""
        tree = ""
        if trace_id:
            spans = spans_for_trace(trace_id)
            if spans:
                tree = "\n" + format_span_tree(spans)
        log.warning(
            "slow %s request: %.1fms (threshold %.0fms) trace_id=%s timing=%s%s",
            kind,
            total_ms,
            self.config.slow_request_ms,
            trace_id or "-",
            timing,
            tree,
        )

    # --- verify batching ---------------------------------------------------

    def _flush_verify(self, batch: list[PendingResult]) -> None:
        """Merge → one `verify_proof_bundle` → split verdicts by span.

        Conflicting witness blocks (same CID, different bytes) partition
        the batch greedily: each request joins the current merge unless one
        of its blocks contradicts a block already merged, in which case it
        starts/joins a later sub-merge. Verdicts are unaffected — a merge
        only ever contains mutually consistent witnesses, and within one
        merge identical CIDs carry identical bytes, so deduplication is
        lossless."""
        remaining = batch
        while remaining:
            merged: list[PendingResult] = []
            deferred: list[PendingResult] = []
            by_cid: dict = {}
            for pending in remaining:
                bundle: UnifiedProofBundle = pending.payload
                conflict = any(
                    by_cid.get(b.cid, b.data) != b.data for b in bundle.blocks
                )
                if conflict:
                    deferred.append(pending)
                else:
                    for b in bundle.blocks:
                        by_cid.setdefault(b.cid, b.data)
                    merged.append(pending)
            self._verify_merged(merged)
            remaining = deferred

    def _verify_merged(self, merged: list[PendingResult]) -> None:
        exec_start = monotonic()
        storage_proofs: list = []
        event_proofs: list = []
        blocks: list[ProofBlock] = []
        seen: set = set()
        spans: list[tuple[int, int, int, int]] = []
        for pending in merged:
            bundle: UnifiedProofBundle = pending.payload
            s0, e0 = len(storage_proofs), len(event_proofs)
            storage_proofs.extend(bundle.storage_proofs)
            event_proofs.extend(bundle.event_proofs)
            for b in bundle.blocks:
                if b.cid not in seen:
                    seen.add(b.cid)
                    blocks.append(b)
            spans.append((s0, len(storage_proofs), e0, len(event_proofs)))

        # the batch executes once, under the OLDEST member's trace: its
        # request tree gets the full execution spans, while every member
        # still gets its own server_timing/trace_id from its timestamps
        with use_context(merged[0].trace_ctx):
            with self.metrics.stage("serve.verify_batch"):
                result = verify_proof_bundle(
                    UnifiedProofBundle(
                        storage_proofs=storage_proofs,
                        event_proofs=event_proofs,
                        blocks=blocks,
                    ),
                    self._trust,
                    event_filter=self._event_filter,
                    verify_witness_cids=self.config.verify_witness_cids,
                )
        self.metrics.count("serve.batches.verify")

        now = monotonic()
        slow: list[tuple[PendingResult, float, dict]] = []
        for pending, (s0, s1, e0, e1) in zip(merged, spans):
            total_ms = (now - pending.enqueued_at) * 1e3
            timing = self._request_timing(pending, exec_start, now, "verify_ms")
            self.metrics.observe("serve.latency_ms.verify", total_ms)
            pending.complete(
                VerifyResponse(
                    storage_results=result.storage_results[s0:s1],
                    event_results=result.event_results[e0:e1],
                    batch_size=len(merged),
                    server_timing=timing,
                    trace_id=(
                        pending.trace_ctx.trace_id if pending.trace_ctx else ""
                    ),
                )
            )
            if total_ms > self.config.slow_request_ms:
                slow.append((pending, total_ms, timing))
        for pending, total_ms, timing in slow:
            self._maybe_log_slow(pending, "verify", total_ms, timing)

    # --- generate batching -------------------------------------------------

    def _batch_job_dir(self, unique: dict) -> Optional[str]:
        """Per-batch journal dir under ``config.range_job_dir``.

        A job manifest binds its directory to one exact request (spec +
        pair range), so each distinct batch composition needs its own
        subdirectory; the key digest makes a re-submitted identical batch
        land on the same journal and resume instead of regenerate.
        """
        root = self.config.range_job_dir
        if not root:
            return None
        ident = hashlib.sha256(repr(sorted(unique)).encode()).hexdigest()[:16]
        path = os.path.join(root, f"batch-{ident}")
        os.makedirs(path, exist_ok=True)
        return path

    def _flush_generate(self, batch: list[PendingResult]) -> None:
        """Deduplicate pairs → one range-driver call → split proofs by pair."""
        if isinstance(batch[0].payload, _RangeWindowRequest):
            # lanes assemble exclusively from themselves, so a batch is
            # either all interactive pairs or all windows (low OR push)
            self._flush_range_windows(batch)
            return
        exec_start = monotonic()
        unique: dict[tuple, TipsetPair] = {}
        for pending in batch:
            req: _GenerateRequest = pending.payload
            unique.setdefault(req.key, req.pair)
        pairs = list(unique.values())

        job_dir = self._batch_job_dir(unique)
        journal_us0 = self.metrics.counter_value("jobs.chunk_journal_us")
        # a coalesced batch shares one driver call, so cooperative abort is
        # only safe when the whole batch is one request's work — a shared
        # batch must finish for the members that did NOT cancel
        batch_scope = batch[0].cancel_scope if len(batch) == 1 else None
        with use_context(batch[0].trace_ctx), use_scope(batch_scope):
            with self.metrics.stage("serve.generate_batch"):
                if len(pairs) > 1:
                    # multi-pair batch: stage-overlapped engine (bit-identical
                    # output; scan of later chunks overlaps recording)
                    bundle = generate_event_proofs_for_range_pipelined(
                        self._store,
                        pairs,
                        self._spec,
                        chunk_size=self.config.range_chunk_size,
                        metrics=self.metrics,
                        scan_threads=self.config.range_scan_threads,
                        threads=self.config.threads,
                        pipeline_depth=self.config.range_pipeline_depth,
                        job_dir=job_dir,
                        match_backend=self._match_backend,
                    )
                elif job_dir is not None:
                    # journalled single-pair path: the chunked driver is the
                    # serial engine plus write-ahead chunk commits
                    bundle = generate_event_proofs_for_range_chunked(
                        self._store,
                        pairs,
                        self._spec,
                        chunk_size=self.config.range_chunk_size,
                        metrics=self.metrics,
                        job_dir=job_dir,
                        match_backend=self._match_backend,
                    )
                else:
                    bundle = generate_event_proofs_for_range(
                        self._store,
                        pairs,
                        self._spec,
                        metrics=self.metrics,
                        match_backend=self._match_backend,
                    )
        self.metrics.count("serve.batches.generate")
        if self.lotus_down:
            # the whole batch was satisfied from warm local tiers while
            # every upstream breaker is open — degraded mode's success path
            self.metrics.count("degraded.warm_served", len(batch))
        # Wall-clock microseconds the range driver spent journalling chunk
        # commits while this batch executed (one flush thread drives the
        # generate queue, so the counter delta is this batch's journalling)
        journal_us = (
            self.metrics.counter_value("jobs.chunk_journal_us") - journal_us0
        )

        by_key: dict[tuple, list] = {key: [] for key in unique}
        # EventProof pins (parent_tipset_cids, child_block_cid); a child
        # block cid identifies its pair within one batch
        child_block_to_key: dict[str, tuple] = {}
        for key, pair in unique.items():
            for c in pair.child.cids:
                child_block_to_key[str(c)] = key
        for proof in bundle.event_proofs:
            by_key[child_block_to_key[proof.child_block_cid]].append(proof)

        now = monotonic()
        slow: list[tuple[PendingResult, float, dict]] = []
        for pending in batch:
            req = pending.payload
            total_ms = (now - pending.enqueued_at) * 1e3
            timing = self._request_timing(pending, exec_start, now, "generate_ms")
            if journal_us > 0:
                timing["journal_ms"] = round(journal_us / 1e3, 3)
            self.metrics.observe("serve.latency_ms.generate", total_ms)
            pending.complete(
                GenerateResponse(
                    bundle=UnifiedProofBundle(
                        storage_proofs=[],
                        event_proofs=list(by_key[req.key]),
                        blocks=bundle.blocks,
                    ),
                    batch_size=len(batch),
                    server_timing=timing,
                    trace_id=(
                        pending.trace_ctx.trace_id if pending.trace_ctx else ""
                    ),
                )
            )
            if total_ms > self.config.slow_request_ms:
                slow.append((pending, total_ms, timing))
        for pending, total_ms, timing in slow:
            self._maybe_log_slow(pending, "generate", total_ms, timing)

    def _flush_range_windows(self, batch: list[PendingResult]) -> None:
        """Execute backfill/push windows: one canonical chunked-driver
        call per window (byte-identical to the same pairs served
        interactively). Windows fail individually — one bad window never
        poisons its batch neighbors' jobs."""
        for pending in batch:
            req: _RangeWindowRequest = pending.payload
            try:
                with use_context(pending.trace_ctx), use_scope(
                    pending.cancel_scope
                ):
                    with self.metrics.stage("serve.backfill_window"):
                        bundle = generate_event_proofs_for_range_chunked(
                            self._store,
                            req.pairs,
                            req.spec if req.spec is not None else self._spec,
                            chunk_size=req.chunk_size or len(req.pairs),
                            metrics=self.metrics,
                            match_backend=self._match_backend,
                            storage_specs=req.storage_specs,
                        )
            except BaseException as exc:  # fail-soft: the window's job sees the error; other windows proceed
                pending.fail(exc)
                continue
            if self.lotus_down:
                self.metrics.count("degraded.warm_served")
            pending.complete(bundle)


def sequential_verify_baseline(
    bundles: Sequence[UnifiedProofBundle],
    trust_policy: Optional[TrustPolicy] = None,
    event_filter=None,
) -> list[VerifyResponse]:
    """The per-request comparator: one `verify_proof_bundle` call per
    request, no coalescing. The serve bench leg and the bit-identical
    concurrency test measure the micro-batcher against exactly this."""
    trust = trust_policy or TrustPolicy.accept_all()
    out = []
    for bundle in bundles:
        result = verify_proof_bundle(bundle, trust, event_filter=event_filter)
        out.append(
            VerifyResponse(
                storage_results=result.storage_results,
                event_results=result.event_results,
                batch_size=1,
            )
        )
    return out
