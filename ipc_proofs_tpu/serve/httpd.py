"""JSON-over-HTTP front end for `ProofService` (stdlib `http.server` only).

A deliberately thin shim: every serving decision — batching, admission,
deadlines, drain — lives in `serve/service.py`; this module only maps HTTP
to the in-process API and serving errors to status codes:

- ``POST /v1/verify``  → `QueueFullError` ⇒ 503 + ``Retry-After``,
  `ServiceClosedError` ⇒ 503 (draining), `DeadlineExceededError` ⇒ 504,
  malformed bundle ⇒ 400.
- ``POST /v1/generate`` → same mapping; the request names a tipset pair by
  index into the server's configured pair table (the hermetic/demo mode —
  a production deployment would resolve pairs from its chain store).
- ``POST /v1/generate_range`` → multi-pair canonical range bundle for an
  explicit ``pair_indexes`` list — the scatter-gather sub-request the
  cluster router dispatches (see `cluster/router.py`). With
  ``"aggregate": true`` the index list may repeat (K co-tipset claims):
  ONE canonical bundle over the distinct pairs comes back with a
  ``claims`` span table (`ipc_proofs_tpu/witness/`). A ``trace``
  carrier in any POST body parents this request's spans under the remote
  caller's span (`obs.adopted_span`).

Witness negotiation (README "Witness diet"): generate bodies may carry
``witness_encoding`` (or an ``Accept-Witness-Encoding`` header) and
``base_digest`` (or ``If-Witness-Base``); the chosen encoding is echoed
in the ``witness_encoding`` field AND a ``Witness-Encoding`` header, an
unknown encoding is a typed 400 (``error_type: witness_encoding``), and
an unknown delta base falls back to a full bundle
(``witness.delta_fallbacks``). ``POST /v1/verify`` accepts plain or
``blocks_frame``-compressed bundles plus an optional ``claims`` table for
per-claim verdicts out of one shared replay.

Streaming wire (README "Streaming wire & tenant QoS"): generate bodies
may carry ``"stream": true`` (or ``Accept:
application/x-ipc-bundle-stream``) to receive the chunked binary IPBS
stream (`witness/stream.py`) instead of a buffered JSON body — on a
disk-warm daemon the block section is ``memoryview`` slices straight out
of `SegmentStore` segments, handed to ``socket.sendmsg`` without copying
through Python. ``GET /v1/backfill/<id>/chunks`` under the same Accept
header streams one document per result chunk. Per-tenant QoS
(``--tenant-rate`` / ``--tenant-burst``) throttles at admission with a
typed 429 + ``Retry-After`` (`serve/qos.py`); response bytes charge the
tenant ledger at send time, streamed chunks included.
- ``GET /metrics``  → `utils/metrics.py` snapshot (stage timers, queue
  depths, batch sizes, p50/p90/p99 latency, rejection counters) as JSON.
- ``GET /metrics.prom`` → the same snapshot in Prometheus text exposition
  format (`obs/prom.py`) for a stock Prometheus scraper.
- ``GET /debug/flight`` → the always-on flight recorder: last N completed
  spans + recent WARN/ERROR log records (`obs/flight.py`).
- ``GET /healthz``  → ``{"status": "ok" | "degraded" | "draining"}``; with
  an `EndpointPool` attached, ``"degraded"`` means some endpoint's circuit
  breaker is open/half-open and the body carries per-endpoint breaker
  state (still HTTP 200 — the service itself serves from what remains;
  draining stays 503). With a follower attached the body carries
  ``last_finalized_epoch``; with standing queries, subscription/delivery
  gauges.
- ``POST /v1/subscribe`` / ``POST /v1/unsubscribe`` /
  ``GET /v1/subscriptions`` / ``GET /v1/deliveries?sub=<id>&cursor=<n>``
  → the standing-query plane (`ipc_proofs_tpu/subs/`), mounted when the
  server is built with ``subs=`` (``serve --subs-dir``). Deliveries is
  the long-poll fallback to webhook push; asking from cursor N acks
  everything ≤ N.

Every POST opens a trace root span (`obs/trace.py`) on the handler thread
before admission, so batching/execution spans parent into the request's
trace; 200 responses carry ``trace_id`` + ``server_timing`` in the body
and a standards-shaped ``Server-Timing`` header.

With a `DurableAdmission` queue attached (``serve --queue-dir``), POSTs
route through the journal: the request is fsync'd before execution, an
``idempotency_key`` in the body dedupes client retries (the response
carries ``idempotency_key`` and ``cached``), and ``/healthz`` reports
``resumed_jobs`` / ``journal_bytes`` from the queue journal.

`ThreadingHTTPServer` gives one thread per connection; those threads do no
proof work — they block on ``PendingResult.result()`` while the service's
worker pool executes batches, so slow clients never stall batch execution.
"""

from __future__ import annotations

import json
import os
import select
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence
from urllib.parse import parse_qs, unquote, urlsplit

from ipc_proofs_tpu.obs.fleet import (
    TenantLedger,
    extract_tenant,
    subtree_for_response,
)
from ipc_proofs_tpu.obs.flight import get_flight_recorder
from ipc_proofs_tpu.obs.prom import CONTENT_TYPE as _PROM_CONTENT_TYPE
from ipc_proofs_tpu.obs.prom import render_prometheus
from ipc_proofs_tpu.obs.trace import adopted_span, tracing_enabled
from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
from ipc_proofs_tpu.proofs.range import TipsetPair
from ipc_proofs_tpu.serve.batcher import (
    QueueFullError,
    ServiceClosedError,
)
from ipc_proofs_tpu.serve.qos import (
    AdmitRejectedError,
    GradientLimiter,
    TenantQoS,
    TenantThrottledError,
)
from ipc_proofs_tpu.serve.service import ProofService
from ipc_proofs_tpu.store.failover import DegradedError
from ipc_proofs_tpu.storex import SegmentStoreError
from ipc_proofs_tpu.utils.deadline import (
    CancelledError,
    CancelScope,
    Deadline,
    DeadlineError,
    use_scope,
)
from ipc_proofs_tpu.witness import (
    AggregatedBundle,
    WitnessEncodingError,
    WitnessError,
    aggregate_range_bundle,
    encode_bundle_fields,
    negotiate_witness,
    parse_bundle_obj,
)
from ipc_proofs_tpu.witness.stream import (
    CHUNKED_TERMINATOR,
    STREAM_CONTENT_TYPE,
    BundleStreamWriter,
    negotiate_stream,
    send_buffers,
    stream_backfill_chunks,
    stream_bundle_doc,
)

__all__ = ["ProofHTTPServer"]

_MAX_BODY_BYTES = 64 * 1024 * 1024  # one bundle; far above any sane request
# how often a handler thread blocked on a pending result checks whether the
# client hung up (EOF on the socket) — the window between a disconnect and
# the in-flight work being cancelled
_DISCONNECT_POLL_S = 0.1


class _Handler(BaseHTTPRequestHandler):
    # set per server subclass via ProofHTTPServer
    service: ProofService
    pairs: Sequence[TipsetPair]
    durable = None  # Optional[DurableAdmission]
    subs = None  # Optional[subs.StandingQueries]
    slo = None  # Optional[obs.slo.SloWatchdog]
    tenants = None  # Optional[obs.fleet.TenantLedger]
    qos = None  # Optional[serve.qos.TenantQoS]
    admit = None  # Optional[serve.qos.GradientLimiter] (--admit-gradient)

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        pass

    # --- plumbing ----------------------------------------------------------

    def _send_json(self, status: int, obj: dict, headers: Optional[dict] = None):
        body = json.dumps(obj).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)
        # response bytes charge the tenant AT SEND TIME (the streamed path
        # does the same with the writer's byte count), so ``tenant.bytes.*``
        # reflects what actually crossed the wire, not just request bodies
        if getattr(self, "_account_response", False) and self.tenants is not None:
            self.tenants.account_bytes(self._tenant, len(body))

    # --- streamed responses (application/x-ipc-bundle-stream) -------------

    def _start_stream(self, encoding: str) -> None:
        """200 + chunked transfer for an IPBS body. No Content-Length (the
        length is unknown until the last shard/window lands) and no
        Server-Timing header — the timing breakdown rides the trailer
        chunk instead, where ``stream_ms`` can be measured."""
        self.send_response(200)
        self.send_header("Content-Type", STREAM_CONTENT_TYPE)
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Witness-Encoding", encoding)
        self.end_headers()
        self.wfile.flush()

    def _send_buffers(self, buffers) -> None:
        """One HTTP chunk, scatter-gather, straight to the socket —
        `witness.stream.send_buffers` (memoryview payloads go mmap →
        kernel with no Python-side copy)."""
        send_buffers(self.connection, buffers)

    def _stream_ok(self, stream_fn, encoding: str) -> None:
        """Send one streamed 200. ``stream_fn(writer)`` emits the
        document(s); an exception after the status line is gone becomes a
        typed in-band ``E`` chunk (`StreamAbortError` client-side) — the
        byte-identical-or-typed-error invariant past the point where HTTP
        status codes can carry it."""
        self._start_stream(encoding)
        writer = BundleStreamWriter(
            self._send_buffers, metrics=self.service.metrics
        )
        try:
            stream_fn(writer)
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True
            return
        except WitnessError as exc:
            writer.error(str(exc), exc.error_type)
        except Exception as exc:  # fail-soft: headers are already on the wire — the only sound exit is an in-band typed abort chunk, never a half-document
            writer.error(str(exc), "internal")
        try:
            self.connection.sendall(CHUNKED_TERMINATOR)
        except OSError:
            pass
        self.service.metrics.count("serve.stream.responses")
        if getattr(self, "_account_response", False) and self.tenants is not None:
            self.tenants.account_bytes(self._tenant, writer.bytes_sent)
        # one stream per connection: don't risk framing drift poisoning a
        # keep-alive successor request
        self.close_connection = True

    def _send_text(self, status: int, text: str, content_type: str):
        body = text.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    @staticmethod
    def _server_timing_header(timing: dict) -> str:
        """RFC-shaped Server-Timing value: ``queue;dur=1.2, verify;dur=3.4``
        (metric names come from the server_timing dict, ``_ms`` stripped)."""
        parts = []
        for key, value in timing.items():
            name = key[:-3] if key.endswith("_ms") else key
            parts.append(f"{name};dur={value}")
        return ", ".join(parts)

    def _negotiate_witness(self, body: dict):
        """Resolve the request's witness options (encoding, delta base).

        Unknown/disabled encodings are a TYPED 400 (``error_type:
        witness_encoding`` + ``witness.encoding_rejects``), never a silent
        plain response; returns None after sending the error."""
        cfg = self.service.config
        try:
            return negotiate_witness(
                body,
                headers=self.headers,
                allow_compress=cfg.witness_compress,
                allow_delta=cfg.witness_delta,
            )
        except WitnessEncodingError as exc:
            self.service.metrics.count("witness.encoding_rejects")
            self._send_json(400, {"error": str(exc), "error_type": exc.error_type})
            return None

    def _negotiate_stream(self, body: dict) -> Optional[bool]:
        """Resolve whether this response goes out as an IPBS chunk stream
        (body ``"stream"`` wins, else the ``Accept`` header). Returns
        None after sending the typed 400 for a malformed field."""
        try:
            return negotiate_stream(body, headers=self.headers)
        except WitnessEncodingError as exc:
            self.service.metrics.count("witness.encoding_rejects")
            self._send_json(400, {"error": str(exc), "error_type": exc.error_type})
            return None

    def _witness_fields(self, bundle, opts, claims=None) -> dict:
        return encode_bundle_fields(
            bundle,
            opts,
            bases=self.service.witness_bases,
            metrics=self.service.metrics,
            claims=claims,
        )

    def _read_json_body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0 or length > _MAX_BODY_BYTES:
            raise ValueError(f"Content-Length required, 0 < n <= {_MAX_BODY_BYTES}")
        self._body_bytes = length  # tenant byte accounting reads this
        obj = json.loads(self.rfile.read(length))
        if not isinstance(obj, dict):
            raise ValueError("request body must be a JSON object")
        return obj

    # --- routes ------------------------------------------------------------

    def do_GET(self):
        path = urlsplit(self.path).path
        if path in ("/metrics", "/metrics.json"):
            # /metrics.json is the federation scrape surface: the raw
            # snapshot dict the router's fleet view merges per shard
            self._send_json(200, self.service.metrics_snapshot())
        elif path == "/metrics.prom":
            self._send_text(
                200,
                render_prometheus(self.service.metrics.snapshot()),
                _PROM_CONTENT_TYPE,
            )
        elif path == "/debug/flight":
            self._send_json(200, get_flight_recorder().snapshot())
        elif path == "/healthz":
            health = self.service.health()
            if self.durable is not None:
                health.update(self.durable.health_fields())
            if self.subs is not None:
                health.update(self.subs.health_fields())
            epoch = self.service.metrics.snapshot().get("gauges", {}).get(
                "follow.last_finalized_epoch"
            )
            if epoch is not None:
                health["last_finalized_epoch"] = int(epoch)
            if self.slo is not None:
                health["slo"] = self.slo.status()
            # draining = stop routing here (503); degraded = still serving
            # from healthy endpoints, breaker detail in the body (200)
            self._send_json(503 if health["status"] == "draining" else 200, health)
        elif path == "/v1/subscriptions":
            if self.subs is None:
                self._send_json(404, {"error": "standing queries disabled"})
            else:
                self._send_json(200, self.subs.subscriptions())
        elif path == "/v1/deliveries":
            self._handle_deliveries()
        elif path == "/v1/backfill":
            if self.backfill is None:
                self._send_json(404, {"error": "backfill disabled"})
            else:
                self._send_json(200, {"jobs": self.backfill.jobs()})
        elif path.startswith("/v1/backfill/"):
            self._handle_backfill_get(path)
        elif path == "/v1/segments":
            self._handle_segments_list()
        elif path.startswith("/v1/segments/"):
            self._handle_segment_get(path)
        elif path.startswith("/v1/blocks/"):
            self._handle_block_get(path)
        elif path.startswith("/v1/registry/"):
            self._handle_registry_get(path)
        else:
            self._send_json(404, {"error": f"no such path: {self.path}"})

    # --- provenance registry -------------------------------------------------

    def _handle_registry_get(self, path: str) -> None:
        """`/v1/registry/{head,entry,proof,consistency,base}`: the audit
        surface. ``head`` publishes the checkpoint (size + tree root +
        chain tip); ``entry?seq=N`` returns one sealed record;
        ``proof?seq=N`` (or ``?digest=<bundle digest>``) an inclusion
        proof against the current root; ``consistency?old_size=N`` the
        proof that the current root extends the size-N checkpoint;
        ``base?fleet=F&key=K`` the fleet directory's newest common acked
        base for a filter key (digest + CID set)."""
        reg = self.service.registry
        if reg is None:
            self._send_json(404, {"error": "registry disabled"})
            return
        q = parse_qs(urlsplit(self.path).query)

        def _int_param(name):
            try:
                return int(q[name][0])
            except (KeyError, IndexError, ValueError):
                return None

        if path == "/v1/registry/head":
            self._send_json(200, reg.head())
        elif path == "/v1/registry/entry":
            seq = _int_param("seq")
            entry = reg.entry(seq) if seq is not None else None
            if entry is None:
                self._send_json(404, {"error": f"no registry entry seq={seq}"})
            else:
                self._send_json(200, entry)
        elif path == "/v1/registry/proof":
            seq = _int_param("seq")
            if seq is None and "digest" in q:
                seq = reg.seq_of(q["digest"][0])
            proof = reg.inclusion_proof(seq) if seq is not None else None
            if proof is None:
                self._send_json(404, {"error": "no such registry record"})
            else:
                self._send_json(200, proof)
        elif path == "/v1/registry/consistency":
            old = _int_param("old_size")
            proof = reg.consistency(old) if old is not None else None
            if proof is None:
                self._send_json(
                    404, {"error": "old_size required, 0 <= old_size <= size"}
                )
            else:
                self._send_json(200, proof)
        elif path == "/v1/registry/base":
            # fleet base directory query: the newest base every member of
            # (fleet, key) acked — what a post-failover delta builds on
            fleet = (q.get("fleet") or [""])[0]
            key = (q.get("key") or [""])[0]
            if not key:
                self._send_json(404, {"error": "key required"})
                return
            digest = reg.newest_common_base(fleet or "default", key)
            cids = reg.lookup_base(digest) if digest else None
            self._send_json(
                200,
                {
                    "fleet": fleet or "default",
                    "key": key,
                    "digest": digest,
                    "cids": sorted(c.hex() for c in cids) if cids else None,
                },
            )
        else:
            self._send_json(404, {"error": f"no such path: {self.path}"})

    def _registry_append(
        self, digest: str, *, verdict: str = "", key: str = "", trace: str = "",
        cids=None,
    ) -> None:
        """Seal one served bundle into the provenance chain — fail-soft:
        any trouble counts `registry.append_failures` inside the writer
        and the response goes out bit-identical either way.

        ``digest`` and ``cids`` accept zero-arg callables (bound methods)
        resolved only past the ``reg is None`` gate: with the registry
        disabled the serve path must not pay for digesting or CID-set
        materialization it will never use."""
        reg = self.service.registry
        if reg is None:
            return
        if callable(digest):
            digest = digest()
        if not digest:
            return
        try:
            if callable(cids):
                cids = cids()
            reg.append_served(
                digest,
                trace=trace,
                tenant=getattr(self, "_tenant", None) or "",
                key=key,
                verdict=verdict,
                cids=cids,
            )
        except Exception:  # fail-soft: a registry write failure must never block serving
            self.service.metrics.count("registry.append_failures")

    # --- replication plane (storex.replica peers call these) ----------------

    def _send_bytes(self, status: int, data: bytes) -> None:
        self.send_response(status)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _handle_segments_list(self):
        """``GET /v1/segments`` — the replication inventory: every segment
        file this shard holds (owner token + active flag), so a replica
        can diff against its own set and pull only what's missing."""
        disk = self.service.disk_store
        if disk is None:
            self._send_json(404, {"error": "no disk tier (serve without --store-dir)"})
            return
        self._send_json(
            200, {"segments": disk.segment_files(), "owner": disk.owner}
        )

    def _handle_segment_get(self, path: str):
        """``GET /v1/segments/<name>`` — one whole segment file, raw.
        Append-only CRC framing makes the transfer trivially safe: the
        puller re-scans every frame before believing a byte."""
        disk = self.service.disk_store
        if disk is None:
            self._send_json(404, {"error": "no disk tier (serve without --store-dir)"})
            return
        name = unquote(path[len("/v1/segments/") :])
        seg_path = disk.segment_path(name)
        if seg_path is None:
            self._send_json(404, {"error": f"no such segment: {name}"})
            return
        try:
            with open(seg_path, "rb") as fh:
                data = fh.read()
        except OSError:
            # evicted between the lookup and the read — a miss, not a fault
            self._send_json(404, {"error": f"no such segment: {name}"})
            return
        self._send_bytes(200, data)

    def _handle_block_get(self, path: str):
        """``GET /v1/blocks/<cid>`` — one block from the LOCAL tiers only
        (read-repair). 404 means this shard doesn't hold it; the route
        never touches the upstream, so a neighbour's repair can't launder
        a Lotus fetch through us."""
        data = self.service.read_block_local(unquote(path[len("/v1/blocks/") :]))
        if data is None:
            self._send_json(404, {"error": "block not in local tiers"})
        else:
            self._send_bytes(200, data)

    def _handle_segment_put(self, path: str):
        """``POST /v1/segments/<name>`` — ingest one pushed segment file
        (rebalance handoff / re-replication push). Idempotent: a name
        already registered is a no-op; every frame is CRC-scanned before
        registration; own-owner names are a typed 400 (a shard must never
        shadow its own active segments)."""
        disk = self.service.disk_store
        if disk is None:
            self._send_json(404, {"error": "no disk tier (serve without --store-dir)"})
            return
        name = unquote(path[len("/v1/segments/") :])
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = 0
        if length <= 0 or length > _MAX_BODY_BYTES:
            self._send_json(
                400,
                {"error": f"Content-Length required, 0 < n <= {_MAX_BODY_BYTES}"},
            )
            return
        raw = self.rfile.read(length)
        try:
            blocks = disk.ingest_segment_file(name, raw)
        except SegmentStoreError as exc:
            self._send_json(400, {"error": str(exc), "error_type": "segment_ingest"})
            return
        self._send_json(200, {"segment": name, "blocks": blocks})

    def _handle_replica_peers(self, body: dict):
        """``POST /v1/replica_peers`` — install this shard's read-repair
        peer set (the router computes it from ring arcs)."""
        peers = body.get("peers")
        if not isinstance(peers, list) or not all(
            isinstance(p, dict)
            and isinstance(p.get("name"), str)
            and isinstance(p.get("url"), str)
            for p in peers
        ):
            self._send_json(
                400, {"error": "peers must be a list of {name, url} objects"}
            )
            return
        try:
            self.service.set_replica_peers(peers)
        except RuntimeError as exc:
            self._send_json(400, {"error": str(exc)})
            return
        self._send_json(200, {"peers": len(peers)})

    def _handle_replicate(self, body: dict):
        """``POST /v1/replicate`` — run one pull-sync pass against the
        named source shards (optionally owner-filtered to the ring arcs
        this shard replicates). Synchronous: the response carries the
        pass's pulled/pending counts for the router's lag gauges."""
        sources = body.get("sources")
        if not isinstance(sources, list) or not all(
            isinstance(s, dict)
            and isinstance(s.get("name"), str)
            and isinstance(s.get("url"), str)
            for s in sources
        ):
            self._send_json(
                400, {"error": "sources must be a list of {name, url} objects"}
            )
            return
        owners = body.get("owners")
        if owners is not None and (
            not isinstance(owners, list)
            or not all(isinstance(o, str) for o in owners)
        ):
            self._send_json(400, {"error": "owners must be a list of strings"})
            return
        try:
            out = self.service.replicate_from(sources, owners=owners)
        except RuntimeError as exc:
            self._send_json(400, {"error": str(exc)})
            return
        self._send_json(200, out)

    def _handle_backfill_get(self, path: str):
        """``GET /v1/backfill/<id>`` — job status/cursor;
        ``GET /v1/backfill/<id>/chunks?cursor=<n>[&wait_s=<s>]`` — the
        long-poll chunk fetch, `subs/delivery.py` cursor semantics: a
        poll from cursor N acks everything ≤ N (streamed payloads drop
        from memory; the journal keeps the bytes) and blocks up to
        ``wait_s`` for the first chunk above it."""
        if self.backfill is None:
            self._send_json(404, {"error": "backfill disabled"})
            return
        rest = path[len("/v1/backfill/") :]
        job_id, _, tail = rest.partition("/")
        job = self.backfill.job(job_id)
        if job is None:
            self._send_json(404, {"error": f"no such backfill job: {job_id}"})
            return
        if tail == "":
            self._send_json(200, job.status())
        elif tail == "chunks":
            q = parse_qs(urlsplit(self.path).query)
            try:
                cursor = int((q.get("cursor") or ["0"])[0])
                wait_s = min(30.0, max(0.0, float((q.get("wait_s") or ["0"])[0])))
            except ValueError:
                self._send_json(400, {"error": "cursor/wait_s must be numeric"})
                return
            out = job.chunks_after(cursor, wait_s=wait_s)
            if negotiate_stream({}, headers=self.headers):
                self._stream_backfill_chunks(out)
            else:
                self._send_json(200, out)
        else:
            self._send_json(404, {"error": f"no such path: {self.path}"})

    def _stream_backfill_chunks(self, out: dict) -> None:
        """``GET /v1/backfill/<id>/chunks`` with
        ``Accept: application/x-ipc-bundle-stream`` — the multi-document
        stream form: one IPBS document per result chunk (block payloads
        sliced zero-copy out of the segment tier when warm), closed by a
        metadata-only envelope document carrying the poll fields
        (``job_id`` / ``state`` / ``cursor`` / ``acked``)."""
        self._stream_ok(
            lambda w: stream_backfill_chunks(
                w, out, slicer=self.service.read_block_slice
            ),
            "identity",
        )

    def _handle_deliveries(self):
        """``GET /v1/deliveries?sub=<id>&cursor=<n>[&wait_s=<s>]`` — the
        long-poll fallback: acks everything ≤ cursor, returns what's
        above it (blocking up to ``wait_s``, capped server-side)."""
        if self.subs is None:
            self._send_json(404, {"error": "standing queries disabled"})
            return
        q = parse_qs(urlsplit(self.path).query)
        sub_id = (q.get("sub") or [""])[0]
        if not sub_id:
            self._send_json(400, {"error": "sub query parameter required"})
            return
        try:
            cursor = int((q.get("cursor") or ["0"])[0])
            wait_s = min(30.0, max(0.0, float((q.get("wait_s") or ["0"])[0])))
        except ValueError:
            self._send_json(400, {"error": "cursor/wait_s must be numeric"})
            return
        out = self.subs.deliveries(sub_id, cursor=cursor, wait_s=wait_s)
        if out is None:
            self._send_json(404, {"error": f"no such subscription: {sub_id}"})
        else:
            self._send_json(200, out)

    def do_POST(self):
        # segment ingest carries a RAW octet-stream body (a whole segment
        # file) — route it before the JSON body parse below
        if self.path.startswith("/v1/segments/"):
            self._handle_segment_put(urlsplit(self.path).path)
            return
        try:
            body = self._read_json_body()
        except (ValueError, json.JSONDecodeError) as exc:
            self._send_json(400, {"error": f"bad request body: {exc}"})
            return
        # the span opens BEFORE admission on this handler thread, so the
        # batcher captures it and execution parents under it. A "trace"
        # carrier in the body (the cluster router's scatter hop) parents
        # this request's spans under the remote dispatch span — one trace
        # covers the whole scatter-gather; without one this is a trace root
        carrier = body.get("trace")
        # tenant accounting at admission: the sanitized label rides the
        # request through batcher/durable-queue; bytes charge the body size
        self._tenant = extract_tenant(body, self.headers)
        self._active_span = None  # set for remote-carried requests (stitching)
        self._account_response = False
        self._cancel_scope = None  # set for proof paths below
        self._admit_slot = None
        self._queue_delay_ms = 0.0  # AIMD signal, filled from server_timing
        if self.path in ("/v1/verify", "/v1/generate", "/v1/generate_range"):
            if self.tenants is not None:
                self.tenants.account(self._tenant, getattr(self, "_body_bytes", 0))
                self._account_response = True
            # QoS admission sits at the very front door — an exhausted
            # bucket never touches the batcher, so a heavy tenant's burst
            # costs one bucket check, not a queue slot
            if self.qos is not None:
                try:
                    self.qos.admit(self._tenant)
                except TenantThrottledError as exc:
                    self._send_json(
                        429,
                        {
                            "error": str(exc),
                            "error_type": "tenant_throttled",
                            # the HONEST refill estimate (seconds until the
                            # bucket actually holds one token), not a fixed
                            # constant — the header rounds it up to >= 1s
                            "retry_after_s": exc.retry_after_s,
                        },
                        headers={
                            "Retry-After": f"{max(1, round(exc.retry_after_s))}"
                        },
                    )
                    return
            # deadline propagation: X-IPC-Deadline-Ms header / deadline_ms
            # body field is the caller's REMAINING budget. A budget already
            # below the admission floor is refused typed here — admitting it
            # would burn a worker on a response nobody can use
            if not self._parse_deadline(body):
                return
            # adaptive admission (--admit-gradient): the AIMD concurrency
            # gate sits after the per-tenant bucket (cheap, per-tenant
            # fairness first) and before any queue slot is taken
            if self.admit is not None:
                try:
                    self._admit_slot = self.admit.acquire(self._tenant)
                except AdmitRejectedError as exc:
                    self._send_json(
                        429,
                        {
                            "error": str(exc),
                            "error_type": "admit_rejected",
                            "retry_after_s": exc.retry_after_s,
                        },
                        headers={
                            "Retry-After": f"{max(1, round(exc.retry_after_s))}"
                        },
                    )
                    return
        try:
            self._route_post(body, carrier)
        finally:
            if self._admit_slot is not None:
                self.admit.release(
                    self._admit_slot, queue_delay_ms=self._queue_delay_ms
                )

    def _route_post(self, body: dict, carrier) -> None:
        if self.path == "/v1/verify":
            with adopted_span("http.verify", carrier, {"path": self.path}) as sp:
                if carrier is not None:
                    self._active_span = sp
                self._handle_verify(body)
        elif self.path == "/v1/generate":
            with adopted_span("http.generate", carrier, {"path": self.path}) as sp:
                if carrier is not None:
                    self._active_span = sp
                self._handle_generate(body)
        elif self.path == "/v1/generate_range":
            with adopted_span(
                "http.generate_range", carrier, {"path": self.path}
            ) as sp:
                if carrier is not None:
                    self._active_span = sp
                self._handle_generate_range(body)
        elif self.path == "/v1/subscribe":
            self._handle_subscribe(body)
        elif self.path == "/v1/unsubscribe":
            self._handle_unsubscribe(body)
        elif self.path == "/v1/backfill":
            self._handle_backfill_submit(body)
        elif self.path == "/v1/replica_peers":
            self._handle_replica_peers(body)
        elif self.path == "/v1/replicate":
            self._handle_replicate(body)
        else:
            self._send_json(404, {"error": f"no such path: {self.path}"})

    def _handle_backfill_submit(self, body: dict):
        """``POST /v1/backfill`` — submit one durable backfill job over
        rows ``[pair_start, pair_end)`` of the server pair table (the
        service's event filter is the job's filter; the pair table IS the
        epoch range). Idempotent: an identical range re-submit returns
        the running job, or resumes its journal after a crash."""
        if self.backfill is None:
            self._send_json(404, {"error": "backfill disabled"})
            return
        n = len(self.pairs)
        start = body.get("pair_start")
        end = body.get("pair_end")

        def _row(v) -> bool:
            return isinstance(v, int) and not isinstance(v, bool)

        if not (_row(start) and _row(end) and 0 <= start < end <= n):
            self._send_json(
                400,
                {
                    "error": "pair_start/pair_end must be ints with "
                    f"0 <= start < end <= {n} (server pair table)"
                },
            )
            return
        wsize = body.get("window_size")
        if wsize is not None and (not _row(wsize) or wsize < 1):
            self._send_json(400, {"error": "window_size must be a positive int"})
            return
        sub_id = body.get("sub_id")
        if sub_id is not None and not isinstance(sub_id, str):
            self._send_json(400, {"error": "sub_id must be a string"})
            return
        try:
            job = self.backfill.submit(
                start, end, window_size=wsize, sub_id=sub_id
            )
        except (ValueError, RuntimeError) as exc:
            self._send_json(400, {"error": str(exc)})
            return
        self._send_json(200, job.status())

    def _handle_subscribe(self, body: dict):
        if self.subs is None:
            self._send_json(404, {"error": "standing queries disabled"})
            return
        try:
            self._send_json(200, self.subs.subscribe(body))
        except ValueError as exc:
            self._send_json(400, {"error": str(exc)})

    def _handle_unsubscribe(self, body: dict):
        if self.subs is None:
            self._send_json(404, {"error": "standing queries disabled"})
            return
        try:
            self._send_json(200, self.subs.unsubscribe(body))
        except ValueError as exc:
            self._send_json(400, {"error": str(exc)})

    @staticmethod
    def _claim_results(claims, storage_results, event_results) -> list:
        """Per-claim verdicts: each claim's span slices of the flat
        per-proof result vectors (one shared replay, K verdicts)."""
        out = []
        for c in claims:
            s = storage_results[c.storage_lo : c.storage_hi]
            e = event_results[c.event_lo : c.event_hi]
            out.append(
                {
                    "storage_results": s,
                    "event_results": e,
                    "all_valid": all(s) and all(e),
                }
            )
        return out

    def _handle_verify(self, body: dict):
        obj = body.get("bundle", body)
        try:
            # plain or compressed (``blocks_frame``) wire form — the
            # witness-plane parser handles both, digest-checked
            bundle = parse_bundle_obj(obj)
            claims = None
            if body.get("claims") is not None:
                claims = AggregatedBundle.claims_from_json(
                    body["claims"], bundle
                ).claims
        except WitnessError as exc:
            self._send_json(
                400,
                {"error": str(exc), "error_type": exc.error_type},
            )
            return
        except (ValueError, KeyError, TypeError) as exc:
            self._send_json(400, {"error": f"malformed bundle: {exc}"})
            return
        timeout_s = self._effective_timeout(body)
        if self.durable is not None:
            # journal the PLAIN bundle obj (compressed frames expand before
            # admission, so journal replay never needs the codec)
            plain = obj if "blocks_frame" not in obj else bundle.to_json_obj()
            self._submit_durable(
                "verify", plain, body, claims=claims,
                seal=lambda done: self._registry_append(
                    bundle.digest,
                    verdict=(
                        "valid"
                        if (done.get("result") or {}).get("all_valid")
                        else "invalid"
                    ),
                    key="verify",
                )
                if done.get("ok")
                else None,
            )
            return

        def render(resp):
            self._registry_append(
                bundle.digest,
                verdict="valid" if resp.all_valid() else "invalid",
                key="verify",
                trace=resp.trace_id,
            )
            out = {
                "storage_results": resp.storage_results,
                "event_results": resp.event_results,
                "all_valid": resp.all_valid(),
                "batch_size": resp.batch_size,
                "trace_id": resp.trace_id,
                "server_timing": resp.server_timing,
            }
            if claims is not None:
                out["claim_results"] = self._claim_results(
                    claims, resp.storage_results, resp.event_results
                )
            return out

        self._submit(
            lambda: self.service.submit_verify(
                bundle,
                timeout_s=timeout_s,
                tenant=self._tenant,
                cancel_scope=self._cancel_scope,
            ),
            render,
            pending=True,
        )

    def _handle_generate(self, body: dict):
        idx = body.get("pair_index")
        if not isinstance(idx, int) or not (0 <= idx < len(self.pairs)):
            self._send_json(
                400,
                {
                    "error": "pair_index must be an int in "
                    f"[0, {len(self.pairs)}) (server pair table)"
                },
            )
            return
        opts = self._negotiate_witness(body)
        if opts is None:
            return
        stream = self._negotiate_stream(body)
        if stream is None:
            return
        timeout_s = self._effective_timeout(body)
        if self.durable is not None:
            self._submit_durable(
                "generate", idx, body, witness=opts, stream=stream
            )
            return

        def stream_doc(resp, writer):
            digest = stream_bundle_doc(
                writer,
                resp.bundle,
                opts,
                bases=self.service.witness_bases,
                metrics=self.service.metrics,
                head_extra={
                    "n_event_proofs": resp.n_event_proofs,
                    "batch_size": resp.batch_size,
                    "trace_id": resp.trace_id,
                },
                tail_extra={"server_timing": dict(resp.server_timing)},
                slicer=self.service.read_block_slice,
            )
            self._registry_append(
                digest, verdict="served", key=f"pair:{idx}",
                trace=resp.trace_id, cids=resp.bundle.cid_set,
            )

        def render(resp):
            fields = self._witness_fields(resp.bundle, opts)
            self._registry_append(
                fields.get("digest", ""), verdict="served", key=f"pair:{idx}",
                trace=resp.trace_id, cids=resp.bundle.cid_set,
            )
            return dict(
                fields,
                n_event_proofs=resp.n_event_proofs,
                batch_size=resp.batch_size,
                trace_id=resp.trace_id,
                server_timing=resp.server_timing,
            )

        self._submit(
            lambda: self.service.submit_generate(
                self.pairs[idx],
                timeout_s=timeout_s,
                tenant=self._tenant,
                cancel_scope=self._cancel_scope,
            ),
            render,
            stream_fn=stream_doc if stream else None,
            encoding=opts.encoding,
            pending=True,
        )

    def _handle_generate_range(self, body: dict):
        """One multi-pair range sub-request (the scatter-gather unit).

        ``pair_indexes`` selects rows of the server pair table; the
        response bundle is the canonical chunked-driver bytes for exactly
        those pairs, so the router can union sub-bundles bit-identically.
        """
        idxs = body.get("pair_indexes")
        n = len(self.pairs)
        # bool is an int subclass — reject it explicitly, True is not a row
        if (
            not isinstance(idxs, list)
            or not idxs
            or not all(
                isinstance(i, int) and not isinstance(i, bool) and 0 <= i < n
                for i in idxs
            )
        ):
            self._send_json(
                400,
                {
                    "error": "pair_indexes must be a non-empty list of ints "
                    f"in [0, {n}) (server pair table)"
                },
            )
            return
        chunk = body.get("chunk_size")
        if chunk is not None and (
            not isinstance(chunk, int) or isinstance(chunk, bool) or chunk < 1
        ):
            self._send_json(400, {"error": "chunk_size must be a positive int"})
            return
        aggregate = body.get("aggregate", False)
        if not isinstance(aggregate, bool):
            self._send_json(400, {"error": "aggregate must be a boolean"})
            return
        opts = self._negotiate_witness(body)
        if opts is None:
            return
        stream = self._negotiate_stream(body)
        if stream is None:
            return
        if aggregate and len(idxs) > self.service.config.witness_agg_max:
            self._send_json(
                400,
                {
                    "error": f"aggregate request carries {len(idxs)} claims, "
                    f"above --witness-agg-max "
                    f"{self.service.config.witness_agg_max}",
                    "error_type": "witness_agg_max",
                },
            )
            return
        # aggregated requests may repeat pair indexes (K co-tipset claims);
        # the canonical bundle is generated once over the DISTINCT indexes
        # and the claim table maps every claim onto its pair's spans
        gen_idxs = list(dict.fromkeys(idxs)) if aggregate else list(idxs)
        if self.durable is not None:
            self._submit_durable(
                "generate_range",
                {"pair_indexes": gen_idxs, "chunk_size": chunk},
                body,
                witness=opts,
                claim_indexes=list(idxs) if aggregate else None,
                gen_indexes=gen_idxs,
                stream=stream,
            )
            return

        def _claims(bundle):
            if not aggregate:
                return None
            return aggregate_range_bundle(
                bundle,
                self.pairs,
                gen_idxs,
                claim_indexes=idxs,
                metrics=self.service.metrics,
            ).claims_json()

        range_key = "pairs:" + ",".join(str(i) for i in gen_idxs[:32])

        def render(bundle):
            fields = self._witness_fields(bundle, opts, claims=_claims(bundle))
            self._registry_append(
                fields.get("digest", ""), verdict="served", key=range_key,
                cids=bundle.cid_set,
            )
            return dict(
                fields,
                n_event_proofs=len(bundle.event_proofs),
                n_pairs=len(gen_idxs),
            )

        def stream_doc(bundle, writer):
            digest = stream_bundle_doc(
                writer,
                bundle,
                opts,
                bases=self.service.witness_bases,
                metrics=self.service.metrics,
                claims=_claims(bundle),
                head_extra={
                    "n_event_proofs": len(bundle.event_proofs),
                    "n_pairs": len(gen_idxs),
                },
                slicer=self.service.read_block_slice,
            )
            self._registry_append(
                digest, verdict="served", key=range_key, cids=bundle.cid_set
            )

        self._submit(
            # direct synchronous driver call on this handler thread — the
            # scope installs so chunk checkpoints see the deadline (no
            # concurrent disconnect watcher on this path)
            lambda: self._call_scoped(
                lambda: self.service.generate_range(
                    [self.pairs[i] for i in gen_idxs], chunk_size=chunk
                )
            ),
            render,
            stream_fn=stream_doc if stream else None,
            encoding=opts.encoding,
        )

    # --- deadline / cancellation plumbing ----------------------------------

    def _parse_deadline(self, body: dict) -> bool:
        """Install this request's `CancelScope` from its deadline budget.

        ``deadline_ms`` in the body wins over the ``X-IPC-Deadline-Ms``
        header; both mean "milliseconds of budget REMAINING as the request
        reaches me" — each hop re-emits the decremented value, never the
        original. A budget at/below ``--deadline-floor-ms`` is refused
        typed 504 right here (``deadline.rejects.httpd``): admitting work
        that cannot finish inside its budget only burns a worker slot that
        a live request could have used. Returns False after sending an
        error response; a request with no deadline still gets a scope so
        client-disconnect cancellation works."""
        raw = body.get("deadline_ms", None)
        if raw is None:
            raw = self.headers.get("X-IPC-Deadline-Ms")
        deadline = None
        if raw is not None:
            try:
                ms = float(raw)
            except (TypeError, ValueError):
                self._send_json(
                    400, {"error": "deadline_ms must be a number of milliseconds"}
                )
                return False
            deadline = Deadline.from_ms(max(0.0, ms))
            floor_ms = float(
                getattr(self.service.config, "deadline_floor_ms", 0.0)
            )
            if deadline.remaining_ms() <= floor_ms:
                m = self.service.metrics
                m.count("serve.deadline_rejects")
                m.count("deadline.rejects.httpd")
                self._send_json(
                    504,
                    {
                        "error": f"deadline budget {ms:.0f}ms at/below the "
                        f"admission floor ({floor_ms:.0f}ms)",
                        "error_type": "deadline",
                    },
                )
                return False
        self._cancel_scope = CancelScope(deadline)
        return True

    def _effective_timeout(self, body: dict):
        """The batcher timeout for this request: the explicit ``timeout_s``
        clamped to the deadline budget (whichever expires first wins)."""
        timeout_s = body.get("timeout_s")
        scope = getattr(self, "_cancel_scope", None)
        if scope is not None and scope.deadline is not None:
            rem = max(0.0, scope.deadline.remaining_s())
            timeout_s = rem if timeout_s is None else min(float(timeout_s), rem)
        return timeout_s

    def _client_disconnected(self) -> bool:
        """True when the client hung up: the socket is readable AND a
        MSG_PEEK read returns EOF (a pipelined next request makes the
        socket readable too — peeking distinguishes the two without
        consuming bytes)."""
        try:
            readable, _, _ = select.select([self.connection], [], [], 0)
            if not readable:
                return False
            return self.connection.recv(1, socket.MSG_PEEK) == b""
        except (OSError, ValueError):
            return True

    def _await_pending(self, pending):
        """Block on a `PendingResult` while watching the client socket.

        A disconnect cancels the request's scope: the batcher drops it at
        dispatch (or the range driver aborts at its next chunk boundary)
        and the worker time goes to a request somebody still wants. We keep
        waiting after cancelling — the batcher acknowledges with a typed
        `CancelledError` (or completes the batch that already started)."""
        scope = self._cancel_scope
        while True:
            try:
                return pending.result(timeout=_DISCONNECT_POLL_S)
            except TimeoutError:
                if (
                    scope is not None
                    and not scope.cancelled
                    and self._client_disconnected()
                ):
                    scope.cancel("client disconnected")

    def _call_scoped(self, fn):
        """Run a synchronous service call under this request's scope so
        driver checkpoints (`utils.deadline.checkpoint`) see its deadline.
        The call runs on THIS handler thread, so there is no concurrent
        disconnect watcher — expiry aborts at the next chunk/stage/retry
        boundary."""
        scope = getattr(self, "_cancel_scope", None)
        if scope is None:
            return fn()
        with use_scope(scope):
            return fn()

    def _submit(self, call, render, stream_fn=None, encoding=None, pending=False):
        try:
            resp = self._await_pending(call()) if pending else call()
        except QueueFullError as exc:
            self._send_json(
                503,
                {"error": "queue full", "retry_after_s": exc.retry_after_s},
                headers={"Retry-After": f"{max(1, round(exc.retry_after_s))}"},
            )
        except ServiceClosedError:
            self._send_json(503, {"error": "service draining"})
        except CancelledError:
            # only a client disconnect cancels the scope — there is nobody
            # left to answer; close without wasting bytes on the dead socket
            self.close_connection = True
        except DeadlineError as exc:
            # covers batcher DeadlineExceededError + every propagated hop
            # (rpc retry, range chunk, pipeline stage); always typed
            self._send_json(
                504, {"error": str(exc), "error_type": exc.error_type}
            )
        except DegradedError as exc:
            # all breakers open and the request needed the upstream: fail
            # fast typed — warm-tier requests never reach this branch
            self._send_json(
                503, {"error": str(exc), "error_type": exc.error_type}
            )
        except RuntimeError as exc:
            self._send_json(400, {"error": str(exc)})
        else:
            t = getattr(resp, "server_timing", None)
            if isinstance(t, dict) and "queue_ms" in t:
                # the gradient limiter's AIMD signal: pure queue wait, not
                # execution time (a big batch is throughput, not overload)
                self._queue_delay_ms = float(t["queue_ms"])
            if stream_fn is not None:
                # admission/execution errors above still travel as typed
                # JSON statuses — only a successful response streams
                self._stream_ok(lambda w: stream_fn(resp, w), encoding)
                return
            obj = render(resp)
            self._attach_spans(obj)
            headers = {}
            timing = getattr(resp, "server_timing", None)
            if timing:
                headers["Server-Timing"] = self._server_timing_header(timing)
            # satellite contract: the chosen encoding is ALWAYS echoed —
            # the JSON field plus a header the thinnest client can read
            if "witness_encoding" in obj:
                headers["Witness-Encoding"] = obj["witness_encoding"]
            self._send_json(200, obj, headers=headers or None)

    def _attach_spans(self, obj: dict) -> None:
        """Ship this request's span subtree in the response for sampled,
        remote-carried traces — the router grafts it under its dispatch
        span so one exported tree covers router → shard → workers.
        ``spans_pid`` lets an in-process caller (LocalShard) recognize its
        own spans and skip the graft (they are already in its ring)."""
        sp = getattr(self, "_active_span", None)
        if sp is None or not sp.sampled or not tracing_enabled():
            return
        obj["spans"] = subtree_for_response(sp)
        obj["spans_pid"] = os.getpid()

    def _rewitness_result(
        self, result: dict, witness, claims, claim_indexes, gen_indexes
    ) -> dict:
        """Re-encode a journaled done payload under this request's witness
        options.

        The durable journal always holds the PLAIN canonical result (so
        replay/idempotency never depend on a codec or a base another client
        declared); aggregation claims, delta encoding and compression are
        per-response treatments applied on the way out."""
        if "bundle" in result and witness is not None:
            bundle = UnifiedProofBundle.from_json_obj(result["bundle"])
            claims_json = None
            if claim_indexes is not None:
                claims_json = aggregate_range_bundle(
                    bundle,
                    self.pairs,
                    gen_indexes,
                    claim_indexes=claim_indexes,
                    metrics=self.service.metrics,
                ).claims_json()
            result = {k: v for k, v in result.items() if k != "bundle"}
            result.update(self._witness_fields(bundle, witness, claims=claims_json))
            # durable replays are served responses too: the provenance
            # chain records every bundle that leaves the process, cached
            # or fresh
            self._registry_append(
                result.get("digest", ""), verdict="served", key="replay",
                cids=bundle.cid_set,
            )
        if claims is not None and "storage_results" in result:
            result = dict(
                result,
                claim_results=self._claim_results(
                    claims, result["storage_results"], result["event_results"]
                ),
            )
        return result

    def _submit_durable(
        self,
        kind: str,
        payload,
        body: dict,
        witness=None,
        claims=None,
        claim_indexes=None,
        gen_indexes=None,
        stream=False,
        seal=None,
    ):
        """Route one request through the durable admission queue.

        Same error mapping as `_submit`, but the 200 body is the journaled
        done payload: ``{"ok": ..., "result"|"error": ...}`` plus the
        ``idempotency_key`` that names it and ``cached`` (True when served
        from the idempotency cache instead of a fresh execution). Witness
        treatments (``witness``/``claims``/``claim_indexes``) re-encode the
        plain journaled result per-response — see `_rewitness_result`."""
        key = body.get("idempotency_key")
        if key is not None and not isinstance(key, str):
            self._send_json(400, {"error": "idempotency_key must be a string"})
            return
        try:
            # scoped so the durable layer (and any direct driver call it
            # makes) sees this request's deadline through the ambient scope
            key, done, cached = self._call_scoped(
                lambda: self.durable.submit(
                    kind, payload, idempotency_key=key,
                    timeout_s=self._effective_timeout(body),
                    tenant=self._tenant,
                )
            )
        except QueueFullError as exc:
            self._send_json(
                503,
                {"error": "queue full", "retry_after_s": exc.retry_after_s},
                headers={"Retry-After": f"{max(1, round(exc.retry_after_s))}"},
            )
        except ServiceClosedError:
            self._send_json(503, {"error": "service draining"})
        except DeadlineError as exc:
            self._send_json(
                504, {"error": str(exc), "error_type": exc.error_type}
            )
        except DegradedError as exc:
            self._send_json(
                503, {"error": str(exc), "error_type": exc.error_type}
            )
        else:
            if seal is not None:
                # provenance seal for journaled kinds that carry no bundle
                # (verify): fail-soft like every registry append
                try:
                    seal(done)
                except Exception:  # fail-soft: a registry write failure must never block serving
                    self.service.metrics.count("registry.append_failures")
            headers = None
            if (
                stream
                and witness is not None
                and done.get("ok")
                and isinstance(done.get("result"), dict)
                and "bundle" in done["result"]
            ):
                self._stream_durable(
                    done["result"], key, cached, witness, claim_indexes,
                    gen_indexes,
                )
                return
            if done.get("ok") and isinstance(done.get("result"), dict):
                result = self._rewitness_result(
                    done["result"], witness, claims, claim_indexes, gen_indexes
                )
                done = dict(done, result=result)
                if "witness_encoding" in result:
                    headers = {"Witness-Encoding": result["witness_encoding"]}
            out = dict(done, idempotency_key=key, cached=cached)
            self._attach_spans(out)
            self._send_json(200, out, headers=headers)

    def _stream_durable(
        self, result: dict, key, cached, witness, claim_indexes, gen_indexes
    ) -> None:
        """Streamed form of a durable done payload: the journal's PLAIN
        canonical result re-encoded through the IPBS wire under this
        request's witness options.

        Unlike the buffered durable response there is no ``result``
        envelope — the document IS the result, with ``ok`` /
        ``idempotency_key`` / ``cached`` riding the header chunk. Block
        payloads come from the journal JSON, so they stream as copied
        bytes unless the segment tier still holds them warm (the slicer
        is consulted per block either way)."""
        bundle = UnifiedProofBundle.from_json_obj(result["bundle"])
        claims_json = None
        if claim_indexes is not None:
            claims_json = aggregate_range_bundle(
                bundle,
                self.pairs,
                gen_indexes,
                claim_indexes=claim_indexes,
                metrics=self.service.metrics,
            ).claims_json()
        head = {
            k: v
            for k, v in result.items()
            if k not in ("bundle", "server_timing")
        }
        head.update(ok=True, idempotency_key=key, cached=cached)
        timing = result.get("server_timing")
        tail = (
            {"server_timing": dict(timing)} if isinstance(timing, dict) else None
        )

        def doc(writer):
            digest = stream_bundle_doc(
                writer,
                bundle,
                witness,
                bases=self.service.witness_bases,
                metrics=self.service.metrics,
                claims=claims_json,
                head_extra=head,
                tail_extra=tail,
                slicer=self.service.read_block_slice,
            )
            self._registry_append(
                digest, verdict="served", key="replay", cids=bundle.cid_set
            )

        self._stream_ok(doc, witness.encoding)


class ProofHTTPServer:
    """Own one `ProofService` behind a threading HTTP server.

    ``port=0`` binds an ephemeral port (tests); read ``.port`` after
    construction. `serve_forever()` blocks; `start()` runs the accept loop
    on a daemon thread. `shutdown()` stops accepting, then drains the
    service — zero accepted requests are lost.
    """

    def __init__(
        self,
        service: ProofService,
        host: str = "127.0.0.1",
        port: int = 0,
        pairs: Optional[Sequence[TipsetPair]] = None,
        durable=None,
        subs=None,
        slo=None,
        tenants=None,
        backfill=None,
        qos=None,
    ):
        self.service = service
        self.durable = durable
        self.subs = subs
        self.slo = slo
        self.backfill = backfill  # backfill.BackfillEngine (or None)
        # tenant accounting is always on (bounded top-K, so it's safe);
        # pass an explicit ledger to share one across servers or set top_k
        self.tenants = (
            tenants
            if tenants is not None
            else TenantLedger(metrics=service.metrics)
        )
        # QoS enforcement is opt-in (--tenant-rate); built here so the
        # buckets share the ledger's slot labels for tenant.throttled.*
        self.qos = qos
        if self.qos is None and getattr(service.config, "tenant_rate", None):
            self.qos = TenantQoS(
                service.config.tenant_rate,
                burst=service.config.tenant_burst,
                metrics=service.metrics,
                ledger=self.tenants,
            )
        # adaptive admission (--admit-gradient): one AIMD gate shared by
        # every handler thread; replaces the static queue_capacity as the
        # effective concurrency bound (the batcher capacity stays as a
        # hard backstop behind it)
        self.admit = None
        cfg = service.config
        if getattr(cfg, "admit_gradient", False):
            self.admit = GradientLimiter(
                initial=cfg.admit_initial,
                min_limit=cfg.admit_min,
                max_limit=cfg.admit_max,
                delay_budget_ms=cfg.admit_delay_budget_ms,
                tenant_weights=getattr(cfg, "tenant_weights", None),
                metrics=service.metrics,
            )
        handler = type(
            "_BoundHandler",
            (_Handler,),
            {
                "service": service,
                "pairs": list(pairs or []),
                "durable": durable,
                "subs": subs,
                "slo": slo,
                "tenants": self.tenants,
                "backfill": backfill,
                "qos": self.qos,
                "admit": self.admit,
            },
        )
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def serve_forever(self) -> None:
        self._httpd.serve_forever(poll_interval=0.1)

    def start(self) -> "ProofHTTPServer":
        # start()/shutdown() are owner-thread lifecycle calls with a
        # happens-before edge through Thread.start()/join(); no lock needed
        self._thread = threading.Thread(  # ipclint: disable=race-unannotated
            target=self.serve_forever, name="proof-httpd", daemon=True
        )
        self._thread.start()
        return self

    def shutdown(self, timeout: Optional[float] = None) -> None:
        """Stop the accept loop, then drain the service (flushes all
        accepted work before returning).

        Order matters: the standing-query plane drains FIRST — its push
        workers read proof payloads and its matcher reads the blockstore,
        so they must finish before `service.drain()` closes the fetch
        plane and the tiered store underneath them (a SIGTERM mid-push
        must never make a delivery read from a closed tier)."""
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout)
        if self.slo is not None:
            self.slo.stop()
        # backfill aborts at its next window boundary BEFORE the service
        # drains — its window runner submits into the service's batcher,
        # which must still be accepting while running jobs wind down
        if self.backfill is not None:
            self.backfill.close(timeout=timeout)
        if self.subs is not None:
            self.subs.drain()
        self.service.drain(timeout=timeout)
        if self.durable is not None:
            self.durable.close()

    def abort(self) -> None:
        """Crash simulation: stop serving WITHOUT draining.

        Closes the listener and abandons everything in flight — exactly
        what a shard process dying looks like to the cluster router, which
        is what failover tests need to exercise. The durable queue's
        journal is left as crash residue for recovery-on-restart."""
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(1.0)
