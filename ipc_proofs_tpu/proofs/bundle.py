"""Proof claim types and the serializable bundle wire format.

Reference parity: `ProofBlock`/`UnifiedProofBundle`/`UnifiedVerificationResult`
(`src/proofs/common/bundle.rs`), `StorageProof` (`src/proofs/storage/bundle.rs`),
`EventData`/`EventProof`/`EventProofBundle` (`src/proofs/events/bundle.rs`).

Wire format: JSON with snake_case fields, hex strings 0x-prefixed, CIDs as
base32 strings, witness block data base64-encoded — the bundle is the durable
artifact (the reference's only "checkpoint" format, SURVEY.md §5).
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field

from ipc_proofs_tpu.core.cid import CID

__all__ = [
    "ProofBlock",
    "StorageProof",
    "EventData",
    "EventProof",
    "EventProofBundle",
    "UnifiedProofBundle",
    "UnifiedVerificationResult",
]


@dataclass(frozen=True)
class ProofBlock:
    """One witness block: a CID and its raw DAG-CBOR bytes."""

    cid: CID
    data: bytes

    @classmethod
    def _make(cls, cid: CID, data: bytes) -> "ProofBlock":
        """Fast constructor: the frozen-dataclass init pays one
        ``object.__setattr__`` per field, which adds up across the thousands
        of blocks a range witness materializes."""
        out = object.__new__(cls)
        d = out.__dict__
        d["cid"] = cid
        d["data"] = data
        return out

    def to_json_obj(self) -> dict:
        return {"cid": str(self.cid), "data": base64.b64encode(self.data).decode("ascii")}

    @classmethod
    def from_json_obj(cls, obj: dict) -> "ProofBlock":
        return cls(cid=CID.from_string(obj["cid"]), data=base64.b64decode(obj["data"]))


@dataclass
class StorageProof:
    """Claim: actor ``actor_id`` had ``value`` at storage ``slot`` in the
    state root committed by child block ``child_block_cid`` at ``child_epoch``."""

    child_epoch: int
    child_block_cid: str
    parent_state_root: str
    actor_id: int
    actor_state_cid: str
    storage_root: str
    slot: str  # 0x-hex 32 bytes
    value: str  # 0x-hex 32 bytes

    def to_json_obj(self) -> dict:
        return dict(self.__dict__)

    @classmethod
    def from_json_obj(cls, obj: dict) -> "StorageProof":
        return cls(**obj)


@dataclass
class EventData:
    emitter: int
    topics: list[str]  # 0x-hex, 32 bytes each
    data: str  # 0x-hex

    @classmethod
    def _make(cls, **fields) -> "EventData":
        """Fast constructor for bulk claim emission: the kwargs dict IS the
        instance dict (dataclass __init__ costs ~3× this at range scale)."""
        out = object.__new__(cls)
        out.__dict__ = fields
        return out

    def to_json_obj(self) -> dict:
        return dict(self.__dict__)

    @classmethod
    def from_json_obj(cls, obj: dict) -> "EventData":
        return cls(**obj)


@dataclass
class EventProof:
    """Claim: message ``message_cid`` at execution index ``exec_index`` in the
    parent tipset emitted ``event_data`` at ``event_index``."""

    parent_epoch: int
    child_epoch: int
    parent_tipset_cids: list[str]
    child_block_cid: str
    message_cid: str
    exec_index: int
    event_index: int
    event_data: EventData

    @classmethod
    def _make(cls, **fields) -> "EventProof":
        """Fast constructor for bulk claim emission (see EventData._make)."""
        out = object.__new__(cls)
        out.__dict__ = fields
        return out

    def to_json_obj(self) -> dict:
        obj = dict(self.__dict__)
        obj["event_data"] = self.event_data.to_json_obj()
        return obj

    @classmethod
    def from_json_obj(cls, obj: dict) -> "EventProof":
        obj = dict(obj)
        obj["event_data"] = EventData.from_json_obj(obj["event_data"])
        return cls(**obj)


@dataclass
class EventProofBundle:
    proofs: list[EventProof]
    blocks: list[ProofBlock]


@dataclass
class UnifiedProofBundle:
    storage_proofs: list[StorageProof]
    event_proofs: list[EventProof]
    blocks: list[ProofBlock]  # deduplicated, CID-sorted

    # --- persistence -------------------------------------------------------

    def to_json_obj(self) -> dict:
        return {
            "storage_proofs": [p.to_json_obj() for p in self.storage_proofs],
            "event_proofs": [p.to_json_obj() for p in self.event_proofs],
            "blocks": [b.to_json_obj() for b in self.blocks],
        }

    def to_json(self, indent: int | None = None) -> str:
        return json.dumps(self.to_json_obj(), indent=indent)

    @classmethod
    def from_json_obj(cls, obj: dict) -> "UnifiedProofBundle":
        return cls(
            storage_proofs=[StorageProof.from_json_obj(p) for p in obj["storage_proofs"]],
            event_proofs=[EventProof.from_json_obj(p) for p in obj["event_proofs"]],
            blocks=[ProofBlock.from_json_obj(b) for b in obj["blocks"]],
        )

    @classmethod
    def from_json(cls, text: str) -> "UnifiedProofBundle":
        return cls.from_json_obj(json.loads(text))

    def witness_bytes(self) -> int:
        return sum(len(b.data) for b in self.blocks)


@dataclass
class UnifiedVerificationResult:
    storage_results: list[bool] = field(default_factory=list)
    event_results: list[bool] = field(default_factory=list)

    def all_valid(self) -> bool:
        return all(self.storage_results) and all(self.event_results)
