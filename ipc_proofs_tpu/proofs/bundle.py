"""Proof claim types and the serializable bundle wire format.

Reference parity: `ProofBlock`/`UnifiedProofBundle`/`UnifiedVerificationResult`
(`src/proofs/common/bundle.rs`), `StorageProof` (`src/proofs/storage/bundle.rs`),
`EventData`/`EventProof`/`EventProofBundle` (`src/proofs/events/bundle.rs`).

Wire format: JSON with snake_case fields, hex strings 0x-prefixed, CIDs as
base32 strings, witness block data base64-encoded — the bundle is the durable
artifact (the reference's only "checkpoint" format, SURVEY.md §5).
"""

from __future__ import annotations

import base64
import hashlib
import json
from dataclasses import dataclass, field

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.utils.jsonstrict import strict_fields

__all__ = [
    "ProofBlock",
    "StorageProof",
    "EventData",
    "EventProof",
    "EventProofBundle",
    "UnifiedProofBundle",
    "UnifiedVerificationResult",
    "bundle_obj_digest",
]


def bundle_obj_digest(bundle_obj: dict) -> str:
    """Canonical content digest of a bundle's JSON object.

    sha256 over the sort-keys/compact-separators serialization — the ONE
    identity every plane shares: the standing-query idempotency key, the
    delta-witness base identity (`If-Witness-Base`), and the expansion
    check that makes a delta apply fail typed instead of producing
    silently different bytes.
    """
    canon = json.dumps(bundle_obj, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode("utf-8")).hexdigest()


# strict JSON field accessors for this trust boundary — bundles are THE
# untrusted input (a verifier's whole job is checking one); see
# utils/jsonstrict.py for the threat model the shared helpers encode
_S = strict_fields("malformed proof bundle")
_as_map, _get, _as_int, _as_str = _S.as_map, _S.get, _S.as_int, _S.as_str
_as_list, _as_str_list, _b64_strict = _S.as_list, _S.as_str_list, _S.b64_strict


@dataclass(frozen=True)
class ProofBlock:
    """One witness block: a CID and its raw DAG-CBOR bytes."""

    cid: CID
    data: bytes

    @classmethod
    def _make(cls, cid: CID, data: bytes) -> "ProofBlock":
        """Fast constructor: the frozen-dataclass init pays one
        ``object.__setattr__`` per field, which adds up across the thousands
        of blocks a range witness materializes."""
        out = object.__new__(cls)
        d = out.__dict__
        d["cid"] = cid
        d["data"] = data
        return out

    def to_json_obj(self) -> dict:
        return {"cid": str(self.cid), "data": base64.b64encode(self.data).decode("ascii")}

    @classmethod
    def from_json_obj(cls, obj: dict) -> "ProofBlock":
        obj = _as_map(obj, "block")
        return cls(
            cid=CID.from_string(_as_str(_get(obj, "cid", "block"), "block cid")),
            data=_b64_strict(
                _as_str(_get(obj, "data", "block"), "block data"), "block data"
            ),
        )


@dataclass
class StorageProof:
    """Claim: actor ``actor_id`` had ``value`` at storage ``slot`` in the
    state root committed by child block ``child_block_cid`` at ``child_epoch``."""

    child_epoch: int
    child_block_cid: str
    parent_state_root: str
    actor_id: int
    actor_state_cid: str
    storage_root: str
    slot: str  # 0x-hex 32 bytes
    value: str  # 0x-hex 32 bytes

    def to_json_obj(self) -> dict:
        return dict(self.__dict__)

    @classmethod
    def from_json_obj(cls, obj: dict) -> "StorageProof":
        obj = _as_map(obj, "storage proof")
        w = "storage proof"
        return cls(
            child_epoch=_as_int(_get(obj, "child_epoch", w), "child_epoch"),
            child_block_cid=_as_str(_get(obj, "child_block_cid", w), "child_block_cid"),
            parent_state_root=_as_str(
                _get(obj, "parent_state_root", w), "parent_state_root"
            ),
            actor_id=_as_int(_get(obj, "actor_id", w), "actor_id"),
            actor_state_cid=_as_str(_get(obj, "actor_state_cid", w), "actor_state_cid"),
            storage_root=_as_str(_get(obj, "storage_root", w), "storage_root"),
            slot=_as_str(_get(obj, "slot", w), "slot"),
            value=_as_str(_get(obj, "value", w), "value"),
        )


@dataclass
class EventData:
    emitter: int
    topics: list[str]  # 0x-hex, 32 bytes each
    data: str  # 0x-hex

    @classmethod
    def _make(cls, **fields) -> "EventData":
        """Fast constructor for bulk claim emission: the kwargs dict IS the
        instance dict (dataclass __init__ costs ~3× this at range scale)."""
        out = object.__new__(cls)
        out.__dict__ = fields
        return out

    def to_json_obj(self) -> dict:
        return dict(self.__dict__)

    @classmethod
    def from_json_obj(cls, obj: dict) -> "EventData":
        obj = _as_map(obj, "event data")
        return cls(
            emitter=_as_int(_get(obj, "emitter", "event data"), "emitter"),
            topics=_as_str_list(_get(obj, "topics", "event data"), "topics"),
            data=_as_str(_get(obj, "data", "event data"), "data"),
        )


@dataclass
class EventProof:
    """Claim: message ``message_cid`` at execution index ``exec_index`` in the
    parent tipset emitted ``event_data`` at ``event_index``."""

    parent_epoch: int
    child_epoch: int
    parent_tipset_cids: list[str]
    child_block_cid: str
    message_cid: str
    exec_index: int
    event_index: int
    event_data: EventData

    @classmethod
    def _make(cls, **fields) -> "EventProof":
        """Fast constructor for bulk claim emission (see EventData._make)."""
        out = object.__new__(cls)
        out.__dict__ = fields
        return out

    def to_json_obj(self) -> dict:
        obj = dict(self.__dict__)
        obj["event_data"] = self.event_data.to_json_obj()
        return obj

    @classmethod
    def from_json_obj(cls, obj: dict) -> "EventProof":
        obj = _as_map(obj, "event proof")
        w = "event proof"
        return cls(
            parent_epoch=_as_int(_get(obj, "parent_epoch", w), "parent_epoch"),
            child_epoch=_as_int(_get(obj, "child_epoch", w), "child_epoch"),
            parent_tipset_cids=_as_str_list(
                _get(obj, "parent_tipset_cids", w), "parent_tipset_cids"
            ),
            child_block_cid=_as_str(_get(obj, "child_block_cid", w), "child_block_cid"),
            message_cid=_as_str(_get(obj, "message_cid", w), "message_cid"),
            exec_index=_as_int(_get(obj, "exec_index", w), "exec_index"),
            event_index=_as_int(_get(obj, "event_index", w), "event_index"),
            event_data=EventData.from_json_obj(_get(obj, "event_data", w)),
        )


@dataclass
class EventProofBundle:
    proofs: list[EventProof]
    blocks: list[ProofBlock]


@dataclass
class UnifiedProofBundle:
    storage_proofs: list[StorageProof]
    event_proofs: list[EventProof]
    blocks: list[ProofBlock]  # deduplicated, CID-sorted

    # --- persistence -------------------------------------------------------

    def to_json_obj(self) -> dict:
        return {
            "storage_proofs": [p.to_json_obj() for p in self.storage_proofs],
            "event_proofs": [p.to_json_obj() for p in self.event_proofs],
            "blocks": [b.to_json_obj() for b in self.blocks],
        }

    def to_json(self, indent: int | None = None) -> str:
        return json.dumps(self.to_json_obj(), indent=indent)

    @classmethod
    def from_json_obj(cls, obj: dict) -> "UnifiedProofBundle":
        obj = _as_map(obj, "bundle")
        return cls(
            storage_proofs=[
                StorageProof.from_json_obj(p)
                for p in _as_list(_get(obj, "storage_proofs", "bundle"), "storage_proofs")
            ],
            event_proofs=[
                EventProof.from_json_obj(p)
                for p in _as_list(_get(obj, "event_proofs", "bundle"), "event_proofs")
            ],
            blocks=[
                ProofBlock.from_json_obj(b)
                for b in _as_list(_get(obj, "blocks", "bundle"), "blocks")
            ],
        )

    @classmethod
    def from_json(cls, text: str) -> "UnifiedProofBundle":
        return cls.from_json_obj(json.loads(text))

    def witness_bytes(self) -> int:
        return sum(len(b.data) for b in self.blocks)

    def digest(self) -> str:
        """Canonical content digest (see `bundle_obj_digest`)."""
        return bundle_obj_digest(self.to_json_obj())

    def cid_set(self) -> frozenset:
        """The witness-block CID set as raw ``cid.to_bytes()`` keys — the
        delta-witness base identity material (a delta against this bundle
        ships only blocks whose raw CID is absent from this set)."""
        return frozenset(b.cid.to_bytes() for b in self.blocks)


@dataclass
class UnifiedVerificationResult:
    storage_results: list[bool] = field(default_factory=list)
    event_results: list[bool] = field(default_factory=list)

    def all_valid(self) -> bool:
        return all(self.storage_results) and all(self.event_results)
