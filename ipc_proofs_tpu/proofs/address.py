"""ETH address → Filecoin actor ID resolution over RPC.

Reference parity: `resolve_eth_address_to_actor_id`
(`src/proofs/common/address.rs:8-62`): validate 20-byte hex →
`Filecoin.EthAddressToFilecoinAddress` → if delegated (f410) →
`Filecoin.StateLookupID` → numeric id; testnet `t` prefixes normalized.
"""

from __future__ import annotations

from ipc_proofs_tpu.state.address import Address, Protocol

__all__ = ["resolve_eth_address_to_actor_id"]


def _parse_address(text: str) -> Address:
    return Address.from_string(text)


def resolve_eth_address_to_actor_id(client, eth_addr: str) -> int:
    """``client`` is any object with `.request(method, params)` (LotusClient
    or the hermetic fake)."""
    hex_part = eth_addr.removeprefix("0x")
    raw = bytes.fromhex(hex_part)
    if len(raw) != 20:
        raise ValueError(f"Ethereum address must be 20 bytes, got {len(raw)}")

    fil_addr = client.request("Filecoin.EthAddressToFilecoinAddress", [f"0x{hex_part}"])
    address = _parse_address(fil_addr)

    if address.protocol == Protocol.DELEGATED:
        id_addr_str = client.request("Filecoin.StateLookupID", [fil_addr, None])
        return _parse_address(id_addr_str).id()
    return address.id()
