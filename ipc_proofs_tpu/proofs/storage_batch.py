"""Batch storage-proof driver: many (contract × slot) claims in one bundle.

The reference generates storage proofs strictly one at a time — each spec
re-walks the whole state tree through the shared cache
(`src/proofs/generator.rs:43-55`). BASELINE.json config 3 (65k slots across
256 contract roots) makes that shape hot, so this driver re-organizes it:

- mapping-slot preimages for ALL slots hash in one `BatchHashBackend`
  keccak256 call (device or C++) instead of per-spec scalar keccak;
- the child-header extraction and each contract's state-tree walk happen
  ONCE per contract, not once per slot;
- per-slot storage-HAMT walks record independently (host pointer-chasing);
- the witness is deduplicated across the whole grid — slots of the same
  contract share almost their entire path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from ipc_proofs_tpu.proofs.bundle import ProofBlock, StorageProof, UnifiedProofBundle
from ipc_proofs_tpu.proofs.chain import Tipset
from ipc_proofs_tpu.proofs.witness import WitnessCollector
from ipc_proofs_tpu.state.actors import get_actor_state, parse_evm_state
from ipc_proofs_tpu.state.address import Address
from ipc_proofs_tpu.state.events import ascii_to_bytes32, left_pad_32
from ipc_proofs_tpu.state.header import extract_parent_state_root
from ipc_proofs_tpu.state.storage import read_storage_slot
from ipc_proofs_tpu.store.blockstore import Blockstore, CachedBlockstore, RecordingBlockstore
from ipc_proofs_tpu.utils.metrics import Metrics

__all__ = ["MappingSlotSpec", "generate_storage_proofs_batch", "hash_slot_specs"]


@dataclass
class MappingSlotSpec:
    """A Solidity mapping slot to prove: keccak(key32 ‖ be32(slot_index))."""

    actor_id: int
    key: "bytes | str"  # 32-byte mapping key, or an ASCII subnet id
    slot_index: int = 0

    def key32(self) -> bytes:
        if isinstance(self.key, str):
            return ascii_to_bytes32(self.key)
        if len(self.key) != 32:
            raise ValueError("mapping key must be 32 bytes")
        return self.key


def hash_slot_specs(
    specs: Sequence[MappingSlotSpec], hash_backend=None
) -> "list[bytes]":
    """Derive every spec's storage-slot digest in one batch keccak call
    (device or C++ via ``hash_backend``; scalar otherwise). Range drivers
    hash once and reuse the digests across every pair."""
    preimages = [s.key32() + s.slot_index.to_bytes(32, "big") for s in specs]
    if hash_backend is not None:
        return hash_backend.keccak256_batch(preimages)
    from ipc_proofs_tpu.core.hashes import keccak256

    return [keccak256(p) for p in preimages]


def generate_storage_proofs_batch(
    store: Blockstore,
    parent: Tipset,
    child: Tipset,
    specs: Sequence[MappingSlotSpec],
    hash_backend=None,
    metrics: Optional[Metrics] = None,
    precomputed_slots: "Optional[Sequence[bytes]]" = None,
) -> UnifiedProofBundle:
    """Generate storage proofs for a grid of mapping slots.

    ``hash_backend``: optional `BatchHashBackend`; all slot preimages hash in
    one batch call. None = scalar keccak per slot. ``precomputed_slots``
    skips the hashing phase entirely (range drivers hash the grid once for
    all pairs via `hash_slot_specs`).
    """
    metrics = metrics or Metrics()
    cached = CachedBlockstore(store)

    # Phase 1: derive all slot digests in one batch.
    with metrics.stage("slot_hash"):
        if precomputed_slots is not None:
            if len(precomputed_slots) != len(specs):
                raise ValueError("precomputed_slots length must match specs")
            slots = list(precomputed_slots)
        else:
            slots = hash_slot_specs(specs, hash_backend)
    metrics.count("batch_slots", len(slots))

    # Phase 2: child header extraction + cross-check (once for the batch).
    child_cid = child.cids[0]
    header_recorder = RecordingBlockstore(cached)
    child_header_raw = header_recorder.get(child_cid)
    if child_header_raw is None:
        raise KeyError(f"missing child header {child_cid}")
    parent_state_root = extract_parent_state_root(child_header_raw)
    if parent_state_root != child.blocks[0].parent_state_root:
        raise ValueError("ParentStateRoot mismatch between header CBOR and tipset view")

    collector = WitnessCollector(cached)
    collector.add_cid(child_cid)
    collector.add_cid(parent_state_root)
    collector.collect_from_recording(header_recorder)

    # Phase 3: one state-tree walk per distinct contract.
    with metrics.stage("actor_walks"):
        contract_info: dict[int, tuple] = {}
        for actor_id in {s.actor_id for s in specs}:
            recorder = RecordingBlockstore(cached)
            actor = get_actor_state(recorder, parent_state_root, Address.new_id(actor_id))
            evm_state_raw = recorder.get(actor.state)
            if evm_state_raw is None:
                raise KeyError(f"missing EVM state {actor.state}")
            storage_root = parse_evm_state(evm_state_raw).contract_state
            collector.add_cid(actor.state)
            collector.add_cid(storage_root)
            collector.collect_from_recording(recorder)
            contract_info[actor_id] = (actor.state, storage_root)
    metrics.count("batch_contracts", len(contract_info))

    # Phase 4: per-slot storage reads under recording (host pointer-chasing).
    proofs: list[StorageProof] = []
    with metrics.stage("slot_reads"):
        for spec, slot in zip(specs, slots):
            actor_state_cid, storage_root = contract_info[spec.actor_id]
            recorder = RecordingBlockstore(cached)
            raw_value = read_storage_slot(recorder, storage_root, slot) or b""
            collector.collect_from_recording(recorder)
            proofs.append(
                StorageProof(
                    child_epoch=child.height,
                    child_block_cid=str(child_cid),
                    parent_state_root=str(parent_state_root),
                    actor_id=spec.actor_id,
                    actor_state_cid=str(actor_state_cid),
                    storage_root=str(storage_root),
                    slot="0x" + slot.hex(),
                    value="0x" + left_pad_32(raw_value).hex(),
                )
            )

    with metrics.stage("materialize"):
        blocks = collector.materialize()
    return UnifiedProofBundle(storage_proofs=proofs, event_proofs=[], blocks=blocks)
