"""Batch storage-proof driver: many (contract × slot) claims in one bundle.

The reference generates storage proofs strictly one at a time — each spec
re-walks the whole state tree through the shared cache
(`src/proofs/generator.rs:43-55`). BASELINE.json config 3 (65k slots across
256 contract roots) makes that shape hot, so this driver re-organizes it:

- mapping-slot preimages for ALL slots hash in one `BatchHashBackend`
  keccak256 call (device or C++) instead of per-spec scalar keccak;
- the child-header extraction and each contract's state-tree walk happen
  ONCE per contract, not once per slot;
- per-slot storage-HAMT walks record independently (host pointer-chasing);
- the witness is deduplicated across the whole grid — slots of the same
  contract share almost their entire path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from ipc_proofs_tpu.proofs.bundle import StorageProof, UnifiedProofBundle
from ipc_proofs_tpu.proofs.chain import Tipset
from ipc_proofs_tpu.proofs.witness import WitnessCollector
from ipc_proofs_tpu.state.actors import get_actor_state, parse_evm_state
from ipc_proofs_tpu.state.address import Address
from ipc_proofs_tpu.state.events import ascii_to_bytes32, left_pad_32
from ipc_proofs_tpu.state.header import extract_parent_state_root
from ipc_proofs_tpu.state.storage import read_storage_slot
from ipc_proofs_tpu.store.blockstore import Blockstore, CachedBlockstore, RecordingBlockstore
from ipc_proofs_tpu.utils.metrics import Metrics

__all__ = [
    "MappingSlotSpec",
    "generate_storage_proofs_batch",
    "generate_storage_proofs_for_pairs",
    "hash_slot_specs",
]


@dataclass
class MappingSlotSpec:
    """A Solidity mapping slot to prove: keccak(key32 ‖ be32(slot_index))."""

    actor_id: int
    key: "bytes | str"  # 32-byte mapping key, or an ASCII subnet id
    slot_index: int = 0

    def key32(self) -> bytes:
        if isinstance(self.key, str):
            return ascii_to_bytes32(self.key)
        if len(self.key) != 32:
            raise ValueError("mapping key must be 32 bytes")
        return self.key


def hash_slot_specs(
    specs: Sequence[MappingSlotSpec], hash_backend=None
) -> "list[bytes]":
    """Derive every spec's storage-slot digest in one batch keccak call
    (device or C++ via ``hash_backend``; scalar otherwise). Range drivers
    hash once and reuse the digests across every pair."""
    preimages = [s.key32() + s.slot_index.to_bytes(32, "big") for s in specs]
    if hash_backend is not None:
        return hash_backend.keccak256_batch(preimages)
    from ipc_proofs_tpu.core.hashes import keccak256

    return [keccak256(p) for p in preimages]


def generate_storage_proofs_batch(
    store: Blockstore,
    parent: Tipset,
    child: Tipset,
    specs: Sequence[MappingSlotSpec],
    hash_backend=None,
    metrics: Optional[Metrics] = None,
    precomputed_slots: "Optional[Sequence[bytes]]" = None,
) -> UnifiedProofBundle:
    """Generate storage proofs for a grid of mapping slots.

    ``hash_backend``: optional `BatchHashBackend`; all slot preimages hash in
    one batch call. None = scalar keccak per slot. ``precomputed_slots``
    skips the hashing phase entirely (range drivers hash the grid once for
    all pairs via `hash_slot_specs`).
    """
    metrics = metrics or Metrics()
    cached = CachedBlockstore(store)

    # Phase 1: derive all slot digests in one batch.
    with metrics.stage("slot_hash"):
        if precomputed_slots is not None:
            if len(precomputed_slots) != len(specs):
                raise ValueError("precomputed_slots length must match specs")
            slots = list(precomputed_slots)
        else:
            slots = hash_slot_specs(specs, hash_backend)
    metrics.count("batch_slots", len(slots))

    # Phase 2: child header extraction + cross-check (once for the batch).
    child_cid = child.cids[0]
    header_recorder = RecordingBlockstore(cached)
    child_header_raw = header_recorder.get(child_cid)
    if child_header_raw is None:
        raise KeyError(f"missing child header {child_cid}")
    parent_state_root = extract_parent_state_root(child_header_raw)
    if parent_state_root != child.blocks[0].parent_state_root:
        raise ValueError("ParentStateRoot mismatch between header CBOR and tipset view")

    collector = WitnessCollector(cached)
    collector.add_cid(child_cid)
    collector.add_cid(parent_state_root)
    collector.collect_from_recording(header_recorder)

    # Phase 3: one state-tree walk per distinct contract.
    with metrics.stage("actor_walks"):
        contract_info: dict[int, tuple] = {}
        for actor_id in sorted({s.actor_id for s in specs}):
            recorder = RecordingBlockstore(cached)
            actor = get_actor_state(recorder, parent_state_root, Address.new_id(actor_id))
            evm_state_raw = recorder.get(actor.state)
            if evm_state_raw is None:
                raise KeyError(f"missing EVM state {actor.state}")
            storage_root = parse_evm_state(evm_state_raw).contract_state
            collector.add_cid(actor.state)
            collector.add_cid(storage_root)
            collector.collect_from_recording(recorder)
            contract_info[actor_id] = (actor.state, storage_root)
    metrics.count("batch_contracts", len(contract_info))

    # Phase 4: per-slot storage reads under recording (host pointer-chasing).
    proofs: list[StorageProof] = []
    with metrics.stage("slot_reads"):
        for spec, slot in zip(specs, slots):
            actor_state_cid, storage_root = contract_info[spec.actor_id]
            recorder = RecordingBlockstore(cached)
            raw_value = read_storage_slot(recorder, storage_root, slot) or b""
            collector.collect_from_recording(recorder)
            proofs.append(
                StorageProof(
                    child_epoch=child.height,
                    child_block_cid=str(child_cid),
                    parent_state_root=str(parent_state_root),
                    actor_id=spec.actor_id,
                    actor_state_cid=str(actor_state_cid),
                    storage_root=str(storage_root),
                    slot="0x" + slot.hex(),
                    value="0x" + left_pad_32(raw_value).hex(),
                )
            )

    with metrics.stage("materialize"):
        blocks = collector.materialize()
    return UnifiedProofBundle(storage_proofs=proofs, event_proofs=[], blocks=blocks)


def generate_storage_proofs_for_pairs(
    cached: Blockstore,
    pairs: Sequence,
    specs: Sequence[MappingSlotSpec],
    slots: Sequence[bytes],
) -> "Optional[tuple[list[StorageProof], set[bytes]]]":
    """Range-batched storage generation: every (pair × spec) claim in one
    pass — child headers decode once per pair, unique (state root, actor)
    pairs resolve through ONE batched C actors-tree walk (with per-item
    witness recording), storage roots classify once
    (`classify_storage_root`) and the HAMT-encoded ones walk in one more
    batched C call. Returns ``(proofs, witness_cid_bytes)`` with claims in
    (pair, spec) order — field-identical to looping
    `generate_storage_proofs_batch` per pair (tested differentially) —
    or None when the native walker is unavailable. Error types match the
    scalar loop per claim, though batch phase ordering can surface a
    different claim's error first.
    """
    from ipc_proofs_tpu.core.cid import CID
    from ipc_proofs_tpu.core.dagcbor import decode as cbor_decode
    from ipc_proofs_tpu.ipld.hamt import hamt_get_batch_touched
    from ipc_proofs_tpu.state.actors import ActorState, StateRoot
    from ipc_proofs_tpu.state.header import decode_header_lite
    from ipc_proofs_tpu.state.storage import classify_storage_root

    if hamt_get_batch_touched(cached, [], [], []) is None:
        return None
    witness: set[bytes] = set()

    # Phase A: per pair — child header decode + parent-state-root cross-check.
    pair_psr: list[CID] = []
    for pair in pairs:
        child_cid = pair.child.cids[0]
        raw = cached.get(child_cid)
        if raw is None:
            raise KeyError(f"missing child header {child_cid}")
        psr = decode_header_lite(raw).parent_state_root
        if psr != pair.child.blocks[0].parent_state_root:
            raise ValueError(
                "ParentStateRoot mismatch between header CBOR and tipset view"
            )
        pair_psr.append(psr)
        witness.add(child_cid.to_bytes())
        witness.add(psr.to_bytes())

    # Phase B: unique state roots → actors roots (StateRoot block is part
    # of the witness; missing → the scalar get_actor_state KeyError).
    actors_root: dict[CID, CID] = {}
    # dict.fromkeys = dedup in first-seen pair order (set order is salted)
    for psr in dict.fromkeys(pair_psr):
        raw = cached.get(psr)
        if raw is None:
            raise KeyError(f"missing StateRoot {psr}")
        actors_root[psr] = StateRoot.decode(raw).actors

    # Phase C: unique (state root, actor) → ActorState via one batched
    # recorded walk; then EVM state per unique actor-state CID.
    actor_ids = sorted({s.actor_id for s in specs})
    walk_roots: list[CID] = []
    root_pos: dict[CID, int] = {}
    owners: list[int] = []
    keys: list[bytes] = []
    pairs_keys: list[tuple[CID, int]] = []
    for psr in sorted(set(pair_psr), key=CID.to_bytes):
        for actor_id in actor_ids:
            pos = root_pos.setdefault(actors_root[psr], len(walk_roots))
            if pos == len(walk_roots):
                walk_roots.append(actors_root[psr])
            owners.append(pos)
            keys.append(Address.new_id(actor_id).to_bytes())
            pairs_keys.append((psr, actor_id))
    walk = hamt_get_batch_touched(cached, walk_roots, owners, keys)
    assert walk is not None  # availability probed above
    values, touched = walk
    contract_info: dict[tuple[CID, int], tuple[CID, CID]] = {}
    evm_cache: dict[CID, CID] = {}
    for (psr, actor_id), value, item_touched in zip(pairs_keys, values, touched):
        if value is None:
            raise KeyError(f"actor not found for {Address.new_id(actor_id)}")
        witness.update(item_touched)
        actor = ActorState.from_tuple(value)
        storage_root = evm_cache.get(actor.state)
        if storage_root is None:
            evm_state_raw = cached.get(actor.state)
            if evm_state_raw is None:
                raise KeyError(f"missing EVM state {actor.state}")
            storage_root = parse_evm_state(evm_state_raw).contract_state
            evm_cache[actor.state] = storage_root
        witness.add(actor.state.to_bytes())
        witness.add(storage_root.to_bytes())
        contract_info[(psr, actor_id)] = (actor.state, storage_root)

    # Phase D: classify each unique storage root once; HAMT-encoded roots
    # batch their slot walks (grouped by bit width), SmallMap roots resolve
    # host-side against the root block alone. First-match-wins inside a
    # SmallMap mirrors `_small_map_lookup`'s list scan.
    unique_roots = sorted(
        {info[1] for info in contract_info.values()}, key=CID.to_bytes
    )
    resolver: dict[CID, tuple] = {}
    for root in unique_roots:
        raw = cached.get(root)
        if raw is None:
            raise KeyError(f"missing contract_state root {root}")
        witness.add(root.to_bytes())
        kind, payload, bw = classify_storage_root(cbor_decode(raw))
        if kind == "smallmap":
            first_wins: dict[bytes, bytes] = {}
            for k, v in payload["v"]:
                first_wins.setdefault(k, v)
            resolver[root] = ("map", first_wins)
        elif payload is None and 1 <= bw <= 8:
            resolver[root] = ("hamt", root, bw)  # C: direct at the root
        elif payload is not None and 1 <= bw <= 8:
            resolver[root] = ("hamt", payload, bw)
        else:
            resolver[root] = ("scalar", None)  # odd bit widths: scalar read

    # batched HAMT slot walks, grouped by bit width; distinct (state root,
    # actor) pairs often share one storage root across a range, so walks
    # dedup on (storage_root, slot) — slot_values carries the shared result
    needed: dict[int, tuple[list, dict, list, list, list]] = {}
    walk_seen: set[tuple[CID, bytes]] = set()
    for (psr, actor_id), (_, storage_root) in contract_info.items():
        kind = resolver[storage_root][0]
        if kind != "hamt":
            continue
        _, walk_root, bw = resolver[storage_root]
        group = needed.setdefault(bw, ([], {}, [], [], []))
        g_roots, g_pos, g_owner, g_keys, g_ident = group
        for spec, slot in zip(specs, slots):
            if spec.actor_id != actor_id:
                continue
            ident = (storage_root, slot)
            if ident in walk_seen:
                continue
            walk_seen.add(ident)
            pos = g_pos.setdefault(walk_root, len(g_roots))
            if pos == len(g_roots):
                g_roots.append(walk_root)
            g_owner.append(pos)
            g_keys.append(slot)
            g_ident.append(ident)
    slot_values: dict[tuple[CID, bytes], bytes] = {}
    for bw, (g_roots, _, g_owner, g_keys, g_ident) in sorted(needed.items()):
        walk = hamt_get_batch_touched(cached, g_roots, g_owner, g_keys, bit_width=bw)
        assert walk is not None
        for ident, value, item_touched in zip(g_ident, walk[0], walk[1]):
            witness.update(item_touched)
            slot_values[ident] = value

    # Phase E: claims in (pair, spec) order — strings cached per CID.
    str_cache: dict[CID, str] = {}

    def _s(cid: CID) -> str:
        out = str_cache.get(cid)
        if out is None:
            out = str(cid)
            str_cache[cid] = out
        return out

    slot_hex = ["0x" + s.hex() for s in slots]
    proofs: list[StorageProof] = []
    for pair, psr in zip(pairs, pair_psr):
        child_cid = pair.child.cids[0]
        child_str = _s(child_cid)
        psr_str = _s(psr)
        for j, spec in enumerate(specs):
            actor_state_cid, storage_root = contract_info[(psr, spec.actor_id)]
            kind = resolver[storage_root]
            if kind[0] == "map":
                raw_value = kind[1].get(slots[j])
            elif kind[0] == "hamt":
                raw_value = slot_values[(storage_root, slots[j])]
            else:  # odd bit width: the scalar cascade, recorded
                recorder = RecordingBlockstore(cached)
                raw_value = read_storage_slot(recorder, storage_root, slots[j])
                witness.update(c.to_bytes() for c in recorder.take_seen())
            proofs.append(
                StorageProof(
                    child_epoch=pair.child.height,
                    child_block_cid=child_str,
                    parent_state_root=psr_str,
                    actor_id=spec.actor_id,
                    actor_state_cid=_s(actor_state_cid),
                    storage_root=_s(storage_root),
                    slot=slot_hex[j],
                    value="0x" + left_pad_32(raw_value or b"").hex(),
                )
            )
    return proofs, witness
