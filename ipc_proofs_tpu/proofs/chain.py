"""Tipset: the chain-view type the generators take as input.

Re-design of the reference's `ApiTipset`/`ApiBlockHeader` JSON mirror types
(`src/client/types.rs:42-60`): instead of carrying a partial JSON projection,
a `Tipset` holds the block CIDs plus fully decoded `BlockHeader`s, and can be
built either from Lotus RPC JSON (online) or straight from a blockstore
(fixtures / offline), which the reference cannot do.
"""

from __future__ import annotations

import base64
from dataclasses import dataclass

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.state.events import Receipt
from ipc_proofs_tpu.state.header import BlockHeader
from ipc_proofs_tpu.store.blockstore import Blockstore

__all__ = ["Tipset", "receipt_from_api_json"]


def receipt_from_api_json(obj: dict) -> Receipt:
    """`ApiReceipt` JSON → `Receipt` (reference `client/types.rs:22-37`):
    ``Return`` is base64 (null/empty → b""), ``EventsRoot`` a CIDMap or null.

    This is the wire conversion for the `Filecoin.ChainGetParentReceipts`
    fallback pathway — see `event_generator.scan_receipts_from_api`.
    """
    ret = obj.get("Return")
    events_root = obj.get("EventsRoot")
    return Receipt(
        exit_code=obj["ExitCode"],
        return_data=base64.b64decode(ret) if ret else b"",
        gas_used=obj.get("GasUsed", 0),
        events_root=CID.from_string(events_root["/"]) if events_root else None,
    )


@dataclass
class Tipset:
    cids: list[CID]
    blocks: list[BlockHeader]
    height: int

    def __post_init__(self):
        if len(self.cids) != len(self.blocks):
            raise ValueError("tipset cids/blocks length mismatch")
        if not self.cids:
            raise ValueError("empty tipset")

    @classmethod
    def from_blockstore(cls, store: Blockstore, cids: list[CID]) -> "Tipset":
        blocks = []
        for cid in cids:
            raw = store.get(cid)
            if raw is None:
                raise KeyError(f"missing header {cid}")
            blocks.append(BlockHeader.decode(raw))
        return cls(cids=cids, blocks=blocks, height=blocks[0].height)

    @classmethod
    def from_api_json(cls, obj: dict) -> "Tipset":
        """Build from a `Filecoin.ChainGetTipSetByHeight` response.

        Note: unlike the reference we re-derive headers from their CBOR when
        available; here we trust the JSON fields we need (the generators
        cross-check against raw header CBOR anyway, mirroring
        `storage/generator.rs:72-103`).
        """
        cids = [CID.from_string(c["/"]) for c in obj["Cids"]]
        blocks = []
        for header_json in obj["Blocks"]:
            blocks.append(
                BlockHeader(
                    parents=[CID.from_string(c["/"]) for c in header_json["Parents"]],
                    height=header_json["Height"],
                    parent_state_root=CID.from_string(header_json["ParentStateRoot"]["/"]),
                    parent_message_receipts=CID.from_string(
                        header_json["ParentMessageReceipts"]["/"]
                    ),
                    messages=CID.from_string(header_json["Messages"]["/"]),
                    timestamp=header_json.get("Timestamp", 0),
                )
            )
        return cls(cids=cids, blocks=blocks, height=obj["Height"])

    @classmethod
    def fetch(cls, client, height: int) -> "Tipset":
        """Fetch by height over RPC (`Filecoin.ChainGetTipSetByHeight`)."""
        return cls.from_api_json(client.request("Filecoin.ChainGetTipSetByHeight", [height, None]))
