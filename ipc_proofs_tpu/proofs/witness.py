"""Witness collection: the set of IPLD blocks a verifier will need.

Reference parity: `WitnessCollector` (`src/proofs/common/witness.rs:9-72`) —
accumulates CIDs (ordered set), drains `RecordingBlockstore`s, and
materializes to `ProofBlock`s by re-fetching bytes (cache hits in practice).
"""

from __future__ import annotations

from typing import Iterable

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.proofs.bundle import ProofBlock
from ipc_proofs_tpu.store.blockstore import Blockstore, RecordingBlockstore

__all__ = ["WitnessCollector", "block_cid_set", "load_witness_store"]


def block_cid_set(blocks: Iterable[ProofBlock]) -> frozenset:
    """Raw ``cid.to_bytes()`` keys for a block list — the canonical-set
    identity the delta-witness plane diffs against (see
    `ipc_proofs_tpu/witness/delta.py`)."""
    return frozenset(b.cid.to_bytes() for b in blocks)


class WitnessCollector:
    def __init__(self, store: Blockstore):
        self._store = store
        self._needed: set[CID] = set()

    def add_cid(self, cid: CID) -> None:
        self._needed.add(cid)

    def add_cids(self, cids: Iterable[CID]) -> None:
        self._needed.update(cids)

    def collect_from_recording(self, recorder: RecordingBlockstore) -> None:
        self._needed.update(recorder.take_seen())

    def collect_from_recordings(self, recorders: Iterable[RecordingBlockstore]) -> None:
        for recorder in recorders:
            self.collect_from_recording(recorder)

    def needed_cids(self) -> set[CID]:
        """The accumulated CID set (callers merging several collectors'
        witness sets without materializing each separately)."""
        return set(self._needed)

    def materialize(self) -> list[ProofBlock]:
        """Fetch every needed CID's bytes; CID-sorted like the reference's
        BTreeSet iteration order."""
        blocks = []
        for cid in sorted(self._needed):
            raw = self._store.get(cid)
            if raw is None:
                raise KeyError(f"missing witness block {cid}")
            blocks.append(ProofBlock(cid=cid, data=raw))
        return blocks


def load_witness_store(
    blocks: Iterable[ProofBlock],
    verify_cids: bool = False,
    base_blocks: "Iterable[ProofBlock] | None" = None,
):
    """Load witness blocks into an isolated MemoryBlockstore
    (reference `storage/verifier.rs:68-78`, `events/verifier.rs:79-89`).

    ``verify_cids=True`` recomputes every CID on load — the explicit
    integrity check the reference skips (SURVEY.md §2b note on `put_keyed`);
    the TPU backend batches the same recomputation.

    ``base_blocks`` is the delta-witness overlay seam: a verifier holding
    a base epoch's blocks loads them UNDER the delta's blocks (same CID ⇒
    same bytes by CID-addressing, so overlay order is cosmetic) and
    verifies without ever materializing the merged block list.
    """
    from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

    store = MemoryBlockstore(verify_cids=verify_cids)
    if not verify_cids:
        # bulk path: one call, no per-block method dispatch (a range
        # witness is thousands of blocks)
        if base_blocks is not None:
            store.put_many_trusted(base_blocks)
        store.put_many_trusted(blocks)
        return store
    if base_blocks is not None:
        for block in base_blocks:
            store.put_keyed(block.cid, block.data)
    for block in blocks:
        store.put_keyed(block.cid, block.data)
    return store
