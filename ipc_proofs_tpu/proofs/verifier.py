"""Unified proof verification under a trust policy.

Reference parity: `verify_proof_bundle` (`src/proofs/verifier.rs`): adapts
the `TrustPolicy` into closures, verifies all storage proofs, then all event
proofs against the shared witness.
"""

from __future__ import annotations

from typing import Callable, Optional

from ipc_proofs_tpu.proofs.bundle import (
    EventProofBundle,
    UnifiedProofBundle,
    UnifiedVerificationResult,
)
from ipc_proofs_tpu.proofs.event_verifier import verify_event_proof
from ipc_proofs_tpu.proofs.storage_verifier import verify_storage_proof
from ipc_proofs_tpu.proofs.trust import TrustPolicy
from ipc_proofs_tpu.state.events import ActorEvent

__all__ = ["verify_proof_bundle"]


def verify_proof_bundle(
    bundle: UnifiedProofBundle,
    trust_policy: TrustPolicy,
    event_filter: Optional[Callable[[ActorEvent], bool]] = None,
    verify_witness_cids: bool = False,
    cid_backend=None,
) -> UnifiedVerificationResult:
    """Verify all proofs in ``bundle`` under ``trust_policy``.

    ``verify_witness_cids`` recomputes every witness block's CID — the
    explicit integrity check the reference skips. With ``cid_backend`` (a
    `BatchHashBackend`) the recomputation runs as ONE batch (C++ or TPU,
    BASELINE.json config 4); otherwise it happens scalar on load. Raises
    ValueError on any mismatching block.
    """
    if verify_witness_cids and cid_backend is not None:
        from ipc_proofs_tpu.core.cid import BLAKE2B_256

        batch = [b for b in bundle.blocks if b.cid.mh_code == BLAKE2B_256]
        if batch and not cid_backend.verify_block_cids(
            [b.cid.digest for b in batch], [b.data for b in batch]
        ):
            raise ValueError("witness block bytes do not hash to their claimed CIDs")
        # non-blake2b blocks (rare) still verify scalar below
        verify_witness_cids = any(b.cid.mh_code != BLAKE2B_256 for b in bundle.blocks)

    # One witness store for the whole bundle: loaded (and, when requested,
    # CID-verified) exactly once, shared by every storage and event proof.
    # The reference rebuilds it per storage proof (`storage/verifier.rs:68-78`).
    from ipc_proofs_tpu.proofs.witness import load_witness_store

    shared_store = load_witness_store(bundle.blocks, verify_cids=verify_witness_cids)

    def child_verifier(epoch, cid):
        try:
            return trust_policy.verify_child_header(epoch, cid)
        except Exception:  # fail-soft: a throwing trust policy is a rejection — the proof verdict reports invalid, never crashes verify
            return False

    def parent_verifier(epoch, cids):
        try:
            return trust_policy.verify_parent_tipset(epoch, cids)
        except Exception:  # fail-soft: a throwing trust policy is a rejection — the proof verdict reports invalid, never crashes verify
            return False

    # Storage proofs: batched replay when the native HAMT walker is
    # available (shared header decodes + one actors-tree walk for the
    # bundle; verdict-identical to the scalar loop), scalar otherwise.
    storage_results = None
    if bundle.storage_proofs:
        from ipc_proofs_tpu.proofs.storage_verifier import verify_storage_proofs_batch

        storage_results = verify_storage_proofs_batch(
            shared_store, bundle.storage_proofs, child_verifier
        )
    if storage_results is None:
        storage_results = [
            verify_storage_proof(proof, bundle.blocks, child_verifier, store=shared_store)
            for proof in bundle.storage_proofs
        ]

    event_bundle = EventProofBundle(proofs=bundle.event_proofs, blocks=bundle.blocks)
    event_results = verify_event_proof(
        event_bundle,
        parent_verifier,
        child_verifier,
        check_event=event_filter,
        store=shared_store,
    )

    return UnifiedVerificationResult(
        storage_results=storage_results, event_results=event_results
    )
