"""go-f3 gpbft signing payloads (wire-level certificate interop).

A finality certificate's aggregate signature covers the DECIDE payload of
the gpbft instance that produced it. go-f3 marshals that payload with a
custom binary layout (NOT cbor) — ``gpbft.Payload.MarshalForSigning`` —
over a domain-separation prefix, the instance/round/phase numbers, the
supplemental data, and the EC chain's canonical key. This module
reconstructs that layout field-for-field:

    "GPBFT" ":" network_name ":"            (ASCII, no terminator)
    instance  — uint64 BE
    round     — uint64 BE
    phase     — uint8   (DECIDE = 5)
    supplemental_data.commitments — 32 raw bytes
    ec_chain.Key()                — see below
    supplemental_data.power_table — raw CID bytes

where ``ECChain.Key()`` concatenates, per tipset:

    epoch        — int64 BE
    commitments  — 32 raw bytes
    len(key)     — uint32 BE
    key          — the tipset key: the blocks' CID bytes, concatenated
    power_table  — raw CID bytes

Derivation note: the layout is reconstructed from the public go-f3 source
(``gpbft/types.go``: ``Payload.MarshalForSigning`` + ``ECChain.Key``);
byte-level fixtures from a live go-f3 node are unfetchable in this
zero-egress environment (NOTES_r05.md), so the one residual interop risk
is a field-order memory error here — each field is written by one line
below, so any future vector mismatch is a one-line fix. The reference
leaves this entire boundary as TODO stubs (`src/proofs/trust/mod.rs:58,72`).
"""

from __future__ import annotations

import struct
from typing import Sequence

__all__ = [
    "DOMAIN_SEPARATION_TAG",
    "DECIDE_PHASE",
    "DEFAULT_NETWORK",
    "ec_chain_key",
    "payload_marshal_for_signing",
]

DOMAIN_SEPARATION_TAG = "GPBFT"

# gpbft phase numbering (go-f3 gpbft/gpbft.go): INITIAL=0, QUALITY=1,
# CONVERGE=2, PREPARE=3, COMMIT=4, DECIDE=5, TERMINATED=6
DECIDE_PHASE = 5

DEFAULT_NETWORK = "filecoin"


def commitments32(raw: bytes, what: str, strict: bool = False) -> bytes:
    """Commitments are a fixed [32]byte in go-f3; empty means all-zero on
    the ENCODE side (the dataclass default). ``strict`` (wire decode)
    requires exactly 32 bytes — cborgen rejects any other length, and
    tolerating b"" there would create a second wire form."""
    if not raw and not strict:
        return bytes(32)
    if len(raw) != 32:
        raise ValueError(f"{what} commitments must be 32 bytes, got {len(raw)}")
    return bytes(raw)


_commitments32 = commitments32  # internal alias


def tipset_key_bytes(key: "Sequence[str]") -> bytes:
    """Lotus ``TipSetKey.Bytes()``: the blocks' binary CIDs concatenated."""
    from ipc_proofs_tpu.core.cid import CID

    return b"".join(CID.from_string(c).to_bytes() for c in key)


def ec_chain_key(tipsets: Sequence) -> bytes:
    """``ECChain.Key()``: the canonical byte key of an EC chain.

    ``tipsets``: objects with ``epoch`` (int), ``key`` (list of CID
    strings), ``power_table`` (CID string), ``commitments`` (bytes).
    """
    from ipc_proofs_tpu.core.cid import CID

    out = bytearray()
    for ts in tipsets:
        out += struct.pack(">q", ts.epoch)
        out += _commitments32(ts.commitments, "ECTipSet")
        key_bytes = tipset_key_bytes(ts.key)
        out += struct.pack(">I", len(key_bytes))
        out += key_bytes
        out += CID.from_string(ts.power_table).to_bytes()
    return bytes(out)


def payload_marshal_for_signing(
    instance: int,
    ec_chain: Sequence,
    supplemental_commitments: bytes,
    supplemental_power_table: str,
    round_: int = 0,
    phase: int = DECIDE_PHASE,
    network: str = DEFAULT_NETWORK,
) -> bytes:
    """``Payload.MarshalForSigning``: the exact byte string the committee's
    aggregate BLS signature covers. For a finality certificate the payload
    is the instance's DECIDE (round 0, phase 5) over its EC chain."""
    from ipc_proofs_tpu.core.cid import CID

    out = bytearray()
    out += DOMAIN_SEPARATION_TAG.encode("ascii")
    out += b":"
    out += network.encode("utf-8")
    out += b":"
    out += struct.pack(">Q", instance)
    out += struct.pack(">Q", round_)
    out += struct.pack(">B", phase)
    out += _commitments32(supplemental_commitments, "SupplementalData")
    out += ec_chain_key(ec_chain)
    if supplemental_power_table:
        out += CID.from_string(supplemental_power_table).to_bytes()
    return bytes(out)
