"""Multi-tipset range driver: batch proof generation over many epoch pairs.

The reference operates on exactly one (parent H, child H+1) pair per run
(`src/main.rs`); the north-star workload is a 4096-tipset range. This driver
re-shapes the work TPU-first:

- Phase A (host):   decode receipts + events for EVERY pair — pointer
                    chasing stays on host, feeding flat lists;
- Phase B (device): ONE batched predicate call over all events in the range
                    (`BatchHashBackend.event_match_mask`), instead of the
                    reference's per-receipt loops;
- Phase C (host):   per-pair pass-2 recording only for matching receipts;
- Phase D:          one merged, CID-deduplicated witness — adjacent pairs
                    share headers/TxMeta/receipt paths, so the range-level
                    dedup is strictly stronger than the reference's
                    per-bundle dedup.

Mixed bundles: every driver accepts ``storage_specs`` (a
`storage_batch.MappingSlotSpec` grid proved at every pair, slot keccaks
hashed once range-wide) and merges both proof kinds into the one
deduplicated, checkpoint-resumable witness — the range generalization of
the reference's unified bundle (`src/proofs/generator.rs:25-95`).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional, Sequence

from ipc_proofs_tpu.proofs.bundle import ProofBlock, UnifiedProofBundle
from ipc_proofs_tpu.proofs.chain import Tipset
from ipc_proofs_tpu.proofs.event_generator import (
    EventMatcher,
    collect_base_witness_and_exec_order,
    match_receipt_indices,
    record_matching_receipts,
    scan_receipt_events,
)
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.witness import WitnessCollector
from ipc_proofs_tpu.state.events import StampedEvent
from ipc_proofs_tpu.store.blockstore import Blockstore, CachedBlockstore
from ipc_proofs_tpu.utils.deadline import (
    checkpoint as _dl_checkpoint,
    remaining_budget_s as _remaining_budget_s,
)
from ipc_proofs_tpu.utils.metrics import Metrics, get_metrics
from ipc_proofs_tpu.utils.lockdep import named_lock

__all__ = [
    "TipsetPair",
    "generate_event_proofs_for_range",
    "generate_event_proofs_for_range_chunked",
    "generate_event_proofs_for_range_pipelined",
    "generate_and_verify_range_overlapped",
]


def generate_and_verify_range_overlapped(
    store: Blockstore,
    pairs: Sequence[TipsetPair],
    spec: EventProofSpec,
    chunk_size: int,
    verify_chunk,
    match_backend=None,
    metrics: Optional[Metrics] = None,
    storage_specs=None,
    generate_fn=None,
    scan_threads: "int | None" = None,
    pipeline_depth: int = 2,
    checkpoint_dir: "str | None" = None,
    scan_retries: int = 2,
    force_pipeline: "bool | None" = None,
    job_dir: "str | None" = None,
    record_workers: "int | None" = None,
    verify_workers: "int | None" = None,
    threads: "int | None" = None,
) -> "tuple[UnifiedProofBundle, list]":
    """Overlap VERIFICATION with generation across chunks: chunk k's bundle
    verifies while chunk k+1 generates — the generation-verification
    analog of the pipelined driver's scan/record overlap, and the last
    structural concurrency on the headline path that needs no extra
    hardware.

    Default path (no ``generate_fn``): the integrated pipeline — scan
    (``scan_threads`` workers) ∥ record (``record_workers``) ∥ merge ∥
    verify (``verify_workers``) in ONE bounded-queue executor
    (`generate_event_proofs_for_range_pipelined` with its verify stage),
    so scan(k+1), record(k), and verify(k-1) all run concurrently; storage
    specs flow through the same pipeline as storage chunks. With a custom
    ``generate_fn`` it composes over the chunked driver instead: chunk
    bundles verify on a worker thread via the ``on_chunk`` hook.

    ``verify_chunk(bundle) -> result`` is the caller's verification closure
    (it runs off-thread; per-chunk results are returned in chunk order).
    Each chunk bundle is self-contained (its witness covers its proofs), so
    per-chunk verdicts match whole-bundle verification verdict-for-verdict;
    the merged bundle is bit-identical to the chunked driver's over the
    same ``chunk_size`` — both pinned by tests/test_range.py.
    """
    if generate_fn is None:
        verify_results: list = []
        merged = generate_event_proofs_for_range_pipelined(
            store,
            pairs,
            spec,
            chunk_size=chunk_size,
            match_backend=match_backend,
            metrics=metrics,
            storage_specs=storage_specs,
            scan_threads=scan_threads,
            pipeline_depth=pipeline_depth,
            verify_chunk=verify_chunk,
            verify_results=verify_results,
            checkpoint_dir=checkpoint_dir,
            scan_retries=scan_retries,
            force_pipeline=force_pipeline,
            job_dir=job_dir,
            record_workers=record_workers,
            verify_workers=verify_workers,
            threads=threads,
        )
        return merged, verify_results

    from concurrent.futures import ThreadPoolExecutor

    verify_results = []
    with ThreadPoolExecutor(max_workers=1) as pool:
        futures: list = []
        merged = generate_event_proofs_for_range_chunked(
            store,
            pairs,
            spec,
            chunk_size=chunk_size,
            checkpoint_dir=checkpoint_dir,
            job_dir=job_dir,
            match_backend=match_backend,
            metrics=metrics,
            storage_specs=storage_specs,
            generate_fn=generate_fn,
            on_chunk=lambda bundle: futures.append(pool.submit(verify_chunk, bundle)),
        )
        verify_results = [f.result() for f in futures]
    return merged, verify_results


@dataclass
class TipsetPair:
    parent: Tipset
    child: Tipset


def _offer_chunk_spine(store, chunk) -> None:
    """Async fetch plane look-ahead: offer a chunk's tipset header CIDs as
    speculative wants before its scan needs them — the plane batch-fetches
    the headers in one round-trip and chases their receipt/state links
    while earlier chunks are still recording, so record-stage block fetches
    land out of order and the order-preserving emitter re-sequences.
    A no-op against stores without a plane underneath."""
    offer = getattr(store, "offer_links", None)
    if offer is None:
        return
    links: list = []
    for pair in chunk:
        links.extend(pair.parent.cids)
        links.extend(pair.child.cids)
    if links:
        offer(links)


def _request_spec_repr(spec: EventProofSpec, chunk_size: int, storage_specs) -> bytes:
    """Byte identity of one range request for checkpoint keying.

    Checkpoints are only valid for the exact request that wrote them —
    the digest covers the event spec, storage specs, and chunk size, so a
    re-run with different specs regenerates instead of silently resuming
    stale bundles. (Shared by the chunked and pipelined drivers; both
    produce interchangeable checkpoint files.)
    """
    return repr(
        (
            spec.event_signature,
            spec.topic_1,
            spec.actor_id_filter,
            chunk_size,
            [
                (s.actor_id, s.key32().hex(), s.slot_index)
                for s in (storage_specs or [])
            ],
        )
    ).encode()


def _chunk_checkpoint_digest(spec_repr: bytes, chunk) -> str:
    """Digest of (request identity, chunk tipset identity) — a chunk of a
    DIFFERENT epoch range never resumes from a shared checkpoint dir."""
    import hashlib

    h = hashlib.sha256(spec_repr)
    for pair in chunk:
        for cid in pair.parent.cids:
            h.update(cid.to_bytes())
        for cid in pair.child.cids:
            h.update(cid.to_bytes())
    return h.hexdigest()[:12]


def generate_event_proofs_for_range_chunked(
    store: Blockstore,
    pairs: Sequence[TipsetPair],
    spec: EventProofSpec,
    chunk_size: int,
    checkpoint_dir: "str | None" = None,
    match_backend=None,
    metrics: Optional[Metrics] = None,
    storage_specs=None,
    scan_workers: int = 0,
    generate_fn=None,
    on_chunk=None,
    job_dir: "str | None" = None,
) -> UnifiedProofBundle:
    """Chunked, resumable range generation.

    Splits ``pairs`` into chunks of ``chunk_size``; each finished chunk's
    bundle is written to ``checkpoint_dir/chunk_NNNN.json`` and skipped on
    re-run (crash recovery for long ranges — the reference aborts the whole
    run on any error and restarts from zero, SURVEY.md §5). The merged
    bundle deduplicates witness blocks across chunks. ``storage_specs``
    prove at every pair of every chunk and ride the same resumable
    checkpoints (both proof kinds serialize in the chunk bundles).

    ``generate_fn`` overrides the per-chunk generator (same signature as
    `generate_event_proofs_for_range` minus ``scan_workers`` — e.g. the
    pipelined driver for intra-generation overlap). ``on_chunk(bundle)``
    is called with every chunk bundle as it becomes available (generated
    OR resumed) — the hook the gen/verify-overlapped driver builds on.

    ``job_dir`` adds write-ahead journaling on top of (or instead of)
    checkpoint files: each completed chunk commits one fsync'd journal
    record (`ipc_proofs_tpu.jobs`), and a re-run with the same job dir
    resumes from the last committed chunk even after SIGKILL mid-write
    (torn tails are discarded). Checkpoint hits are re-committed into
    the journal so either artifact alone can resume the run.
    """
    import os

    metrics = metrics if metrics is not None else get_metrics()
    if checkpoint_dir is not None:
        os.makedirs(checkpoint_dir, exist_ok=True)

    spec_repr = _request_spec_repr(spec, chunk_size, storage_specs)
    job = None
    if job_dir is not None:
        from ipc_proofs_tpu.jobs import job_manifest, resume_or_create

        job = resume_or_create(
            job_dir, job_manifest(spec_repr, pairs, chunk_size), metrics=metrics
        )

    from ipc_proofs_tpu.utils.deadline import checkpoint

    storage_proofs = []
    event_proofs = []
    all_blocks: set[ProofBlock] = set()
    try:
        for chunk_index, start in enumerate(range(0, len(pairs), chunk_size)):
            # chunk boundary = cancellation/deadline boundary: a cancelled
            # or expired request stops here typed instead of generating
            # the remaining chunks for nobody (committed chunks stay in
            # the checkpoint/journal for a budgeted re-run to resume)
            checkpoint("range.chunk")
            chunk = pairs[start : start + chunk_size]
            digest = (
                _chunk_checkpoint_digest(spec_repr, chunk)
                if (checkpoint_dir is not None or job is not None)
                else None
            )
            path = (
                os.path.join(
                    checkpoint_dir, f"chunk_{digest}_{chunk_index:04d}.json"
                )
                if checkpoint_dir is not None
                else None
            )
            if job is not None and job.has_chunk(chunk_index):
                bundle = UnifiedProofBundle.from_json_obj(
                    job.bundle_obj(chunk_index, digest)
                )
                metrics.count("range_chunks_resumed")
            elif path is not None and os.path.exists(path):
                with open(path) as fh:
                    bundle = UnifiedProofBundle.from_json(fh.read())
                metrics.count("range_chunks_resumed")
                if job is not None:  # checkpoint hit the journal missed
                    job.commit_chunk(chunk_index, digest, bundle)
            else:
                # look ahead one chunk: its headers ride the fetch plane's
                # batches while THIS chunk scans/records (no-op without one)
                _offer_chunk_spine(store, chunk)
                _offer_chunk_spine(
                    store, pairs[start + chunk_size : start + 2 * chunk_size]
                )
                if generate_fn is not None:
                    bundle = generate_fn(
                        store,
                        chunk,
                        spec,
                        match_backend=match_backend,
                        metrics=metrics,
                        storage_specs=storage_specs,
                    )
                else:
                    bundle = generate_event_proofs_for_range(
                        store,
                        chunk,
                        spec,
                        match_backend=match_backend,
                        metrics=metrics,
                        storage_specs=storage_specs,
                        scan_workers=scan_workers,
                    )
                if path is not None:
                    tmp = path + ".tmp"
                    with open(tmp, "w") as fh:
                        fh.write(bundle.to_json())
                    os.replace(tmp, path)  # atomic: partial writes never count
                if job is not None:
                    job.commit_chunk(chunk_index, digest, bundle)
                metrics.count("range_chunks_generated")
            if on_chunk is not None:
                on_chunk(bundle)
            storage_proofs.extend(bundle.storage_proofs)
            event_proofs.extend(bundle.event_proofs)
            all_blocks.update(bundle.blocks)
    finally:
        if job is not None:
            job.close()

    return UnifiedProofBundle(
        storage_proofs=storage_proofs,
        event_proofs=event_proofs,
        blocks=sorted(all_blocks, key=lambda b: b.cid.to_bytes()),
    )


def generate_event_proofs_for_range(
    store: Blockstore,
    pairs: Sequence[TipsetPair],
    spec: EventProofSpec,
    match_backend=None,
    metrics: Optional[Metrics] = None,
    scan_workers: int = 0,
    storage_specs=None,
) -> UnifiedProofBundle:
    """Generate event proofs for ``spec`` across a whole range of tipset
    pairs, with one device mask call for the entire range.

    ``scan_workers > 0`` runs Phase A over a thread pool — for RPC-backed
    stores this overlaps block fetches across pairs (the reference fetches
    strictly one block at a time, `client/blockstore.rs:21-28`).

    ``storage_specs``: optional `storage_batch.MappingSlotSpec` grid proved
    against EVERY pair in the range (the reference's unified bundle mixes
    N storage + M event specs for one pair, `src/proofs/generator.rs:25-95`;
    this is its range generalization — e.g. tracking a subnet's nonce slot
    across the whole range). Slot-preimage keccaks hash ONCE range-wide;
    both proof kinds share one deduplicated witness.
    """
    metrics = metrics if metrics is not None else get_metrics()
    matcher = EventMatcher(spec.event_signature, spec.topic_1)
    cached = CachedBlockstore(store)
    matching_per_pair, native_ok = _scan_and_match(
        cached, pairs, spec, matcher, match_backend, metrics, scan_workers
    )
    with metrics.stage("range_record"):
        event_proofs, witness_bytes, fallback_blocks = _record_chunk(
            cached, pairs, matching_per_pair, matcher, spec, native_ok
        )
    metrics.count("range_proofs", len(event_proofs))

    storage_proofs: list = []
    if storage_specs:
        with metrics.stage("range_storage"):
            storage_proofs, storage_witness, storage_blocks = _storage_for_pairs(
                cached, pairs, storage_specs, match_backend
            )
        metrics.count("range_storage_proofs", len(storage_proofs))
        witness_bytes = witness_bytes | storage_witness
        fallback_blocks = list(fallback_blocks) + list(storage_blocks)

    with metrics.stage("range_record"):
        blocks = _materialize_witness(cached, witness_bytes, fallback_blocks)
    return UnifiedProofBundle(
        storage_proofs=storage_proofs, event_proofs=event_proofs, blocks=blocks
    )


def _storage_for_pairs(
    cached: Blockstore,
    pairs: Sequence[TipsetPair],
    storage_specs,
    hash_backend,
    slots=None,
) -> "tuple[list, set[bytes], list[ProofBlock]]":
    """Prove every storage spec at every pair: slot digests hashed once for
    the whole range (``slots`` carries the precomputed digests when the
    pipelined driver proves per-chunk). Returns ``(proofs,
    witness_cid_bytes, fallback_blocks)`` — the range-batched generator
    contributes raw CID bytes for the shared end-of-bundle
    materialization; the per-pair scalar fallback (no native walker)
    contributes materialized blocks."""
    from ipc_proofs_tpu.proofs.storage_batch import (
        generate_storage_proofs_batch,
        generate_storage_proofs_for_pairs,
        hash_slot_specs,
    )

    if slots is None:
        slots = hash_slot_specs(storage_specs, hash_backend)
    batched = generate_storage_proofs_for_pairs(cached, pairs, storage_specs, slots)
    if batched is not None:
        proofs, witness_bytes = batched
        return proofs, witness_bytes, []
    proofs = []
    blocks: set[ProofBlock] = set()
    for pair in pairs:
        bundle = generate_storage_proofs_batch(
            cached,
            pair.parent,
            pair.child,
            storage_specs,
            precomputed_slots=slots,
        )
        proofs.extend(bundle.storage_proofs)
        blocks.update(bundle.blocks)
    return proofs, set(), sorted(blocks, key=lambda b: b.cid.to_bytes())


def _scan_and_match(
    cached: Blockstore,
    pairs: Sequence[TipsetPair],
    spec: EventProofSpec,
    matcher: EventMatcher,
    match_backend,
    metrics: Metrics,
    scan_workers: int = 0,
    match_call=None,
    native_threads: "int | None" = None,
) -> "tuple[list[list[int]], bool]":
    """Phases A+B: scan every pair's receipts/events, run the match
    predicate, return (matching receipt indices per pair, whether the
    native scan pathway ran — the record phase reuses the same fast block
    access when it did).

    ``match_call`` substitutes for ``match_backend.event_match_mask_fp``
    on the unfused fp path (the pipelined driver passes a
    `parallel.pipeline.MatchCoalescer` so concurrent chunks share one
    device call). ``native_threads`` caps the native scanner's per-call
    pthread fan-out (the caller's share of the process thread budget)."""
    # Phase A: host decode of every pair's receipts + events. With a match
    # backend the native scanner emits flat tensors directly (no per-event
    # Python objects); otherwise (or if the C extension is unavailable) the
    # Python scan materializes StampedEvents.
    scan_batch = None
    scans = None
    with metrics.stage("range_scan"):
        import os

        roots = [pair.child.blocks[0].parent_message_receipts for pair in pairs]
        # Fused scan+match: single-chip, fp-capable backends fold the match
        # predicate into the C walk itself (scan_match_hits) — the match
        # leg disappears and no per-event arrays are materialized. A mesh
        # keeps the unfused flat-tensor path: sharded multichip batches
        # want the mask where the rest of the sharded pipeline runs.
        # IPC_SCAN_FUSED_MATCH=0 forces the unfused path (differential knob).
        if (
            match_backend is not None
            and hasattr(match_backend, "event_match_mask_fp")
            and getattr(match_backend, "mesh", None) is None
            and os.environ.get("IPC_SCAN_FUSED_MATCH", "1") != "0"
        ):
            from ipc_proofs_tpu.proofs.scan_native import has_raw_map, scan_match_hits

            if has_raw_map(cached):
                hits = scan_match_hits(
                    cached,
                    roots,
                    matcher.topic0,
                    matcher.topic1,
                    spec.actor_id_filter,
                    threads=native_threads,
                )
                if hits is not None:
                    n_events, hit_pairs, hit_exec = hits
                    metrics.count("range_events", n_events)
                    # the match leg collapsed into the scan: record it as a
                    # (near-)zero stage so per-stage accounting stays complete
                    with metrics.stage("range_match"):
                        matching_per_pair = [[] for _ in pairs]
                        prev = None
                        # walk order ⇒ (pair, exec) ascending, dups adjacent
                        for p, e in zip(hit_pairs.tolist(), hit_exec.tolist()):
                            if (p, e) != prev:
                                matching_per_pair[p].append(e)
                                prev = (p, e)
                    return matching_per_pair, True
        if match_backend is not None and hasattr(match_backend, "event_match_mask_flat"):
            from ipc_proofs_tpu.proofs.scan_native import has_raw_map, scan_events_flat

            # Memory-backed stores only: an RPC-backed store would serialize
            # every fetch through the C fallback callable, losing the
            # scan_workers thread-pool overlap that hides network latency.
            if has_raw_map(cached):
                scan_batch = scan_events_flat(cached, roots, threads=native_threads)
        if scan_batch is None:
            if scan_workers > 0:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(max_workers=scan_workers) as pool:
                    scans = list(pool.map(lambda r: scan_receipt_events(cached, r), roots))
            else:
                scans = [scan_receipt_events(cached, root) for root in roots]

    # Phase B: one batched predicate over all events in the range.
    with metrics.stage("range_match"):
        if scan_batch is not None:
            import numpy as np

            metrics.count("range_events", scan_batch.n_events)
            matching_per_pair: list[list[int]] = [[] for _ in pairs]
            if scan_batch.n_events:
                # fingerprint path when the backend offers it: 8× less
                # host→device transfer; pass 2 confirms hits exactly either way
                if hasattr(match_backend, "event_match_mask_fp"):
                    fp_call = (
                        match_call
                        if match_call is not None
                        else match_backend.event_match_mask_fp
                    )
                    mask = fp_call(
                        scan_batch.fp,
                        scan_batch.n_topics,
                        scan_batch.emitters,
                        scan_batch.valid,
                        matcher.topic0,
                        matcher.topic1,
                        spec.actor_id_filter,
                    )[: scan_batch.n_events]
                else:
                    mask = match_backend.event_match_mask_flat(
                        scan_batch.topics,
                        scan_batch.n_topics,
                        scan_batch.emitters,
                        scan_batch.valid,
                        matcher.topic0,
                        matcher.topic1,
                        spec.actor_id_filter,
                    )[: scan_batch.n_events]
                sel = np.nonzero(mask)[0]
                hits = sorted(
                    set(
                        zip(
                            scan_batch.pair_ids[sel].tolist(),
                            scan_batch.exec_idx[sel].tolist(),
                        )
                    )
                )
                for pair_pos, exec_index in hits:
                    matching_per_pair[pair_pos].append(exec_index)
        elif match_backend is not None:
            flat: list[StampedEvent] = []
            owners: list[tuple[int, int]] = []  # (pair_pos, scan_pos)
            for pair_pos, scanned in enumerate(scans):
                for scan_pos, (_, _, events) in enumerate(scanned):
                    flat.extend(events)
                    owners.extend([(pair_pos, scan_pos)] * len(events))
            mask = (
                match_backend.event_match_mask(
                    flat, matcher.topic0, matcher.topic1, spec.actor_id_filter
                )
                if flat
                else []
            )
            metrics.count("range_events", len(flat))
            hit_receipts: dict[int, set[int]] = {}
            for k, hit in enumerate(mask):
                if hit:
                    pair_pos, scan_pos = owners[k]
                    hit_receipts.setdefault(pair_pos, set()).add(scan_pos)
            matching_per_pair = [
                [scans[p][s][0] for s in sorted(hit_receipts.get(p, ()))]
                for p in range(len(pairs))
            ]
        else:
            matching_per_pair = [
                match_receipt_indices(scanned, matcher, spec.actor_id_filter)
                for scanned in scans
            ]
    return matching_per_pair, scan_batch is not None


def _record_chunk(
    cached: Blockstore,
    pairs: Sequence[TipsetPair],
    matching_per_pair: "list[list[int]]",
    matcher: EventMatcher,
    spec: EventProofSpec,
    native_ok: bool,
) -> "tuple[list, set[bytes], list[ProofBlock]]":
    """Phase C: pass 2. Pairs with no matching receipts contribute no
    proofs, so their base witness (headers, TxMeta walks, exec-order
    blocks) is dead weight for the verifier — skip them entirely. (The
    reference always collects the base witness because it runs one pair
    per invocation, `events/generator.rs:122-145`; a range bundle's
    witness only needs to cover the proofs it carries.)

    Returns ``(event_proofs, witness_cid_bytes, fallback_blocks)`` — the
    witness stays a set of raw CID bytes until the whole bundle
    materializes ONCE (`_materialize_witness`); cross-chunk union on bytes
    avoids hashing materialized ProofBlocks per chunk.

    Native path: TWO C calls cover every matching pair — the batched
    TxMeta/message-AMT walker (exec order + base witness) and the batched
    pass-2 recorder (receipts paths + events AMTs + payload-mode event
    arrays); claims become a numpy mask + array slicing. Any failed group
    (or a store without a raw map, or no extension) falls back to the
    scalar pass 2 — whose already-materialized blocks ride along in
    ``fallback_blocks`` — so errors surface identically.
    """
    matching_pairs = [
        (pair, matching)
        for pair, matching in zip(pairs, matching_per_pair)
        if matching
    ]
    native = None
    # native_ok ⇒ the native extension loaded and the store exposes a raw
    # map (the scan used it), so the walkers use the same fast block access
    if matching_pairs and native_ok:
        native = _record_pass2_native(
            cached, matching_pairs, matcher, spec.actor_id_filter
        )
    if native is not None:
        event_proofs, witness_bytes = native
        return event_proofs, witness_bytes, []
    event_proofs = []
    all_blocks: set[ProofBlock] = set()
    for pair, matching in matching_pairs:
        collector = WitnessCollector(cached)
        # one set of TxMeta walks yields both the recorded base
        # witness and the execution order (they touch the same blocks)
        exec_order = collect_base_witness_and_exec_order(
            collector, cached, pair.parent, pair.child
        )
        proofs, recordings = record_matching_receipts(
            cached,
            pair.parent,
            pair.child,
            exec_order,
            matching,
            matcher,
            spec.actor_id_filter,
        )
        collector.collect_from_recordings(recordings)
        event_proofs.extend(proofs)
        all_blocks.update(collector.materialize())
    return event_proofs, set(), sorted(all_blocks, key=lambda b: b.cid.to_bytes())


def _materialize_witness(
    cached: Blockstore,
    witness_bytes: "set[bytes]",
    extra_blocks: "Sequence[ProofBlock]" = (),
) -> "list[ProofBlock]":
    """Phase D: ONE materialization for the whole bundle — CID objects come
    from one batched C call, block bytes from the raw byte-keyed map (one
    probe each; the CID-keyed store path would pay a hash+eq on every
    freshly parsed CID). ``extra_blocks`` (scalar-fallback and storage
    blocks, already materialized) dedup against the byte set by CID bytes.
    Output is CID-byte-sorted — the bundle's canonical witness order.

    Fast path: ``scan_ext.materialize_blocks`` does the sort, the probes
    (persistent snapshot table first), and the ProofBlock construction in
    one C pass; CID parsing stays the dagcbor extension's batch call either
    way, so malformed-CID acceptance is identical."""
    from ipc_proofs_tpu.backend.native import load_dagcbor_ext, load_scan_ext
    from ipc_proofs_tpu.core.cid import CID
    from ipc_proofs_tpu.proofs.scan_native import _raw_view, _snap_kw

    by_cid: "dict[bytes, ProofBlock]" = {}
    for block in extra_blocks:
        by_cid[block.cid.to_bytes()] = block
    todo_set = witness_bytes - by_cid.keys() if by_cid else witness_bytes
    raw_map, _ = _raw_view(cached)
    ext = load_dagcbor_ext()
    scan_ext = load_scan_ext()
    if (
        ext is not None
        and hasattr(ext, "make_cids")
        and scan_ext is not None
        and hasattr(scan_ext, "materialize_blocks")
    ):
        todo_list = list(todo_set)
        blocks = scan_ext.materialize_blocks(
            raw_map,
            todo_list,
            ext.make_cids,
            ProofBlock,
            lambda cid: cached.get(cid),
            **_snap_kw(cached, raw_map, len(todo_list)),
        )
        if not by_cid:
            return blocks  # already CID-byte-sorted
        for block in blocks:
            by_cid[block.cid.to_bytes()] = block
        return [by_cid[k] for k in sorted(by_cid)]
    todo = sorted(todo_set)
    if ext is not None and hasattr(ext, "make_cids"):
        cids = ext.make_cids(todo)
    else:
        cids = [CID.from_bytes(b) for b in todo]
    make_block = ProofBlock._make
    for cid_bytes, cid in zip(todo, cids):
        raw = raw_map.get(cid_bytes)
        if raw is None:
            raw = cached.get(cid)
        if raw is None:
            raise KeyError(f"missing witness block {cid}")
        by_cid[cid_bytes] = make_block(cid, raw)
    return [by_cid[k] for k in sorted(by_cid)]


_SKIP = object()
"""Merge-stage sentinel: "folded; nothing for the verify stage"."""


class _MergeFold:
    """Merge-on-arrival accumulator for the pipelined driver.

    The old pipelined driver buffered every chunk's witness CIDs in shared
    sets mutated by a single record worker and ran ONE post-drain
    CID-sorted union + materialization after the pipeline finished — a
    serial tail that grew with range size. This fold replaces it: the
    merge stage folds each chunk's output the moment the ordered emitter
    delivers it (input order), materializing only the CIDs no earlier
    chunk already contributed, so the post-pipeline step shrinks to one
    final sort over already-materialized blocks.

    Bit-identity with the post-drain union holds by construction: the
    store is content-addressed (one CID ⇒ one byte string, so first-wins
    vs last-wins insertion is immaterial), the per-chunk ``todo`` sets
    partition exactly the CID set the old single pass covered, and
    `finish` emits the same canonical CID-byte-sorted order.

    The merge stage runs one worker, but the accumulator is still
    lock-guarded: the driver thread reads the proof counts after the
    pipeline drains, and the serial fallback folds from the caller
    thread.
    """

    def __init__(self, cached: Blockstore):
        self._cached = cached
        self._lock = named_lock("_MergeFold._lock")
        self.event_proofs: list = []  # guarded-by: _lock
        self.storage_proofs: list = []  # guarded-by: _lock
        self._by_cid: "dict[bytes, ProofBlock]" = {}  # guarded-by: _lock

    def fold(self, proofs, witness_bytes, extra_blocks, storage: bool = False):
        """Fold one chunk's output: proofs concatenate in arrival order
        (= input order under the ordered emitter), already-materialized
        ``extra_blocks`` register by CID bytes, and only the
        not-yet-seen ``witness_bytes`` CIDs materialize from the store."""
        with self._lock:
            (self.storage_proofs if storage else self.event_proofs).extend(proofs)
            for block in extra_blocks:
                self._by_cid.setdefault(block.cid.to_bytes(), block)
            todo = set(witness_bytes) - self._by_cid.keys()
            if todo:
                for block in _materialize_witness(self._cached, todo):
                    self._by_cid.setdefault(block.cid.to_bytes(), block)

    def finish(self) -> UnifiedProofBundle:
        """One final CID-byte sort over the (already materialized) union
        — the bundle's canonical witness order."""
        with self._lock:
            return UnifiedProofBundle(
                storage_proofs=self.storage_proofs,
                event_proofs=self.event_proofs,
                blocks=[self._by_cid[k] for k in sorted(self._by_cid)],
            )

    @property
    def n_event_proofs(self) -> int:
        with self._lock:
            return len(self.event_proofs)

    @property
    def n_storage_proofs(self) -> int:
        with self._lock:
            return len(self.storage_proofs)


def generate_event_proofs_for_range_pipelined(
    store: Blockstore,
    pairs: Sequence[TipsetPair],
    spec: EventProofSpec,
    chunk_size: int = 512,
    match_backend=None,
    metrics: Optional[Metrics] = None,
    storage_specs=None,
    scan_threads: "int | None" = None,
    pipeline_depth: int = 2,
    verify_chunk=None,
    verify_results: "list | None" = None,
    checkpoint_dir: "str | None" = None,
    scan_retries: int = 2,
    force_pipeline: "bool | None" = None,
    job_dir: "str | None" = None,
    record_workers: "int | None" = None,
    verify_workers: "int | None" = None,
    threads: "int | None" = None,
) -> UnifiedProofBundle:
    """Stage-overlapped range generation on the bounded-queue pipeline
    executor (`parallel.pipeline.run_pipeline`): chunks flow scan+match →
    record → merge → optional verify with at most ``pipeline_depth``
    chunks buffered between stages. Every stage except merge is
    multi-worker. The shared thread budget
    (`utils.threads.resolve_thread_budget`: ``threads`` > ``IPC_THREADS``
    > ``scan_threads`` > ``IPC_SCAN_THREADS`` > CPU affinity) partitions
    into scan/record/verify workers plus the native scanner's per-call
    pthread fan-out, so the process never runs more threads than the
    budget; ``record_workers`` / ``verify_workers`` override their shares
    explicitly.

    Record is chunk-local — each worker builds its own proofs +
    witness-CID buffer with no shared state — and the single-worker merge
    stage folds outputs in input order (`_MergeFold`), replacing the old
    post-drain serial witness union. Storage specs no longer prove in a
    range-wide pass after the pipeline: each chunk's storage leg rides
    the SAME pipeline as a tagged storage item (slot keccaks still hashed
    once up front), so storage proving overlaps event scan/record. When
    several scan workers are in flight on the unfused fp-match path,
    their per-chunk device predicate calls coalesce into one batched
    dispatch (`parallel.pipeline.MatchCoalescer`) — fewer, larger device
    calls with bit-identical masks (the predicate is elementwise).

    Bundle output is bit-identical to the unpipelined driver over the
    same chunking for ANY worker/depth/chunk-size combination (pinned by
    tests/test_range_pipeline.py's grid): the ordered emitter hands the
    merge stage chunk outputs in input order, proofs concatenate in chunk
    order, and the witness union is content-addressed and CID-sorted. A
    worker exception cancels pending work and re-raises here.

    **Single-core fallback:** on a host where ``os.cpu_count() == 1`` the
    pipeline's queue/thread overhead costs more than the overlap pays
    (BENCH_r07: 0.62× vs serial), so the driver runs the SAME stage
    functions inline per chunk — bit-identical output by construction.
    Override with ``force_pipeline=True`` (or env ``IPC_FORCE_PIPELINE=1``)
    to keep the threaded pipeline regardless.

    ``verify_chunk(bundle) -> result`` switches the record stage to emit a
    self-contained bundle per chunk (its witness covers exactly its
    proofs) for the verify stage; per-chunk results append to
    ``verify_results`` in chunk order. Storage proofs appear only in the
    merged bundle, never in per-chunk bundles.

    ``checkpoint_dir`` makes the pipelined path resumable with the same
    per-chunk checkpoint files as `generate_event_proofs_for_range_chunked`
    (interchangeable digests): finished chunks load from disk in the scan
    stage (skipping the store entirely) and new chunk bundles are written
    atomically as they record. ``scan_retries`` bounds transparent
    re-scans of a chunk after a transient store/RPC error — a scan is a
    pure read, so re-running it is deterministic; semantic `RpcError`s
    fail fast.

    ``job_dir`` is the stronger durability contract
    (`ipc_proofs_tpu.jobs`): every completed chunk appends one fsync'd
    write-ahead journal record, so a SIGKILL at ANY byte — including
    mid-record (torn tail) — resumes to a byte-identical final bundle
    (pinned by tools/crashtest.py, including its concurrent-record
    seeds). Concurrent record workers may commit chunks out of index
    order — the journal's per-index completed map makes that resume-safe
    — and `jobs.RangeJob` serializes the appends, so the journal's
    record-count clock stays deterministic. On a worker failure the
    journaling stage's queued inputs are drained
    (`PipelineStage.drain_on_cancel`) so chunks whose upstream work
    finished are still committed before the exception re-raises.
    """
    import os

    from ipc_proofs_tpu.parallel.pipeline import (
        MatchCoalescer,
        PipelineStage,
        run_pipeline,
    )
    from ipc_proofs_tpu.store.rpc import RpcError
    from ipc_proofs_tpu.utils.threads import resolve_thread_budget

    metrics = metrics if metrics is not None else get_metrics()
    matcher = EventMatcher(spec.event_signature, spec.topic_1)
    cached = CachedBlockstore(store)
    chunks = [pairs[k : k + chunk_size] for k in range(0, len(pairs), chunk_size)]
    budget = resolve_thread_budget(threads=threads, scan_threads=scan_threads)
    scan_workers = budget.scan_workers
    rec_workers = (
        max(1, int(record_workers)) if record_workers else budget.record_workers
    )
    ver_workers = (
        max(1, int(verify_workers)) if verify_workers else budget.verify_workers
    )
    if force_pipeline is None:
        force_pipeline = os.environ.get("IPC_FORCE_PIPELINE", "") == "1"
    serial_fallback = (os.cpu_count() or 1) == 1 and not force_pipeline

    spec_repr = None
    if checkpoint_dir is not None or job_dir is not None:
        spec_repr = _request_spec_repr(spec, chunk_size, storage_specs)
    if checkpoint_dir is not None:
        os.makedirs(checkpoint_dir, exist_ok=True)
    job = None
    if job_dir is not None:
        from ipc_proofs_tpu.jobs import job_manifest, resume_or_create

        job = resume_or_create(
            job_dir, job_manifest(spec_repr, pairs, chunk_size), metrics=metrics
        )

    def _chunk_digest(chunk) -> "str | None":
        if spec_repr is None:
            return None
        return _chunk_checkpoint_digest(spec_repr, chunk)

    def _ckpt_path(index: int, chunk) -> "str | None":
        if checkpoint_dir is None:
            return None
        return os.path.join(
            checkpoint_dir,
            f"chunk_{_chunk_checkpoint_digest(spec_repr, chunk)}_{index:04d}.json",
        )

    # checkpoint/journal mode (like verify mode) materializes self-contained
    # per-chunk bundles; the cheap shared-witness path needs none of them
    per_chunk_bundles = (
        verify_chunk is not None or checkpoint_dir is not None or job is not None
    )

    storage_slots = None
    if storage_specs:
        from ipc_proofs_tpu.proofs.storage_batch import hash_slot_specs

        # one keccak batch covers every chunk's storage leg
        with metrics.stage("range_storage"):
            storage_slots = hash_slot_specs(storage_specs, match_backend)

    fold = _MergeFold(cached)

    match_call = None
    # A mesh-carrying backend wants the coalescer even with one scan worker:
    # the coalescer's bucket padding keeps dispatch shapes mesh-divisible.
    if (
        not serial_fallback
        and (
            scan_workers > 1
            or getattr(match_backend, "mesh", None) is not None
        )
        and match_backend is not None
        and hasattr(match_backend, "event_match_mask_fp")
    ):
        match_call = MatchCoalescer(match_backend, metrics=metrics).match_fp

    def _scan_once(chunk):
        # _scan_and_match times itself (range_scan / range_match) — the
        # executor must not wrap it again (no metrics_stage here)
        return _scan_and_match(
            cached,
            chunk,
            spec,
            matcher,
            match_backend,
            metrics,
            match_call=match_call,
            native_threads=budget.native_scan_threads,
        )

    def _scan(item):
        kind, index, chunk = item
        if kind == "storage":
            return item  # storage proves in the record stage; nothing to scan
        if job is not None and job.has_chunk(index):
            return kind, index, chunk, None  # journal-committed — record replays it
        path = _ckpt_path(index, chunk)
        if path is not None and os.path.exists(path):
            return kind, index, chunk, None  # resumed — record loads from disk
        # several scan workers offer concurrently — their chunks' header
        # fetches coalesce into shared fetch-plane batches (no-op without
        # a plane below the cache)
        _offer_chunk_spine(cached, chunk)
        attempt = 0
        while True:
            try:
                return kind, index, chunk, _scan_once(chunk)
            except RpcError:
                raise  # semantic protocol errors: retrying re-asks the same question
            except (ConnectionError, TimeoutError, OSError, RuntimeError) as exc:
                attempt += 1
                if attempt > max(0, scan_retries):
                    raise
                metrics.count("range_scan_retries")
                from ipc_proofs_tpu.utils.log import get_logger

                get_logger(__name__).warning(
                    "scan of chunk %d failed (%s) — retry %d/%d",
                    index, exc, attempt, scan_retries,
                )
                # back off before rescanning: under the pool's lotus_down
                # posture an immediate retry is refused without touching
                # an endpoint (fail fast), so the wait has to span the
                # breaker window for the next attempt to win the probe
                # slot. Deadline-aware: a budget that cannot cover the
                # wait re-raises now instead of sleeping past it.
                delay = min(0.05 * (2.0 ** (attempt - 1)), 0.5)  # ipclint: disable=det-float (retry backoff is wall-clock, not a proof value)
                rem = _remaining_budget_s()
                if rem is not None and rem <= delay:
                    raise
                _dl_checkpoint("range.scan_retry")
                time.sleep(delay)

    def _record(scanned):
        # chunk-local: every branch returns a tagged tuple for the merge
        # stage and touches NO shared accumulator (that is what lets the
        # stage run several workers while staying bit-identical)
        if scanned[0] == "storage":
            _, index, chunk = scanned
            with metrics.stage("range_storage"):
                proofs, witness, blocks = _storage_for_pairs(
                    cached, chunk, storage_specs, match_backend, slots=storage_slots
                )
            metrics.count("range_storage_proofs", len(proofs))
            return "storage", proofs, witness, blocks
        _, index, chunk, scan_out = scanned
        path = _ckpt_path(index, chunk)
        if scan_out is None:
            with metrics.stage("range_record"):
                if job is not None and job.has_chunk(index):
                    bundle = UnifiedProofBundle.from_json_obj(
                        job.bundle_obj(index, _chunk_digest(chunk))
                    )
                else:
                    with open(path) as fh:
                        bundle = UnifiedProofBundle.from_json(fh.read())
                metrics.count("range_chunks_resumed")
            return "bundle", index, chunk, bundle, False  # already journaled
        matching_per_pair, native_ok = scan_out
        with metrics.stage("range_record"):
            proofs, chunk_witness, chunk_fallback = _record_chunk(
                cached, chunk, matching_per_pair, matcher, spec, native_ok
            )
            if not per_chunk_bundles:
                return "chunk", proofs, chunk_witness, chunk_fallback
            # verify/checkpoint/journal mode: materialize a self-contained
            # chunk bundle so it can replay off-thread and/or persist
            blocks = _materialize_witness(cached, chunk_witness, chunk_fallback)
            bundle = UnifiedProofBundle(
                storage_proofs=[], event_proofs=proofs, blocks=blocks
            )
            if path is not None:
                tmp = path + ".tmp"
                with open(tmp, "w") as fh:
                    fh.write(bundle.to_json())
                os.replace(tmp, path)  # atomic: partial writes never count
            if path is not None or job is not None:
                metrics.count("range_chunks_generated")
            if job is not None and verify_chunk is None:
                # no verify stage: the record stage IS the commit point
                # (RangeJob serializes concurrent workers' appends)
                job.commit_chunk(index, _chunk_digest(chunk), bundle)
        return "bundle", index, chunk, bundle, True

    def _merge(recorded):
        kind = recorded[0]
        with metrics.stage("range_merge"):
            if kind == "storage":
                _, proofs, witness, blocks = recorded
                fold.fold(proofs, witness, blocks, storage=True)
                return _SKIP
            if kind == "chunk":
                _, proofs, witness, blocks = recorded
                fold.fold(proofs, witness, blocks)
                return _SKIP
            _, index, chunk, bundle, fresh = recorded
            fold.fold(bundle.event_proofs, (), bundle.blocks)
        if verify_chunk is not None:
            return index, chunk, bundle, fresh
        return _SKIP

    stages = [
        PipelineStage("scan", _scan, workers=scan_workers),
        # with a journal and no verify stage, record is the commit point:
        # drain its queue on abort so finished scans still journal
        PipelineStage(
            "record",
            _record,
            workers=rec_workers,
            drain_on_cancel=job is not None and verify_chunk is None,
        ),
        PipelineStage("merge", _merge),
    ]
    stage_fns = [_scan, _record, _merge]
    if verify_chunk is not None:

        def _verify(recorded):
            if recorded is _SKIP:
                return _SKIP  # storage item — nothing to replay
            index, chunk, bundle, fresh = recorded
            with metrics.stage("range_verify"):
                result = verify_chunk(bundle)
            if job is not None and fresh:
                # commit chunk + verdict in ONE record (the journal's
                # per-chunk contract); resumed chunks re-verify but don't
                # re-commit
                job.commit_chunk(
                    index, _chunk_digest(chunk), bundle, verify=_verdict_obj(result)
                )
            return result

        stages.append(
            PipelineStage(
                "verify", _verify, workers=ver_workers, drain_on_cancel=job is not None
            )
        )
        stage_fns.append(_verify)

    # storage items interleave with their event chunk so both legs of
    # chunk k are in flight together; merge still folds in input order
    items: list = []
    for index, chunk in enumerate(chunks):
        items.append(("event", index, chunk))
        if storage_specs:
            items.append(("storage", index, chunk))
    try:
        if items:
            if serial_fallback:
                from ipc_proofs_tpu.utils.deadline import checkpoint

                metrics.count("range_pipeline_serial_fallback")
                results = []
                for item in items:
                    # same cancellation boundary the threaded pipeline has
                    # at each stage hand-off
                    checkpoint("range.chunk")
                    out = item
                    for fn in stage_fns:
                        out = fn(out)
                    results.append(out)
            else:
                results = run_pipeline(items, stages, depth=max(1, pipeline_depth))
            if verify_chunk is not None and verify_results is not None:
                verify_results.extend(r for r in results if r is not _SKIP)
        metrics.count("range_proofs", fold.n_event_proofs)
        with metrics.stage("range_merge"):
            return fold.finish()
    finally:
        if job is not None:
            job.close()


def _verdict_obj(result):
    """Best-effort JSON projection of a caller's verify verdict for the
    journal record (the verdict is informational — resumed chunks
    re-verify live, so fidelity beyond JSON-representability isn't
    load-bearing)."""
    import json

    try:
        json.dumps(result)
        return result
    except (TypeError, ValueError):
        return repr(result)


def _record_pass2_native(
    cached: Blockstore,
    matching_pairs: "list[tuple[TipsetPair, list[int]]]",
    matcher: EventMatcher,
    actor_id_filter: Optional[int],
) -> "Optional[tuple[list, set[bytes]]]":
    """Phase C over the native walkers: returns (event_proofs,
    witness_cid_bytes) or None when either extension pathway is
    unavailable. Verdict- and byte-identical to the scalar pass 2 (tested
    differentially); groups the C side fails on are redone scalar."""
    import numpy as np

    from ipc_proofs_tpu.core.cid import CID
    from ipc_proofs_tpu.proofs.bundle import EventData, EventProof
    from ipc_proofs_tpu.proofs.exec_order import collect_exec_orders_for_pairs
    from ipc_proofs_tpu.proofs.scan_native import record_receipt_paths

    walks = collect_exec_orders_for_pairs(
        cached,
        [[h.messages for h in pair.parent.blocks] for pair, _ in matching_pairs],
    )
    if walks is None:
        return None
    rec = record_receipt_paths(
        cached,
        [pair.child.blocks[0].parent_message_receipts for pair, _ in matching_pairs],
        [matching for _, matching in matching_pairs],
    )
    if rec is None:
        return None

    sb = rec.batch
    # claim mask over ALL emitted events at once — THE shared host
    # predicate (extract_evm_log validity + matches_log + actor filter),
    # evaluated on the C-parsed arrays
    if sb.n_events:
        from ipc_proofs_tpu.proofs.scan_native import match_mask_flat_np

        mask = match_mask_flat_np(
            sb.topics, sb.n_topics, sb.emitters, sb.valid,
            matcher.topic0, matcher.topic1, actor_id_filter,
        )
    else:
        mask = np.zeros(0, dtype=bool)

    # Two passes over the groups so every CID string in every claim renders
    # in ONE batched C call (cid_strs): pass A collects witness bytes, runs
    # scalar redo for failed groups, and gathers the raw CID bytes each
    # native claim needs; pass B builds the EventProof objects from the
    # pre-rendered strings. Per-group proof lists keep the emission order
    # identical to the single-pass formulation (group order, row order).
    from ipc_proofs_tpu.backend.native import load_dagcbor_ext

    witness: set[bytes] = set()
    witness_items: list[bytes] = []  # good-group flat appends; one union below
    goff = rec.row_offsets(len(matching_pairs))
    # ONE vectorized pass resolves every claim row and its group up front
    # (a per-group nonzero over the mask slice was ~2 us x thousands of
    # groups). side="right" minus 1 maps a row offset to the unique group
    # whose [goff[g], goff[g+1]) span contains it, including through runs
    # of empty groups with equal offsets.
    rows_by_group: "dict[int, tuple[list[int], list[int]]]" = {}
    if mask.size:
        sel = np.nonzero(mask)[0]
        if len(sel):
            sel_group = (np.searchsorted(goff, sel, side="right") - 1).tolist()
            sel_exec = sb.exec_idx[sel].tolist()
            for g_, r_, e_ in zip(sel_group, sel.tolist(), sel_exec):
                entry = rows_by_group.get(g_)
                if entry is None:
                    entry = rows_by_group[g_] = ([], [])
                entry[0].append(r_)
                entry[1].append(e_)
    per_group_proofs: "list[list]" = [[] for _ in matching_pairs]
    claim_rows: "list[tuple[int, int]]" = []  # (group, row)
    str_bytes: "list[bytes]" = []  # cid bytes to render, in claim order
    group_str_base: "dict[int, int]" = {}  # group → offset of its parents+child
    good: "list[int]" = []  # native-handled groups (witness gathered flat below)
    for g, (pair, matching) in enumerate(matching_pairs):
        walk = walks[g]
        if walk is None or rec.failed[g]:
            collector = WitnessCollector(cached)
            exec_order = collect_base_witness_and_exec_order(
                collector, cached, pair.parent, pair.child
            )
            redo_proofs, recordings = record_matching_receipts(
                cached,
                pair.parent,
                pair.child,
                exec_order,
                matching,
                matcher,
                actor_id_filter,
            )
            collector.collect_from_recordings(recordings)
            per_group_proofs[g] = redo_proofs
            witness.update(c.to_bytes() for c in collector.needed_cids())
            continue

        exec_msgs, exec_touched = walk
        for i in matching:
            if i >= len(exec_msgs):
                raise KeyError(f"missing message at execution index {i}")
        good.append(g)
        witness_items.extend(exec_touched)

        grp = rows_by_group.get(g)
        if grp is None:
            continue
        rows, execs = grp
        group_str_base[g] = len(str_bytes)
        str_bytes.extend(c.to_bytes() for c in pair.parent.cids)
        str_bytes.append(pair.child.cids[0].to_bytes())
        for row, exec_i in zip(rows, execs):
            claim_rows.append((g, row))
            str_bytes.append(exec_msgs[exec_i])

    # header-derived witness CIDs for all good groups in four flat
    # comprehensions (per-group extends cost a genexp per group), plus the
    # recorder's touched blocks — ALL of them when no group fell back
    # (the common case: one list, no per-group slicing)
    good_pairs = [matching_pairs[g][0] for g in good]
    witness_items += [c.to_bytes() for p in good_pairs for c in p.parent.cids]
    witness_items += [p.child.cids[0].to_bytes() for p in good_pairs]
    witness_items += [
        p.child.blocks[0].parent_message_receipts.to_bytes() for p in good_pairs
    ]
    witness_items += [
        h.messages.to_bytes() for p in good_pairs for h in p.parent.blocks
    ]
    if len(good) == len(matching_pairs):
        witness_items.extend(rec.all_touched())
    else:
        for g in good:
            witness_items.extend(rec.touched(g))
    witness.update(witness_items)
    ext = load_dagcbor_ext()
    if ext is not None and hasattr(ext, "cid_strs"):
        strs = ext.cid_strs(str_bytes)
    else:
        strs = [str(CID.from_bytes(b)) for b in str_bytes]

    # message-cid string positions are laid out per group after its
    # parents+child block; claims of one group are contiguous in claim_rows
    msg_pos: "list[int]" = []
    pos = 0
    for g, _row in claim_rows:
        base = group_str_base[g] + len(matching_pairs[g][0].parent.cids) + 1
        pos = base if pos < base else pos
        msg_pos.append(pos)
        pos += 1

    from ipc_proofs_tpu.backend.native import load_scan_ext

    scan_ext = load_scan_ext()
    if claim_rows and scan_ext is not None and hasattr(scan_ext, "build_event_claims"):
        n_groups = len(matching_pairs)
        claims = scan_ext.build_event_claims(
            strs=strs,
            rows=np.fromiter(
                (row for _, row in claim_rows), np.int64, count=len(claim_rows)
            ),
            group_of=np.fromiter(
                (g for g, _ in claim_rows), np.int64, count=len(claim_rows)
            ),
            msg_pos=np.asarray(msg_pos, np.int64),
            str_base=np.fromiter(
                (group_str_base.get(g, 0) for g in range(n_groups)),
                np.int64, count=n_groups,
            ),
            n_parents=np.fromiter(
                (len(p.parent.cids) for p, _ in matching_pairs),
                np.int64, count=n_groups,
            ),
            parent_epoch=np.fromiter(
                (p.parent.height for p, _ in matching_pairs),
                np.int64, count=n_groups,
            ),
            child_epoch=np.fromiter(
                (p.child.height for p, _ in matching_pairs),
                np.int64, count=n_groups,
            ),
            exec_idx=sb.exec_idx,
            event_idx=sb.event_idx,
            emitters=sb.emitters,
            n_topics=sb.n_topics,
            topics_off=sb.topics_off,
            data_off=sb.data_off,
            data_len=sb.data_len,
            topics_pool=sb.topics_pool,
            data_pool=sb.data_pool,
            proof_cls=EventProof,
            data_cls=EventData,
        )
        for (g, _row), proof in zip(claim_rows, claims):
            per_group_proofs[g].append(proof)
    else:
        # gather every claim's columns in one numpy fancy-index per column —
        # per-claim np-scalar int() conversions were the loop's hottest ops
        if claim_rows:
            rows_arr = np.fromiter(
                (row for _, row in claim_rows), dtype=np.int64, count=len(claim_rows)
            )
            exec_idx_l = sb.exec_idx[rows_arr].tolist()
            event_idx_l = sb.event_idx[rows_arr].tolist()
            emitters_l = sb.emitters[rows_arr].tolist()
            n_topics_l = sb.n_topics[rows_arr].tolist()
            toff_l = sb.topics_off[rows_arr].tolist()
            doff_l = sb.data_off[rows_arr].tolist()
            dlen_l = sb.data_len[rows_arr].tolist()
        topics_pool = sb.topics_pool
        data_pool = sb.data_pool
        make_proof = EventProof._make
        make_data = EventData._make

        for j, (g, row) in enumerate(claim_rows):
            pair = matching_pairs[g][0]
            base = group_str_base[g]
            n_parents = len(pair.parent.cids)
            nt = n_topics_l[j]
            toff = toff_l[j]
            doff = doff_l[j]
            per_group_proofs[g].append(
                make_proof(
                    parent_epoch=pair.parent.height,
                    child_epoch=pair.child.height,
                    parent_tipset_cids=strs[base : base + n_parents],
                    child_block_cid=strs[base + n_parents],
                    message_cid=strs[msg_pos[j]],
                    exec_index=exec_idx_l[j],
                    event_index=event_idx_l[j],
                    event_data=make_data(
                        emitter=emitters_l[j],
                        topics=[
                            "0x" + topics_pool[toff + 32 * k : toff + 32 * (k + 1)].hex()
                            for k in range(nt)
                        ],
                        data="0x" + data_pool[doff : doff + dlen_l[j]].hex(),
                    ),
                )
            )

    proofs: list = []
    for group_proofs in per_group_proofs:
        proofs.extend(group_proofs)
    return proofs, witness
